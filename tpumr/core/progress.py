"""Weighted phase/progress tree ≈ ``org.apache.hadoop.util.Progress``
(reference: src/core/org/apache/hadoop/util/Progress.java): a node's progress
is its own fraction if it is a leaf, else progress of completed children plus
the current child's fractional contribution.
"""

from __future__ import annotations


class Progress:
    def __init__(self, status: str = "") -> None:
        self.status = status
        self._children: list[Progress] = []
        self._current = 0
        self._progress = 0.0

    def add_phase(self, status: str = "") -> "Progress":
        child = Progress(status)
        self._children.append(child)
        return child

    def start_next_phase(self) -> None:
        if self._current < len(self._children) - 1:
            self._current += 1

    def phase(self) -> "Progress":
        return self._children[self._current] if self._children else self

    def set(self, progress: float) -> None:
        self._progress = min(1.0, max(0.0, progress))

    def complete(self) -> None:
        self._progress = 1.0
        if self._children:
            self._current = len(self._children) - 1
            for c in self._children:
                c.complete()

    def get(self) -> float:
        if not self._children:
            return self._progress
        done = sum(1.0 for c in self._children[: self._current])
        return (done + self._children[self._current].get()) / len(self._children)
