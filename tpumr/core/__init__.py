from tpumr.core.configuration import Configuration
from tpumr.core.counters import Counter, CounterGroup, Counters
from tpumr.core.progress import Progress

__all__ = ["Configuration", "Counter", "CounterGroup", "Counters", "Progress"]
