"""Hierarchical job counters.

≈ ``org.apache.hadoop.mapred.Counters`` (reference:
src/mapred/org/apache/hadoop/mapred/Counters.java): named groups of named
counters, incremented by tasks, serialized in every heartbeat, and summed
job-wide. The TPU build additionally makes backend placement a first-class
counter group (the reference's GPU observability was log-only — SURVEY.md §5).
"""

from __future__ import annotations

import threading
from typing import Iterator


class TaskCounter:
    """Framework counter names (≈ Task.Counter enum)."""
    MAP_INPUT_RECORDS = "MAP_INPUT_RECORDS"
    MAP_OUTPUT_RECORDS = "MAP_OUTPUT_RECORDS"
    MAP_INPUT_BYTES = "MAP_INPUT_BYTES"
    MAP_OUTPUT_BYTES = "MAP_OUTPUT_BYTES"
    COMBINE_INPUT_RECORDS = "COMBINE_INPUT_RECORDS"
    COMBINE_OUTPUT_RECORDS = "COMBINE_OUTPUT_RECORDS"
    REDUCE_INPUT_GROUPS = "REDUCE_INPUT_GROUPS"
    REDUCE_INPUT_RECORDS = "REDUCE_INPUT_RECORDS"
    REDUCE_OUTPUT_RECORDS = "REDUCE_OUTPUT_RECORDS"
    REDUCE_SHUFFLE_BYTES = "REDUCE_SHUFFLE_BYTES"
    #: bytes that actually crossed the shuffle wire (post wire-codec
    #: compression) — the ratio REDUCE_SHUFFLE_WIRE_BYTES /
    #: REDUCE_SHUFFLE_BYTES is the wire compression win per job
    REDUCE_SHUFFLE_WIRE_BYTES = "REDUCE_SHUFFLE_WIRE_BYTES"
    #: copier segment placement (ShuffleRamManager budget outcome):
    #: how many map outputs merged straight from RAM vs spilled local
    REDUCE_SHUFFLE_SEGMENTS_MEM = "REDUCE_SHUFFLE_SEGMENTS_MEM"
    REDUCE_SHUFFLE_SEGMENTS_DISK = "REDUCE_SHUFFLE_SEGMENTS_DISK"
    #: fetch failures the copier survived (local retries, penalty box,
    #: and fetch-failure reports to the master — shuffle fault tolerance)
    REDUCE_FETCH_FAILURES = "REDUCE_FETCH_FAILURES"
    SPILLED_RECORDS = "SPILLED_RECORDS"
    #: shuffle merge engine: background in-memory merges that freed
    #: ShuffleRamManager budget mid-copy (≈ InMemFSMergeThread), and the
    #: segments they consumed
    SHUFFLE_INMEM_MERGES = "SHUFFLE_INMEM_MERGES"
    SHUFFLE_INMEM_MERGE_SEGMENTS = "SHUFFLE_INMEM_MERGE_SEGMENTS"
    #: background disk-run merges during the copy phase (≈ the
    #: reference LocalFSMerger): accumulated per-segment spills folded
    #: into one sorted run while fetchers wait on the wire, keeping the
    #: final merge single-pass
    SHUFFLE_DISK_MERGES = "SHUFFLE_DISK_MERGES"
    SHUFFLE_DISK_MERGE_SEGMENTS = "SHUFFLE_DISK_MERGE_SEGMENTS"
    #: bounded-fan-in merging (≈ Merger intermediate passes honoring
    #: io.sort.factor): intermediate passes run and segments they merged
    MERGE_PASSES = "MERGE_PASSES"
    MERGE_PASS_SEGMENTS = "MERGE_PASS_SEGMENTS"
    FRAMEWORK_GROUP = "tpumr.TaskCounter"


class BackendCounter:
    """New in the TPU build: per-backend placement/runtime counters."""
    CPU_MAP_TASKS = "CPU_MAP_TASKS"
    TPU_MAP_TASKS = "TPU_MAP_TASKS"
    CPU_MAP_MILLIS = "CPU_MAP_MILLIS"
    TPU_MAP_MILLIS = "TPU_MAP_MILLIS"
    TPU_DEVICE_BYTES_STAGED = "TPU_DEVICE_BYTES_STAGED"
    CPU_BATCH_MAP_TASKS = "CPU_BATCH_MAP_TASKS"
    TPU_SHUFFLE_RECORDS = "TPU_SHUFFLE_RECORDS"
    TPU_SHUFFLE_BYTES = "TPU_SHUFFLE_BYTES"
    #: gang reduces whose device sort ran on a REAL accelerator backend
    #: (vs the same vectorized path on the CPU backend) — lets a job
    #: artifact PROVE which backend sorted it, not just that the dense
    #: path ran
    DEVICE_SORT_ON_ACCEL = "DEVICE_SORT_ON_ACCEL"
    SHUFFLE_HOST_FALLBACKS = "SHUFFLE_HOST_FALLBACKS"
    GROUP = "tpumr.BackendCounter"


class JobCounter:
    LAUNCHED_MAP_TASKS = "LAUNCHED_MAP_TASKS"
    LAUNCHED_REDUCE_TASKS = "LAUNCHED_REDUCE_TASKS"
    DATA_LOCAL_MAPS = "DATA_LOCAL_MAPS"
    RACK_LOCAL_MAPS = "RACK_LOCAL_MAPS"
    FAILED_MAP_TASKS = "FAILED_MAP_TASKS"
    FAILED_REDUCE_TASKS = "FAILED_REDUCE_TASKS"
    SPECULATIVE_MAPS = "SPECULATIVE_MAPS"
    #: accelerator fault tolerance: TIPs pinned CPU-only after repeated
    #: device/compile-classed TPU failures, and attempts the tracker
    #: reaper failed for progress silence (failure_class=timeout)
    TPU_DEMOTIONS = "TPU_DEMOTIONS"
    TASKS_REAPED_TIMEOUT = "TASKS_REAPED_TIMEOUT"
    GROUP = "tpumr.JobCounter"


class Counter:
    __slots__ = ("name", "display_name", "_value", "_lock")

    def __init__(self, name: str, display_name: str | None = None,
                 value: int = 0) -> None:
        self.name = name
        self.display_name = display_name or name
        self._value = int(value)
        self._lock = threading.Lock()

    @property
    def value(self) -> int:
        return self._value

    def increment(self, amount: int = 1) -> None:
        with self._lock:
            self._value += amount

    def set_value(self, value: int) -> None:
        with self._lock:
            self._value = int(value)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Counter({self.name}={self._value})"


class CounterGroup:
    def __init__(self, name: str) -> None:
        self.name = name
        self._counters: dict[str, Counter] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def __iter__(self) -> Iterator[Counter]:
        return iter(self._counters.values())

    def __len__(self) -> int:
        return len(self._counters)

    def merge(self, other: "CounterGroup") -> None:
        for c in other:
            self.counter(c.name).increment(c.value)


class Counters:
    """Thread-safe counter set: group → name → value."""

    def __init__(self) -> None:
        self._groups: dict[str, CounterGroup] = {}
        self._lock = threading.Lock()
        #: (group, name) -> Counter fast path: incr() runs once per
        #: RECORD on the host map/reduce paths — the two-level locked
        #: lookup is profiling-visible. CPython dict reads are atomic;
        #: insertion goes through the locked path once per counter.
        self._flat: dict[tuple, Counter] = {}

    def group(self, name: str) -> CounterGroup:
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                g = self._groups[name] = CounterGroup(name)
            return g

    def counter(self, group: str, name: str) -> Counter:
        key = (group, name)
        c = self._flat.get(key)
        if c is None:
            c = self.group(group).counter(name)
            self._flat[key] = c
        return c

    def incr(self, group: str, name: str, amount: int = 1) -> None:
        self.counter(group, name).increment(amount)

    def value(self, group: str, name: str) -> int:
        return self.counter(group, name).value

    def __iter__(self) -> Iterator[CounterGroup]:
        return iter(list(self._groups.values()))

    def merge(self, other: "Counters") -> None:
        """Sum another counter set into this one (≈ Counters.incrAllCounters)."""
        for g in other:
            self.group(g.name).merge(g)

    # wire format (heartbeats / history)

    def to_dict(self) -> dict[str, dict[str, int]]:
        return {g.name: {c.name: c.value for c in g} for g in self}

    @classmethod
    def from_dict(cls, d: dict[str, dict[str, int]]) -> "Counters":
        out = cls()
        for gname, cs in d.items():
            for cname, v in cs.items():
                out.counter(gname, cname).set_value(v)
        return out

    def __repr__(self) -> str:  # pragma: no cover
        total = sum(len(g) for g in self)
        return f"Counters({len(self._groups)} groups, {total} counters)"
