"""Distributed job tracing — spans across the whole control plane.

New capability beyond the reference (its GPU observability was log-only,
SURVEY.md §5): every stage of a job's life — submit → schedule → launch →
map → spill → shuffle fetch → merge → commit — is recorded as a SPAN
(``trace_id``, ``span_id``, ``parent_span_id``, name, role, backend,
start/end, attributes) so the question the hybrid CPU/TPU scheduler
lives or dies on ("where does wall-clock actually go?") is answerable
from one queryable timeline instead of grepping daemon logs.

Design:

- **One trace per job.** The JobMaster mints a ``trace_id`` at submit
  when ``tpumr.trace.enabled`` is true (job conf or master conf) and
  stores it in the job conf (``tpumr.trace.id``), which already flows to
  every tracker (get_job_conf) and child process (the task file). Span
  context crosses process boundaries on existing seams: launch actions
  carry the scheduling span's context on the Task, the umbilical task
  file ships it to isolated children, and shuffle fetch spans name their
  source address per fetch.
- **Off by default, near-zero cost.** Without the flag no tracer is
  consulted beyond a None check: the ambient helpers short-circuit on a
  thread-local read, and daemons never stamp trace context on tasks of
  untraced jobs.
- **Per-process JSONL flush.** Each daemon/process appends finished
  spans to ``<trace dir>/trace-<trace_id>.<role>-<uniq>.jsonl`` next to
  the job history (``tpumr.trace.dir``, default ``tpumr.history.dir``).
  One file per tracer instance — no cross-process append interleaving.
  The JobMaster merges the files on demand (``/tracejson?job=`` and the
  ``get_job_trace`` RPC) into Chrome trace-event JSON loadable by
  ``chrome://tracing`` / Perfetto.
- **Critical path.** :func:`critical_path` walks the span tree backward
  from the last-finishing leaf (the classic makespan-dominating chain)
  and reports each span's contribution — the measurement substrate every
  later perf PR benchmarks against.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from contextlib import contextmanager
from typing import Any, Iterator

ENABLED_KEY = "tpumr.trace.enabled"
TRACE_ID_KEY = "tpumr.trace.id"
TRACE_DIR_KEY = "tpumr.trace.dir"
SAMPLE_KEY = "tpumr.trace.sample"

#: flush to disk once this many finished spans are buffered (spans also
#: flush explicitly at task/job completion so merges see fresh data)
FLUSH_THRESHOLD = 256

#: hard per-process buffer bound: when the flusher can't keep up (or no
#: trace dir is configured and nothing drains the buffer between
#: threshold flushes), the OLDEST buffered spans are dropped and counted
#: (``Tracer.dropped``) — a scale-harness run with hundreds of simulated
#: trackers must never let trace buffering grow without bound
MAX_BUFFERED = 8192

_id_lock = threading.Lock()
_id_counter = 0


def new_span_id() -> str:
    """Unique-enough 16-hex span id (random, no coordination needed)."""
    return os.urandom(8).hex()


def _uniq() -> int:
    global _id_counter
    with _id_lock:
        _id_counter += 1
        return _id_counter


def _safe_trace_id(trace_id: str) -> str:
    """Trace ids become file names — constrain to a safe alphabet."""
    return re.sub(r"[^A-Za-z0-9_.-]", "_", str(trace_id))[:128]


def trace_enabled(conf: Any) -> bool:
    """The one ``tpumr.trace.enabled`` predicate — handles typed confs
    (get_boolean) and plain submission dicts (string/bool values)."""
    try:
        return bool(conf.get_boolean(ENABLED_KEY, False))
    except (AttributeError, TypeError, ValueError):
        v = conf.get(ENABLED_KEY)
        return v is True or str(v).lower() in ("true", "1")


def trace_sample_rate(conf: Any) -> float:
    """Per-job head-sampling rate (``tpumr.trace.sample``, default 1.0):
    the master draws once at submit — a sampled-out job is simply not
    traced (no id minted, zero per-span cost anywhere), which is how a
    cluster runs hundreds of trackers with tracing on without the JSONL
    volume scaling with job count. Clamped to [0, 1]; a malformed value
    falls back to 1.0 (trace rather than silently lose everything)."""
    try:
        v = conf.get(SAMPLE_KEY)
    except (AttributeError, TypeError):
        return 1.0
    if v is None or v == "":
        return 1.0
    try:
        return min(1.0, max(0.0, float(v)))
    except (TypeError, ValueError):
        return 1.0


def trace_dir_from_conf(conf: Any) -> "str | None":
    """The one trace-sink resolution chain: ``tpumr.trace.dir``, else
    next to the job history (``tpumr.history.dir``), else None (spans
    buffered then dropped). Every daemon/CLI consults THIS so they can
    never write and read traces in different places."""
    d = conf.get(TRACE_DIR_KEY) or conf.get("tpumr.history.dir")
    return str(d) if d else None


class Span:
    """One timed operation. Mutable until :meth:`Tracer.finish`."""

    __slots__ = ("trace_id", "span_id", "parent_span_id", "name", "role",
                 "backend", "start", "end", "attributes")

    def __init__(self, trace_id: str, span_id: str, parent_span_id: str,
                 name: str, role: str, backend: str = "",
                 start: float = 0.0, end: float = 0.0,
                 attributes: "dict | None" = None) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id
        self.name = name
        self.role = role
        self.backend = backend
        self.start = start
        self.end = end
        self.attributes = attributes if attributes is not None else {}

    def set(self, **attrs: Any) -> "Span":
        self.attributes.update(attrs)
        return self

    @property
    def context(self) -> dict:
        """Wire-able propagation context ({trace_id, span_id})."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @property
    def duration(self) -> float:
        return max(0.0, (self.end or time.time()) - self.start)

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_span_id": self.parent_span_id, "name": self.name,
                "role": self.role, "backend": self.backend,
                "start": self.start, "end": self.end,
                "attributes": self.attributes}


class Tracer:
    """Thread-safe per-process span buffer + JSONL flusher for one
    daemon role. Construct via :meth:`from_conf` (returns None when
    tracing is off — callers keep a ``tracer is None`` fast path)."""

    def __init__(self, role: str, trace_dir: "str | None" = None,
                 hostname: "str | None" = None) -> None:
        self.role = role
        self.trace_dir = trace_dir
        if hostname is None:
            import socket
            hostname = socket.gethostname()
        self.hostname = hostname
        self._lock = threading.Lock()
        self._finished: list[Span] = []
        #: spans dropped at the MAX_BUFFERED high-water mark (observable
        #: tell that the flusher fell behind the span rate)
        self.dropped = 0
        #: serializes the file-append phase of flush() — concurrent
        #: flushes (threshold thread + an explicit caller) must not
        #: interleave partial lines in one tracer's file
        self._flush_lock = threading.Lock()
        self._flush_pending = False
        #: per-tracer file suffix: many tracers (mini-cluster daemons)
        #: share a process; each appends to its OWN file so line writes
        #: never interleave
        self._fileid = f"{os.getpid():x}-{_uniq():x}"

    @classmethod
    def from_conf(cls, conf: Any, role: str) -> "Tracer | None":
        """A tracer when ``tpumr.trace.enabled`` is set, else None."""
        if not trace_enabled(conf):
            return None
        return cls(role, trace_dir=trace_dir_from_conf(conf))

    # ------------------------------------------------------------ spans

    def start_span(self, name: str, trace_id: str,
                   parent: "dict | Span | str | None" = None,
                   role: "str | None" = None, backend: str = "",
                   **attrs: Any) -> Span:
        if isinstance(parent, Span):
            parent_id = parent.span_id
        elif isinstance(parent, dict):
            parent_id = str(parent.get("span_id", ""))
        else:
            parent_id = parent or ""
        return Span(trace_id=str(trace_id), span_id=new_span_id(),
                    parent_span_id=parent_id, name=name,
                    role=role or self.role, backend=backend,
                    start=time.time(), attributes=dict(attrs))

    def finish(self, span: Span) -> Span:
        span.end = time.time()
        span.attributes.setdefault("host", self.hostname)
        with self._lock:
            self._finished.append(span)
            n = len(self._finished)
            if n > MAX_BUFFERED:
                # flusher outrun (or no sink): shed the OLDEST spans —
                # bounded memory beats a complete-but-growing buffer
                shed = n - MAX_BUFFERED
                del self._finished[:shed]
                self.dropped += shed
                n = MAX_BUFFERED
        if n >= FLUSH_THRESHOLD:
            # finish() is called from hot paths that may hold daemon
            # locks (the master records schedule spans mid-heartbeat) —
            # the growth-bound flush must never do disk I/O there
            self._schedule_flush()
        return span

    def _schedule_flush(self) -> None:
        with self._lock:
            if self._flush_pending:
                return
            self._flush_pending = True

        def run() -> None:
            try:
                self.flush()
            finally:
                with self._lock:
                    self._flush_pending = False

        threading.Thread(target=run, name="trace-flush",
                         daemon=True).start()

    @contextmanager
    def span(self, name: str, trace_id: str,
             parent: "dict | Span | str | None" = None,
             role: "str | None" = None, backend: str = "",
             **attrs: Any) -> "Iterator[Span]":
        s = self.start_span(name, trace_id, parent=parent, role=role,
                            backend=backend, **attrs)
        try:
            yield s
        except BaseException as e:
            s.set(error=f"{type(e).__name__}: {e}")
            raise
        finally:
            self.finish(s)

    def instant(self, name: str, trace_id: str,
                parent: "dict | Span | str | None" = None,
                role: "str | None" = None, **attrs: Any) -> Span:
        """A zero-ish-duration marker span (scheduling decisions,
        penalty-box holds)."""
        s = self.start_span(name, trace_id, parent=parent, role=role,
                            **attrs)
        return self.finish(s)

    # ------------------------------------------------------------ flush

    def pending(self) -> "list[Span]":
        with self._lock:
            return list(self._finished)

    def flush(self) -> int:
        """Append buffered finished spans to per-trace JSONL files.
        Returns the number of spans written (0 when no dir is
        configured — spans are then dropped rather than growing without
        bound)."""
        with self._flush_lock:
            with self._lock:
                spans, self._finished = self._finished, []
            if not spans:
                return 0
            if not self.trace_dir:
                return 0
            by_trace: dict[str, list[Span]] = {}
            for s in spans:
                by_trace.setdefault(s.trace_id, []).append(s)
            written = 0
            try:
                os.makedirs(self.trace_dir, exist_ok=True)
                for tid, group in by_trace.items():
                    path = os.path.join(
                        self.trace_dir,
                        f"trace-{_safe_trace_id(tid)}."
                        f"{_safe_trace_id(self.role)}-{self._fileid}.jsonl")
                    # default=str: ambient spans accept arbitrary user
                    # attrs (numpy scalars, paths) — one unserializable
                    # value must not sink the whole batch
                    blob = "".join(json.dumps(s.to_dict(), default=str)
                                   + "\n" for s in group)
                    with open(path, "a") as f:
                        f.write(blob)
                    written += len(group)
            except Exception:  # noqa: BLE001 — tracing must never take
                return written  # a daemon down; spans lost, job is not
            return written


# ------------------------------------------------------------ ambient
# Thread-local "current tracer + span" so deep code (spill loops, the
# shuffle copier, the TPU runner) records child spans without threading
# a tracer through every signature. Disabled == one attribute lookup.

_ambient = threading.local()


@contextmanager
def activate(tracer: "Tracer | None", span: "Span | None"):
    """Install ``tracer``/``span`` as the calling thread's ambient trace
    context for the duration (task run threads, child main)."""
    prev = getattr(_ambient, "ctx", None)
    _ambient.ctx = (tracer, span) if tracer is not None else None
    try:
        yield
    finally:
        _ambient.ctx = prev


def capture() -> "tuple | None":
    """Snapshot the ambient context for hand-off to worker threads
    (the shuffle copier's fetch pool)."""
    return getattr(_ambient, "ctx", None)


@contextmanager
def activate_captured(ctx: "tuple | None"):
    prev = getattr(_ambient, "ctx", None)
    _ambient.ctx = ctx
    try:
        yield
    finally:
        _ambient.ctx = prev


def current() -> "tuple[Tracer, Span] | None":
    return getattr(_ambient, "ctx", None)


@contextmanager
def span(name: str, backend: str = "", role: "str | None" = None,
         **attrs: Any) -> "Iterator[Span | None]":
    """Ambient child span: records under the thread's active span, or
    no-ops (yielding None) when tracing is inactive."""
    ctx = getattr(_ambient, "ctx", None)
    if ctx is None:
        yield None
        return
    tracer, parent = ctx
    s = tracer.start_span(name, parent.trace_id, parent=parent,
                          role=role or parent.role, backend=backend,
                          **attrs)
    prev = ctx
    _ambient.ctx = (tracer, s)
    try:
        yield s
    except BaseException as e:
        s.set(error=f"{type(e).__name__}: {e}")
        raise
    finally:
        _ambient.ctx = prev
        tracer.finish(s)


def instant(name: str, **attrs: Any) -> None:
    """Ambient marker span (no-op when tracing is inactive)."""
    ctx = getattr(_ambient, "ctx", None)
    if ctx is None:
        return
    tracer, parent = ctx
    tracer.instant(name, parent.trace_id, parent=parent, role=parent.role,
                   **attrs)


# ------------------------------------------------------------ merge/export


def read_trace_files(trace_dir: str, trace_id: str) -> "list[dict]":
    """All flushed spans of one trace, merged across every daemon's
    per-process file, sorted by start time."""
    import glob
    safe = _safe_trace_id(trace_id)
    spans: list[dict] = []
    for path in sorted(glob.glob(os.path.join(trace_dir,
                                              f"trace-{safe}.*.jsonl"))):
        try:
            with open(path) as f:
                for line in f:
                    if line.strip():
                        spans.append(json.loads(line))
        except (OSError, ValueError):
            continue
    spans.sort(key=lambda s: s.get("start", 0.0))
    return spans


def to_chrome_trace(spans: "list[dict]") -> dict:
    """Chrome trace-event JSON (the object form with ``traceEvents``):
    one complete ("ph":"X") event per span, processes = roles (with
    process_name metadata so chrome://tracing / Perfetto label the
    swimlanes), threads = per-role span rows keyed by host+attempt so
    concurrent tasks render on separate rows."""
    events: list[dict] = []
    role_pids: dict[str, int] = {}
    tids: dict[tuple, int] = {}
    next_tid: dict[int, int] = {}     # per-pid lane counter, O(1)/lane
    for s in spans:
        role = s.get("role", "?")
        pid = role_pids.get(role)
        if pid is None:
            pid = role_pids[role] = len(role_pids) + 1
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0, "args": {"name": role}})
        attrs = s.get("attributes") or {}
        lane = (pid, attrs.get("host", ""), attrs.get("attempt_id", ""))
        tid = tids.get(lane)
        if tid is None:
            tid = tids[lane] = next_tid[pid] = next_tid.get(pid, 0) + 1
            label = ":".join(str(p) for p in lane[1:] if p) or role
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": label}})
        start = float(s.get("start", 0.0))
        end = float(s.get("end", 0.0)) or start
        events.append({
            "name": s.get("name", "?"),
            "cat": role + ("," + s["backend"] if s.get("backend") else ""),
            "ph": "X",
            "ts": int(start * 1e6),
            "dur": max(1, int((end - start) * 1e6)),
            "pid": pid,
            "tid": tid,
            "args": {**attrs, "span_id": s.get("span_id", ""),
                     "parent_span_id": s.get("parent_span_id", "")},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def critical_path(spans: "list[dict]") -> dict:
    """The chain of spans that determined the trace's makespan: from the
    root (no in-trace parent; longest), repeatedly descend into the
    child whose SUBTREE ends latest — the dependency chain the parent
    was last waiting on (a zero-duration scheduling marker whose task
    subtree runs long is on the path; a late bookkeeping leaf is not
    unless it really ended last). Returns the path with per-span
    durations and contribution percentages (self time = duration not
    covered by the chosen child's subtree), plus the trace makespan."""
    if not spans:
        return {"path": [], "total_s": 0.0, "self_total_s": 0.0,
                "makespan_s": 0.0}
    by_id = {s["span_id"]: s for s in spans if s.get("span_id")}
    children: dict[str, list[dict]] = {}
    for s in spans:
        p = s.get("parent_span_id", "")
        if p and p in by_id:
            children.setdefault(p, []).append(s)
    roots = [s for s in spans
             if not s.get("parent_span_id")
             or s["parent_span_id"] not in by_id]

    def dur(s: dict) -> float:
        return max(0.0, float(s.get("end", 0.0) or 0.0)
                   - float(s.get("start", 0.0)))

    sub_end: dict[str, float] = {}

    def subtree_end(s: dict) -> float:
        sid = s.get("span_id", "")
        cached = sub_end.get(sid)
        if cached is not None:
            return cached
        sub_end[sid] = float(s.get("end", 0.0) or 0.0)  # cycle guard
        out = max([float(s.get("end", 0.0) or 0.0)]
                  + [subtree_end(k) for k in children.get(sid, [])])
        sub_end[sid] = out
        return out

    EPS = 1e-9
    MAX_PATH = 512
    root = max(roots, key=dur)
    seen: set[str] = set()
    path_nodes: "list[tuple[dict, float]]" = []   # (span, self seconds)

    def decompose(node: dict) -> None:
        """Append ``node`` and its time-ordered critical chain: walking
        BACKWARD from node's end, repeatedly take the child whose
        subtree ends latest while still fitting before the current
        point — the dependency the remaining interval was waiting on.
        Gaps (waiting on something outside this subtree, e.g. a reduce
        stalled on map outputs) stay charged to the node's self time,
        which is exactly where an analyst should look next."""
        sid = node.get("span_id", "")
        if sid in seen or len(path_nodes) >= MAX_PATH:
            return
        seen.add(sid)
        kids = [k for k in children.get(sid, [])
                if k.get("span_id") not in seen]
        chain: list[dict] = []
        # walk back from where the node's SUBTREE finished — an instant
        # marker (schedule) has zero duration but its task subtree is
        # the whole point of following it
        cur = subtree_end(node)
        floor = float(node.get("start", 0.0))
        avail = list(kids)
        while cur > floor + EPS and avail:
            cands = [k for k in avail if subtree_end(k) <= cur + EPS]
            if not cands:
                break
            c = max(cands, key=subtree_end)
            avail.remove(c)
            chain.append(c)
            cur = float(c.get("start", 0.0))
        covered = sum(min(subtree_end(c),
                          float(node.get("end", 0.0) or 0.0))
                      - float(c.get("start", 0.0)) for c in chain)
        path_nodes.append((node, max(0.0, dur(node) - max(0.0, covered))))
        for c in reversed(chain):              # chronological order
            decompose(c)

    decompose(root)
    path = [{"span_id": n.get("span_id", ""),
             "name": n.get("name", "?"),
             "role": n.get("role", "?"),
             "backend": n.get("backend", ""),
             "duration_s": dur(n),
             "self_s": self_s,
             "attributes": n.get("attributes") or {}}
            for n, self_s in path_nodes]
    makespan = max((float(s.get("end", 0.0) or 0.0) for s in spans),
                   default=0.0) - min((float(s.get("start", 0.0))
                                       for s in spans), default=0.0)
    total_self = sum(p["self_s"] for p in path) or 1.0
    for p in path:
        p["contribution_pct"] = round(100.0 * p["self_s"] / total_self, 2)
    return {"path": path,
            "total_s": sum(p["duration_s"] for p in path),
            "self_total_s": sum(p["self_s"] for p in path),
            "makespan_s": max(0.0, makespan)}


#: swimlane colors per role (the jobtracker's /trace page); backend
#: overrides make hybrid placement visible at a glance
_LANE_COLORS = {"jobtracker": "#6246ea", "tasktracker": "#3b8ea5",
                "task": "#2cb67d", "shuffle": "#e8a33d"}
_BACKEND_COLORS = {"tpu": "#7f5af0", "cpu": "#2cb67d"}


def swimlane_svg(spans: "list[dict]", width: int = 960) -> str:
    """Self-contained SVG timeline: one row per span, grouped by role,
    x-scaled to the trace window. Escapes all span-derived text (span
    names can contain attempt ids but attributes are job-controlled)."""
    from html import escape
    if not spans:
        return "<p class='dim'>no spans</p>"
    t0 = min(float(s.get("start", 0.0)) for s in spans)
    t1 = max(float(s.get("end", 0.0) or s.get("start", 0.0))
             for s in spans)
    window = max(t1 - t0, 1e-6)
    order = {"jobtracker": 0, "tasktracker": 1, "task": 2}
    rows = sorted(spans, key=lambda s: (order.get(s.get("role", ""), 9),
                                        float(s.get("start", 0.0))))
    dropped = max(0, len(rows) - 400)
    rows = rows[:400]       # a 50k-map job must not render 50k rects —
    #                         the full trace is one click away in JSON
    left, row_h, pad = 260, 16, 2
    height = len(rows) * (row_h + pad) + 24
    parts = [f"<svg width='{width}' height='{height}' "
             f"font-family='monospace' font-size='11'>"]
    for i, s in enumerate(rows):
        start = float(s.get("start", 0.0))
        end = float(s.get("end", 0.0) or start)
        x = left + (start - t0) / window * (width - left - 10)
        w = max(1.0, (end - start) / window * (width - left - 10))
        y = i * (row_h + pad) + 14
        color = _BACKEND_COLORS.get(s.get("backend", ""),
                                    _LANE_COLORS.get(s.get("role", ""),
                                                     "#94a1b2"))
        label = (f"{s.get('role', '?')}/{s.get('name', '?')} "
                 f"{(s.get('attributes') or {}).get('attempt_id', '')}")
        parts.append(
            f"<text x='2' y='{y + 11}' fill='currentColor'>"
            f"{escape(label[:40])}</text>"
            f"<rect x='{x:.1f}' y='{y}' width='{w:.1f}' "
            f"height='{row_h}' fill='{color}' rx='2'>"
            f"<title>{escape(s.get('name', '?'))} "
            f"{end - start:.4f}s</title></rect>")
    parts.append(
        f"<text x='{left}' y='{height - 2}' fill='currentColor'>"
        f"window {window:.3f}s · "
        + (f"{dropped} spans not shown · " if dropped else "")
        + "<tspan fill='#7f5af0'>&#9632; tpu</tspan> "
        "<tspan fill='#2cb67d'>&#9632; cpu/task</tspan> "
        "<tspan fill='#3b8ea5'>&#9632; tracker</tspan> "
        "<tspan fill='#6246ea'>&#9632; master</tspan></text>")
    parts.append("</svg>")
    return "".join(parts)


def validate_chrome_trace(doc: Any) -> "list[str]":
    """Schema check for the trace-event format (used by tests and the
    CLI): returns a list of problems, empty when loadable."""
    problems: list[str] = []
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return ["not an object with a traceEvents array"]
    events = doc["traceEvents"]
    if not isinstance(events, list):
        return ["traceEvents is not an array"]
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i} is not an object")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "M", "B", "E", "i", "C"):
            problems.append(f"event {i}: unknown phase {ph!r}")
            continue
        if "pid" not in ev or "name" not in ev:
            problems.append(f"event {i}: missing pid/name")
        if ph == "X":
            if not isinstance(ev.get("ts"), int) \
                    or not isinstance(ev.get("dur"), int):
                problems.append(f"event {i}: X event needs int ts/dur")
    return problems
