"""The configuration-key registry — single source of truth.

Every dotted config key the tree reads is declared here once: key,
type, default, one doc line. ``tpumr lint`` (tpumr/tools/tpulint)
enforces the contract repo-wide: reads of unregistered ``tpumr.*`` /
``mapred.*`` / ``io.*`` keys fail the build, literal call-site
defaults that contradict this file fail the build, and registered keys
nothing reads fail the build. ``tpumr lint --conf-doc`` generates
``docs/CONFIG.md`` from this table, so the operator reference can
never drift from the code.

Keys read through f-strings (``f"tpumr.fi.{point}.probability"``)
register as PATTERN entries whose ``*`` spans any characters
(including dots).

The typed readers at the bottom (:func:`get_int` et al.) read a key
with its registered type and default — the adoption surface for
modules that used to carry their own fallback literals. A call site
may still pass a literal default, but the linter insists it equals the
registered one.
"""

from __future__ import annotations

from dataclasses import dataclass
from fnmatch import fnmatchcase
from typing import Any


@dataclass(frozen=True)
class ConfKey:
    key: str
    type: str            # str | int | float | bool | strings | size | class
    default: Any
    doc: str
    pattern: bool = False


def _K(key: str, type: str, default: Any, doc: str,
       pattern: bool = False) -> ConfKey:
    return ConfKey(key, type, default, doc, pattern)


_ENTRIES: "tuple[ConfKey, ...]" = (
    _K('datajoin.maxNumOfValuesPerGroup', 'int', 100,
        "contrib/datajoin: max values buffered per join group."),
    _K('dfs.block.size', 'int', 8388608,
        "tdfs block size, bytes."),
    _K('dfs.hosts', 'str', None,
        "Datanode include file (empty = all may join)."),
    _K('dfs.hosts.exclude', 'str', None,
        "Datanode exclude/decommission file."),
    _K('dfs.permissions', 'bool', True,
        "Enforce tdfs permission checks."),
    _K('dfs.permissions.supergroup', 'str', 'supergroup',
        "Group granted tdfs superuser rights."),
    _K('dfs.replication', 'int', 3,
        "Default tdfs replication factor."),
    _K('dfs.safemode.threshold.pct', 'float', 0.999,
        "Fraction of blocks that must report before the NameNode leaves "
        "safemode."),
    _K('failmon.disk.paths', 'strings', None,
        "Disks failmon monitors."),
    _K('failmon.log.files', 'strings', None,
        "Log files failmon scrapes."),
    _K('failmon.store.dir', 'str', None,
        "failmon local event store directory."),
    _K('failmon.upload.url', 'str', None,
        "failmon upload destination."),
    _K('fs.checkpoint.period', 'int', 3600,
        "SecondaryNameNode checkpoint interval, seconds."),
    _K('fs.default.name', 'str', 'file:///',
        "Default filesystem URI for relative paths (tdfs://HOST:PORT/ "
        "or file:///)."),
    _K('fs.gs.auth.token', 'str', None,
        "Static bearer token for the gs:// object-store client."),
    _K('fs.gs.emulation.dir', 'str', None,
        "Local directory backing the gs:// emulation filesystem."),
    _K('fs.gs.endpoint', 'str', None,
        "Override endpoint URL for gs:// (emulators, proxies)."),
    _K('fs.trash.checkpoint.interval.s', 'str', None,
        "NameNode-side trash checkpoint sweep period, seconds."),
    _K('fs.trash.interval', 'int', 0,
        "Minutes between trash checkpoints; 0 disables the trash "
        "(deletes are immediate)."),
    _K('fs.trash.root', 'str', None,
        "Override for the per-user trash root directory."),
    _K('hadoop.security.groups.cache.secs', 'int', 300,
        "User->groups resolution cache TTL, seconds."),
    _K('io.sort.factor', 'int', 10,
        "Maximum segments merged per merge pass (map spills and reduce "
        "merges)."),
    _K('io.sort.mb', 'int', 100,
        "Map-side sort buffer size, MiB (spills past it)."),
    _K('io.sort.spill.percent', 'float', 0.8,
        "Sort-buffer fill fraction that triggers a background spill."),
    _K('key.value.separator.in.input.line', 'str', '\t',
        "KeyValueTextInputFormat separator between key and value."),
    _K('map.output.key.field.separator', 'str', '\t',
        "KeyFieldBasedPartitioner/Comparator field separator."),
    _K('mapred.acls.enabled', 'bool', False,
        "Enforce queue/job ACLs."),
    _K('mapred.cache.files', 'str', '',
        "Distributed-cache file URIs shipped to tasks."),
    _K('mapred.cluster.administrators', 'str', '',
        "Cluster admin ACL (user/group list)."),
    _K('mapred.combiner.class', 'class', None,
        "Combiner class (dotted name)."),
    _K('mapred.compress.map.output', 'bool', False,
        "Compress intermediate map output."),
    _K('mapred.data.field.separator', 'str', '\t',
        "FieldSelection mapper/reducer field separator."),
    _K('mapred.fairscheduler.pool', 'str', None,
        "Fair-scheduler pool this job lands in."),
    _K('mapred.healthChecker.interval.ms', 'int', 10000,
        "Node-health script period, ms."),
    _K('mapred.healthChecker.script.path', 'str', None,
        "Node-health script path (unset = health checks off)."),
    _K('mapred.hosts', 'str', None,
        "Tracker include file (empty = all may join); live-reloadable "
        "via mradmin -refreshNodes."),
    _K('mapred.hosts.exclude', 'str', None,
        "Tracker exclude file; excluded trackers are evicted on "
        "refresh."),
    _K('mapred.input.dir', 'strings', None,
        "Comma-separated input paths."),
    _K('mapred.input.format.class', 'class', None,
        "InputFormat class (dotted name)."),
    _K('mapred.job.map.memory.mb', 'int', 0,
        "Per-map memory demand for the memory-aware scheduler gate, "
        "MiB."),
    _K('mapred.job.name', 'str', '',
        "Human-readable job name (history, status pages)."),
    _K('mapred.job.priority', 'str', 'NORMAL',
        "Initial job priority (VERY_HIGH..VERY_LOW)."),
    _K('mapred.job.queue.name', 'str', None,
        "Queue the job is submitted to."),
    _K('mapred.job.reduce.memory.mb', 'int', 0,
        "Per-reduce memory demand for the memory-aware scheduler gate, "
        "MiB."),
    _K('mapred.job.shuffle.input.buffer.percent', 'float', 0.7,
        "Fraction of the RAM budget map outputs may fill."),
    _K('mapred.job.shuffle.merge.percent', 'float', 0.66,
        "Fill fraction that triggers an in-memory merge."),
    _K('mapred.job.tracker', 'str', None,
        "JobTracker address HOST:PORT, or 'local' for the in-process "
        "runner."),
    _K('mapred.job.tracker.http.port', 'int', -1,
        "JobTracker status HTTP port (-1 = auto)."),
    _K('mapred.jobtracker.map.optionalscheduling', 'bool', False,
        "Starve the CPU map pool when remaining maps fit the "
        "accelerator capacity (Shirahata convergence rule)."),
    _K('mapred.jobtracker.restart.recover', 'bool', False,
        "Replay completed work from the history log on master restart."),
    _K('mapred.jobtracker.restart.recovery.grace.ms', 'int', 3000,
        "Hold a recovered job's scheduling until its trackers re-join, "
        "ms."),
    _K('mapred.jobtracker.taskScheduler', 'class', None,
        "TaskScheduler class the master loads."),
    _K('mapred.line.input.format.linespermap', 'int', 1,
        "NLineInputFormat: lines per split."),
    _K('mapred.local.dir', 'str', None,
        "Tracker-local scratch directory."),
    _K('mapred.local.map.tasks.maximum', 'int', 1,
        "Local-runner parallel map width."),
    _K('mapred.map.max.attempts', 'int', 4,
        "Attempts per map task before the job fails."),
    _K('mapred.map.multithreadedrunner.threads', 'int', 10,
        "MultithreadedMapRunner thread count."),
    _K('mapred.map.output.compression.codec', 'str', 'zlib',
        "Map-output (shuffle/spill) codec; native tlz is the hot-path "
        "choice."),
    _K('mapred.map.runner.class', 'class', None,
        "MapRunner class driving the map loop on CPU."),
    _K('mapred.map.runner.tpu.class', 'class', None,
        "MapRunner class driving the map loop on the TPU pass."),
    _K('mapred.map.tasks', 'int', 1,
        "Requested number of map tasks (input splits may override)."),
    _K('mapred.mapper.class', 'class', None,
        "Mapper class (dotted name)."),
    _K('mapred.mapper.regex', 'str', '',
        "Regex for the built-in grep mapper."),
    _K('mapred.mapper.regex.group', 'int', 0,
        "Capture group the grep mapper emits."),
    _K('mapred.max.fetch.failures.per.map', 'int', 3,
        "Distinct reducers reporting fetch failure before a map "
        "re-executes."),
    _K('mapred.max.split.size', 'int', 2**63 - 1,
        "Upper bound on input split size, bytes (2**63-1 = uncapped; "
        "CombineFileInputFormat treats it as its pack-target sentinel)."),
    _K('mapred.min.split.size', 'int', 1,
        "Lower bound on input split size, bytes."),
    _K('mapred.output.compress', 'bool', False,
        "Compress job output files."),
    _K('mapred.output.compression.codec', 'str', 'none',
        "Job output compression codec (none/zlib/tlz)."),
    _K('mapred.output.dir', 'str', None,
        "Job output directory."),
    _K('mapred.output.format.class', 'class', None,
        "OutputFormat class (dotted name)."),
    _K('mapred.output.key.comparator.class', 'class', None,
        "Sort comparator for map output keys."),
    _K('mapred.output.value.groupfn.class', 'class', None,
        "Grouping comparator for the reduce phase."),
    _K('mapred.partitioner.class', 'class', None,
        "Partitioner class (dotted name)."),
    _K('mapred.queue.acls.file', 'str', None,
        "Queue ACLs file, live-reloadable via mradmin -refreshQueues."),
    _K('mapred.queue.names', 'str', None,
        "Configured queue names (unset = single 'default')."),
    _K('mapred.reduce.max.attempts', 'int', 4,
        "Attempts per reduce task before the job fails."),
    _K('mapred.reduce.slowstart.completed.maps', 'float', 0.05,
        "Map completion fraction before reduces schedule."),
    _K('mapred.reduce.speculative.execution', 'bool', None,
        "Reduce-side speculation override (unset = master switch)."),
    _K('mapred.reduce.tasks', 'int', 1,
        "Number of reduce tasks (0 = map-only job)."),
    _K('mapred.reducer.class', 'class', None,
        "Reducer class (dotted name)."),
    _K('mapred.speculative.execution', 'bool', True,
        "Speculative execution master switch."),
    _K('mapred.speculative.lag.factor', 'float', 1.5,
        "How far behind the mean a task must run to speculate."),
    _K('mapred.speculative.min.runtime.s', 'float', 10.0,
        "Minimum runtime before a task may be speculated, seconds."),
    _K('mapred.task.limit.maxrss.mb', 'int', 0,
        "Process-isolation RSS kill limit, MiB (0 = off)."),
    _K('mapred.task.profile', 'bool', False,
        "Enable the per-task cProfile profiler."),
    _K('mapred.task.timeout', 'int', 600000,
        "Ms without task progress before the tracker reaps the attempt."),
    _K('mapred.task.tracker.http.port', 'int', -1,
        "Tracker status/shuffle HTTP port (-1 = auto)."),
    _K('mapred.task.tracker.task-controller', 'str', None,
        "Task controller: thread/process isolation backend."),
    _K('mapred.tasktracker.map.cpu.tasks.maximum', 'int', 3,
        "CPU map slots per tracker (the Shirahata hybrid split)."),
    _K('mapred.tasktracker.map.tpu.tasks.maximum', 'int', 1,
        "TPU map slots per tracker (one chip = one slot)."),
    _K('mapred.tasktracker.memory.mb', 'int', -1,
        "Tracker-advertised memory for the scheduler gate, MiB (-1 = "
        "unadvertised)."),
    _K('mapred.tasktracker.reduce.tasks.maximum', 'int', 2,
        "Reduce slots per tracker."),
    _K('mapred.text.key.comparator.options', 'str', '',
        "KeyFieldBasedComparator sort options (-k, -n, -r)."),
    _K('mapred.text.key.value.fields.spec', 'str', '0:1-',
        "FieldSelection key:value field spec."),
    _K('mapred.textoutputformat.separator', 'str', '\t',
        "TextOutputFormat key/value separator."),
    _K('mapred.userlog.retain.hours', 'float', 24.0,
        "Hours task userlogs are retained."),
    _K('mapreduce.mapper.multithreadedmapper.class', 'class', None,
        "New-API multithreaded mapper delegate class."),
    _K('mapreduce.mapper.multithreadedmapper.threads', 'int', 10,
        "New-API multithreaded mapper thread count."),
    _K('mapreduce.mapper.regex', 'str', None,
        "New-API alias of mapred.mapper.regex."),
    _K('mapreduce.mapper.regex.group', 'str', None,
        "New-API alias of mapred.mapper.regex.group."),
    _K('mapreduce.output.lazyoutputformat.outputformat', 'class', None,
        "LazyOutputFormat delegate class."),
    _K('stream.combine.command', 'str', None,
        "Streaming combiner command line."),
    _K('stream.map.command', 'str', None,
        "Streaming map command line."),
    _K('stream.map.input', 'str', 'text',
        "Streaming map input serialization (text/typedbytes)."),
    _K('stream.map.input.ignoreKey', 'bool', False,
        "Feed only values to the map command."),
    _K('stream.map.output', 'str', 'text',
        "Streaming map output serialization."),
    _K('stream.map.output.field.separator', 'str', '\t',
        "Streaming map output field separator."),
    _K('stream.reduce.command', 'str', None,
        "Streaming reduce command line."),
    _K('stream.reduce.input', 'str', 'text',
        "Streaming reduce input serialization."),
    _K('stream.reduce.output', 'str', 'text',
        "Streaming reduce output serialization."),
    _K('tdfs.client.dn.conns', 'int', 2,
        "Pooled connections per datanode in the client's shared "
        "RPC pool."),
    _K('tdfs.client.dn.idle.s', 'float', 60.0,
        "Seconds an idle pooled datanode connection survives before "
        "the pool closes it."),
    _K('tdfs.client.nn.backoff.ms', 'float', 200.0,
        "Base backoff between NameNode RPC transport retries, ms "
        "(jittered exponential)."),
    _K('tdfs.client.nn.retries', 'int', 1,
        "NameNode RPC transport retries per call — what carries a "
        "client across a NameNode restart (resends replay from the "
        "server response cache, never re-execute)."),
    _K('tdfs.client.read.acquire.retries', 'int', 3,
        "Block-location refetches a reader attempts when every cached "
        "replica fails or the location list is empty (a restarted "
        "NameNode re-learning its datanodes) before giving up — "
        "HDFS's dfs.client.max.block.acquire.failures."),
    _K('tdfs.client.read.acquire.backoff.ms', 'float', 300.0,
        "Pause before each block-location refetch, giving datanodes "
        "a heartbeat window to re-register with a restarted "
        "NameNode."),
    _K('tdfs.client.read.chunk.bytes', 'str', None,
        "Client read chunk size, bytes."),
    _K('tdfs.client.read.pipeline.depth', 'int', 4,
        "Chunk reads kept in flight per replica connection "
        "(pipelined read window)."),
    _K('tdfs.client.write.chunk.bytes', 'str', None,
        "Client write chunk size, bytes."),
    _K('tdfs.client.write.pipeline.depth', 'int', 4,
        "Chunk writes kept in flight while shipping a block "
        "(pipelined write window)."),
    _K('tdfs.datanode.capacity', 'int', 1099511627776,
        "Advertised datanode capacity, bytes."),
    _K('tdfs.datanode.fdcache.capacity', 'int', 64,
        "Open block-file descriptors the datanode read path caches "
        "(pinned LRU)."),
    _K('tdfs.datanode.expiry.s', 'int', 10,
        "Seconds without a heartbeat before a datanode is declared "
        "dead."),
    _K('tdfs.datanode.heartbeat.s', 'float', 1.0,
        "Datanode -> NameNode heartbeat period, seconds."),
    _K('tdfs.datanode.scan.period.s', 'str', None,
        "Block-scanner (checksum verification) full-cycle period, "
        "seconds."),
    _K('tdfs.edits.auto.checkpoint.mb', 'int', 256,
        "Edit-log volume that triggers a self-checkpoint, MiB."),
    _K('tdfs.edits.segment.mb', 'int', 16,
        "Edit-log segment roll size, MiB."),
    _K('tdfs.hotblocks.cool.s', 'float', 15.0,
        "Seconds a block must stay below the hot threshold before "
        "its replica boost expires (cool-down)."),
    _K('tdfs.hotblocks.replicate.cap', 'int', 4,
        "Max replicas the hot-block policy will boost a block to "
        "(bounded by live datanodes)."),
    _K('tdfs.hotblocks.replicate.min.reads', 'int', 200,
        "Minimum sketched reads a block needs before the hot-block "
        "policy considers boosting it."),
    _K('tdfs.hotblocks.replicate.share', 'float', 0.3,
        "Share of all sketched reads at which a block is declared "
        "hot and gets extra replicas."),
    _K('tdfs.http.port', 'int', -1,
        "NameNode status HTTP port (-1 = auto)."),
    _K('tdfs.lease.hard.limit.s', 'int', 60,
        "Write-lease hard expiry, seconds (lease recovery fences dead "
        "writers)."),
    _K('tdfs.namenode.lock.stripe.depth', 'int', 2,
        "Path components that pick a namespace lock stripe; shorter "
        "paths use the structural lock."),
    _K('tdfs.namenode.lock.stripes', 'int', 8,
        "Namespace lock stripes (per-subtree locks); cross-stripe "
        "ops take the structural lock."),
    _K('tdfs.read.wire.codec', 'str', 'tlz',
        "Wire compression codec for chunked block reads "
        "('none' disables)."),
    _K('tdfs.replication.interval.s', 'float', 1.0,
        "NameNode re-replication monitor period, seconds."),
    _K('tdfs.superuser', 'str', '',
        "Extra tdfs superuser principal."),
    _K('tdfs.upload.stale.s', 'int', 600,
        "Seconds before a half-uploaded block replica is "
        "garbage-collected."),
    _K('tdfsproxy.permissions.file', 'str', None,
        "tdfsproxy per-path permissions file."),
    _K('tdfsproxy.ssl.cert', 'str', None,
        "tdfsproxy TLS certificate file."),
    _K('tdfsproxy.ssl.key', 'str', None,
        "tdfsproxy TLS key file."),
    _K('topology.script.file.name', 'str', None,
        "Executable resolving host -> rack for topology-aware "
        "placement."),
    _K('total.order.partitioner.path', 'str', None,
        "Partition-boundary keys file for the total-order partitioner."),
    _K('tpumr.acls.require.verified', 'bool', False,
        "Reject unsigned callers once ACLs are on."),
    _K('tpumr.block.access.lifetime.s', 'float', 3600.0,
        "NameNode-minted block access stamp lifetime, seconds."),
    _K('tpumr.brownout.cadence.factor', 'float', 3.0,
        "Brownout heartbeat-cadence stretch multiplier while the "
        "'cadence' shed step is active (capped at the instructed max)."),
    _K('tpumr.brownout.dwell.ms', 'int', 3000,
        "Min ms between brownout level transitions — one step per "
        "dwell, so shedding ramps instead of slamming."),
    _K('tpumr.brownout.enabled', 'bool', False,
        "Master brownout mode: under sustained SLO pressure the master "
        "sheds deferrable load in ranked steps (trace sampling -> "
        "heartbeat cadence -> speculation + history I/O)."),
    _K('tpumr.brownout.engage.ticks', 'int', 3,
        "Consecutive breached flight-recorder windows before the "
        "brownout steps up one level."),
    _K('tpumr.brownout.release.ticks', 'int', 3,
        "Consecutive clear flight-recorder windows before the brownout "
        "steps back down one level."),
    _K('tpumr.cache.dir', 'str', None,
        "Distributed-cache local materialization root."),
    _K('tpumr.cache.executables', 'str', '',
        "Distributed-cache entries to mark executable."),
    _K('tpumr.capacity.queues', 'str', 'default',
        "Capacity scheduler: configured queues."),
    _K('tpumr.capacity.supports-priority', 'bool', False,
        "Capacity scheduler: honor job priority."),
    _K('tpumr.chain.reduce.mappers', 'str', None,
        "ChainReducer: post-reduce mapper chain."),
    _K('tpumr.chain.reducer', 'str', None,
        "ChainReducer: the wrapped reducer."),
    _K('tpumr.cluster.id.suffix', 'str', '',
        "Suffix appended to the master's start-time cluster id (shard "
        "workers set s<k> so same-millisecond shard boots can't mint "
        "colliding job ids)."),
    _K('tpumr.cpu.batch.map', 'bool', True,
        "Vectorized CPU batch path for kernel maps."),
    _K('tpumr.datajoin.mappers', 'str', None,
        "datajoin: per-source mapper class list."),
    _K('tpumr.db.connect', 'str', None,
        "DB input/output: connection string."),
    _K('tpumr.db.input.count.query', 'str', None,
        "DB input: row-count query."),
    _K('tpumr.db.input.fields', 'str', None,
        "DB input: selected fields."),
    _K('tpumr.db.input.order.by', 'str', None,
        "DB input: split ordering column."),
    _K('tpumr.db.input.query', 'str', None,
        "DB input: explicit query."),
    _K('tpumr.db.input.table', 'str', None,
        "DB input: table name."),
    _K('tpumr.db.module', 'str', 'sqlite3',
        "DB input/output: DB-API module name."),
    _K('tpumr.db.output.fields', 'str', None,
        "DB output: inserted fields."),
    _K('tpumr.db.output.table', 'str', None,
        "DB output: table name."),
    _K('tpumr.dense.split.rows', 'int', 0,
        "Dense-tensor input format: rows per split (0 = one split)."),
    _K('tpumr.devcache.heartbeat.tags', 'int', 32,
        "Max device-cache tags a tracker piggybacks per heartbeat for "
        "affinity placement (0 = don't advertise)."),
    _K('tpumr.devcache.required.tags', 'str', '',
        "Comma list of device-cache tags this job's tasks want warm "
        "(empty = derived from the job's known side inputs)."),
    _K('tpumr.dfs.bench.op.slo.ms', 'int', 100,
        "bench_dfs: NameNode op-latency p99 SLO (merged nn_op_seconds) "
        "a rung must hold to count as sustainable, ms."),
    _K('tpumr.dfs.bench.read.slo.ms', 'int', 250,
        "bench_dfs: client-side end-to-end read round-trip p99 SLO a "
        "rung must hold to count as sustainable, ms."),
    _K('tpumr.dfs.bench.recovery.client.slo.s', 'float', 15.0,
        "bench_dfs --recovery-only: nn-kill -> first client op success "
        "SLO, seconds (clients riding tdfs.client.nn.retries across "
        "the outage)."),
    _K('tpumr.dfs.bench.recovery.replication.slo.s', 'float', 30.0,
        "bench_dfs --recovery-only: dn-kill -> replication-restored "
        "SLO, seconds (includes the datanode expiry window)."),
    _K('tpumr.dfs.bench.recovery.safemode.slo.s', 'float', 10.0,
        "bench_dfs --recovery-only: nn-kill -> safemode-exit SLO, "
        "seconds (editlog replay + enough block reports)."),
    _K('tpumr.distcp.preserve', 'bool', False,
        "distcp: preserve file attributes."),
    _K('tpumr.distcp.update', 'bool', False,
        "distcp: skip up-to-date targets."),
    _K('tpumr.distcp.work', 'str', None,
        "distcp work/staging directory."),
    _K('tpumr.dn.hotblocks.halflife.s', 'float', 60.0,
        "Half-life of the datanode read sketch's per-heartbeat "
        "exponential decay, seconds (0 disables; keeps the hot-block "
        "view current so replica boosts can cool down)."),
    _K('tpumr.dn.hotblocks.k', 'int', 64,
        "SpaceSaving counters per datanode read sketch (bounds hot-"
        "block memory; any block read more than total/k times is "
        "guaranteed tracked)."),
    _K('tpumr.dn.hotblocks.top', 'int', 16,
        "Top sketch entries a datanode piggybacks per heartbeat into "
        "the namenode's cluster hot-block table."),
    _K('tpumr.dn.http.port', 'int', -1,
        "DataNode status/metrics HTTP port (0 = ephemeral, -1 = off)."),
    _K('tpumr.fairscheduler.preemption', 'bool', False,
        "Fair scheduler: enable preemption."),
    _K('tpumr.fairscheduler.preemption.interval.ms', 'int', 1000,
        "Fair scheduler: preemption check period, ms."),
    _K('tpumr.fairscheduler.preemption.timeout.ms', 'int', 15000,
        "Fair scheduler: starvation window before preempting, ms."),
    _K('tpumr.fi.dn.partition.ms', 'int', 3000,
        "Ms the dn.partition fault seam silences a DataNode's "
        "heartbeats (reads keep serving; NN expiry + rejoin follow)."),
    _K('tpumr.fi.jt.heartbeat.slow.ms', 'int', 400,
        "Ms the jt.heartbeat.slow fault seam stalls master heartbeat "
        "handling (drives the flight-recorder incident e2e)."),
    _K('tpumr.fi.nn.op.slow.ms', 'int', 400,
        "Ms the nn.op.slow fault seam stalls NameNode op handling "
        "(drives the NN flight-recorder incident e2e)."),
    _K('tpumr.fi.rpc.delay.ms', 'int', 100,
        "Ms the rpc.delay fault seam stalls a call."),
    _K('tpumr.fi.seed', 'str', None,
        "Fault-injection RNG seed (per-(seed,point) streams; chaos runs "
        "replay deterministically)."),
    _K('tpumr.fi.task.slow.ms', 'int', 2000,
        "Ms the task.slow fault seam crawls before the real work runs."),
    _K('tpumr.grep.group', 'int', 0,
        "Grep example: capture group."),
    _K('tpumr.grep.pattern', 'str', None,
        "Grep example: regex."),
    _K('tpumr.heartbeat.batch', 'int', 0,
        "Max co-located tracker beats coalesced into one heartbeat_batch "
        "RPC by the scale fleet (0/1 = one pipelined RPC per beat). "
        "Replay semantics hold per member — a resent batch never "
        "double-folds a tracker."),
    _K('tpumr.heartbeat.beats.per.second', 'int', 0,
        "Target master-wide beat rate for adaptive cadence (0 = fixed "
        "cadence)."),
    _K('tpumr.heartbeat.delta', 'bool', True,
        "Delta-encode heartbeats (only changed statuses ride the wire)."),
    _K('tpumr.heartbeat.interval.max.ms', 'int', 0,
        "Adaptive-cadence staleness cap, ms (0 = uncapped)."),
    _K('tpumr.heartbeat.interval.ms', 'int', 1000,
        "Tracker heartbeat cadence floor, ms."),
    _K('tpumr.heartbeat.lostmaster.backoff.max.ms', 'int', 15000,
        "Cap on the tracker's lost-master heartbeat backoff, ms."),
    _K('tpumr.history.async', 'bool', True,
        "Write job-history events from a bounded background queue "
        "instead of on the heartbeat's deferred phase (readers flush "
        "first, so recovery and retired-status reads stay exact)."),
    _K('tpumr.history.dir', 'str', None,
        "Job history directory (events, per-job metrics rollups, "
        "traces)."),
    _K('tpumr.history.queue.max', 'int', 10000,
        "Bound on queued history events before new ones are dropped and "
        "counted in history_writes_dropped (must stay 0 in bench runs)."),
    _K('tpumr.jax.cache.dir', 'str', None,
        "JAX persistent compilation cache directory."),
    _K('tpumr.jax.cache.min.compile.secs', 'float', 0.5,
        "Min compile time before an executable is persisted, seconds."),
    _K('tpumr.job.id', 'str', '',
        "This job's id (framework-set, task-side)."),
    _K('tpumr.jobclient.rpc.retries', 'int', 3,
        "Transport retries for the job submit/poll client channel "
        "(wider than the daemon default: wait_for_completion must "
        "survive master restarts)."),
    _K('tpumr.jobtracker.rpc.reactor', 'bool', True,
        "Serve master RPC on the shared reactor (vs "
        "thread-per-connection)."),
    _K('tpumr.kmeans.centroids', 'str', None,
        "KMeans op: serialized centroids."),
    _K('tpumr.kmeans.centroids.out', 'str', None,
        "KMeans iterative driver: where the centroid-update reducer "
        "writes the NEXT round's centroid .npy (round-templated in "
        "pipelines, so rounds never rewrite one path)."),
    _K('tpumr.kmeans.use.pallas', 'bool', False,
        "KMeans op: use the Pallas kernel."),
    _K('tpumr.local.run.on.tpu', 'bool', False,
        "Local runner executes the TPU pass too."),
    _K('tpumr.map.kernel', 'str', None,
        "Registered TPU map kernel name (ops registry)."),
    _K('tpumr.mapreduce.mapper.class', 'class', None,
        "New-API mapper class bridge key."),
    _K('tpumr.mapreduce.partitioner.class', 'class', None,
        "New-API partitioner class bridge key."),
    _K('tpumr.master.shards', 'int', 0,
        "Shard worker processes the master partitions its tracker fleet "
        "across (0 = classic single-process master). Trackers hash to a "
        "shard by crc32(name); each shard owns its trackers' full "
        "heartbeat fast path and the jobs routed to it."),
    _K('tpumr.master.shards.poll.ms', 'int', 250,
        "Coordinator period for pulling per-shard metrics snapshots and "
        "folding them into the merged /metrics and flight-recorder "
        "view, ms."),
    _K('tpumr.matmul.b', 'str', None,
        "Matmul op: serialized B operand."),
    _K('tpumr.matmul.bf16', 'bool', True,
        "Matmul op: compute in bf16."),
    _K('tpumr.metrics.file', 'str', None,
        "File sink path for metrics records."),
    _K('tpumr.metrics.period.ms', 'int', 10000,
        "Metrics publish period, ms."),
    _K('tpumr.metrics.piggyback.interval.ms', 'int', 0,
        "Min ms between tracker metrics piggybacks on heartbeats (0 = "
        "every beat)."),
    _K('tpumr.metrics.udp', 'str', None,
        "UDP sink HOST:PORT for metrics records."),
    _K('tpumr.nn.audit.enabled', 'bool', False,
        "NameNode audit log (logger 'tpumr.nn.audit'): one line per "
        "mutating/metadata op with caller, cmd, src, dst, perm."),
    _K('tpumr.nn.audit.rate.limit', 'int', 200,
        "Max audit lines per second; the overflow is counted "
        "(audit_suppressed) instead of written, so an op storm can't "
        "turn the audit log into the bottleneck."),
    _K('tpumr.nn.incident.slo.ms', 'int', 0,
        "NameNode flight-recorder SLO: a windowed nn_op_seconds p99 "
        "over this arms an incident snapshot (0 = recorder off)."),
    _K('tpumr.ops.device.cache.mb', 'int', 1024,
        "Ops-level device cache budget, MiB."),
    _K('tpumr.pipeline.conf.hooks.allowed', 'strings', 'tpumr.',
        "Dotted-prefix allowlist for pipeline conf_hook callables — "
        "hooks run IN THE MASTER PROCESS, so only operator-vetted "
        "module prefixes may execute (default: the tpumr tree)."),
    _K('tpumr.pipeline.handoff.dir', 'str', None,
        "Tracker-local root for streamed-handoff reduce spills (set by "
        "the tracker; outlives job cleanup until the pipeline ends)."),
    _K('tpumr.pipeline.handoff.poll.ms', 'int', 200,
        "Downstream handoff reader poll period, ms (event feed + DFS "
        "fallback probes)."),
    _K('tpumr.pipeline.handoff.source', 'str', None,
        "INTERNAL in-process seam: the tracker's handoff stream-source "
        "factory object, stashed in the stage conf for thread-isolated "
        "maps (never serialized; absent = DFS fallback only)."),
    _K('tpumr.pipeline.handoff.timeout.ms', 'int', 600000,
        "Bound on a downstream map waiting for one upstream partition "
        "(stream or committed fallback) before the attempt fails."),
    _K('tpumr.pipeline.handoff.upstream', 'str', None,
        "Stage conf: JSON list of upstream job ids a streamed stage "
        "fetches from (stamped by the pipeline engine)."),
    _K('tpumr.pipeline.id', 'str', None,
        "Stage conf: the owning pipeline id (stamped by the engine; "
        "anchors scheduler ordering and trace parenting)."),
    _K('tpumr.pipeline.node', 'str', None,
        "Stage conf: the owning graph node id (stamped by the engine)."),
    _K('tpumr.pipeline.round', 'int', 0,
        "Stage conf: loop-node round number (stamped by the engine)."),
    _K('tpumr.pipeline.stream.handoff', 'bool', False,
        "Stage conf: tee this stage's reduce output into map-output "
        "(IFile) framing served over the shuffle wire for downstream "
        "stages (set by the engine on stream out-edges)."),
    _K('tpumr.pipes.executable', 'str', None,
        "Pipes binary URI."),
    _K('tpumr.pipes.piped.input', 'bool', True,
        "Feed pipes input over stdin (vs the application pulling)."),
    _K('tpumr.pipes.tpu.executable', 'str', None,
        "Pipes binary for the TPU pass."),
    _K('tpumr.policy.file', 'str', None,
        "Service-level authorization policy file."),
    _K('tpumr.prof.enabled', 'bool', False,
        "Continuous profiler master switch: stack sampling, cpu_share "
        "subsystem attribution, gil_delay_seconds, /stacks + /flame."),
    _K('tpumr.prof.hz', 'int', 19,
        "Profiler sampling rate (Hz); co-prime with common timer grids "
        "so periodic work cannot hide between samples."),
    _K('tpumr.prof.incident.cooldown.ms', 'int', 60000,
        "Min ms between flight-recorder incident bundles — a sustained "
        "breach writes one bundle per window, not a stream."),
    _K('tpumr.prof.incident.dir', 'str', None,
        "Flight-recorder bundle directory (default: an incidents/ dir "
        "next to the job history)."),
    _K('tpumr.prof.incident.slo.ms', 'int', 250,
        "Windowed heartbeat p99 (handling or lag) above this arms the "
        "flight recorder — the bench_scale dual-p99 SLO, live."),
    _K('tpumr.prof.trie.max.nodes', 'int', 20000,
        "Profiler stack-trie node budget; overflow folds into (other) "
        "so profiler memory stays bounded."),
    _K('tpumr.prof.window.s', 'float', 120.0,
        "Profiler sample-retention window for /stacks?seconds= queries "
        "and the cpu_share gauges."),
    _K('tpumr.profile.ewma', 'float', 0.0,
        "EWMA weight for the job's TPU acceleration profile (0 = plain "
        "mean)."),
    _K('tpumr.randomwriter.max.key', 'int', 100,
        "RandomWriter: max key size, bytes."),
    _K('tpumr.randomwriter.max.value', 'int', 1000,
        "RandomWriter: max value size, bytes."),
    _K('tpumr.randomwriter.min.key', 'int', 10,
        "RandomWriter: min key size, bytes."),
    _K('tpumr.randomwriter.min.value', 'int', 0,
        "RandomWriter: min value size, bytes."),
    _K('tpumr.rpc.client.backoff.ms', 'int', 200,
        "Base jittered backoff between RPC transport retries, ms."),
    _K('tpumr.rpc.client.retries', 'int', 1,
        "Transport retries per daemon RPC call (trackers lean on the "
        "lost-master backoff instead)."),
    _K('tpumr.rpc.secret', 'str', None,
        "Cluster RPC secret (inline; prefer the .file form)."),
    _K('tpumr.rpc.secret.file', 'str', None,
        "File holding the cluster RPC secret."),
    _K('tpumr.rpc.token.file', 'str', None,
        "Delegation-token credential file."),
    _K('tpumr.rpc.user.key', 'str', None,
        "Per-user signing key (hex) for personal-credential RPC."),
    _K('tpumr.rpc.user.key.file', 'str', None,
        "File holding the per-user signing key."),
    _K('tpumr.scheduler.affinity', 'bool', True,
        "Prefer TPU slots on trackers whose device cache already holds "
        "the job's side-input tags."),
    _K('tpumr.scheduler.affinity.defer.passes', 'int', 3,
        "Heartbeats a job's TPU assignment may be deferred waiting for "
        "a tag-warm tracker before placing cold (0 = never defer)."),
    _K('tpumr.scheduler.mode', 'str', 'shirahata',
        "'shirahata' slot split or 'minimize' (the f(x,y) makespan "
        "search)."),
    _K('tpumr.scenario.class', 'str', None,
        "Traffic class tag on a submitted job (scenario lab): keys the "
        "per-class latency percentiles and SLO verdicts."),
    _K('tpumr.scenario.dir', 'str', None,
        "Directory of operator-authored *.toml scenario specs for "
        "'tpumr scenario -list' / 'tpumr simulate -scenario'."),
    _K('tpumr.scenario.name', 'str', None,
        "Active scenario name on the master; stamped into flight-"
        "recorder incident bundles as workload context."),
    _K('tpumr.security.authorization', 'bool', False,
        "Service-level authorization (policy file) master switch."),
    _K('tpumr.shuffle.batch.bytes', 'int', 8 << 20,
        "Total payload budget of one batched multi-segment fetch "
        "response, bytes."),
    _K('tpumr.shuffle.batch.segments', 'int', 8,
        "Max map outputs coalesced into one get_map_outputs_batch RPC "
        "(1 = per-segment fetches)."),
    _K('tpumr.shuffle.chunk.bytes', 'int', 1 << 20,
        "Serve-side chunking of map output reads, bytes."),
    _K('tpumr.shuffle.conns.per.target', 'int', 2,
        "Pooled shuffle connections per source tracker; fetchers "
        "multiplex over them instead of one socket each."),
    _K('tpumr.shuffle.copy.backoff.max.ms', 'float', 10000.0,
        "Penalty-box backoff cap, ms."),
    _K('tpumr.shuffle.copy.backoff.ms', 'float', 200.0,
        "Base per-source penalty-box backoff, ms (jittered, "
        "exponential)."),
    _K('tpumr.shuffle.copy.retries', 'int', 3,
        "Transport retries per fetch round."),
    _K('tpumr.shuffle.device', 'bool', False,
        "Stage shuffle through device memory (TPU-side partition/sort)."),
    _K('tpumr.shuffle.device.capacity', 'int', 0,
        "Device shuffle cache capacity, bytes (0 = auto)."),
    _K('tpumr.shuffle.device.key.bytes', 'int', 0,
        "Fixed key width for device shuffle records, bytes."),
    _K('tpumr.shuffle.device.ranges', 'int', 1,
        "Partition ranges per device sort pass."),
    _K('tpumr.shuffle.device.value.bytes', 'int', 0,
        "Fixed value width for device shuffle records, bytes."),
    _K('tpumr.shuffle.fd.cache.size', 'int', 64,
        "Open spill file descriptors the serving tracker caches (LRU) "
        "so chunk reads pread instead of open+seek per chunk."),
    _K('tpumr.shuffle.fetch.max.failures', 'int', 50,
        "Total fetch failures before the reduce attempt aborts."),
    _K('tpumr.shuffle.fetch.pipeline.depth', 'int', 4,
        "Chunk requests kept in flight per connection while streaming "
        "one segment (1 = one chunk per round trip)."),
    _K('tpumr.shuffle.fetch.retries.per.source', 'int', 3,
        "Fetch failures per map location before a report goes up the "
        "umbilical."),
    _K('tpumr.shuffle.merge.enabled', 'bool', True,
        "Background merge engine on the reduce side."),
    _K('tpumr.shuffle.merge.reserve.wait.ms', 'float', 2000.0,
        "Ms a fetch waits for merge headroom before spilling straight "
        "to disk."),
    _K('tpumr.shuffle.parallel.copies', 'int', 5,
        "Concurrent fetch streams per reduce."),
    _K('tpumr.shuffle.poll.ms', 'int', 200,
        "Completion-event poll period while the reduce waits for maps, "
        "ms."),
    _K('tpumr.shuffle.ram.mb', 'float', 128.0,
        "In-memory shuffle budget per reduce, MiB."),
    _K('tpumr.shuffle.size.priority', 'bool', True,
        "Order pending shuffle fetches largest-advertised-output first "
        "(completion events carry map output sizes)."),
    _K('tpumr.shuffle.timeout.ms', 'int', 600000,
        "Shuffle phase overall deadline, ms."),
    _K('tpumr.shuffle.wire.codec', 'str', 'tlz',
        "Wire compression for chunks of UNCOMPRESSED spills ('none' "
        "disables); decompressed copier-side inside the RAM budget."),
    _K('tpumr.sleep.hang.attempts', 'int', 1,
        "Sleep example: attempts that hang before succeeding."),
    _K('tpumr.sleep.hang.map', 'int', -1,
        "Sleep example: map index that hangs (-1 = none)."),
    _K('tpumr.sleep.map.ms', 'int', 100,
        "Sleep example: per-map sleep, ms."),
    _K('tpumr.sleep.reduce.ms', 'int', 100,
        "Sleep example: per-reduce sleep, ms."),
    _K('tpumr.speculative.cap', 'int', 2,
        "Max speculative attempts in flight per job (targeted mode)."),
    _K('tpumr.speculative.critical.fraction', 'float', 0.75,
        "A straggler is speculated only when its remaining time is "
        "within this fraction of the job's longest remaining path."),
    _K('tpumr.speculative.rate.ewma', 'float', 0.4,
        "Smoothing factor for per-task progress-rate EWMAs (the "
        "remaining-work estimator's input)."),
    _K('tpumr.speculative.targeted', 'bool', True,
        "LATE-style targeted speculation (estimated-finish stragglers "
        "on the critical path, capped) instead of blanket twins."),
    _K('tpumr.task.attempt.id', 'str', '',
        "This attempt's id (framework-set, task-side)."),
    _K('tpumr.task.input.path', 'str', None,
        "Current input path (framework-set, task-side)."),
    _K('tpumr.task.isolation', 'str', 'thread',
        "Task isolation mode: 'thread' (default) or 'process' (child "
        "per CPU attempt)."),
    _K('tpumr.task.local.dir', 'str', None,
        "Per-task scratch dir (framework-set)."),
    _K('tpumr.task.partition', 'int', -1,
        "This task's partition number (framework-set; -1 = unset)."),
    _K('tpumr.task.profile.sort', 'str', 'cumulative',
        "Profiler report sort column."),
    _K('tpumr.task.status.report.interval.ms', 'int', 1000,
        "Min ms between unchanged RUNNING status re-ships on delta "
        "beats (0 = every beat)."),
    _K('tpumr.task.strip.cluster.secret', 'bool', False,
        "Strip the cluster RPC secret from process-isolated task "
        "children."),
    _K('tpumr.task.user', 'str', None,
        "User a process-isolated task child runs as."),
    _K('tpumr.task.userlogs.dir', 'str', None,
        "Override for task userlog directory."),
    _K('tpumr.task.work.dir', 'str', None,
        "Task working directory (framework-set)."),
    _K('tpumr.tasktracker.reactor', 'bool', True,
        "Serve the tracker RPC surface (umbilical + shuffle) through "
        "the selector reactor instead of thread-per-connection."),
    _K('tpumr.topology.map', 'str', None,
        "Inline host->rack map (JSON/dict), the script-less topology "
        "source."),
    _K('tpumr.tpu.attempt.retries', 'int', 1,
        "Device/compile-classed failures before a TIP is pinned "
        "CPU-only."),
    _K('tpumr.tpu.device.probe.interval.ms', 'int', 10000,
        "Quarantined-device probe cadence, ms."),
    _K('tpumr.tpu.device.probe.max.interval.ms', 'int', 300000,
        "Probe cadence backoff cap, ms."),
    _K('tpumr.tpu.device.quarantine.failures', 'int', 3,
        "Consecutive device-classed failures before a device is "
        "quarantined (0 = off)."),
    _K('tpumr.tpu.job.quarantine.tips', 'int', 3,
        "Distinct device-failing TIPs before the job's TPU pass is "
        "disabled."),
    _K('tpumr.tpu.output.cache', 'bool', True,
        "Keep map output device-resident for the device shuffle."),
    _K('tpumr.tpu.pipeline.window', 'int', 32,
        "Cold-dispatch pipeline window, records."),
    _K('tpumr.tpu.pipeline.window.mb', 'int', 2048,
        "Pipeline window byte budget, MiB."),
    _K('tpumr.tpu.split.cache', 'bool', True,
        "Cache staged input splits in device memory (HBM)."),
    _K('tpumr.tpu.split.cache.mb', 'int', 2048,
        "Split-cache HBM budget, MiB."),
    _K('tpumr.trace.dir', 'str', None,
        "Span-file directory (default: next to job history)."),
    _K('tpumr.trace.enabled', 'bool', False,
        "Distributed tracing master switch (set at submit)."),
    _K('tpumr.trace.id', 'str', '',
        "Trace id (framework-set; the job id)."),
    _K('tpumr.trace.sample', 'str', None,
        "Per-job head-sampling rate in [0,1]."),
    _K('tpumr.tracker.expiry.ms', 'int', 10000,
        "Ms without a heartbeat before a tracker's lease expires "
        "(monotonic deadline)."),
    _K('tpumr.tracker.max.faults', 'int', 4,
        "Fault charges before a tracker is blacklisted."),
    _K('tpumr.tracker.registry.shards', 'int', 16,
        "Stripe count of the tracker-registry lock (rank 30)."),
    _K('tpumr.wordcount.vectorized', 'bool', True,
        "Wordcount op: vectorized kernel path."),
    _K('user.name', 'str', '',
        "Caller identity override (tests/tools); normally derived from "
        "the process owner."),
    _K('hadoop.proxyuser.*', 'str', None,
        "Proxy-user (doas) host/group allowlists.", pattern=True),
    _K('mapred.queue.*', 'str', None,
        "Per-queue ACL keys: "
        "mapred.queue.<name>.acl-{submit-job,administer-jobs}.", pattern=True),
    _K('mapreduce.job.acl-*', 'str', None,
        "Per-job ACLs: acl-view-job / acl-modify-job.", pattern=True),
    _K('tpumr.capacity.*', 'str', None,
        "Capacity scheduler per-queue knobs: "
        "tpumr.capacity.<queue>.{guaranteed-capacity,...}.", pattern=True),
    _K('tpumr.fairscheduler.pool.*', 'str', None,
        "Fair scheduler per-pool knobs.", pattern=True),
    _K('tpumr.fi.*', 'str', None,
        "Per-seam fault-injection knobs: tpumr.fi.<point>.probability / "
        ".max.failures (docs/OPERATIONS.md lists the seams).", pattern=True),
    _K('tpumr.scenario.slo.*', 'str', None,
        "Per-traffic-class latency SLOs (scenario lab): "
        "tpumr.scenario.slo.<class>.{assign,complete}.ms.", pattern=True),
    _K('tpumr.user.groups.*', 'str', None,
        "Static user->groups mapping entries.", pattern=True),
)


REGISTRY: "dict[str, ConfKey]" = {e.key: e for e in _ENTRIES}

_PATTERNS: "tuple[ConfKey, ...]" = tuple(
    e for e in _ENTRIES if e.pattern)


def lookup(key: str) -> "ConfKey | None":
    """Exact entry, else the first pattern entry matching ``key``."""
    e = REGISTRY.get(key)
    if e is not None:
        return e
    for p in _PATTERNS:
        if fnmatchcase(key, p.key):
            return p
    return None


def pattern_matches(pattern_key: str, key: str) -> bool:
    return fnmatchcase(key, pattern_key)


def pattern_covers(pattern_key: str, read_prefix: str) -> bool:
    """Could a dynamic read with this literal prefix produce keys the
    pattern matches? True when the prefixes agree up to the pattern's
    first wildcard."""
    head = pattern_key.split("*", 1)[0]
    return head.startswith(read_prefix) or read_prefix.startswith(head)


def suggest(key: str, n: int = 3, cutoff: int = 4) -> "list[str]":
    """Closest registered keys by edit distance — typo'd dotted keys
    silently read defaults forever, so the finding names the likely
    intent."""
    scored = sorted(
        ((_distance(key, k, cutoff + 1), k) for k in REGISTRY),
        key=lambda t: (t[0], t[1]))
    return [k for d, k in scored[:n] if d <= cutoff]


def _distance(a: str, b: str, cap: int) -> int:
    """Levenshtein with an early-out cap (band optimization is not
    worth it at registry scale)."""
    if abs(len(a) - len(b)) >= cap:
        return cap
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, start=1):
        cur = [i]
        best = i
        for j, cb in enumerate(b, start=1):
            cur.append(min(prev[j] + 1, cur[-1] + 1,
                           prev[j - 1] + (ca != cb)))
            best = min(best, cur[-1])
        if best >= cap:
            return cap
        prev = cur
    return prev[-1]


# ------------------------------------------------- typed, registry-backed


def _entry(key: str) -> ConfKey:
    e = lookup(key)
    if e is None:
        raise KeyError(f"config key {key!r} is not registered in "
                       f"tpumr/core/confkeys.py")
    return e


def default_of(key: str) -> Any:
    return _entry(key).default


_TRUE = {"true", "yes", "on", "1"}
_FALSE = {"false", "no", "off", "0"}


def get(conf: Any, key: str) -> Any:
    """Registry-defaulted read; works on Configuration objects AND the
    plain dict confs jobs ship over the wire."""
    v = conf.get(key)
    return _entry(key).default if v in (None, "") else v


def get_int(conf: Any, key: str) -> "int | None":
    e = _entry(key)
    if hasattr(conf, "get_int"):
        return conf.get_int(key, e.default)
    v = conf.get(key)
    if v in (None, ""):
        return e.default
    return int(v)


def get_float(conf: Any, key: str) -> "float | None":
    e = _entry(key)
    if hasattr(conf, "get_float"):
        return conf.get_float(key, e.default)
    v = conf.get(key)
    if v in (None, ""):
        return e.default
    return float(v)


def get_boolean(conf: Any, key: str) -> "bool | None":
    e = _entry(key)
    if hasattr(conf, "get_boolean"):
        return conf.get_boolean(key, e.default)
    v = conf.get(key)
    if v in (None, ""):
        return e.default
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in _TRUE:
        return True
    if s in _FALSE:
        return False
    return e.default
