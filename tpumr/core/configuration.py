"""Layered key/value configuration.

TPU-era equivalent of ``org.apache.hadoop.conf.Configuration``
(reference: src/core/org/apache/hadoop/conf/Configuration.java, 1455 LoC):
resources are layered in addition order, later layers override earlier ones,
explicit ``set()`` overrides all resources, values support ``${var}``
expansion against other keys and environment variables, and typed getters
parse on read. Resources here are dicts / JSON / TOML files instead of the
reference's XML, but the semantics (layering, expansion, final-ish defaults)
are the same.
"""

from __future__ import annotations

import copy
import json
import os
import re
from typing import Any, Callable, Iterator, Mapping

_VAR_PAT = re.compile(r"\$\{([^}$\s]+)\}")
_MAX_SUBST = 20  # Configuration.java caps substitution depth the same way

_TRUE = {"true", "yes", "on", "1"}
_FALSE = {"false", "no", "off", "0"}

# size suffixes for get_memory-style keys (e.g. "100m" in io.sort.mb-like keys)
_SIZE_SUFFIX = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


class Configuration:
    """Layered configuration with variable expansion and typed getters."""

    #: process-wide default resources added to every new Configuration
    #: (≈ Configuration.addDefaultResource for core-default.xml etc.)
    _default_resources: list[Mapping[str, Any]] = []

    def __init__(self, other: "Configuration | None" = None,
                 load_defaults: bool = True) -> None:
        self._resources: list[dict[str, Any]] = []
        self._overlay: dict[str, Any] = {}   # explicit set() wins over resources
        self._deprecations: dict[str, str] = {}
        if other is not None:
            self._resources = [dict(r) for r in other._resources]
            self._overlay = dict(other._overlay)
            self._deprecations = dict(other._deprecations)
        elif load_defaults:
            for res in Configuration._default_resources:
                self._resources.append(dict(res))

    # ------------------------------------------------------------------ setup

    @classmethod
    def add_default_resource(cls,
                             resource: "Mapping[str, Any] | str") -> None:
        """Add a process-wide default layer: a dict, or a path to a
        .json/.toml file (same forms as add_resource)."""
        if isinstance(resource, str):
            cls._default_resources.append(cls._load_file(resource))
        else:
            cls._default_resources.append(dict(resource))

    def add_resource(self, resource: "Mapping[str, Any] | str") -> None:
        """Add a resource layer: a dict, or a path to a .json/.toml file."""
        if isinstance(resource, str):
            self._resources.append(self._load_file(resource))
        else:
            self._resources.append(dict(resource))

    @staticmethod
    def _load_file(path: str) -> dict[str, Any]:
        with open(path, "rb") as f:
            data = f.read()
        if path.endswith(".toml"):
            import tomllib
            raw = tomllib.loads(data.decode("utf-8"))
            # flatten nested tables into dotted keys
            flat: dict[str, Any] = {}

            def walk(prefix: str, node: Any) -> None:
                if isinstance(node, dict):
                    for k, v in node.items():
                        walk(f"{prefix}.{k}" if prefix else k, v)
                else:
                    flat[prefix] = node

            walk("", raw)
            return flat
        return json.loads(data.decode("utf-8"))

    def add_deprecation(self, old_key: str, new_key: str) -> None:
        self._deprecations[old_key] = new_key

    # ------------------------------------------------------------------ access

    def _translate(self, key: str) -> str:
        seen = set()
        while key in self._deprecations and key not in seen:
            seen.add(key)
            key = self._deprecations[key]
        return key

    def _raw(self, key: str) -> Any:
        key = self._translate(key)
        if key in self._overlay:
            return self._overlay[key]
        for res in reversed(self._resources):
            if key in res:
                return res[key]
        raise KeyError(key)

    def get(self, key: str, default: Any = None) -> Any:
        try:
            val = self._raw(key)
        except KeyError:
            return default
        if isinstance(val, str):
            return self._substitute(val)
        return val

    def _substitute(self, val: str) -> str:
        for _ in range(_MAX_SUBST):
            m = _VAR_PAT.search(val)
            if m is None:
                return val
            name = m.group(1)
            try:
                rep = self._raw(name)
            except KeyError:
                rep = os.environ.get(name)
            if rep is None:
                return val  # unresolvable — leave literally, like the reference
            val = val[: m.start()] + str(rep) + val[m.end():]
        return val

    def set(self, key: str, value: Any) -> None:
        self._overlay[self._translate(key)] = value

    def set_if_unset(self, key: str, value: Any) -> None:
        if self.get(key) is None:
            self.set(key, value)

    def unset(self, key: str) -> None:
        key = self._translate(key)
        self._overlay.pop(key, None)
        for res in self._resources:
            res.pop(key, None)

    def __contains__(self, key: str) -> bool:
        return self.get(key) is not None

    # typed getters (≈ Configuration.getInt/getLong/getFloat/getBoolean/...)

    def get_int(self, key: str, default: int = 0) -> int:
        v = self.get(key)
        if v is None:
            return default
        if isinstance(v, bool):
            return int(v)
        if isinstance(v, str):
            s = v.strip()
            # decimal by default (leading zeros OK); 0x/0o/0b prefixes honored
            return int(s, 0) if s[1:2] in ("x", "o", "b") and s[:1] == "0" else int(s, 10)
        return int(v)

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self.get(key)
        return default if v is None else float(v)

    def get_boolean(self, key: str, default: bool = False) -> bool:
        v = self.get(key)
        if v is None:
            return default
        if isinstance(v, bool):
            return v
        s = str(v).strip().lower()
        if s in _TRUE:
            return True
        if s in _FALSE:
            return False
        return default

    def get_strings(self, key: str, default: list[str] | None = None) -> list[str]:
        v = self.get(key)
        if v is None:
            return list(default or [])
        if isinstance(v, (list, tuple)):
            return [str(x) for x in v]
        return [s.strip() for s in str(v).split(",") if s.strip()]

    def get_size(self, key: str, default: int = 0) -> int:
        """Parse '64m'/'1g' style sizes into bytes."""
        v = self.get(key)
        if v is None:
            return default
        if isinstance(v, (int, float)):
            return int(v)
        s = str(v).strip().lower()
        if s and s[-1] in _SIZE_SUFFIX:
            return int(float(s[:-1]) * _SIZE_SUFFIX[s[-1]])
        return int(float(s))

    def get_class(self, key: str, default: type | None = None) -> type | None:
        """Resolve a dotted class name (≈ Configuration.getClass via
        ReflectionUtils)."""
        from tpumr.utils.reflection import resolve_class
        v = self.get(key)
        if v is None:
            return default
        if isinstance(v, type):
            return v
        return resolve_class(str(v))

    def set_class(self, key: str, cls: type) -> None:
        from tpumr.utils.reflection import class_name, resolve_class
        name = class_name(cls)
        try:
            importable = resolve_class(name) is cls
        except (ImportError, TypeError):
            importable = False
        # dotted name when round-trippable (wire-safe for job submission);
        # the class object itself otherwise (in-process local jobs only)
        self.set(key, name if importable else cls)

    # ------------------------------------------------------------------ misc

    def keys(self) -> list[str]:
        out: dict[str, None] = {}
        for res in self._resources:
            out.update(dict.fromkeys(res))
        out.update(dict.fromkeys(self._overlay))
        return list(out)

    def __iter__(self) -> Iterator[tuple[str, Any]]:
        for k in self.keys():
            yield k, self.get(k)

    def to_dict(self) -> dict[str, Any]:
        return {k: self.get(k) for k in self.keys()}

    def copy(self) -> "Configuration":
        return copy.deepcopy(self)

    def write_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)

    def __len__(self) -> int:
        return len(self.keys())

    def __repr__(self) -> str:  # pragma: no cover
        return f"Configuration({len(self)} keys, {len(self._resources)} resources)"


#: substrings marking a conf key as credential-bearing; values of such keys
#: must never leave the process over status/HTTP surfaces (≈ the reference
#: ConfServlet's credential sanitization)
SENSITIVE_KEY_MARKERS = ("secret", "password", "passwd", "credential",
                         "token", "private.key")


def is_sensitive_key(key: str) -> bool:
    low = key.lower()
    return any(m in low for m in SENSITIVE_KEY_MARKERS)


REDACTED = "*** redacted ***"


def redact_mapping(d: Mapping[str, Any]) -> dict[str, Any]:
    """Mask credential-bearing values in a plain conf mapping (used by
    every status surface that serves conf: JT /json/conf, history)."""
    return {k: (REDACTED if is_sensitive_key(k) else v) for k, v in d.items()}


def redacted_dict(conf: "Configuration") -> dict[str, Any]:
    """Conf as a dict safe for status endpoints: secret-bearing values
    (tpumr.rpc.secret*, *password*, …) are masked, key presence kept."""
    return redact_mapping({k: conf.get(k) for k in sorted(conf.keys())})
