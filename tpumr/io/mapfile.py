"""MapFile — a sorted, indexed SequenceFile directory.

≈ ``org.apache.hadoop.io.MapFile`` (reference: src/core/org/apache/hadoop/
io/MapFile.java): a directory holding ``data`` (records in key order) and
``index`` (every Nth key → seek position). ``Reader.get(key)`` bisects the
in-memory index and scans at most one index interval of the data file.
Keys must be appended in non-decreasing order (the reference's checkKey).
"""

from __future__ import annotations

import bisect
from typing import Any, Iterator

from tpumr.fs.filesystem import FileSystem, Path
from tpumr.io import sequencefile

DATA_NAME = "data"
INDEX_NAME = "index"


class Writer:
    def __init__(self, fs: FileSystem, dirname: "str | Path",
                 index_interval: int = 128, codec: str = "none") -> None:
        self.dir = Path(str(dirname))
        fs.mkdirs(self.dir)
        self._data_stream = fs.create(self.dir.child(DATA_NAME))
        self._index_stream = fs.create(self.dir.child(INDEX_NAME))
        # small blocks so an index interval spans whole blocks cheaply
        self._data = sequencefile.Writer(self._data_stream, codec=codec,
                                         block_records=min(64,
                                                           index_interval))
        self._index = sequencefile.Writer(self._index_stream)
        self.index_interval = max(1, index_interval)
        self._count = 0
        self._last_key: Any = None

    def append(self, key: Any, value: Any) -> None:
        if self._last_key is not None and key < self._last_key:
            raise ValueError(f"keys out of order: {key!r} after "
                             f"{self._last_key!r}")
        if self._count % self.index_interval == 0:
            pos = self._data.sync_pos()
            self._index.append(key, pos)
        self._data.append(key, value)
        self._last_key = key
        self._count += 1

    def close(self) -> None:
        self._data.close()
        self._index.close()
        self._data_stream.close()
        self._index_stream.close()

    def __enter__(self) -> "Writer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class Reader:
    def __init__(self, fs: FileSystem, dirname: "str | Path") -> None:
        self.dir = Path(str(dirname))
        with fs.open(self.dir.child(INDEX_NAME)) as f:
            self._index: list[tuple[Any, int]] = list(
                sequencefile.Reader(f))
        self._keys = [k for k, _ in self._index]
        self._data_stream = fs.open(self.dir.child(DATA_NAME))
        self._data = sequencefile.Reader(self._data_stream)

    def get(self, key: Any, default: Any = None) -> Any:
        """Value of the FIRST record with exactly ``key`` (≈
        MapFile.Reader.get). bisect_left so duplicate keys spanning an
        index boundary scan from the interval holding the first one."""
        if not self._keys or key < self._keys[0]:
            return default
        i = max(0, bisect.bisect_left(self._keys, key) - 1)
        self._data.sync(self._index[i][1])
        for k, v in self._data:
            if k == key:
                return v
            if k > key:
                return default
        return default

    def get_closest(self, key: Any, default: Any = None) -> Any:
        """(key, value) of the first record with key >= ``key``
        (≈ MapFile.Reader.getClosest)."""
        if not self._index:
            return default
        i = max(0, bisect.bisect_left(self._keys, key) - 1)
        self._data.sync(self._index[i][1])
        for k, v in self._data:
            if k >= key:
                return (k, v)
        return default

    def __iter__(self) -> Iterator[tuple[Any, Any]]:
        self._data.sync(0)
        return iter(self._data)

    def close(self) -> None:
        self._data_stream.close()

    def __enter__(self) -> "Reader":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
