"""Pinned LRU cache of open read-only file descriptors.

Grown out of the shuffle server (PR 13's ``SpillFdCache``) and now
shared with the datanode's block read path: both serve a file that is
read start-to-finish in ~1 MiB slices by many concurrent callers, and
both used to pay O(chunks · open) syscalls and dentry walks for it.
Here every chunk is one ``os.pread`` on a cached fd: stateless (no
shared file position, so a reactor's pool threads read concurrently),
exactly the payload slice is allocated (``pread`` returns the bytes
the response frame ships — no staging buffer to copy out of), and the
fd survives across chunks and callers until LRU pressure or an
explicit invalidation closes it.

Pinning: an fd being pread by one thread may be evicted by another;
eviction under pin marks the entry dead and the LAST unpin closes it —
never a read on a closed (possibly reused) fd number.
"""

from __future__ import annotations

import os
import threading


class FdCache:
    """LRU of open read-only fds keyed by path, safe for concurrent
    readers. ``invalidate(prefix)`` is the correctness lever for
    writers: any path that was replaced/unlinked MUST be invalidated or
    a cached fd keeps serving the old inode."""

    class _Ent:
        __slots__ = ("fd", "pins", "dead")

        def __init__(self, fd: int) -> None:
            self.fd = fd
            self.pins = 0
            self.dead = False

    def __init__(self, capacity: int = 64) -> None:
        self._cap = max(1, int(capacity))
        # insertion order = recency order (re-inserted on every hit)
        self._entries: "dict[str, FdCache._Ent]" = {}
        self._lock = threading.Lock()
        # bumped by every invalidate(): _pin's miss path opens OUTSIDE
        # the lock, so an invalidation landing between its open and its
        # insert would otherwise cache an fd of the just-replaced inode
        # — serving the OLD bytes forever (the staleness bug chaos
        # surfaces when re-replication deletes/recreates a block id)
        self._epoch = 0
        self.opens = 0
        self.evictions = 0

    def pread(self, path: str, n: int, offset: int) -> bytes:
        ent = self._pin(path)
        try:
            return os.pread(ent.fd, n, offset)
        finally:
            self._unpin(ent)

    def _pin(self, path: str) -> "FdCache._Ent":
        for _attempt in range(8):
            with self._lock:
                ent = self._entries.pop(path, None)
                if ent is not None:
                    self._entries[path] = ent   # most-recently used again
                    ent.pins += 1
                    return ent
                epoch0 = self._epoch
            fd = os.open(path, os.O_RDONLY)
            close_now = None
            try:
                with self._lock:
                    ent = self._entries.get(path)
                    if ent is not None:
                        # lost an open race — use the cached fd, drop ours
                        ent.pins += 1
                        close_now = fd
                        return ent
                    if self._epoch != epoch0:
                        # an invalidate() ran while we were opening: our
                        # fd may reference the replaced/unlinked inode —
                        # caching it would serve stale bytes forever
                        close_now = fd
                        continue
                    return self._insert_locked(path, fd)
            finally:
                if close_now is not None:
                    try:
                        os.close(close_now)
                    except OSError:
                        pass
        # invalidation storm: open while HOLDING the lock, which excludes
        # invalidate() entirely — pathological path, never the fast one
        with self._lock:
            ent = self._entries.pop(path, None)
            if ent is not None:
                self._entries[path] = ent
                ent.pins += 1
                return ent
            return self._insert_locked(path, os.open(path, os.O_RDONLY))

    def _insert_locked(self, path: str, fd: int) -> "FdCache._Ent":
        self.opens += 1
        ent = FdCache._Ent(fd)
        ent.pins = 1
        self._entries[path] = ent
        while len(self._entries) > self._cap:
            victim_path = next(iter(self._entries))
            victim = self._entries.pop(victim_path)
            self.evictions += 1
            if victim.pins:
                victim.dead = True   # last unpin closes it
            else:
                try:
                    os.close(victim.fd)
                except OSError:
                    pass
        return ent

    def _unpin(self, ent: "FdCache._Ent") -> None:
        with self._lock:
            ent.pins -= 1
            if ent.dead and ent.pins == 0:
                try:
                    os.close(ent.fd)
                except OSError:
                    pass

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def invalidate(self, prefix: str = "") -> None:
        """Drop (and close) every cached fd whose path starts with
        ``prefix`` — callers unlink or atomically replace files, and a
        cached fd would otherwise keep serving the OLD inode (shuffle:
        pinning a purged job's disk blocks; datanode: returning stale
        block bytes after a re-write). '' drops everything."""
        with self._lock:
            self._epoch += 1
            victims = [p for p in self._entries if p.startswith(prefix)] \
                if prefix else list(self._entries)
            for p in victims:
                ent = self._entries.pop(p)
                if ent.pins:
                    ent.dead = True
                else:
                    try:
                        os.close(ent.fd)
                    except OSError:
                        pass
