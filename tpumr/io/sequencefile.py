"""SequenceFile — the framework's key/value container format.

≈ ``org.apache.hadoop.io.SequenceFile`` (reference: src/core/org/apache/
hadoop/io/SequenceFile.java, 3256 LoC): a binary stream of key/value records
with a header, periodic 16-byte sync markers enabling split-at-any-offset
reads, and optional block compression. Differences from the reference,
deliberately: record-compressed mode is dropped (block mode dominates), and
keys/values are raw bytes produced by :mod:`tpumr.io.writable`'s typed codec
rather than class-name-bound Writables (the header carries codec metadata
instead of Java class names).
"""

from __future__ import annotations

import os
import struct
from io import BytesIO
from typing import Any, BinaryIO, Iterator

from tpumr.io.compress import get_codec
from tpumr.io.writable import read_vint, write_vint, serialize, deserialize

MAGIC = b"TSEQ"
VERSION = 1
SYNC_SIZE = 16
SYNC_INTERVAL = 100 * SYNC_SIZE  # bytes between syncs ≈ SequenceFile.SYNC_INTERVAL
_SYNC_ESCAPE = 0xFFFFFFFF  # uint32 length sentinel preceding a sync marker


class Writer:
    """Stream writer. ``block_size`` records are buffered then flushed as one
    (optionally compressed) block behind a sync marker."""

    def __init__(self, stream: BinaryIO, codec: str = "none",
                 metadata: dict[str, str] | None = None,
                 block_records: int = 1000) -> None:
        self._out = stream
        self._codec = get_codec(codec)
        self._block_records = max(1, block_records)
        self._sync = os.urandom(SYNC_SIZE)
        self._buf: list[tuple[bytes, bytes]] = []
        self._since_sync = 0
        meta = dict(metadata or {})
        meta["codec"] = self._codec.name
        header = BytesIO()
        header.write(MAGIC)
        header.write(bytes((VERSION,)))
        mb = serialize(meta)
        write_vint(header, len(mb))  # type: ignore[arg-type]
        header.write(mb)             # type: ignore[arg-type]
        header.write(self._sync)
        self._out.write(header.getvalue())

    def append(self, key: Any, value: Any) -> None:
        self.append_raw(serialize(key), serialize(value))  # type: ignore[arg-type]

    def append_raw(self, kbytes: bytes, vbytes: bytes) -> None:
        self._buf.append((kbytes, vbytes))
        if len(self._buf) >= self._block_records:
            self._flush_block()

    def _flush_block(self) -> None:
        if not self._buf:
            return
        body = BytesIO()
        write_vint(body, len(self._buf))
        for k, v in self._buf:
            write_vint(body, len(k))
            body.write(k)
            write_vint(body, len(v))
            body.write(v)
        self._emit_block(body.getvalue())
        self._buf.clear()

    def _emit_block(self, body: bytes) -> None:
        payload = self._codec.compress(body)
        if self._since_sync >= SYNC_INTERVAL:
            self._out.write(struct.pack(">I", _SYNC_ESCAPE))
            self._out.write(self._sync)
            self._since_sync = 0
        self._out.write(struct.pack(">I", len(payload)))
        self._out.write(payload)
        self._since_sync += len(payload) + 4

    def append_fixed_rows(self, rows, klen: int) -> None:
        """Vectorized bulk append of fixed-width raw records: ``rows`` is a
        ``[n, klen+vlen] uint8`` array whose first ``klen`` bytes per row
        are the key. Produces byte-identical framing to per-record
        ``append(bytes, bytes)`` calls (every serialized length is a
        per-file constant, so frames are a numpy tile job) — the write
        path of the device-shuffled reduce, where per-record Python append
        would dominate the whole job."""
        import numpy as np

        from tpumr.io.writable import serialize
        n = int(rows.shape[0])
        if n == 0:
            return
        self._flush_block()  # keep scalar-appended records ordered first
        vlen = int(rows.shape[1]) - klen

        def field_prefix(length: int) -> bytes:
            ser = serialize(b"\x00" * length)
            ser_prefix = ser[:len(ser) - length]  # tag+vint, payload off
            head = BytesIO()
            write_vint(head, len(ser_prefix) + length)
            return head.getvalue() + ser_prefix

        kf = np.frombuffer(field_prefix(klen), np.uint8)
        vf = np.frombuffer(field_prefix(vlen), np.uint8)
        frame_len = len(kf) + klen + len(vf) + vlen
        frames = np.empty((n, frame_len), np.uint8)
        frames[:, :len(kf)] = kf
        frames[:, len(kf):len(kf) + klen] = rows[:, :klen]
        off = len(kf) + klen
        frames[:, off:off + len(vf)] = vf
        frames[:, off + len(vf):] = rows[:, klen:]

        per = self._block_records  # same block granularity as scalar appends
        for lo in range(0, n, per):
            m = min(per, n - lo)
            head = BytesIO()
            write_vint(head, m)
            # block-sized copies only — one big tobytes() would double the
            # peak memory of exactly the large-partition path this serves
            self._emit_block(head.getvalue() + frames[lo:lo + m].tobytes())

    def sync_now(self) -> None:
        self.sync_pos()

    def sync_pos(self) -> int:
        """Flush pending records, emit a sync marker, and return the escape
        offset — a position where ``Reader.sync(pos)`` lands exactly (the
        seekable-entry contract MapFile indexes rely on)."""
        self._flush_block()
        pos = self._out.tell()
        self._out.write(struct.pack(">I", _SYNC_ESCAPE))
        self._out.write(self._sync)
        self._since_sync = 0
        return pos

    def close(self) -> None:
        """Flush pending records. The caller owns (and closes) the stream."""
        self._flush_block()
        self._out.flush()

    def __enter__(self) -> "Writer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def _parse_fixed_block(body: bytes):
    """Vectorized block parse: when every record in the block shares the
    first record's exact frame bytes outside the two payloads — i.e.
    bytes-tagged keys and values of one constant width each, the terasort
    layout — the whole block is one ``[n, frame]`` reshape. Returns
    ``(keys [n, klen] u8, values [n, vlen] u8)`` or None (caller falls
    back to the per-record parser)."""
    import numpy as np

    from tpumr.io.writable import _TAG_BYTES, _vint_at
    try:
        n, rec0 = _vint_at(body, 0)
        if n <= 0:
            return None
        # first record, scalar: vint(len kser) ++ kser ++ vint(len vser)
        # ++ vser, where kser = tag ++ vint(klen) ++ key payload
        kser_len, kser0 = _vint_at(body, rec0)
        if body[kser0] != _TAG_BYTES[0]:
            return None
        klen, kpay0 = _vint_at(body, kser0 + 1)
        if kser0 + kser_len != kpay0 + klen:
            return None
        vser_len, vser0 = _vint_at(body, kpay0 + klen)
        if body[vser0] != _TAG_BYTES[0]:
            return None
        vlen, vpay0 = _vint_at(body, vser0 + 1)
        if vser0 + vser_len != vpay0 + vlen:
            return None
    except IndexError:
        return None
    frame = vpay0 + vlen - rec0
    if len(body) - rec0 != n * frame:
        return None
    arr = np.frombuffer(body, np.uint8, n * frame, rec0).reshape(n, frame)
    # every non-payload column must match record 0's bytes exactly (same
    # lengths, same tags) — a cheap full proof that the reshape is valid
    kpay = kpay0 - rec0
    vhdr = kpay + klen
    vpay = vpay0 - rec0
    meta_idx = np.concatenate([np.arange(0, kpay),
                               np.arange(vhdr, vpay)])
    if n > 1 and not (arr[1:, meta_idx] == arr[0, meta_idx]).all():
        return None
    return arr[:, kpay:kpay + klen], arr[:, vpay:vpay + vlen]


class Reader:
    """Stream reader; supports ``sync(pos)`` — skip forward to the first sync
    marker at/after ``pos`` then read whole blocks — which is what makes a
    SequenceFile splittable at arbitrary byte offsets (the InputFormat
    contract, ≈ SequenceFile.Reader.sync)."""

    def __init__(self, stream: BinaryIO) -> None:
        self._in = stream
        if self._in.read(len(MAGIC)) != MAGIC:
            raise ValueError("not a tpumr SequenceFile (bad magic)")
        version = self._in.read(1)[0]
        if version != VERSION:
            raise ValueError(f"unsupported SequenceFile version {version}")
        mlen = read_vint(self._in)
        self.metadata: dict[str, str] = deserialize(self._in.read(mlen))
        self._codec = get_codec(self.metadata.get("codec", "none"))
        self._sync = self._in.read(SYNC_SIZE)
        self._header_end = self._in.tell()

    def __iter__(self) -> Iterator[tuple[Any, Any]]:
        for k, v in self.iter_raw():
            yield deserialize(k), deserialize(v)

    def _position_for_range(self, start: int, end: int) -> bool:
        """Position the stream at the first block of split [start, end);
        False when the split owns nothing. The ownership rule shared by
        the per-record and batch readers (every record is read by exactly
        one of a set of covering splits, ≈ SequenceFileRecordReader)."""
        if end <= self._header_end:
            # the header's trailing sync marker is the file's first boundary:
            # a split ending at/inside the header owns nothing (its successor
            # starting there syncs to header_end and owns the first block)
            return False
        if not self.sync(start):
            return False
        if start > self._header_end:
            # boundary = position of the 4-byte escape preceding the marker we
            # landed on; if it is already past `end` this split owns nothing
            boundary = self._in.tell() - SYNC_SIZE - 4
            if boundary >= end:
                return False
        return True

    def iter_range(self, start: int, end: int) -> Iterator[tuple[Any, Any]]:
        """Records of the split [start, end): from the first sync at/after
        ``start`` up to the first sync at/after ``end``."""
        if not self._position_for_range(start, end):
            return
        for k, v in self.iter_raw(end=end):
            yield deserialize(k), deserialize(v)

    def iter_block_bodies(self, end: int | None = None) -> Iterator[bytes]:
        """Decompressed block bodies from the current position; stops at
        the first sync at/after ``end`` (iter_raw's end-side rule)."""
        while True:
            pos = self._in.tell()
            raw = self._in.read(4)
            if len(raw) < 4:
                return
            (length,) = struct.unpack(">I", raw)
            if length == _SYNC_ESCAPE:
                marker = self._in.read(SYNC_SIZE)
                if marker != self._sync:
                    raise IOError("corrupt file: bad sync marker")
                if end is not None and pos >= end:
                    return
                continue
            payload = self._in.read(length)
            if len(payload) < length:
                raise EOFError("truncated block")
            yield self._codec.decompress(payload)

    def iter_raw(self, end: int | None = None) -> Iterator[tuple[bytes, bytes]]:
        for body in self.iter_block_bodies(end):
            block = BytesIO(body)
            n = read_vint(block)
            for _ in range(n):
                klen = read_vint(block)
                k = block.read(klen)
                vlen = read_vint(block)
                v = block.read(vlen)
                yield k, v

    def read_batch_range(self, start: int, end: int):
        """Records of the split [start, end) as one
        :class:`~tpumr.io.recordbatch.RecordBatch` — the whole-split read
        for kernel jobs. Blocks whose serialized records all share the
        first record's byte-level frame (fixed-width bytes keys/values —
        the terasort layout) parse as ONE numpy reshape; anything else
        falls back to the per-record path with the same
        bytes/str/serialize value semantics as the reader-drain staging
        path (tpu_runner.stage_batch)."""
        import numpy as np

        from tpumr.io.recordbatch import RecordBatch
        from tpumr.io.writable import serialize

        if not self._position_for_range(start, end):
            return RecordBatch.empty()

        key_chunks: list[np.ndarray] = []   # [n, klen] u8 per fast block
        val_chunks: list[np.ndarray] = []
        slow: list[tuple[bytes, bytes]] = []  # (key, value) payloads

        for body in self.iter_block_bodies(end):
            if body[:1] == b"\x00":  # vint 0: empty block, nothing to parse
                continue
            if not slow:
                parsed = _parse_fixed_block(body)
                if parsed is not None and key_chunks and (
                        parsed[0].shape[1] != key_chunks[0].shape[1]
                        or parsed[1].shape[1] != val_chunks[0].shape[1]):
                    parsed = None  # widths changed across blocks: go slow
                if parsed is not None:
                    key_chunks.append(parsed[0])
                    val_chunks.append(parsed[1])
                    continue
                # first ragged block: demote prior fast chunks to the slow
                # list so record order is preserved (and stay slow — a
                # mixed file is rare and order beats vectorization)
                for karr, varr in zip(key_chunks, val_chunks):
                    slow.extend((karr[i].tobytes(), varr[i].tobytes())
                                for i in range(karr.shape[0]))
                key_chunks, val_chunks = [], []
            block = BytesIO(body)
            n = read_vint(block)
            for _ in range(n):
                klen = read_vint(block)
                k = deserialize(block.read(klen))
                vlen = read_vint(block)
                v = deserialize(block.read(vlen))
                k = k if isinstance(k, (bytes, bytearray)) else (
                    k.encode("utf-8") if isinstance(k, str) else serialize(k))
                v = v if isinstance(v, (bytes, bytearray)) else (
                    v.encode("utf-8") if isinstance(v, str) else serialize(v))
                slow.append((bytes(k), bytes(v)))

        if slow:
            return RecordBatch.from_pairs(slow)
        if not key_chunks:
            return RecordBatch.empty()
        keys = np.concatenate(key_chunks)
        vals = np.concatenate(val_chunks)
        n = keys.shape[0]
        ko = (np.arange(n + 1, dtype=np.int64) * keys.shape[1]).astype(np.int32)
        vo = (np.arange(n + 1, dtype=np.int64) * vals.shape[1]).astype(np.int32)
        return RecordBatch(keys.reshape(-1), ko, vals.reshape(-1), vo)

    def sync(self, pos: int) -> bool:
        """Position the reader at the first sync marker at/after byte ``pos``.
        Returns False if no further sync exists (reader is at EOF)."""
        if pos <= self._header_end:
            self._in.seek(self._header_end)
            return True
        # Boundary identity is the 4-byte escape position: a marker "belongs"
        # to pos iff its escape starts at >= pos, i.e. the marker pattern
        # itself starts at >= pos+4. Scanning from pos+4 keeps this side
        # consistent with iter_raw's end-side rule (escape pos >= end), so
        # adjacent splits never double-own the 4-byte escape window.
        self._in.seek(pos + 4)
        # scan for the 16-byte marker
        window = self._in.read(SYNC_SIZE)
        if len(window) < SYNC_SIZE:
            return False
        buf = bytearray(window)
        while bytes(buf) != self._sync:
            nxt = self._in.read(1)
            if not nxt:
                return False
            buf = buf[1:] + nxt
        return True

    def tell(self) -> int:
        return self._in.tell()

    def close(self) -> None:
        """No-op: the caller owns (and closes) the stream."""

    def __enter__(self) -> "Reader":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
