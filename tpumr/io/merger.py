"""Bounded-fan-in multi-pass merging — the Merger pass engine.

≈ ``org.apache.hadoop.mapred.Merger`` (reference: src/mapred/org/apache/
hadoop/mapred/Merger.java — MergeQueue.merge's pass selection): when the
number of sorted runs exceeds ``io.sort.factor``, intermediate passes
merge a subset of runs into an on-disk IFile run until one final merge of
at most ``factor`` streams remains. A 500-map shuffle then never holds
500 open streams / heap entries at once — fan-in, file descriptors, and
heap size are all bounded by the factor.

Divergence from the reference, documented: Merger.java sorts runs by
size and merges the globally smallest ones, which reorders equal keys
across runs (Hadoop guarantees nothing about value order). Here each
pass merges the size-minimal CONTIGUOUS window of the run list and the
resulting run takes its window's position, so the segment-order
tiebreak for equal keys is preserved end-to-end: multi-pass output is
byte-identical to a flat ``ifile.merge_sorted`` over the same runs.
First-pass width ≈ Merger.getPassFactor: sized so every later pass
(including the final one) runs at full factor, minimizing pass count.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Callable, Iterable, Iterator

from tpumr.io import ifile

#: counter names live here (not core.counters) so tpumr.io stays free of
#: mapred imports; TaskCounter re-exports the same strings
MERGE_PASSES = "MERGE_PASSES"
MERGE_PASS_SEGMENTS = "MERGE_PASS_SEGMENTS"
FRAMEWORK_GROUP = "tpumr.TaskCounter"


class DiskRun:
    """One intermediate merged run on local disk: a single-partition
    IFile payload, streamed back through the incremental decompressor
    (never materialized) when the next pass or the final merge reads
    it."""

    in_memory = False

    def __init__(self, path: str, codec: str, raw_length: int,
                 offset: int, length: int, records: int = 0) -> None:
        self.path = path
        self.codec = codec
        self.raw_length = raw_length
        self.offset = offset
        self.length = length
        self.records = records

    def __iter__(self) -> Iterator[tuple[bytes, bytes]]:
        return ifile.iter_chunked_segment(
            ifile.file_region_chunks(self.path, self.offset, self.length),
            self.codec)

    def close(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


def write_run(records: Iterable[tuple[bytes, bytes]], run_dir: str,
              codec: str = "none", prefix: str = "merge") -> DiskRun:
    """Drain ``records`` (sorted) into a single-partition IFile run in
    ``run_dir`` and return the streaming view over it.

    Frames the segment directly (byte-identical to ``ifile.Writer`` with
    one partition) through block-sized ``b"".join`` batches instead of
    four BytesIO method calls per record — run writing sits on the
    background merger's critical path, throttling fetchers that wait on
    freed budget. Object overhead stays bounded: fragments collapse into
    a block every ~4 MB."""
    import struct

    from tpumr.io.compress import get_codec
    from tpumr.io.writable import _vint_bytes

    os.makedirs(run_dir, exist_ok=True)
    fd, path = tempfile.mkstemp(prefix=f"{prefix}-", suffix=".run",
                                dir=run_dir)
    n = 0
    parts: "list[bytes]" = []
    blocks: "list[bytes]" = []
    acc = 0
    append = parts.append
    try:
        for kb, vb in records:
            append(_vint_bytes(len(kb)))
            append(kb)
            append(_vint_bytes(len(vb)))
            append(vb)
            n += 1
            acc += len(kb) + len(vb) + 4
            if acc >= (1 << 22):
                blocks.append(b"".join(parts))
                parts.clear()
                acc = 0
        blocks.append(b"".join(parts))
        raw = _vint_bytes(n) + b"".join(blocks)
        payload = get_codec(codec).compress(raw)
        with os.fdopen(fd, "wb") as f:
            f.write(ifile.MAGIC)
            f.write(struct.pack(">I", len(payload)))
            f.write(payload)
    except BaseException:
        try:
            os.unlink(path)
        except OSError:
            pass
        raise
    # payload begins after MAGIC (4) + the 4-byte length prefix
    return DiskRun(path, codec, len(raw), offset=len(ifile.MAGIC) + 4,
                   length=len(payload), records=n)


def _padded_vint(value: int, width: int = 5) -> bytes:
    """LEB128 vint padded to a FIXED width with 0x80 continuation bytes
    (non-minimal encodings decode identically), so a placeholder written
    before the record count is known can be patched in place at the
    end. width=5 covers counts below 2^35."""
    out = bytearray()
    for _ in range(width - 1):
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    if value > 0x7F:
        raise ValueError("record count exceeds padded vint width")
    out.append(value)
    return bytes(out)


def write_run_streaming(records: Iterable[tuple[bytes, bytes]],
                        run_dir: str, prefix: str = "merge") -> DiskRun:
    """Bounded-memory run writer for UNBOUNDED record streams (the
    intermediate bounded-fan-in passes, whose window can span most of a
    wide shuffle): frames records straight to the file in ~4 MB joined
    blocks, never holding the run in memory. Uncompressed — the IFile
    whole-block compression would require buffering the payload, and
    intermediate runs are transient local files read back exactly once.
    The record-count vint is written as a fixed-width padded placeholder
    and patched at the end; the result still decodes as a standard
    single-partition IFile segment."""
    import struct

    from tpumr.io.writable import _vint_bytes

    os.makedirs(run_dir, exist_ok=True)
    fd, path = tempfile.mkstemp(prefix=f"{prefix}-", suffix=".run",
                                dir=run_dir)
    head = len(ifile.MAGIC) + 4
    n = 0
    raw_len = 5
    parts: "list[bytes]" = []
    acc = 0
    append = parts.append
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(ifile.MAGIC)
            f.write(struct.pack(">I", 0))        # payload length, patched
            f.write(_padded_vint(0))             # record count, patched
            for kb, vb in records:
                append(_vint_bytes(len(kb)))
                append(kb)
                append(_vint_bytes(len(vb)))
                append(vb)
                n += 1
                acc += len(kb) + len(vb) + 4
                if acc >= (1 << 22):
                    block = b"".join(parts)
                    f.write(block)
                    raw_len += len(block)
                    parts.clear()
                    acc = 0
            block = b"".join(parts)
            f.write(block)
            raw_len += len(block)
            f.seek(head - 4)
            f.write(struct.pack(">I", raw_len))  # codec none: payload=raw
            f.write(_padded_vint(n))
    except BaseException:
        try:
            os.unlink(path)
        except OSError:
            pass
        raise
    return DiskRun(path, "none", raw_len, offset=head, length=raw_len,
                   records=n)


def _pass_width(n: int, factor: int, first_pass: bool) -> int:
    """≈ Merger.getPassFactor: the first pass merges just enough runs
    that every subsequent pass (and the final merge) is a full-factor
    merge — the pass count is then minimal for the given factor."""
    if not first_pass or n <= factor:
        return factor
    mod = (n - 1) % (factor - 1)
    return factor if mod == 0 else mod + 1


def _min_window(runs: "list[Any]", width: int) -> int:
    """Start index of the contiguous ``width``-run window with the
    smallest total raw bytes (ties: leftmost). Contiguity is what keeps
    multi-pass output byte-identical to the flat merge — see the module
    docstring divergence note."""
    sizes = [max(0, int(getattr(r, "raw_length", 0) or 0)) for r in runs]
    best_start, cur = 0, sum(sizes[:width])
    best = cur
    for start in range(1, len(runs) - width + 1):
        cur += sizes[start + width - 1] - sizes[start - 1]
        if cur < best:
            best, best_start = cur, start
    return best_start


class BoundedMerge:
    """A lazy bounded-fan-in merge over sorted runs.

    Iterating performs the intermediate passes (writing on-disk runs
    under ``run_dir``, each consumed input closed as soon as its pass
    finishes — a memory segment's budget reservation is released there,
    not at job end) and then yields the final ≤ ``factor``-way merge.
    ``close()`` deletes any intermediate runs (and the run dir, when
    this merge created it). ``passes`` / ``max_fan_in`` expose the pass
    structure for counters, tests, and the merge:pass trace spans."""

    def __init__(self, segments: "list[Iterable[tuple[bytes, bytes]]]",
                 sort_key: "Callable[[bytes], Any] | None",
                 factor: int, run_dir: "str | None" = None,
                 reporter: Any = None, prefix: str = "merge") -> None:
        self._segments = list(segments)
        self._sort_key = sort_key
        self.factor = max(2, int(factor))
        self._run_dir = run_dir
        self._own_dir: "str | None" = None
        self._reporter = reporter
        self._prefix = prefix
        self._made: "list[DiskRun]" = []
        self.passes = 0
        self.max_fan_in = 0

    def _dir(self) -> str:
        if self._run_dir is None:
            self._run_dir = self._own_dir = tempfile.mkdtemp(
                prefix="tpumr-merge-")
        return self._run_dir

    def _one_pass(self, runs: "list[Any]", first: bool) -> None:
        from tpumr.core import tracing
        width = _pass_width(len(runs), self.factor, first)
        start = _min_window(runs, width)
        batch = runs[start:start + width]
        with tracing.span("merge:pass", fan_in=len(batch),
                          remaining=len(runs)) as s:
            # streaming writer: a pass window can span most of a wide
            # shuffle, so the run must never be resident as one buffer
            run = write_run_streaming(
                ifile.merge_sorted(batch, self._sort_key),
                self._dir(), prefix=self._prefix)
            if s is not None:
                s.set(run_bytes=run.length, records=run.records)
        for seg in batch:
            close = getattr(seg, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 — cleanup best-effort
                    pass
        self._made.append(run)
        runs[start:start + width] = [run]
        self.passes += 1
        self.max_fan_in = max(self.max_fan_in, width)
        if self._reporter is not None:
            self._reporter.incr_counter(FRAMEWORK_GROUP, MERGE_PASSES, 1)
            self._reporter.incr_counter(FRAMEWORK_GROUP,
                                        MERGE_PASS_SEGMENTS, width)

    def __iter__(self) -> Iterator[tuple[bytes, bytes]]:
        runs: "list[Any]" = list(self._segments)
        first = True
        while len(runs) > self.factor:
            self._one_pass(runs, first)
            first = False
        self.max_fan_in = max(self.max_fan_in, len(runs))
        return iter(ifile.merge_sorted(runs, self._sort_key))

    def close(self) -> None:
        for run in self._made:
            run.close()
        self._made = []
        if self._own_dir is not None:
            import shutil
            shutil.rmtree(self._own_dir, ignore_errors=True)
            self._own_dir = None
