"""TFile — sorted, block-compressed, indexed key/value container.

≈ ``org.apache.hadoop.io.file.tfile.TFile`` (reference:
src/core/org/apache/hadoop/io/file/tfile/ — TFile.java, BCFile.java,
~8k LoC): the third container format next to SequenceFile and MapFile.
Contracts kept:

- keys are raw byte strings appended in non-decreasing order (enforced
  at append, ≈ TFile.Writer.append's key-ordering check);
- records live in independently COMPRESSED data blocks (≈ BCFile data
  blocks), so a scan touching one key range decompresses only the blocks
  it crosses;
- a data-block index of (first_key, offset, length) supports
  ``seek_to(key)`` by binary search (≈ TFile.Reader.createScannerByKey);
- named META blocks ride in the same file (≈ BCFile meta blocks);
- readers address the file by ranges: ``scanner(start_key, stop_key)``
  yields [start_key, stop_key) like TFile.Reader.createScanner.

Single-stream layout (offsets from 0):

    MAGIC "TFL1"
    data block*        each: codec-compressed concat of
                       (vint klen, vint vlen, key, value)*
    meta block*        codec-compressed blobs
    index              compressed list of data-block entries
    trailer            json: codec, counts, index/meta offsets
    u32 trailer_len, MAGIC "TFL1"

The trailer is self-describing JSON — version-friendly, greppable, and
costs a few dozen bytes per file (these are block-scale containers).
"""

from __future__ import annotations

import io
import json
import struct
from bisect import bisect_left
from typing import Any, BinaryIO, Iterator

from tpumr.io.compress import get_codec
from tpumr.io.writable import read_vint, write_vint

MAGIC = b"TFL1"
_U32 = struct.Struct(">I")


class TFileError(ValueError):
    pass


class Writer:
    """Append-only sorted writer (≈ TFile.Writer). The caller owns the
    stream (SequenceFile convention in this codebase)."""

    def __init__(self, stream: BinaryIO, codec: str = "zlib",
                 block_bytes: int = 64 * 1024) -> None:
        self._f = stream
        self.codec_name = codec if codec else "none"
        self._codec = get_codec(self.codec_name)
        self.block_bytes = block_bytes
        self._buf = io.BytesIO()
        self._buf_first_key: bytes | None = None
        self._buf_records = 0
        self._last_key: bytes | None = None
        #: (first_key, offset, compressed_len, n_records)
        self._index: list[tuple[bytes, int, int, int]] = []
        self._meta: dict[str, tuple[int, int]] = {}
        self._meta_pending: dict[str, bytes] = {}
        self._n_records = 0
        self._closed = False
        self._f.write(MAGIC)
        self._pos = len(MAGIC)

    def append(self, key: bytes, value: bytes) -> None:
        key, value = bytes(key), bytes(value)
        if self._last_key is not None and key < self._last_key:
            raise TFileError(
                f"keys out of order: {key!r} after {self._last_key!r} "
                "(TFile keys must be appended sorted)")
        self._last_key = key
        if self._buf_first_key is None:
            self._buf_first_key = key
        write_vint(self._buf, len(key))
        write_vint(self._buf, len(value))
        self._buf.write(key)
        self._buf.write(value)
        self._buf_records += 1
        self._n_records += 1
        if self._buf.tell() >= self.block_bytes:
            self._flush_block()

    def write_meta(self, name: str, data: bytes) -> None:
        """Named meta block (≈ BCFile prepareMetaBlock); written at
        close."""
        self._meta_pending[name] = bytes(data)

    def _flush_block(self) -> None:
        if self._buf_records == 0:
            return
        raw = self._buf.getvalue()
        packed = self._codec.compress(raw)
        self._index.append((self._buf_first_key or b"", self._pos,
                            len(packed), self._buf_records))
        self._f.write(packed)
        self._pos += len(packed)
        self._buf = io.BytesIO()
        self._buf_first_key = None
        self._buf_records = 0

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._flush_block()
        for name, data in self._meta_pending.items():
            packed = self._codec.compress(data)
            self._f.write(packed)
            self._meta[name] = (self._pos, len(packed))
            self._pos += len(packed)
        index_blob = io.BytesIO()
        for first_key, off, clen, n in self._index:
            write_vint(index_blob, len(first_key))
            index_blob.write(first_key)
            write_vint(index_blob, off)
            write_vint(index_blob, clen)
            write_vint(index_blob, n)
        packed_index = self._codec.compress(index_blob.getvalue())
        index_off = self._pos
        self._f.write(packed_index)
        self._pos += len(packed_index)
        trailer = json.dumps({
            "codec": self.codec_name,
            "records": self._n_records,
            "blocks": len(self._index),
            "index": [index_off, len(packed_index)],
            "meta": {k: list(v) for k, v in self._meta.items()},
        }).encode()
        self._f.write(trailer)
        self._f.write(_U32.pack(len(trailer)))
        self._f.write(MAGIC)
        self._f.flush()

    def __enter__(self) -> "Writer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class Reader:
    """Range/seek reader (≈ TFile.Reader + Scanner). Needs a seekable
    stream; the caller owns it."""

    def __init__(self, stream: BinaryIO) -> None:
        self._f = stream
        self._f.seek(0)
        if self._f.read(len(MAGIC)) != MAGIC:
            raise TFileError("not a TFile (bad leading magic)")
        self._f.seek(-(len(MAGIC) + _U32.size), io.SEEK_END)
        tlen_at = self._f.tell()
        tlen = _U32.unpack(self._f.read(_U32.size))[0]
        if self._f.read(len(MAGIC)) != MAGIC:
            raise TFileError("not a TFile (bad trailing magic)")
        self._f.seek(tlen_at - tlen)
        trailer = json.loads(self._f.read(tlen))
        self.codec_name = trailer["codec"]
        self._codec = get_codec(self.codec_name)
        self.num_records = trailer["records"]
        self._meta = {k: tuple(v) for k, v in trailer["meta"].items()}
        idx_off, idx_len = trailer["index"]
        self._f.seek(idx_off)
        blob = io.BytesIO(self._codec.decompress(self._f.read(idx_len)))
        #: parallel arrays for bisect
        self.block_keys: list[bytes] = []
        self._blocks: list[tuple[int, int, int]] = []
        end = len(blob.getvalue())
        while blob.tell() < end:
            klen = read_vint(blob)
            key = blob.read(klen)
            off = read_vint(blob)
            clen = read_vint(blob)
            n = read_vint(blob)
            self.block_keys.append(key)
            self._blocks.append((off, clen, n))

    # ------------------------------------------------------------ access

    def meta_names(self) -> list[str]:
        return sorted(self._meta)

    def meta(self, name: str) -> bytes:
        off, clen = self._meta[name]
        self._f.seek(off)
        return self._codec.decompress(self._f.read(clen))

    def _block_records(self, i: int) -> Iterator[tuple[bytes, bytes]]:
        off, clen, n = self._blocks[i]
        self._f.seek(off)
        blob = io.BytesIO(self._codec.decompress(self._f.read(clen)))
        for _ in range(n):
            klen = read_vint(blob)
            vlen = read_vint(blob)
            yield blob.read(klen), blob.read(vlen)

    def scanner(self, start_key: "bytes | None" = None,
                stop_key: "bytes | None" = None
                ) -> Iterator[tuple[bytes, bytes]]:
        """Yield (key, value) over [start_key, stop_key), decompressing
        only the blocks the range crosses (≈ createScanner(byte[],byte[]))."""
        if not self._blocks:
            return
        first = 0
        if start_key is not None:
            # one block BEFORE the leftmost whose first_key >= start_key:
            # duplicate keys equal to a later block's first key may span
            # the boundary backwards (bisect_right here would skip them)
            first = max(0, bisect_left(self.block_keys,
                                       bytes(start_key)) - 1)
        for i in range(first, len(self._blocks)):
            if stop_key is not None and self.block_keys[i] >= stop_key:
                return
            for k, v in self._block_records(i):
                if start_key is not None and k < start_key:
                    continue
                if stop_key is not None and k >= stop_key:
                    return
                yield k, v

    def seek_to(self, key: bytes) -> Iterator[tuple[bytes, bytes]]:
        """Scanner positioned at the first record with key >= ``key``."""
        return self.scanner(start_key=key)

    def get(self, key: bytes, default: Any = None) -> Any:
        """First value whose key == ``key`` (binary-searched block)."""
        key = bytes(key)
        for k, v in self.scanner(start_key=key):
            if k == key:
                return v
            if k > key:
                break
        return default

    def __iter__(self) -> Iterator[tuple[bytes, bytes]]:
        return self.scanner()
