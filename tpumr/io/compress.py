"""Compression codec framework.

≈ ``org.apache.hadoop.io.compress`` (reference: src/core/org/apache/hadoop/
io/compress/ + JNI zlib/snappy in src/native/): pluggable codecs addressed by
name / file extension, used by SequenceFile blocks, IFile spill segments and
shuffle transfers. Python's zlib/gzip/bz2/lzma stand in for the JNI codecs; a
snappy codec is registered only if the optional module is importable.
"""

from __future__ import annotations

import bz2
import gzip
import lzma
import zlib


class CompressionCodec:
    name = "none"
    extension = ""

    def compress(self, data: bytes) -> bytes:
        return data

    def decompress(self, data: bytes) -> bytes:
        return data

    def decompressor(self) -> "Decompressor":
        """Streaming decompressor (≈ the Decompressor SPI the JNI codecs
        implement): feed compressed chunks, get raw bytes incrementally
        — the memory-bounded shuffle/merge path depends on this. Codecs
        without native streaming inherit a buffering fallback (whole
        payload held until flush)."""
        return _BufferingDecompressor(self)


class Decompressor:
    """feed(data) -> raw bytes now available; flush() -> remaining raw."""

    def feed(self, data: bytes) -> bytes:
        raise NotImplementedError

    def flush(self) -> bytes:
        return b""


class _PassthroughDecompressor(Decompressor):
    def feed(self, data: bytes) -> bytes:
        return data


class _BufferingDecompressor(Decompressor):
    """Fallback for codecs without a streaming object (e.g. snappy)."""

    def __init__(self, codec: "CompressionCodec") -> None:
        self._codec = codec
        self._parts: list[bytes] = []

    def feed(self, data: bytes) -> bytes:
        self._parts.append(data)
        return b""

    def flush(self) -> bytes:
        return self._codec.decompress(b"".join(self._parts))


class _ObjDecompressor(Decompressor):
    """Adapter over stdlib decompressobj-style objects."""

    def __init__(self, obj) -> None:
        self._obj = obj

    def feed(self, data: bytes) -> bytes:
        return self._obj.decompress(data)

    def flush(self) -> bytes:
        fl = getattr(self._obj, "flush", None)
        return fl() if fl is not None else b""


class ZlibCodec(CompressionCodec):
    """≈ DefaultCodec/zlib (src/native/.../zlib/ZlibCompressor.c)."""
    name = "zlib"
    extension = ".deflate"

    def __init__(self, level: int = 6) -> None:
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def decompress(self, data: bytes) -> bytes:
        return zlib.decompress(data)

    def decompressor(self) -> Decompressor:
        return _ObjDecompressor(zlib.decompressobj())


class GzipCodec(CompressionCodec):
    name = "gzip"
    extension = ".gz"

    def compress(self, data: bytes) -> bytes:
        return gzip.compress(data)

    def decompress(self, data: bytes) -> bytes:
        return gzip.decompress(data)

    def decompressor(self) -> Decompressor:
        return _ObjDecompressor(zlib.decompressobj(16 + zlib.MAX_WBITS))


class Bzip2Codec(CompressionCodec):
    name = "bzip2"
    extension = ".bz2"

    def compress(self, data: bytes) -> bytes:
        return bz2.compress(data)

    def decompress(self, data: bytes) -> bytes:
        return bz2.decompress(data)

    def decompressor(self) -> Decompressor:
        return _ObjDecompressor(bz2.BZ2Decompressor())


class LzmaCodec(CompressionCodec):
    name = "lzma"
    extension = ".xz"

    def compress(self, data: bytes) -> bytes:
        return lzma.compress(data)

    def decompress(self, data: bytes) -> bytes:
        return lzma.decompress(data)

    def decompressor(self) -> Decompressor:
        return _ObjDecompressor(lzma.LZMADecompressor())


class NullCodec(CompressionCodec):
    name = "none"

    def decompressor(self) -> Decompressor:
        return _PassthroughDecompressor()


_REGISTRY: dict[str, type[CompressionCodec]] = {
    "none": NullCodec,
    "zlib": ZlibCodec,
    "default": ZlibCodec,
    "gzip": GzipCodec,
    "bzip2": Bzip2Codec,
    "lzma": LzmaCodec,
}

try:  # optional, mirrors the reference's build-time snappy gate
    import snappy as _snappy  # type: ignore

    class SnappyCodec(CompressionCodec):
        name = "snappy"
        extension = ".snappy"

        def compress(self, data: bytes) -> bytes:
            return _snappy.compress(data)

        def decompress(self, data: bytes) -> bytes:
            return _snappy.decompress(data)

    _REGISTRY["snappy"] = SnappyCodec
except ImportError:
    pass


def get_codec(name: str | None) -> CompressionCodec:
    if not name:
        return NullCodec()
    cls = _REGISTRY.get(name.lower())
    if cls is None:
        raise ValueError(f"unknown codec {name!r}; have {sorted(_REGISTRY)}")
    return cls()


def codec_for_path(path: str) -> CompressionCodec | None:
    """Pick a codec by file extension (≈ CompressionCodecFactory)."""
    for cls in _REGISTRY.values():
        if cls.extension and path.endswith(cls.extension):
            return cls()
    return None


def register_codec(cls: type[CompressionCodec]) -> None:
    _REGISTRY[cls.name] = cls
