"""Compression codec framework.

≈ ``org.apache.hadoop.io.compress`` (reference: src/core/org/apache/hadoop/
io/compress/ + JNI zlib/snappy in src/native/): pluggable codecs addressed by
name / file extension, used by SequenceFile blocks, IFile spill segments and
shuffle transfers. Python's zlib/gzip/bz2/lzma stand in for the JNI codecs; a
snappy codec is registered only if the optional module is importable.
"""

from __future__ import annotations

import bz2
import gzip
import lzma
import zlib


class CompressionCodec:
    name = "none"
    extension = ""

    def compress(self, data: bytes) -> bytes:
        return data

    def decompress(self, data: bytes) -> bytes:
        return data


class ZlibCodec(CompressionCodec):
    """≈ DefaultCodec/zlib (src/native/.../zlib/ZlibCompressor.c)."""
    name = "zlib"
    extension = ".deflate"

    def __init__(self, level: int = 6) -> None:
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def decompress(self, data: bytes) -> bytes:
        return zlib.decompress(data)


class GzipCodec(CompressionCodec):
    name = "gzip"
    extension = ".gz"

    def compress(self, data: bytes) -> bytes:
        return gzip.compress(data)

    def decompress(self, data: bytes) -> bytes:
        return gzip.decompress(data)


class Bzip2Codec(CompressionCodec):
    name = "bzip2"
    extension = ".bz2"

    def compress(self, data: bytes) -> bytes:
        return bz2.compress(data)

    def decompress(self, data: bytes) -> bytes:
        return bz2.decompress(data)


class LzmaCodec(CompressionCodec):
    name = "lzma"
    extension = ".xz"

    def compress(self, data: bytes) -> bytes:
        return lzma.compress(data)

    def decompress(self, data: bytes) -> bytes:
        return lzma.decompress(data)


class NullCodec(CompressionCodec):
    name = "none"


_REGISTRY: dict[str, type[CompressionCodec]] = {
    "none": NullCodec,
    "zlib": ZlibCodec,
    "default": ZlibCodec,
    "gzip": GzipCodec,
    "bzip2": Bzip2Codec,
    "lzma": LzmaCodec,
}

try:  # optional, mirrors the reference's build-time snappy gate
    import snappy as _snappy  # type: ignore

    class SnappyCodec(CompressionCodec):
        name = "snappy"
        extension = ".snappy"

        def compress(self, data: bytes) -> bytes:
            return _snappy.compress(data)

        def decompress(self, data: bytes) -> bytes:
            return _snappy.decompress(data)

    _REGISTRY["snappy"] = SnappyCodec
except ImportError:
    pass


def get_codec(name: str | None) -> CompressionCodec:
    if not name:
        return NullCodec()
    cls = _REGISTRY.get(name.lower())
    if cls is None:
        raise ValueError(f"unknown codec {name!r}; have {sorted(_REGISTRY)}")
    return cls()


def codec_for_path(path: str) -> CompressionCodec | None:
    """Pick a codec by file extension (≈ CompressionCodecFactory)."""
    for cls in _REGISTRY.values():
        if cls.extension and path.endswith(cls.extension):
            return cls()
    return None


def register_codec(cls: type[CompressionCodec]) -> None:
    _REGISTRY[cls.name] = cls
