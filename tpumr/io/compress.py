"""Compression codec framework.

≈ ``org.apache.hadoop.io.compress`` (reference: src/core/org/apache/hadoop/
io/compress/ + JNI zlib/snappy in src/native/): pluggable codecs addressed by
name / file extension, used by SequenceFile blocks, IFile spill segments and
shuffle transfers. Python's zlib/gzip/bz2/lzma stand in for the JNI codecs; a
snappy codec is registered only if the optional module is importable.
"""

from __future__ import annotations

import bz2
import gzip
import lzma
import zlib


class CompressionCodec:
    name = "none"
    extension = ""

    def compress(self, data: bytes) -> bytes:
        return data

    def decompress(self, data: bytes) -> bytes:
        return data

    def decompressor(self) -> "Decompressor":
        """Streaming decompressor (≈ the Decompressor SPI the JNI codecs
        implement): feed compressed chunks, get raw bytes incrementally
        — the memory-bounded shuffle/merge path depends on this. Codecs
        without native streaming inherit a buffering fallback (whole
        payload held until flush)."""
        return _BufferingDecompressor(self)


class Decompressor:
    """feed(data) -> raw bytes now available; flush() -> remaining raw."""

    def feed(self, data: bytes) -> bytes:
        raise NotImplementedError

    def flush(self) -> bytes:
        return b""


class _PassthroughDecompressor(Decompressor):
    def feed(self, data: bytes) -> bytes:
        return data


class _BufferingDecompressor(Decompressor):
    """Fallback for codecs without a streaming object (e.g. snappy)."""

    def __init__(self, codec: "CompressionCodec") -> None:
        self._codec = codec
        self._parts: list[bytes] = []

    def feed(self, data: bytes) -> bytes:
        self._parts.append(data)
        return b""

    def flush(self) -> bytes:
        return self._codec.decompress(b"".join(self._parts))


class _ObjDecompressor(Decompressor):
    """Adapter over stdlib decompressobj-style objects."""

    def __init__(self, obj) -> None:
        self._obj = obj

    def feed(self, data: bytes) -> bytes:
        return self._obj.decompress(data)

    def flush(self) -> bytes:
        fl = getattr(self._obj, "flush", None)
        return fl() if fl is not None else b""


class ZlibCodec(CompressionCodec):
    """≈ DefaultCodec/zlib (src/native/.../zlib/ZlibCompressor.c)."""
    name = "zlib"
    extension = ".deflate"

    def __init__(self, level: int = 6) -> None:
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def decompress(self, data: bytes) -> bytes:
        return zlib.decompress(data)

    def decompressor(self) -> Decompressor:
        return _ObjDecompressor(zlib.decompressobj())


class GzipCodec(CompressionCodec):
    name = "gzip"
    extension = ".gz"

    def compress(self, data: bytes) -> bytes:
        return gzip.compress(data)

    def decompress(self, data: bytes) -> bytes:
        return gzip.decompress(data)

    def decompressor(self) -> Decompressor:
        return _ObjDecompressor(zlib.decompressobj(16 + zlib.MAX_WBITS))


class Bzip2Codec(CompressionCodec):
    name = "bzip2"
    extension = ".bz2"

    def compress(self, data: bytes) -> bytes:
        return bz2.compress(data)

    def decompress(self, data: bytes) -> bytes:
        return bz2.decompress(data)

    def decompressor(self) -> Decompressor:
        return _ObjDecompressor(bz2.BZ2Decompressor())


class LzmaCodec(CompressionCodec):
    name = "lzma"
    extension = ".xz"

    def compress(self, data: bytes) -> bytes:
        return lzma.compress(data)

    def decompress(self, data: bytes) -> bytes:
        return lzma.decompress(data)

    def decompressor(self) -> Decompressor:
        return _ObjDecompressor(lzma.LZMADecompressor())


class NullCodec(CompressionCodec):
    name = "none"

    def decompressor(self) -> Decompressor:
        return _PassthroughDecompressor()


class TlzCodec(CompressionCodec):
    """The framework's NATIVE fast codec (native/tlz/tlz.c) — the role
    of the reference's JNI zlib/snappy tier (src/native/src/org/apache/
    hadoop/io/compress/): shuffle/spill compression sits on the hot
    path, and measured on this harness stdlib zlib tops out ~134 MB/s
    (level 1, text) vs tlz's ~450 MB/s at the SAME ratio, with a
    memcpy-speed stored mode for incompressible data (~3 GB/s vs zlib
    burning 40 MB/s for nothing). Hosts without a C toolchain stay
    format-compatible: they WRITE valid stored frames and READ via the
    pure-Python decoder below, so a mixed cluster never mis-parses a
    shuffle stream."""

    name = "tlz"
    extension = ".tlz"

    @staticmethod
    def _py_decompress(data: bytes) -> bytes:
        """Pure-Python frame reader — the no-toolchain fallback. Slow,
        but every host can always READ tlz frames, so a cluster with
        mixed toolchain availability never mis-parses a stream."""
        import struct
        if len(data) < 12 or data[:3] != b"TLZ" or \
                data[3:4] not in (b"0", b"1"):
            raise ValueError("corrupt tlz frame (bad header)")
        (raw_len,) = struct.unpack("<Q", data[4:12])
        if data[3:4] == b"0":
            out = data[12:]
            if len(out) != raw_len:
                raise ValueError("corrupt tlz frame (stored length)")
            return out
        out = bytearray()
        r = 12
        n = len(data)

        def ext(r: int, v: int) -> "tuple[int, int]":
            while True:
                if r >= n:
                    raise ValueError("corrupt tlz frame (ext)")
                b = data[r]
                r += 1
                v += b
                if b != 255:
                    return r, v

        while len(out) < raw_len:
            if r >= n:
                raise ValueError("corrupt tlz frame (truncated)")
            token = data[r]
            r += 1
            lit = token >> 4
            if lit == 15:
                r, lit = ext(r, lit)
            if lit > n - r or lit > raw_len - len(out):
                raise ValueError("corrupt tlz frame (literals)")
            out += data[r:r + lit]
            r += lit
            if len(out) == raw_len:
                break
            mlen = token & 0xF
            if r + 2 > n:
                raise ValueError("corrupt tlz frame (offset)")
            offset = data[r] | (data[r + 1] << 8)
            r += 2
            if mlen == 15:
                r, mlen = ext(r, mlen)
            mlen += 4
            if offset == 0 or offset > len(out) \
                    or mlen > raw_len - len(out):
                raise ValueError("corrupt tlz frame (match)")
            for _ in range(mlen):   # byte-wise: overlap replicates runs
                out.append(out[-offset])
        return bytes(out)

    @staticmethod
    def _py_store(data: bytes) -> bytes:
        """No-toolchain compress fallback: a valid STORED frame — zero
        compression, but format-identical, so any native reader (or the
        Python one above) decodes it."""
        import struct
        return b"TLZ0" + struct.pack("<Q", len(data)) + data

    @staticmethod
    def _lib():
        import ctypes

        def configure(lib):
            u64, i64, cp = (ctypes.c_uint64, ctypes.c_int64,
                            ctypes.c_char_p)
            lib.tlz_bound.restype = u64
            lib.tlz_bound.argtypes = [u64]
            lib.tlz_compress.restype = i64
            lib.tlz_compress.argtypes = [cp, u64, cp, u64]
            lib.tlz_raw_size.restype = i64
            lib.tlz_raw_size.argtypes = [cp, u64]
            lib.tlz_decompress.restype = i64
            lib.tlz_decompress.argtypes = [cp, u64, cp, u64]

        from tpumr.utils.nativelib import load_native_lib
        return load_native_lib("tlz", "libtlz.so", configure)

    @classmethod
    def available(cls) -> bool:
        return cls._lib() is not None

    def compress(self, data: bytes) -> bytes:
        import ctypes
        lib = self._lib()
        if lib is None:
            return self._py_store(data)
        cap = lib.tlz_bound(len(data))
        out = ctypes.create_string_buffer(cap)
        n = lib.tlz_compress(data, len(data), out, cap)
        if n < 0:
            raise RuntimeError("tlz compression failed")
        return ctypes.string_at(out, n)   # single copy on the hot path

    def decompress(self, data: bytes) -> bytes:
        import ctypes
        lib = self._lib()
        if lib is None:
            return self._py_decompress(data)
        raw = lib.tlz_raw_size(data, len(data))
        if raw < 0:
            raise ValueError("corrupt tlz frame (bad header)")
        # the length word is untrusted frame data: bound it by the
        # format's maximum expansion (a ver-1 sequence emits at most
        # 255 bytes/input byte via extension runs; stored is 1:1)
        # before letting it size an allocation
        body = len(data) - 12
        if raw > max(0, body) * (1 if data[3:4] == b"0" else 255):
            raise ValueError("corrupt tlz frame (implausible length)")
        out = ctypes.create_string_buffer(raw if raw else 1)
        n = lib.tlz_decompress(data, len(data), out, raw)
        if n != raw:
            raise ValueError("corrupt tlz frame (payload)")
        return ctypes.string_at(out, raw)


_REGISTRY: dict[str, type[CompressionCodec]] = {
    "none": NullCodec,
    "zlib": ZlibCodec,
    "default": ZlibCodec,
    "gzip": GzipCodec,
    "bzip2": Bzip2Codec,
    "lzma": LzmaCodec,
    "tlz": TlzCodec,
}

try:  # optional, mirrors the reference's build-time snappy gate
    import snappy as _snappy  # type: ignore

    class SnappyCodec(CompressionCodec):
        name = "snappy"
        extension = ".snappy"

        def compress(self, data: bytes) -> bytes:
            return _snappy.compress(data)

        def decompress(self, data: bytes) -> bytes:
            return _snappy.decompress(data)

    _REGISTRY["snappy"] = SnappyCodec
except ImportError:
    pass


def get_codec(name: str | None) -> CompressionCodec:
    if not name:
        return NullCodec()
    cls = _REGISTRY.get(name.lower())
    if cls is None:
        raise ValueError(f"unknown codec {name!r}; have {sorted(_REGISTRY)}")
    return cls()


def wire_codec_or_none(name: "str | None") -> str:
    """Resolve a configured shuffle wire codec to one THIS process can
    run at native speed, else 'none'. The wire codec is a transport
    optimization, never a format commitment: a copier without the
    native tlz library must not request tlz frames it can only
    store-decode (the pure-python fallback handles stored frames, not
    compressed blocks), so unavailable codecs silently degrade to an
    uncompressed wire rather than failing fetches."""
    if not name or name == "none":
        return "none"
    cls = _REGISTRY.get(name.lower())
    if cls is None:
        return "none"
    avail = getattr(cls, "available", None)
    if callable(avail) and not avail():
        return "none"
    return name.lower()


#: the tiniest chunk worth a compression attempt: below this the codec
#: frame overhead eats the win and the CPU is pure waste
WIRE_MIN_BYTES = 1024


def wire_compress(out: dict, wire: str) -> None:
    """Compress one served chunk's payload bytes for the wire, in
    place, when it pays: the client OFFERED a codec, the payload itself
    is uncompressed (re-compressing zlib'd bytes only burns CPU), and
    the result actually shrank (pre-compressed/random data rides raw —
    the response omits ``wire`` and the client skips the decode).
    Shared by the shuffle server and the datanode block read path;
    any size field the caller set stays payload-relative whatever the
    wire carried."""
    if (not wire or wire == "none" or out.get("codec", "none") != "none"
            or len(out["data"]) < WIRE_MIN_BYTES):
        return
    try:
        comp = get_codec(wire).compress(bytes(out["data"]))
    except Exception:  # noqa: BLE001 — wire codec is best-effort
        return
    if len(comp) < len(out["data"]):
        out["wire"] = wire
        out["data"] = comp


def codec_for_path(path: str) -> CompressionCodec | None:
    """Pick a codec by file extension (≈ CompressionCodecFactory)."""
    for cls in _REGISTRY.values():
        if cls.extension and path.endswith(cls.extension):
            return cls()
    return None


def register_codec(cls: type[CompressionCodec]) -> None:
    _REGISTRY[cls.name] = cls
