"""IFile — the map-output spill format.

≈ ``org.apache.hadoop.mapred.IFile`` + ``SpillRecord`` (reference:
src/mapred/org/apache/hadoop/mapred/{IFile,SpillRecord,Merger}.java): sorted
key/value runs written per partition, addressed by an index of
(offset, raw_length, compressed_length) triples so the shuffle server can
serve one partition's segment without parsing the rest. Segments are
optionally zlib-compressed as whole blocks (the reference compresses the
record stream; whole-segment blocks are simpler and favour the batch-centric
TPU data path).
"""

from __future__ import annotations

import heapq
import struct
from dataclasses import dataclass
from io import BytesIO
from typing import Any, BinaryIO, Callable, Iterable, Iterator

from tpumr.io.compress import get_codec
from tpumr.io.writable import read_vint, write_vint

MAGIC = b"TIFL"


@dataclass
class IndexEntry:
    """≈ IndexRecord (SpillRecord.java): one partition's segment extent."""
    offset: int
    raw_length: int
    part_length: int  # bytes on disk (compressed)


class Writer:
    """Writes one spill file: partitions in order, each a block of sorted
    records. Call ``start_partition`` / ``append`` / ``end_partition``."""

    def __init__(self, stream: BinaryIO, codec: str = "none") -> None:
        self._out = stream
        self._codec = get_codec(codec)
        self._codec_name = self._codec.name
        self._out.write(MAGIC)
        self._pos = len(MAGIC)
        self.index: list[IndexEntry] = []
        self._buf: BytesIO | None = None
        self._nrec = 0

    def start_partition(self) -> None:
        assert self._buf is None, "partition already open"
        self._buf = BytesIO()
        self._nrec = 0

    def append_raw(self, kbytes: bytes, vbytes: bytes) -> None:
        assert self._buf is not None, "start_partition first"
        write_vint(self._buf, len(kbytes))
        self._buf.write(kbytes)
        write_vint(self._buf, len(vbytes))
        self._buf.write(vbytes)
        self._nrec += 1

    def end_partition(self) -> None:
        assert self._buf is not None
        head = BytesIO()
        write_vint(head, self._nrec)
        raw = head.getvalue() + self._buf.getvalue()
        payload = self._codec.compress(raw)
        self._out.write(struct.pack(">I", len(payload)))
        self._out.write(payload)
        self.index.append(IndexEntry(self._pos, len(raw), len(payload) + 4))
        self._pos += len(payload) + 4
        self._buf = None

    def close(self) -> dict:
        """Flush and return the index blob (serializable spill record)."""
        self._out.flush()
        return {
            "codec": self._codec_name,
            "partitions": [(e.offset, e.raw_length, e.part_length) for e in self.index],
        }


def write_index(stream: BinaryIO, index: dict) -> None:
    from tpumr.io.writable import serialize
    serialize(index, stream)


def read_index(stream: BinaryIO) -> dict:
    from tpumr.io.writable import deserialize
    return deserialize(stream)


def read_partition(stream: BinaryIO, index: dict,
                   partition: int) -> Iterator[tuple[bytes, bytes]]:
    """Read one partition's records from a spill file given its index."""
    off, raw_len, part_len = index["partitions"][partition]
    stream.seek(off)
    (plen,) = struct.unpack(">I", stream.read(4))
    payload = stream.read(plen)
    codec = get_codec(index.get("codec", "none"))
    return iter_segment(codec.decompress(payload))


def partition_bytes(stream: BinaryIO, index: dict, partition: int) -> bytes:
    """Raw on-disk segment bytes for shuffle transfer (length-prefixed,
    compressed) — served verbatim by the shuffle server."""
    off, _raw, part_len = index["partitions"][partition]
    stream.seek(off)
    return stream.read(part_len)


def iter_segment(raw: bytes) -> Iterator[tuple[bytes, bytes]]:
    buf = BytesIO(raw)
    n = read_vint(buf)
    for _ in range(n):
        klen = read_vint(buf)
        k = buf.read(klen)
        vlen = read_vint(buf)
        v = buf.read(vlen)
        yield k, v


def iter_transferred_segment(data: bytes, codec: str) -> Iterator[tuple[bytes, bytes]]:
    """Decode a segment as produced by :func:`partition_bytes`."""
    (plen,) = struct.unpack(">I", data[:4])
    return iter_segment(get_codec(codec).decompress(data[4: 4 + plen]))


class _ChunkStream:
    """File-like .read(n) over an iterator of byte chunks, decompressing
    incrementally — the memory-bounded half of the shuffle/merge path:
    at most one transfer chunk plus the decompressor's window is resident
    at a time, never the whole raw segment."""

    def __init__(self, chunks: Iterable[bytes], codec: str) -> None:
        self._chunks = iter(chunks)
        self._dec = get_codec(codec).decompressor()
        self._buf = bytearray()
        self._eof = False

    def _fill(self, n: int) -> None:
        while len(self._buf) < n and not self._eof:
            try:
                piece = next(self._chunks)
            except StopIteration:
                self._buf.extend(self._dec.flush())
                self._eof = True
                return
            self._buf.extend(self._dec.feed(piece))

    def read(self, n: int) -> bytes:
        self._fill(n)
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out


def iter_chunked_segment(chunks: Iterable[bytes],
                         codec: str) -> Iterator[tuple[bytes, bytes]]:
    """Iterate records of one partition segment streamed as COMPRESSED
    payload chunks (no length prefix) without materializing the raw
    block — the DiskSegment / streamed-shuffle read path."""
    stream = _ChunkStream(chunks, codec)
    n = read_vint(stream)
    for _ in range(n):
        klen = read_vint(stream)
        k = stream.read(klen)
        vlen = read_vint(stream)
        v = stream.read(vlen)
        if len(k) != klen or len(v) != vlen:
            raise EOFError("truncated segment stream")
        yield k, v


def file_region_chunks(path: str, offset: int, length: int,
                       chunk_bytes: int = 1 << 18) -> Iterator[bytes]:
    """Stream a byte region of a local file in bounded chunks (the
    spill-file read half of the streaming shuffle). Opens the file PER
    CHUNK instead of holding it across yields: the k-way merge keeps one
    iterator live per map output simultaneously, and a reduce over ~1024
    maps would otherwise exhaust the process fd limit mid-merge."""
    pos = offset
    remaining = length
    while remaining > 0:
        with open(path, "rb") as f:
            f.seek(pos)
            piece = f.read(min(chunk_bytes, remaining))
        if not piece:
            raise EOFError(f"truncated spill file {path}")
        pos += len(piece)
        remaining -= len(piece)
        yield piece


def merge_sorted(segments: "list[Iterable[tuple[bytes, bytes]]]",
                 sort_key: Callable[[bytes], Any]) -> Iterator[tuple[bytes, bytes]]:
    """K-way merge of sorted (key,value) streams ≈ Merger.merge
    (mapred/Merger.java). ``sort_key`` maps raw key bytes to the comparable
    used for ordering (the RawComparator seam).

    heapq.merge's ``key=`` path skips the per-segment decorating
    generator layer the old implementation interposed (one Python frame
    per record per segment — ~30% of merge time) and is stable across
    input order, preserving the segment-order tiebreak the reference's
    merge relies on."""
    return heapq.merge(*segments, key=lambda kv: sort_key(kv[0]))
