"""IFile — the map-output spill format.

≈ ``org.apache.hadoop.mapred.IFile`` + ``SpillRecord`` (reference:
src/mapred/org/apache/hadoop/mapred/{IFile,SpillRecord,Merger}.java): sorted
key/value runs written per partition, addressed by an index of
(offset, raw_length, compressed_length) triples so the shuffle server can
serve one partition's segment without parsing the rest. Segments are
optionally zlib-compressed as whole blocks (the reference compresses the
record stream; whole-segment blocks are simpler and favour the batch-centric
TPU data path).
"""

from __future__ import annotations

import heapq
import struct
from dataclasses import dataclass
from io import BytesIO
from operator import itemgetter
from typing import Any, BinaryIO, Callable, Iterable, Iterator

from tpumr.io.compress import get_codec
from tpumr.io.writable import write_vint

MAGIC = b"TIFL"


@dataclass
class IndexEntry:
    """≈ IndexRecord (SpillRecord.java): one partition's segment extent."""
    offset: int
    raw_length: int
    part_length: int  # bytes on disk (compressed)


class Writer:
    """Writes one spill file: partitions in order, each a block of sorted
    records. Call ``start_partition`` / ``append`` / ``end_partition``."""

    def __init__(self, stream: BinaryIO, codec: str = "none") -> None:
        self._out = stream
        self._codec = get_codec(codec)
        self._codec_name = self._codec.name
        self._out.write(MAGIC)
        self._pos = len(MAGIC)
        self.index: list[IndexEntry] = []
        self._buf: BytesIO | None = None
        self._nrec = 0

    def start_partition(self) -> None:
        assert self._buf is None, "partition already open"
        self._buf = BytesIO()
        self._nrec = 0

    def append_raw(self, kbytes: bytes, vbytes: bytes) -> None:
        assert self._buf is not None, "start_partition first"
        write_vint(self._buf, len(kbytes))
        self._buf.write(kbytes)
        write_vint(self._buf, len(vbytes))
        self._buf.write(vbytes)
        self._nrec += 1

    def end_partition(self) -> None:
        assert self._buf is not None
        head = BytesIO()
        write_vint(head, self._nrec)
        raw = head.getvalue() + self._buf.getvalue()
        payload = self._codec.compress(raw)
        self._out.write(struct.pack(">I", len(payload)))
        self._out.write(payload)
        self.index.append(IndexEntry(self._pos, len(raw), len(payload) + 4))
        self._pos += len(payload) + 4
        self._buf = None

    def close(self) -> dict:
        """Flush and return the index blob (serializable spill record)."""
        self._out.flush()
        return {
            "codec": self._codec_name,
            "partitions": [(e.offset, e.raw_length, e.part_length) for e in self.index],
        }


def write_index(stream: BinaryIO, index: dict) -> None:
    from tpumr.io.writable import serialize
    serialize(index, stream)


def read_index(stream: BinaryIO) -> dict:
    from tpumr.io.writable import deserialize
    return deserialize(stream)


def read_partition(stream: BinaryIO, index: dict,
                   partition: int) -> Iterator[tuple[bytes, bytes]]:
    """Read one partition's records from a spill file given its index."""
    off, raw_len, part_len = index["partitions"][partition]
    stream.seek(off)
    (plen,) = struct.unpack(">I", stream.read(4))
    payload = stream.read(plen)
    codec = get_codec(index.get("codec", "none"))
    return iter_segment(codec.decompress(payload))


def partition_bytes(stream: BinaryIO, index: dict, partition: int) -> bytes:
    """Raw on-disk segment bytes for shuffle transfer (length-prefixed,
    compressed) — served verbatim by the shuffle server."""
    off, _raw, part_len = index["partitions"][partition]
    stream.seek(off)
    return stream.read(part_len)


def _vint_at(buf: bytes, pos: int) -> tuple[int, int]:
    """Decode one LEB128 vint at ``pos`` by index arithmetic — the
    merge/spill paths parse one vint per field per record, and the
    BytesIO ``read(1)``-per-byte decoder (method call + bytes alloc per
    byte) was the hottest line of the disk merge under profile."""
    shift = 0
    result = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def iter_segment(raw: bytes) -> Iterator[tuple[bytes, bytes]]:
    try:
        pos = 0
        n, pos = _vint_at(raw, pos)
        for _ in range(n):
            klen, pos = _vint_at(raw, pos)
            k = raw[pos:pos + klen]
            pos += klen
            vlen, pos = _vint_at(raw, pos)
            v = raw[pos:pos + vlen]
            pos += vlen
            if len(v) != vlen:
                raise EOFError("truncated segment")
            yield k, v
    except IndexError:
        raise EOFError("truncated segment") from None


def iter_transferred_segment(data: bytes, codec: str) -> Iterator[tuple[bytes, bytes]]:
    """Decode a segment as produced by :func:`partition_bytes`."""
    (plen,) = struct.unpack(">I", data[:4])
    return iter_segment(get_codec(codec).decompress(data[4: 4 + plen]))


def iter_chunked_segment(chunks: Iterable[bytes],
                         codec: str) -> Iterator[tuple[bytes, bytes]]:
    """Iterate records of one partition segment streamed as COMPRESSED
    payload chunks (no length prefix) without materializing the raw
    block — the DiskSegment / streamed-shuffle read path. Memory-bounded:
    at most one transfer chunk's decompressed output (plus a straddling
    record's tail) is resident at a time, never the whole raw segment.

    Records parse by index arithmetic over the current buffer (see
    :func:`_vint_at`) instead of a file-like ``read(n)`` per field —
    the k-way merge calls this once per record per disk segment, and
    the method-call framing was ~2× the parse cost."""
    dec = get_codec(codec).decompressor()
    it = iter(chunks)
    buf = b""
    pos = 0
    eof = False

    def ensure(need: int) -> None:
        """Grow ``buf`` until ``need`` bytes remain past ``pos``."""
        nonlocal buf, pos, eof
        while len(buf) - pos < need:
            if eof:
                raise EOFError("truncated segment stream")
            try:
                piece = next(it)
            except StopIteration:
                eof = True
                piece = None
            out = dec.flush() if piece is None else dec.feed(piece)
            if out:
                buf = buf[pos:] + out
                pos = 0

    def vint() -> int:
        nonlocal pos
        shift = 0
        result = 0
        while True:
            if pos >= len(buf):
                ensure(1)
            b = buf[pos]
            pos += 1
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result
            shift += 7

    n = vint()
    for _ in range(n):
        klen = vint()
        ensure(klen)
        k = buf[pos:pos + klen]
        pos += klen
        vlen = vint()
        ensure(vlen)
        v = buf[pos:pos + vlen]
        pos += vlen
        yield k, v


def file_region_chunks(path: str, offset: int, length: int,
                       chunk_bytes: int = 1 << 18) -> Iterator[bytes]:
    """Stream a byte region of a local file in bounded chunks (the
    spill-file read half of the streaming shuffle). Opens the file PER
    CHUNK instead of holding it across yields: the k-way merge keeps one
    iterator live per map output simultaneously, and a reduce over ~1024
    maps would otherwise exhaust the process fd limit mid-merge."""
    pos = offset
    remaining = length
    while remaining > 0:
        with open(path, "rb") as f:
            f.seek(pos)
            piece = f.read(min(chunk_bytes, remaining))
        if not piece:
            raise EOFError(f"truncated spill file {path}")
        pos += len(piece)
        remaining -= len(piece)
        yield piece


#: C-implemented key extractor for the raw-key fast path: no Python
#: frame per comparison, unlike a ``lambda kv: sort_key(kv[0])`` closure
_KEY0 = itemgetter(0)

#: two distinct probe keys for :func:`is_raw_sort_key` — identity must
#: hold on BOTH (a function returning one fixed object would pass one)
_PROBE_A = b"\x00\xff tpumr-raw-probe"
_PROBE_B = b"z"


def is_raw_sort_key(sort_key: "Callable[[bytes], Any] | None") -> bool:
    """True when ``sort_key`` orders raw key bytes AS raw key bytes —
    the RawComparator case (``sort_key(k) is k``), probed with two
    sentinel keys so the merge can drop the per-comparison key-fn call
    entirely. ``None`` means raw by convention."""
    if sort_key is None:
        return True
    try:
        return (sort_key(_PROBE_A) is _PROBE_A
                and sort_key(_PROBE_B) is _PROBE_B)
    except Exception:  # noqa: BLE001 — a picky comparator is not raw
        return False


def _merge_two_raw(a: "Iterator[tuple[bytes, bytes]]",
                   b: "Iterator[tuple[bytes, bytes]]"
                   ) -> Iterator[tuple[bytes, bytes]]:
    """Dedicated two-stream raw-key merge: one bytes comparison per
    record, no heap. Equal keys drain from ``a`` first — the same
    segment-order tiebreak heapq.merge guarantees, so the two paths are
    byte-identical. Two segments is the dominant shape on the map side
    (one prior spill + the final buffer) and in merge-pass tails."""
    try:
        ka, va = next(a)
    except StopIteration:
        yield from b
        return
    try:
        kb, vb = next(b)
    except StopIteration:
        yield ka, va
        yield from a
        return
    while True:
        if ka <= kb:
            yield ka, va
            try:
                ka, va = next(a)
            except StopIteration:
                yield kb, vb
                yield from b
                return
        else:
            yield kb, vb
            try:
                kb, vb = next(b)
            except StopIteration:
                yield ka, va
                yield from a
                return


def merge_sorted(segments: "list[Iterable[tuple[bytes, bytes]]]",
                 sort_key: "Callable[[bytes], Any] | None"
                 ) -> Iterator[tuple[bytes, bytes]]:
    """K-way merge of sorted (key,value) streams ≈ Merger.merge
    (mapred/Merger.java). ``sort_key`` maps raw key bytes to the comparable
    used for ordering (the RawComparator seam).

    heapq.merge's ``key=`` path skips the per-segment decorating
    generator layer the old implementation interposed (one Python frame
    per record per segment — ~30% of merge time) and is stable across
    input order, preserving the segment-order tiebreak the reference's
    merge relies on.

    Raw-key fast path: when ``sort_key`` is the identity on bytes (the
    RawComparator case, detected by :func:`is_raw_sort_key`), the merge
    compares raw key bytes directly — ``itemgetter(0)`` instead of a
    Python-level closure, and a dedicated two-stream loop for the
    two-segment shape. All paths keep the same equal-key tiebreak
    (earlier segment first), so they are byte-interchangeable."""
    if not segments:
        return iter(())
    if len(segments) == 1:
        return iter(segments[0])
    if is_raw_sort_key(sort_key):
        if len(segments) == 2:
            return _merge_two_raw(iter(segments[0]), iter(segments[1]))
        return heapq.merge(*segments, key=_KEY0)
    return heapq.merge(*segments, key=lambda kv: sort_key(kv[0]))


def merge_sorted_inmem(segments: "list[Iterable[tuple[bytes, bytes]]]",
                       sort_key: "Callable[[bytes], Any] | None"
                       ) -> "list[tuple[bytes, bytes]]":
    """MATERIALIZED merge for segments already resident in memory (the
    background shuffle merger's kernel): chain the sorted runs and let
    Timsort's run detection + galloping merge them at C speed — ~2× the
    lazy heap merge, at the cost of holding the record list. Callers
    must bound the input; the shuffle merge manager's batches are
    bounded by the ShuffleRamManager budget by construction. The sort
    is stable, so equal-key order (segment order) is byte-identical to
    :func:`merge_sorted`."""
    from itertools import chain
    records = list(chain.from_iterable(segments))
    if is_raw_sort_key(sort_key):
        records.sort(key=_KEY0)
    else:
        records.sort(key=lambda kv: sort_key(kv[0]))
    return records
