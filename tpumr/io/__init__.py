from tpumr.io.writable import (
    write_vint, read_vint, encode_kv, decode_kv,
    serialize, deserialize, RawBytesComparator,
)
from tpumr.io.recordbatch import RecordBatch, DenseBatch

__all__ = [
    "write_vint", "read_vint", "encode_kv", "decode_kv",
    "serialize", "deserialize", "RawBytesComparator",
    "RecordBatch", "DenseBatch",
]
