"""Binary record serialization.

TPU-era stand-in for the reference's ``Writable`` machinery
(src/core/org/apache/hadoop/io/ — IntWritable, LongWritable, Text,
BytesWritable, WritableComparator…): a compact self-describing binary codec
for the Python value types jobs exchange, plus raw byte-wise comparators for
sort order (≈ WritableComparator.compareBytes). Unlike the reference we do
NOT serialize per record on the device path — device jobs use
``tpumr.io.recordbatch`` columnar batches; this codec is for container files,
shuffle frames and RPC payloads.
"""

from __future__ import annotations

import struct
from io import BytesIO
from typing import Any, BinaryIO

import numpy as np

# ------------------------------------------------------------------ varints
# Unsigned LEB128 (different encoding than WritableUtils.writeVInt, same role)


def _vint_bytes(value: int) -> bytes:
    if value < 0x80:
        return bytes((value,))
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def write_vint(out: BinaryIO, value: int) -> None:
    if value < 0:
        raise ValueError("write_vint takes unsigned values; use zigzag first")
    out.write(_vint_bytes(value))  # single encoder, single write() call


def read_vint(inp: BinaryIO) -> int:
    shift = 0
    result = 0
    while True:
        raw = inp.read(1)
        if not raw:
            raise EOFError("EOF inside vint")
        b = raw[0]
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result
        shift += 7


def zigzag(v: int) -> int:
    return (v << 1) if v >= 0 else ((-v) << 1) - 1


def unzigzag(v: int) -> int:
    return (v >> 1) if not v & 1 else -((v + 1) >> 1)


# ------------------------------------------------------------------ typed codec

_T_NULL = 0
_T_BYTES = 1
_T_TEXT = 2
_T_INT = 3      # zigzag varint
_T_FLOAT = 4    # float64 BE
_T_BOOL_T = 5
_T_BOOL_F = 6
_T_LIST = 7
_T_NDARRAY = 8  # dtype-str, shape, raw bytes
_T_DICT = 9

#: one source of truth for the fast-path frames
_TAG_BYTES = bytes((_T_BYTES,))
_TAG_TEXT = bytes((_T_TEXT,))
_TAG_INT = bytes((_T_INT,))


def serialize(obj: Any, out: BinaryIO | None = None) -> bytes | None:
    """Encode a value to the typed binary format. The exact-type fast
    paths matter: this runs twice per record on the host map path (key +
    value), and a BytesIO round-trip per call is profiling-visible.
    ``type() is`` (not isinstance) so bool/np subtypes still take the
    fully-general _write path."""
    if out is None:
        t = type(obj)
        if t is bytes:
            return _TAG_BYTES + _vint_bytes(len(obj)) + obj
        if t is str:
            b = obj.encode("utf-8")
            return _TAG_TEXT + _vint_bytes(len(b)) + b
        if t is int:
            return _TAG_INT + _vint_bytes(zigzag(obj))
        # container path: encode into ONE bytearray (append/extend are
        # the cheapest byte sinks CPython has) instead of a BytesIO with
        # a bytes((tag,)) allocation per element — RPC envelopes are
        # dicts of ~40 small values and this runs per request/response
        # on every heartbeat of every tracker. Byte-identical to _write.
        buf = bytearray()
        _enc(buf, obj)
        return bytes(buf)
    _write(out, obj)
    return None


def _vint_into(buf: bytearray, value: int) -> None:
    if value < 0x80:
        buf.append(value)
        return
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            buf.append(b | 0x80)
        else:
            buf.append(b)
            return


def _enc(buf: bytearray, obj: Any) -> None:
    """bytearray twin of :func:`_write` for the common value types
    (exact-type dispatch; np scalars/arrays and subclasses fall back to
    the general path through a one-element BytesIO round trip)."""
    t = type(obj)
    if t is str:
        b = obj.encode("utf-8")
        buf.append(_T_TEXT)
        _vint_into(buf, len(b))
        buf += b
    elif t is int:
        buf.append(_T_INT)
        _vint_into(buf, zigzag(obj))
    elif t is dict:
        buf.append(_T_DICT)
        _vint_into(buf, len(obj))
        for k, v in obj.items():
            _enc(buf, k)
            _enc(buf, v)
    elif t is bool:
        buf.append(_T_BOOL_T if obj else _T_BOOL_F)
    elif obj is None:
        buf.append(_T_NULL)
    elif t is float:
        buf.append(_T_FLOAT)
        buf += struct.pack(">d", obj)
    elif t is list or t is tuple:
        buf.append(_T_LIST)
        _vint_into(buf, len(obj))
        for item in obj:
            _enc(buf, item)
    elif t is bytes:
        buf.append(_T_BYTES)
        _vint_into(buf, len(obj))
        buf += obj
    else:
        tmp = BytesIO()
        _write(tmp, obj)
        buf += tmp.getvalue()


def _write(out: BinaryIO, obj: Any) -> None:
    if obj is None:
        out.write(bytes((_T_NULL,)))
    elif isinstance(obj, bool):
        out.write(bytes((_T_BOOL_T if obj else _T_BOOL_F,)))
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        out.write(bytes((_T_BYTES,)))
        write_vint(out, len(b))
        out.write(b)
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out.write(bytes((_T_TEXT,)))
        write_vint(out, len(b))
        out.write(b)
    elif isinstance(obj, (int, np.integer)):
        out.write(bytes((_T_INT,)))
        write_vint(out, zigzag(int(obj)))
    elif isinstance(obj, (float, np.floating)):
        out.write(bytes((_T_FLOAT,)))
        out.write(struct.pack(">d", float(obj)))
    elif isinstance(obj, np.ndarray):
        out.write(bytes((_T_NDARRAY,)))
        dt = obj.dtype.str.encode()
        write_vint(out, len(dt))
        out.write(dt)
        write_vint(out, obj.ndim)
        for d in obj.shape:
            write_vint(out, d)
        raw = np.ascontiguousarray(obj).tobytes()
        write_vint(out, len(raw))
        out.write(raw)
    elif isinstance(obj, (list, tuple)):
        out.write(bytes((_T_LIST,)))
        write_vint(out, len(obj))
        for item in obj:
            _write(out, item)
    elif isinstance(obj, dict):
        out.write(bytes((_T_DICT,)))
        write_vint(out, len(obj))
        for k, v in obj.items():
            _write(out, k)
            _write(out, v)
    else:
        raise TypeError(f"unserializable type {type(obj)!r}")


def deserialize(data: "bytes | BinaryIO") -> Any:
    if isinstance(data, (bytes, bytearray)):
        # positional parser on the buffer — no BytesIO, no per-byte
        # read() calls (this runs once per record on the reduce path)
        try:
            return _read_at(data, 0)[0]
        except IndexError:
            # keep the stream path's error contract for corrupt input
            raise EOFError("truncated value buffer") from None
    return _read(data)


def _vint_at(d: "bytes", pos: int) -> "tuple[int, int]":
    shift = 0
    result = 0
    while True:
        b = d[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _read_at(d: "bytes", pos: int) -> "tuple[Any, int]":
    tag = d[pos]
    pos += 1
    if tag == _T_BYTES:
        n, pos = _vint_at(d, pos)
        return bytes(d[pos:pos + n]), pos + n
    if tag == _T_TEXT:
        n, pos = _vint_at(d, pos)
        return bytes(d[pos:pos + n]).decode("utf-8"), pos + n
    if tag == _T_INT:
        v, pos = _vint_at(d, pos)
        return unzigzag(v), pos
    if tag == _T_NULL:
        return None, pos
    if tag == _T_BOOL_T:
        return True, pos
    if tag == _T_BOOL_F:
        return False, pos
    if tag == _T_FLOAT:
        return struct.unpack_from(">d", d, pos)[0], pos + 8
    if tag == _T_NDARRAY:
        n, pos = _vint_at(d, pos)
        dt = np.dtype(bytes(d[pos:pos + n]).decode())
        pos += n
        ndim, pos = _vint_at(d, pos)
        shape = []
        for _ in range(ndim):
            dim, pos = _vint_at(d, pos)
            shape.append(dim)
        nraw, pos = _vint_at(d, pos)
        arr = np.frombuffer(d, dtype=dt, count=-1 if not dt.itemsize else
                            nraw // dt.itemsize, offset=pos) \
            .reshape(tuple(shape)).copy()
        return arr, pos + nraw
    if tag == _T_LIST:
        n, pos = _vint_at(d, pos)
        out = []
        for _ in range(n):
            item, pos = _read_at(d, pos)
            out.append(item)
        return out, pos
    if tag == _T_DICT:
        n, pos = _vint_at(d, pos)
        res = {}
        for _ in range(n):
            k, pos = _read_at(d, pos)
            v, pos = _read_at(d, pos)
            res[k] = v
        return res, pos
    raise ValueError(f"bad type tag {tag}")


def _read(inp: BinaryIO) -> Any:
    raw = inp.read(1)
    if not raw:
        raise EOFError("EOF at value tag")
    tag = raw[0]
    if tag == _T_NULL:
        return None
    if tag == _T_BOOL_T:
        return True
    if tag == _T_BOOL_F:
        return False
    if tag == _T_BYTES:
        return inp.read(read_vint(inp))
    if tag == _T_TEXT:
        return inp.read(read_vint(inp)).decode("utf-8")
    if tag == _T_INT:
        return unzigzag(read_vint(inp))
    if tag == _T_FLOAT:
        return struct.unpack(">d", inp.read(8))[0]
    if tag == _T_NDARRAY:
        dt = np.dtype(inp.read(read_vint(inp)).decode())
        ndim = read_vint(inp)
        shape = tuple(read_vint(inp) for _ in range(ndim))
        raw_bytes = inp.read(read_vint(inp))
        return np.frombuffer(raw_bytes, dtype=dt).reshape(shape).copy()
    if tag == _T_LIST:
        return [_read(inp) for _ in range(read_vint(inp))]
    if tag == _T_DICT:
        n = read_vint(inp)
        return {_read(inp): _read(inp) for _ in range(n)}
    raise ValueError(f"bad type tag {tag}")


# ------------------------------------------------------------------ kv frames


def encode_kv(key: Any, value: Any) -> tuple[bytes, bytes]:
    """Serialize a key/value pair to raw bytes (sortable for keys via
    RawBytesComparator when keys share a type)."""
    return serialize(key), serialize(value)  # type: ignore[return-value]


def decode_kv(kbytes: bytes, vbytes: bytes) -> tuple[Any, Any]:
    return deserialize(kbytes), deserialize(vbytes)


class RawBytesComparator:
    """Byte-wise lexicographic comparator ≈ WritableComparator.compareBytes
    (src/core/org/apache/hadoop/io/WritableComparator.java). Python bytes
    compare lexicographically natively; this class exists as the SPI seam for
    custom raw comparators (JobConf.setOutputKeyComparatorClass)."""

    def compare(self, a: bytes, b: bytes) -> int:
        return (a > b) - (a < b)

    def sort_key(self, a: bytes) -> Any:
        """Key-extractor form used by Python sorts."""
        return a
