"""Columnar record batches — the device-facing record format.

This is the core data-format departure from the reference: where Hadoop's
pipes path streams one Writable record at a time over a socket to the GPU
process (per-record hot loop, mapred/pipes/PipesGPUMapRunner.java:97-107 →
BinaryProtocol MAP_ITEM), the TPU build stages an entire InputSplit into HBM
as a small set of dense arrays and runs the mapper as one XLA/Pallas program.

Two shapes of batch:

- :class:`RecordBatch` — variable-length byte records (text lines, terasort
  rows…): one flat ``uint8`` data array + ``int32`` offset arrays per column.
  Device kernels consume either the flat+offsets form or a padded
  ``[n, width] uint8`` view (fixed width ⇒ static shapes for XLA).
- :class:`DenseBatch` — numeric records (K-Means points, matmul blocks):
  a dense ``[n, d]`` array, MXU-ready.

Both are host-side numpy containers; ``tpumr.mapred.tpu_runner`` is what
moves them into HBM.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Iterator

import numpy as np


def _build_offsets(items: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
    offsets = np.zeros(len(items) + 1, dtype=np.int32)
    if items:
        np.cumsum([len(b) for b in items], out=offsets[1:])
    data = np.frombuffer(b"".join(items), dtype=np.uint8).copy()
    return data, offsets


@dataclass
class RecordBatch:
    """Variable-length byte records as flat data + offsets columns."""

    key_data: np.ndarray            # uint8 [total_key_bytes]
    key_offsets: np.ndarray         # int32 [n+1]
    value_data: np.ndarray          # uint8 [total_value_bytes]
    value_offsets: np.ndarray       # int32 [n+1]

    # ------------------------------------------------------------ construct

    @classmethod
    def from_pairs(cls, pairs: Iterable[tuple[bytes, bytes]]) -> "RecordBatch":
        keys, values = [], []
        for k, v in pairs:
            keys.append(bytes(k))
            values.append(bytes(v))
        kd, ko = _build_offsets(keys)
        vd, vo = _build_offsets(values)
        return cls(kd, ko, vd, vo)

    @classmethod
    def from_values(cls, values: Iterable[bytes]) -> "RecordBatch":
        """Key-less batch (keys empty) — e.g. raw text lines."""
        vals = [bytes(v) for v in values]
        vd, vo = _build_offsets(vals)
        n = len(vals)
        return cls(np.zeros(0, np.uint8), np.zeros(n + 1, np.int32), vd, vo)

    @classmethod
    def empty(cls) -> "RecordBatch":
        z = np.zeros(0, np.uint8)
        o = np.zeros(1, np.int32)
        return cls(z, o.copy(), z.copy(), o.copy())

    # ------------------------------------------------------------ inspect

    @property
    def num_records(self) -> int:
        return len(self.key_offsets) - 1

    def __len__(self) -> int:
        return self.num_records

    def key(self, i: int) -> bytes:
        return self.key_data[self.key_offsets[i]: self.key_offsets[i + 1]].tobytes()

    def value(self, i: int) -> bytes:
        return self.value_data[self.value_offsets[i]: self.value_offsets[i + 1]].tobytes()

    def __iter__(self) -> Iterator[tuple[bytes, bytes]]:
        for i in range(self.num_records):
            yield self.key(i), self.value(i)

    def to_pairs(self) -> list[tuple[bytes, bytes]]:
        return list(self)

    @property
    def nbytes(self) -> int:
        return int(self.key_data.nbytes + self.value_data.nbytes
                   + self.key_offsets.nbytes + self.value_offsets.nbytes)

    @property
    def value_lengths(self) -> np.ndarray:
        return (self.value_offsets[1:] - self.value_offsets[:-1]).astype(np.int32)

    def joined_values(self, sep: int = 0x20) -> bytes:
        """All values as one buffer with ``sep`` between records — the
        whole-split view for kernels that scan bytes (tokenizers, regex):
        one C-level ``np.insert`` instead of per-record Python or an
        O(total) int64 scatter."""
        n = self.num_records
        if n == 0:
            return b""
        return np.insert(self.value_data,
                         self.value_offsets[1:-1].astype(np.int64),
                         sep).tobytes()

    # ------------------------------------------------------------ device views

    def padded_values(self, width: int, fill: int = 0) -> tuple[np.ndarray, np.ndarray]:
        """Return ``([n, width] uint8, [n] int32 lengths)`` — the static-shape
        view device kernels consume. Records longer than ``width`` are
        truncated (callers pick width ≥ max length when loss matters)."""
        return _pad(self.value_data, self.value_offsets, width, fill)

    def padded_keys(self, width: int, fill: int = 0) -> tuple[np.ndarray, np.ndarray]:
        return _pad(self.key_data, self.key_offsets, width, fill)

    # ------------------------------------------------------------ combine

    @classmethod
    def concat(cls, batches: "list[RecordBatch]") -> "RecordBatch":
        if not batches:
            return cls.empty()
        kd = np.concatenate([b.key_data for b in batches])
        vd = np.concatenate([b.value_data for b in batches])

        def cat_offsets(offs: list[np.ndarray]) -> np.ndarray:
            out = [offs[0]]
            base = int(offs[0][-1])
            for o in offs[1:]:
                out.append(o[1:] + base)
                base += int(o[-1])
            return np.concatenate(out).astype(np.int32)

        return cls(kd, cat_offsets([b.key_offsets for b in batches]),
                   vd, cat_offsets([b.value_offsets for b in batches]))

    def slice(self, start: int, stop: int) -> "RecordBatch":
        ko = self.key_offsets[start: stop + 1]
        vo = self.value_offsets[start: stop + 1]
        return RecordBatch(
            self.key_data[ko[0]: ko[-1]].copy(), (ko - ko[0]).astype(np.int32),
            self.value_data[vo[0]: vo[-1]].copy(), (vo - vo[0]).astype(np.int32),
        )


def _pad(data: np.ndarray, offsets: np.ndarray, width: int,
         fill: int) -> tuple[np.ndarray, np.ndarray]:
    n = len(offsets) - 1
    lengths = (offsets[1:] - offsets[:-1]).astype(np.int32)
    if n and data.shape[0] == n * width and (lengths == width).all():
        # fixed-width records: the padded view IS a reshape (no copy)
        return data.reshape(n, width), lengths
    out = np.full((n, width), fill, dtype=np.uint8)
    # vectorized gather: for each row, take min(len, width) bytes
    take = np.minimum(lengths, width)
    # build flat source indices
    row_idx = np.repeat(np.arange(n), take)
    col_idx = np.concatenate([np.arange(t) for t in take]) if n else np.zeros(0, np.int64)
    src_idx = np.repeat(offsets[:-1], take) + col_idx
    out[row_idx, col_idx] = data[src_idx]
    return out, lengths


@dataclass
class DenseBatch:
    """Dense numeric records ``[n, d]`` (+ optional int64 record ids).

    The K-Means / matmul / pi map path: what the reference shipped to a CUDA
    binary one text line at a time (NLineInputFormat, conf/mapred-site.xml:
    14-21 pins 1 line per map), we ship as one MXU-friendly array.
    """

    values: np.ndarray                       # [n, d] float32/bf16/…
    ids: np.ndarray | None = None            # [n] int64 record ids
    meta: dict = field(default_factory=dict)

    @property
    def num_records(self) -> int:
        return int(self.values.shape[0])

    def __len__(self) -> int:
        return self.num_records

    @property
    def nbytes(self) -> int:
        return int(self.values.nbytes + (self.ids.nbytes if self.ids is not None else 0))

    @classmethod
    def concat(cls, batches: "list[DenseBatch]") -> "DenseBatch":
        if not batches:
            return cls(np.zeros((0, 0), np.float32))
        vals = np.concatenate([b.values for b in batches], axis=0)
        ids = None
        if all(b.ids is not None for b in batches):
            ids = np.concatenate([b.ids for b in batches])
        return cls(vals, ids)
