"""Blocked matrix-multiply map kernel.

≈ the reference's GPU-pipes matrix-multiply example job (external to the
tree; BASELINE.json config 4). Each map task owns a row-block of A (its
DenseSplit) and computes ``C_block = A_block @ B`` with B distributed as a
side file (the DistributedCache role). The matmul itself is handed to XLA —
a single ``jnp.dot`` already lowers to optimally-tiled MXU code, and
hand-scheduling it in Pallas would only match it (pallas_guide: don't
re-schedule what the compiler does well). bfloat16 inputs with float32
accumulation are the default on TPU.
"""

from __future__ import annotations

from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np

from tpumr.mapred.api import Mapper
from tpumr.ops.registry import KernelMapper, register_kernel

_b_cache: dict[str, np.ndarray] = {}


def _load_b(conf) -> np.ndarray:
    from tpumr.fs.filesystem import FileSystem
    from tpumr.mapred.input_formats import load_dense
    path = conf.get("tpumr.matmul.b")
    if not path:
        raise ValueError("tpumr.matmul.b not set (path to .npy of B)")
    cached = _b_cache.get(path)
    if cached is None:
        fs = FileSystem.get(path, conf)
        cached = _b_cache[path] = load_dense(fs, path)
    return cached


def clear_b_cache() -> None:
    from tpumr.ops.devcache import clear_device_cache
    _b_cache.clear()
    clear_device_cache("matmul-b:")


def _device_b(conf):
    """B as a DEVICE-resident array, uploaded once per (file, device):
    without this every map task re-shipped the full B (64 MB at 4096²)
    over the tunnel — the dominant term of the measured 0.2× device
    matmul row (see ops/devcache.py)."""
    from tpumr.ops.devcache import device_cached
    host = _load_b(conf)
    return device_cached(f"matmul-b:{conf.get('tpumr.matmul.b')}",
                         host, conf)


@jax.jit
def _matmul_bf16(a, b):
    return jnp.dot(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32)


@jax.jit
def _matmul_f32(a, b):
    return jnp.dot(a, b, preferred_element_type=jnp.float32)


def block_matmul(a, b, bf16: bool = True):
    return (_matmul_bf16 if bf16 else _matmul_f32)(jnp.asarray(a), jnp.asarray(b))


class MatmulCpuMapper(Mapper):
    """CPU slot path: one row at a time through numpy (the profiled slow
    backend)."""

    def configure(self, conf) -> None:
        self._b = _load_b(conf)

    def map(self, key, row, output, reporter):
        output.collect(int(key), np.asarray(row) @ self._b)


class MatmulBlockKernel(KernelMapper):
    name = "matmul-block"
    cpu_mapper_class = MatmulCpuMapper

    def map_batch_launch(self, batch, conf, task):
        b = _device_b(conf)
        bf16 = conf.get_boolean("tpumr.matmul.bf16", True)
        c = block_matmul(batch.values, b, bf16=bf16)
        row0 = int(batch.ids[0]) if batch.ids is not None else 0
        return {"c": c, "row0": row0}

    def map_batch_drain(self, fetched, conf, task) -> Iterable[tuple]:
        yield (int(fetched["row0"]), np.asarray(fetched["c"]))

    def device_output_rows(self, state):
        """Output-chaining hook: C stays resident so a consumer job
        (DenseNpyOutputFormat → DenseInputFormat) reads it from HBM
        instead of round-tripping through the tunnel."""
        return state["c"]

    def map_batch_cpu(self, batch, conf, task) -> Iterable[tuple]:
        """Vectorized host twin (BLAS) — CPU slots do the whole block in
        one gemm, keeping the hybrid comparison batch-vs-batch."""
        b = _load_b(conf)
        c = np.asarray(batch.values, np.float32) @ np.asarray(b, np.float32)
        row0 = int(batch.ids[0]) if batch.ids is not None else 0
        yield (row0, c)


register_kernel(MatmulBlockKernel())
