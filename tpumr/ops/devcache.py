"""Device-resident cache for kernel SIDE INPUTS (DistributedCache files).

The split cache (tpu_runner.split_cache) keeps each task's INPUT split
resident in HBM; this is its twin for the constants every task of a job
shares — K-Means centroids, the matmul B matrix — which the reference
shipped per-node via the DistributedCache (filecache/) and each GPU task
re-uploaded per launch. On a tunneled/remote TPU runtime that re-upload
is the warm-job bottleneck: 25 map tasks × one host→device transfer each
costs 25 network round-trips for bytes that are IDENTICAL every time
(measured round 5: the kmeans warm job spent most of its wall-clock
re-uploading a 1 KB centroid array per task; matmul re-shipped a 64 MB B
per task, the dominant term of its 0.2× row).

Keyed by (tag, current default device): tasks bind devices via
``jax.default_device`` (tpu_runner._select_device), so per-device
residency falls out of the key. Byte-budgeted LRU
(``tpumr.ops.device.cache.mb``, default 1024) — centroids are nothing,
but a few distinct B matrices must not silently pin HBM forever.

Tags embed the source path; iterative drivers that rewrite a side file
between rounds clear by prefix (clear_centroid_cache / clear_b_cache
call :func:`clear_device_cache` with their tag family).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any

_lock = threading.Lock()
#: (tag, device) -> (device_array, nbytes)
_cache: "OrderedDict[tuple, tuple[Any, int]]" = OrderedDict()


def device_cached(tag: str, host_array: Any, conf: Any = None) -> Any:
    """The device-resident image of ``host_array`` under ``tag`` for the
    CURRENT default device — uploaded once, returned from HBM after."""
    import jax
    import jax.numpy as jnp

    dev = jax.config.jax_default_device
    key = (tag, dev)
    with _lock:
        hit = _cache.get(key)
        if hit is not None:
            _cache.move_to_end(key)
            return hit[0]
    arr = jnp.asarray(host_array)          # the one upload
    nbytes = int(getattr(arr, "nbytes", 0))
    budget_mb = 1024
    if conf is not None:
        try:
            budget_mb = int(conf.get("tpumr.ops.device.cache.mb", 1024))
        except (TypeError, ValueError):
            pass
    with _lock:
        _cache[key] = (arr, nbytes)
        total = sum(b for _, b in _cache.values())
        while total > budget_mb * 1024 * 1024 and len(_cache) > 1:
            _k, (_a, b) = _cache.popitem(last=False)
            total -= b
    return arr


def clear_device_cache(tag_prefix: "str | None" = None) -> None:
    with _lock:
        if tag_prefix is None:
            _cache.clear()
            return
        for k in [k for k in _cache if k[0].startswith(tag_prefix)]:
            del _cache[k]
