"""Device-resident cache for kernel SIDE INPUTS (DistributedCache files).

The split cache (tpu_runner.HbmSplitCache) keeps each task's INPUT split
resident in HBM; this is the same machinery applied to the constants
every task of a job shares — K-Means centroids, the matmul B matrix —
which the reference shipped per-node via the DistributedCache
(filecache/) and each GPU task re-uploaded per launch. On a
tunneled/remote TPU runtime that re-upload is the warm-job bottleneck:
25 map tasks × one host→device transfer each costs 25 network
round-trips for bytes that are IDENTICAL every time (measured round 5:
the kmeans warm job spent most of its wall-clock re-uploading a 1 KB
centroid array per task; matmul re-shipped a 64 MB B per task, the
dominant term of its 0.2× row).

One byte-budgeted :class:`HbmSplitCache` (``tpumr.ops.device.cache.mb``,
default 1024, fixed at first use) keyed by (tag, current default
device): tasks bind devices via ``jax.default_device``
(tpu_runner._select_device), so per-device residency falls out of the
key. Tags embed the source path; iterative drivers that rewrite a side
file between rounds clear by prefix (clear_centroid_cache /
clear_b_cache call :func:`clear_device_cache` with their tag family).
"""

from __future__ import annotations

import threading
from typing import Any

_lock = threading.Lock()
_cache = None           # lazily-built HbmSplitCache


def _cache_for(conf: Any):
    global _cache
    with _lock:
        if _cache is None:
            budget_mb = 1024
            if conf is not None:
                try:
                    budget_mb = int(conf.get("tpumr.ops.device.cache.mb",
                                             1024))
                except (TypeError, ValueError):
                    pass
            from tpumr.mapred.tpu_runner import HbmSplitCache
            _cache = HbmSplitCache(budget_mb * 1024 * 1024)
        return _cache


def device_cached(tag: str, host_array: Any, conf: Any = None) -> Any:
    """The device-resident image of ``host_array`` under ``tag`` for the
    CURRENT default device — uploaded once, returned from HBM after."""
    import jax
    import jax.numpy as jnp

    key = (tag, str(jax.config.jax_default_device))
    cache = _cache_for(conf)
    hit = cache.get(key)
    if hit is not None:
        return hit
    arr = jnp.asarray(host_array)          # the one upload
    cache.put(key, arr, int(getattr(arr, "nbytes", 0)))
    return arr


def clear_device_cache(tag_prefix: "str | None" = None) -> None:
    with _lock:
        cache = _cache
    if cache is None:
        return
    if tag_prefix is None:
        cache.clear()
    else:
        cache.drop_where(lambda k: k[0].startswith(tag_prefix))


def inventory(max_tags: int = 32) -> "dict[str, int]":
    """Resident tag → total bytes across devices, MRU-first, bounded to
    ``max_tags`` entries — the devcache inventory trackers piggyback on
    heartbeats so the scheduler can place tasks where their side inputs
    already live. Cheap (one locked snapshot) and safe pre-first-use
    (empty dict when the cache was never built)."""
    with _lock:
        cache = _cache
    if cache is None:
        return {}
    tags: "dict[str, int]" = {}
    # snapshot is LRU→MRU; walk reversed so the bound keeps HOT tags
    for key, nbytes in reversed(cache.snapshot()):
        tag = key[0] if isinstance(key, tuple) else str(key)
        if tag in tags:
            tags[tag] += nbytes
        elif len(tags) < max_tags:
            tags[tag] = nbytes
    return tags


def occupancy() -> "dict[str, Any]":
    """Gauge-shaped occupancy summary: entry count, resident bytes, and
    per-tag-family byte totals (family = tag prefix before ':')."""
    with _lock:
        cache = _cache
    if cache is None:
        return {"entries": 0, "bytes": 0, "families": {}}
    snap = cache.snapshot()
    families: "dict[str, int]" = {}
    total = 0
    for key, nbytes in snap:
        tag = key[0] if isinstance(key, tuple) else str(key)
        family = tag.split(":", 1)[0]
        families[family] = families.get(family, 0) + nbytes
        total += nbytes
    return {"entries": len(snap), "bytes": total, "families": families}
