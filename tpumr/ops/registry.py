"""Kernel-mapper registry.

≈ the role of DistributedCache executable slots in the reference
(mapred/pipes/Submitter.java:349-379: CPU binary → cache[0], GPU binary →
cache[1]): jobs name their accelerator mapper; the node runner resolves it at
launch. Names are strings in job conf (``tpumr.map.kernel``) so submission
stays wire-serializable.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable


class KernelMapper:
    """A whole-batch device mapper.

    Contract: ``map_batch(batch, conf, task)`` consumes a staged
    :class:`~tpumr.io.recordbatch.DenseBatch` or
    :class:`~tpumr.io.recordbatch.RecordBatch` and returns an iterable of
    (key, value) records — typically FEW records, because the kernel
    aggregates on device (per-split partial sums, counts, blocks). This is
    the designed-in advantage over the reference's per-record socket protocol
    (BinaryProtocol MAP_ITEM hot loop, PipesGPUMapRunner.java:97-107): output
    leaves the device pre-combined.

    ``batch.values`` (and dense batch arrays generally) may be READ-ONLY
    numpy views over the input file's buffer (DenseInputFormat stages
    splits zero-copy via ``np.frombuffer``). Kernels must not mutate
    batch arrays in place — copy first (``np.array(batch.values)``) if a
    writable array is needed; ``jnp.asarray`` staging is unaffected.
    """

    #: registry name
    name: str = ""

    def map_batch(self, batch: Any, conf: Any, task: Any) -> Iterable[tuple]:
        """Synchronous batch map. Kernels that implement the two-phase
        launch/drain protocol get this for free (one host transfer per
        task); others override it directly."""
        state = self.map_batch_launch(batch, conf, task)
        if state is None:
            # a kernel that declines batches at runtime must also override
            # map_batch with its own fallback path
            raise NotImplementedError(
                f"kernel {self.name!r}: map_batch_launch declined this "
                "batch (returned None) and map_batch is not overridden")
        import jax
        return self.map_batch_drain(jax.device_get(state), conf, task)

    # ---------------------------------------------- two-phase device protocol
    #
    # Remote/tunneled TPU runtimes charge a full roundtrip per host
    # transfer of a computed array (~tens of ms on a tunneled chip),
    # while dispatch is asynchronous and ~free. Kernels that split into
    #   launch: dispatch device work, return a pytree of jax.Arrays
    #           (plain-python leaves pass through untouched), and
    #   drain:  turn the fetched host pytree into (key, value) records
    # let the runner batch MANY tasks' fetches into ONE jax.device_get —
    # one roundtrip per pipeline window instead of per output array
    # (TpuMapRunner single-task path + LocalJobRunner windowed prelaunch).

    def map_batch_launch(self, batch: Any, conf: Any, task: Any) -> Any:
        """Dispatch the device computation for one staged batch; return a
        pytree whose jax.Array leaves the runner will fetch, or None if
        this kernel does not support the two-phase protocol. Must not
        block on device results. Receives the job-level conf when called
        from the prelaunch window (task-localized conf otherwise)."""
        return None

    def map_batch_drain(self, fetched: Any, conf: Any, task: Any
                        ) -> Iterable[tuple]:
        """Convert the fetched (host) pytree returned by
        :meth:`map_batch_launch` into the task's (key, value) records."""
        raise NotImplementedError

    @classmethod
    def supports_launch(cls) -> bool:
        return cls.map_batch_launch is not KernelMapper.map_batch_launch

    # optional output-chaining hook: the device array whose host image
    # the task's output file will contain (same shape/dtype as the rows
    # the drain writes). Jobs writing through DenseNpyOutputFormat get
    # their output published into the HBM cache so a chained consumer
    # (DenseInputFormat) skips the storage read AND the re-upload —
    # see tpumr/mapred/device_output.py.
    # def device_output_rows(self, state) -> "jax.Array | None"

    # optional: kernels can advertise a CPU mapper class for the hybrid
    # scheduler's CPU slots (same job, both backends)
    cpu_mapper_class: type | None = None

    #: optional vectorized host implementation with the same
    #: ``(batch, conf, task) -> iterable of (key, value)`` contract —
    #: when present, CPU slots run the whole staged split through it
    #: (CpuBatchMapRunner) instead of per-record Python, keeping the
    #: hybrid scheduler's acceleration factor an honest batch-vs-batch
    #: measurement
    map_batch_cpu: Any = None


_REGISTRY: dict[str, KernelMapper] = {}


def register_kernel(kernel: KernelMapper) -> KernelMapper:
    if not kernel.name:
        raise ValueError("kernel needs a name")
    _REGISTRY[kernel.name] = kernel
    return kernel


def get_kernel(name: str) -> KernelMapper:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"no kernel mapper {name!r}; registered: "
                       f"{sorted(_REGISTRY)}") from None


def kernels() -> list[str]:
    return sorted(_REGISTRY)
