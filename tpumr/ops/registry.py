"""Kernel-mapper registry.

≈ the role of DistributedCache executable slots in the reference
(mapred/pipes/Submitter.java:349-379: CPU binary → cache[0], GPU binary →
cache[1]): jobs name their accelerator mapper; the node runner resolves it at
launch. Names are strings in job conf (``tpumr.map.kernel``) so submission
stays wire-serializable.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable


class KernelMapper:
    """A whole-batch device mapper.

    Contract: ``map_batch(batch, conf, task)`` consumes a staged
    :class:`~tpumr.io.recordbatch.DenseBatch` or
    :class:`~tpumr.io.recordbatch.RecordBatch` and returns an iterable of
    (key, value) records — typically FEW records, because the kernel
    aggregates on device (per-split partial sums, counts, blocks). This is
    the designed-in advantage over the reference's per-record socket protocol
    (BinaryProtocol MAP_ITEM hot loop, PipesGPUMapRunner.java:97-107): output
    leaves the device pre-combined.
    """

    #: registry name
    name: str = ""

    def map_batch(self, batch: Any, conf: Any, task: Any) -> Iterable[tuple]:
        raise NotImplementedError

    # optional: kernels can advertise a CPU mapper class for the hybrid
    # scheduler's CPU slots (same job, both backends)
    cpu_mapper_class: type | None = None

    #: optional vectorized host implementation with the same
    #: ``(batch, conf, task) -> iterable of (key, value)`` contract —
    #: when present, CPU slots run the whole staged split through it
    #: (CpuBatchMapRunner) instead of per-record Python, keeping the
    #: hybrid scheduler's acceleration factor an honest batch-vs-batch
    #: measurement
    map_batch_cpu: Any = None


_REGISTRY: dict[str, KernelMapper] = {}


def register_kernel(kernel: KernelMapper) -> KernelMapper:
    if not kernel.name:
        raise ValueError("kernel needs a name")
    _REGISTRY[kernel.name] = kernel
    return kernel


def get_kernel(name: str) -> KernelMapper:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"no kernel mapper {name!r}; registered: "
                       f"{sorted(_REGISTRY)}") from None


def kernels() -> list[str]:
    return sorted(_REGISTRY)
