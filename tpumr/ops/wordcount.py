"""WordCount batch mapper.

≈ the wordcount pipes examples (reference: src/examples/pipes/impl/
wordcount-simple.cc and examples/WordCount.java). Text tokenization is not
MXU work — the win over the reference here is structural, not arithmetic:
the whole split is tokenized in one vectorized pass over a padded byte
matrix (spaces as fill make padding vanish under split()) and counts leave
the map pre-aggregated (one record per distinct word per split), where the
pipes path crossed a socket once per input line and once per emitted word.
"""

from __future__ import annotations

from collections import Counter
from typing import Iterable

from tpumr.mapred.api import Mapper
from tpumr.ops.registry import KernelMapper, register_kernel


class WordCountCpuMapper(Mapper):
    def map(self, key, value, output, reporter):
        for w in value.split():
            output.collect(w, 1)


class WordCountKernel(KernelMapper):
    name = "wordcount"
    cpu_mapper_class = WordCountCpuMapper

    def map_batch(self, batch, conf, task) -> Iterable[tuple]:
        n = batch.num_records
        if n == 0:
            return
        import numpy as np
        data = batch.value_data
        lengths = batch.value_lengths
        # O(total_bytes) space-separated join (NOT pad-to-max, which is
        # O(n_records × longest_record) and explodes on one long line):
        # each source byte lands at its offset plus one separator per
        # preceding record boundary
        total = int(data.shape[0])
        out = np.full(total + n, 0x20, dtype=np.uint8)
        if total:
            dst = np.arange(total, dtype=np.int64) + \
                np.repeat(np.arange(n, dtype=np.int64), lengths)
            out[dst] = data
        counts = Counter(out.tobytes().split())
        for word, cnt in counts.items():
            yield word.decode("utf-8", errors="replace"), cnt

    # tokenization is host work either way — CPU slots run the same
    # vectorized whole-batch pass (CpuBatchMapRunner)
    map_batch_cpu = map_batch


register_kernel(WordCountKernel())
