"""WordCount batch mapper — native single-pass tokenization (numpy fallback).

≈ the wordcount pipes examples (reference: src/examples/pipes/impl/
wordcount-simple.cc and examples/WordCount.java). Text tokenization is not
MXU work — the win over the reference here is structural AND native:

- the PRIMARY path is native/textkit/tokencount.c: one C pass over the
  whole split's bytes with an inline-hashed open-addressing count table
  (~200+ MB/s/core), reached zero-copy from RawTextInputFormat's
  single-record batches;
- the numpy fallback (no C toolchain) is a vectorized byte-matrix
  tokenizer:

- token boundaries come from one C-level edge scan over the whole
  split's byte buffer (whitespace lookup table + sign-change detect);
- tokens are gathered into per-length byte MATRICES with one fancy
  index each (no per-token Python);
- counting distinct tokens is ``np.unique(return_counts=True)`` — a
  C sort per length class, packed into uint64 words for lengths ≤ 8
  (the common case) so the sort is numeric, not memcmp;
- counts leave the map pre-aggregated (one record per distinct word per
  split), where the pipes path crossed a socket once per input line and
  once per emitted word.

Token semantics are EXACTLY ``bytes.split()``'s: split on the six ASCII
whitespace bytes, no empty tokens (verified against the Counter
reference implementation in tests).
"""

from __future__ import annotations

from collections import Counter
from typing import Iterable, Iterator

import numpy as np

from tpumr.mapred.api import Mapper
from tpumr.ops.registry import KernelMapper, register_kernel

#: bytes.split() whitespace: \t \n \v \f \r space
_WS_TABLE = np.zeros(256, dtype=bool)
_WS_TABLE[[9, 10, 11, 12, 13, 32]] = True

def _native_lib():
    """The native single-pass tokenizer (native/textkit), lazily built
    and loaded through the shared loader (tpumr.utils.nativelib — same
    thread/process build serialization as the tlz codec); None when
    unavailable, callers fall back to the numpy path."""

    def configure(lib):
        import ctypes
        lib.tc_count.restype = ctypes.POINTER(ctypes.c_char)
        lib.tc_count.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                 ctypes.POINTER(ctypes.c_uint64)]
        lib.tc_free.argtypes = [ctypes.POINTER(ctypes.c_char)]

    from tpumr.utils.nativelib import load_native_lib
    return load_native_lib("textkit", "libtokencount.so", configure)


def tokenize_count_native(data) -> "Iterator[tuple[bytes, int]] | None":
    """Single-pass C tokenize+count (native/textkit/tokencount.c), or
    None when the native library is unavailable. ``data`` may be bytes
    or a contiguous uint8 ndarray (zero-copy)."""
    import ctypes
    import struct
    lib = _native_lib()
    if lib is None:
        return None
    out_len = ctypes.c_uint64()
    if isinstance(data, np.ndarray):
        arr = np.ascontiguousarray(data, dtype=np.uint8)
        p = lib.tc_count(arr.ctypes.data_as(ctypes.c_char_p), arr.size,
                         ctypes.byref(out_len))
    else:
        p = lib.tc_count(data, len(data), ctypes.byref(out_len))
    if not p:
        return None
    try:
        raw = ctypes.string_at(p, out_len.value)
    finally:
        lib.tc_free(p)

    def entries() -> "Iterator[tuple[bytes, int]]":
        (n,) = struct.unpack_from("<Q", raw, 0)
        pos = 8
        for _ in range(n):
            tlen, count = struct.unpack_from("<IQ", raw, pos)
            pos += 12
            yield raw[pos: pos + tlen], count
            pos += tlen

    return entries()


def tokenize_count(data) -> "Iterator[tuple[bytes, int]]":
    """Yield (token, count) for every distinct ``bytes.split()`` token
    of ``data`` (bytes or uint8 ndarray — any buffer-protocol object,
    consumed read-only) — all heavy lifting in numpy C loops."""
    buf = (data if isinstance(data, np.ndarray)
           else np.frombuffer(data, dtype=np.uint8))
    if buf.size == 0:
        return
    tok = (~_WS_TABLE[buf]).view(np.int8)
    # token edges: +1 where a run of non-whitespace starts, -1 one past
    # its end (virtual whitespace on both sides)
    edges = np.diff(tok, prepend=np.int8(0), append=np.int8(0))
    starts = np.flatnonzero(edges == 1)
    ends = np.flatnonzero(edges == -1)
    lengths = ends - starts
    if starts.size == 0:
        return
    for L in np.unique(lengths):
        L = int(L)
        s = starts[lengths == L]
        # [nL, L] gather — every token of this exact length, no padding
        # ambiguity (a zero byte IN a token cannot alias zero padding)
        mat = buf[s[:, None] + np.arange(L, dtype=s.dtype)]
        if L <= 8:
            # pack into one little-endian uint64 per token: numeric
            # sort beats memcmp-on-void by a wide margin
            if L < 8:
                packed = np.zeros((mat.shape[0], 8), dtype=np.uint8)
                packed[:, :L] = mat
            else:
                packed = np.ascontiguousarray(mat)
            keys = packed.view("<u8").ravel()
            uniq, counts = np.unique(keys, return_counts=True)
            raw = uniq.astype("<u8").tobytes()
            for i in range(uniq.size):
                yield raw[i * 8: i * 8 + L], int(counts[i])
        else:
            keys = np.ascontiguousarray(mat).view(f"V{L}").ravel()
            uniq, counts = np.unique(keys, return_counts=True)
            raw = uniq.tobytes()
            for i in range(uniq.size):
                yield raw[i * L: (i + 1) * L], int(counts[i])


class WordCountCpuMapper(Mapper):
    def map(self, key, value, output, reporter):
        for w in value.split():
            output.collect(w, 1)


class WordCountKernel(KernelMapper):
    name = "wordcount"
    cpu_mapper_class = WordCountCpuMapper

    def map_batch(self, batch, conf, task) -> Iterable[tuple]:
        if batch.num_records == 0:
            return
        # single-record batches (RawTextInputFormat) feed the native
        # tokenizer their value_data view directly — zero copies
        data = (batch.value_data if batch.num_records == 1
                else batch.joined_values())
        nbytes = data.size if isinstance(data, np.ndarray) else len(data)
        if nbytes < 1 << 16 or not bool(
                conf.get_boolean("tpumr.wordcount.vectorized", True)):
            # tiny splits: setup costs more than it saves
            raw = data.tobytes() if isinstance(data, np.ndarray) else data
            for word, cnt in Counter(raw.split()).items():
                yield word.decode("utf-8", errors="replace"), cnt
            return
        native = tokenize_count_native(data)
        if native is None:
            native = tokenize_count(data)   # accepts ndarray zero-copy
        for word, cnt in native:
            yield word.decode("utf-8", errors="replace"), cnt

    # tokenization is host work either way — CPU slots run the same
    # vectorized whole-batch pass (CpuBatchMapRunner)
    map_batch_cpu = map_batch


register_kernel(WordCountKernel())
