"""WordCount batch mapper.

≈ the wordcount pipes examples (reference: src/examples/pipes/impl/
wordcount-simple.cc and examples/WordCount.java). Text tokenization is not
MXU work — the win over the reference here is structural, not arithmetic:
the whole split is tokenized in one vectorized pass over a padded byte
matrix (spaces as fill make padding vanish under split()) and counts leave
the map pre-aggregated (one record per distinct word per split), where the
pipes path crossed a socket once per input line and once per emitted word.
"""

from __future__ import annotations

from collections import Counter
from typing import Iterable

from tpumr.mapred.api import Mapper
from tpumr.ops.registry import KernelMapper, register_kernel


class WordCountCpuMapper(Mapper):
    def map(self, key, value, output, reporter):
        for w in value.split():
            output.collect(w, 1)


class WordCountKernel(KernelMapper):
    name = "wordcount"
    cpu_mapper_class = WordCountCpuMapper

    def map_batch(self, batch, conf, task) -> Iterable[tuple]:
        if batch.num_records == 0:
            return
        # one C-level separator join (records can't merge across the
        # boundary), one C-level whitespace split, one C-level count
        counts = Counter(batch.joined_values().split())
        for word, cnt in counts.items():
            yield word.decode("utf-8", errors="replace"), cnt

    # tokenization is host work either way — CPU slots run the same
    # vectorized whole-batch pass (CpuBatchMapRunner)
    map_batch_cpu = map_batch


register_kernel(WordCountKernel())
