"""Grep batch mapper ≈ the reference Grep example (src/examples/org/apache/
hadoop/examples/Grep.java: map extracts regex matches, emits (match, 1);
reduce sums). The batch path regex-scans the split in one pass."""

from __future__ import annotations

import re
from collections import Counter
from typing import Iterable

import numpy as np

from tpumr.mapred.api import Mapper
from tpumr.ops.registry import KernelMapper, register_kernel


def _pattern(conf) -> "tuple[re.Pattern[bytes], int]":
    pat = conf.get("tpumr.grep.pattern")
    if not pat:
        raise ValueError("tpumr.grep.pattern not set")
    group = conf.get_int("tpumr.grep.group", 0)
    return re.compile(pat.encode()), group


class GrepCpuMapper(Mapper):
    def configure(self, conf) -> None:
        self._re, self._group = _pattern(conf)

    def map(self, key, value, output, reporter):
        data = value.encode() if isinstance(value, str) else value
        for m in self._re.finditer(data):
            output.collect(m.group(self._group).decode("utf-8", "replace"), 1)


class GrepKernel(KernelMapper):
    name = "grep"
    cpu_mapper_class = GrepCpuMapper

    def map_batch(self, batch, conf, task) -> Iterable[tuple]:
        regex, group = _pattern(conf)
        counts: Counter = Counter()
        # zero-copy memoryview slices replace per-record array slicing +
        # tobytes; per-record finditer is kept (reference semantics: a
        # match never crosses a record boundary)
        mv = memoryview(np.ascontiguousarray(batch.value_data))
        offs = batch.value_offsets
        for i in range(batch.num_records):
            for m in regex.finditer(mv[offs[i]:offs[i + 1]]):
                counts[bytes(m.group(group))] += 1
        for match, n in counts.items():
            yield match.decode("utf-8", errors="replace"), n


register_kernel(GrepKernel())
