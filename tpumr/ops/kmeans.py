"""K-Means map kernels: nearest-centroid assignment + device-side partial
aggregation.

The flagship workload (BASELINE.json north star: 100M points, ≥5× CPU-only).
The reference ran K-Means as a CUDA pipes binary fed one point per socket
record (the Shirahata paper's job; conf/mapred-site.xml pins 1 line per map).
Here the whole split is staged as a ``DenseBatch`` and:

- distances are one MXU matmul: ``d²(x,c) = |x|² - 2x·cᵀ + |c|²``;
- the per-cluster partial sums are a second MXU matmul
  (``one_hotᵀ @ points``), so a map task emits k tiny records — the
  all-reduce over centroids rides the shuffle, not per-point traffic;
- the default compute path is fused XLA (it beats the Pallas kernel for
  narrow features — see :func:`assign_and_partials`); a Pallas kernel for
  the fused distance+argmin stays available via ``tpumr.kmeans.use.pallas``
  for wide-d inputs.
"""

from __future__ import annotations

from typing import Any, Iterable

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from tpumr.mapred.api import Mapper
from tpumr.ops.registry import KernelMapper, register_kernel

_BIG = 1e30


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


# ----------------------------------------------------------------- XLA path


@jax.jit
def _assign_and_partials_jax(points, centroids):
    x2 = jnp.sum(points * points, axis=1, keepdims=True)
    c2 = jnp.sum(centroids * centroids, axis=1)
    d2 = x2 - 2.0 * jnp.dot(points, centroids.T,
                            preferred_element_type=jnp.float32) + c2[None, :]
    assign = jnp.argmin(d2, axis=1)
    onehot = jax.nn.one_hot(assign, centroids.shape[0], dtype=points.dtype)
    sums = jnp.dot(onehot.T, points, preferred_element_type=jnp.float32)
    counts = jnp.sum(onehot, axis=0).astype(jnp.int32)
    return assign.astype(jnp.int32), sums, counts


# ----------------------------------------------------------------- Pallas


def _assign_kernel(pts_ref, cent_ref, out_ref):
    pts = pts_ref[:]                      # [bn, d_p] VMEM
    cents = cent_ref[:]                   # [k_p, d_p] VMEM
    d2 = (jnp.sum(pts * pts, axis=1, keepdims=True)
          - 2.0 * jnp.dot(pts, cents.T, preferred_element_type=jnp.float32)
          + jnp.sum(cents * cents, axis=1)[None, :])
    out_ref[:] = jnp.argmin(d2, axis=1).astype(jnp.int32).reshape(-1, 1)


def pallas_assign(points: Any, centroids: Any, block_n: int = 2048,
                  interpret: bool = False):
    """Fused distance+argmin assign step as a Pallas TPU kernel. Inputs are
    padded to MXU-friendly tiles: feature dim to a multiple of 128 lanes,
    centroid rows to a multiple of 8 sublanes (padded rows pushed far away so
    argmin ignores them)."""
    n, d = points.shape
    k = centroids.shape[0]
    d_p = _round_up(max(d, 128), 128)
    k_p = _round_up(max(k, 8), 8)
    bn = min(block_n, _round_up(n, 8))
    n_p = _round_up(n, bn)

    pts = jnp.zeros((n_p, d_p), jnp.float32).at[:n, :d].set(points)
    cents = jnp.zeros((k_p, d_p), jnp.float32).at[:k, :d].set(centroids)
    if k_p > k:
        # push padding centroids far away in a dimension real points are 0 in
        cents = cents.at[k:, :].set(jnp.sqrt(_BIG))

    out = pl.pallas_call(
        _assign_kernel,
        grid=(n_p // bn,),
        in_specs=[pl.BlockSpec((bn, d_p), lambda i: (i, 0)),
                  pl.BlockSpec((k_p, d_p), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_p, 1), jnp.int32),
        interpret=interpret,
    )(pts, cents)
    return out[:n, 0]


def assign_and_partials(points, centroids, use_pallas: bool = False,
                        interpret: bool = False):
    """(assignments [n] i32, partial sums [k,d] f32, counts [k] i32).

    Default is the fused XLA path: measured on v5e, XLA's fusion of this op
    chain beats the Pallas kernel for narrow features (the Mosaic 128-lane
    tile forces d→128 padding, 8× the HBM traffic at d=16: 584ms vs 0.1ms
    per 1M points). The Pallas kernel stays selectable for wide-d inputs
    where the padding vanishes."""
    points = jnp.asarray(points, jnp.float32)
    centroids = jnp.asarray(centroids, jnp.float32)
    if use_pallas:
        assign = pallas_assign(points, centroids, interpret=interpret)
        onehot = jax.nn.one_hot(assign, centroids.shape[0], dtype=jnp.float32)
        sums = jnp.dot(onehot.T, points, preferred_element_type=jnp.float32)
        counts = jnp.sum(onehot, axis=0).astype(jnp.int32)
        return assign, sums, counts
    return _assign_and_partials_jax(points, centroids)


# ------------------------------------------------------------ multi-chip


def make_distributed_step(mesh, axis_name: str = "data"):
    """SPMD K-Means step over a mesh: points stay sharded along the record
    axis; every chip computes local assignments + partial sums (two MXU
    matmuls) and ONE psum over ICI yields identical new centroids on every
    chip — the centroid all-reduce that rode the reference's HTTP shuffle +
    single reduce task now costs one collective (SURVEY.md §5 'distributed
    communication backend' TPU-native mapping).

    Returns jitted ``step(points_shard [N,d] sharded, centroids [k,d]
    replicated) -> (new_centroids [k,d] replicated, counts [k])``.
    """
    import functools
    from jax.sharding import PartitionSpec as P

    from tpumr.parallel import collectives

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P(axis_name), P()), out_specs=(P(), P()))
    def step(points, centroids):
        # nested jit inlines during tracing — same program, public API
        _a, sums, counts = _assign_and_partials_jax(points, centroids)
        sums = collectives.psum(sums, axis_name)
        counts = collectives.psum(counts, axis_name)
        new = sums / jnp.maximum(counts, 1)[:, None].astype(sums.dtype)
        # empty clusters keep their old centroid
        new = jnp.where((counts > 0)[:, None], new, centroids)
        return new, counts

    return jax.jit(step)


# ----------------------------------------------------------------- mapper


_centroid_cache: dict[str, np.ndarray] = {}


def _load_centroids(conf) -> np.ndarray:
    from tpumr.fs.filesystem import FileSystem
    from tpumr.mapred.input_formats import load_dense
    path = conf.get("tpumr.kmeans.centroids")
    if not path:
        raise ValueError("tpumr.kmeans.centroids not set (path to .npy)")
    cached = _centroid_cache.get(path)
    if cached is None:
        fs = FileSystem.get(path, conf)
        cached = _centroid_cache[path] = load_dense(fs, path).astype(np.float32)
    return cached


def clear_centroid_cache() -> None:
    """Iterative drivers rewrite the centroid file between rounds."""
    from tpumr.ops.devcache import clear_device_cache
    _centroid_cache.clear()
    clear_device_cache("kmeans-centroids:")


def _device_centroids(conf):
    """Centroids as a DEVICE-resident array, uploaded once per
    (file, device) instead of once per map task — on a tunneled chip the
    per-task re-upload was the warm-job wall-clock (25 round-trips of
    identical bytes per job; see ops/devcache.py)."""
    from tpumr.ops.devcache import device_cached
    host = _load_centroids(conf)
    tag = f"kmeans-centroids:{conf.get('tpumr.kmeans.centroids')}"
    return device_cached(tag, host.astype(np.float32, copy=False), conf)


def assign_and_partials_numpy(points: np.ndarray, centroids: np.ndarray,
                              chunk: int = 1 << 16
                              ) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized host twin of :func:`assign_and_partials` for CPU map
    slots: chunked ``|x|² - 2x·cᵀ + |c|²`` + argmin (BLAS matmul), partial
    sums via per-dimension bincount (C-speed scatter-add). Returns
    (sums [k,d] f32, counts [k] i64)."""
    points = np.asarray(points, np.float32)
    centroids = np.asarray(centroids, np.float32)
    k, d = centroids.shape
    c2 = np.einsum("kd,kd->k", centroids, centroids)
    sums = np.zeros((k, d), np.float32)
    counts = np.zeros(k, np.int64)
    for lo in range(0, points.shape[0], chunk):
        block = points[lo:lo + chunk]
        # |x|² is constant per row — argmin doesn't need it
        d2 = c2[None, :] - 2.0 * (block @ centroids.T)
        assign = np.argmin(d2, axis=1)
        counts += np.bincount(assign, minlength=k)
        for j in range(d):
            sums[:, j] += np.bincount(assign, weights=block[:, j],
                                      minlength=k)
    return sums, counts


class KMeansCpuMapper(Mapper):
    """CPU-slot mapper for the same job: per-record nearest centroid in
    numpy — deliberately the 'slow backend' the hybrid scheduler profiles
    against (≈ running the CPU pipes binary)."""

    def configure(self, conf) -> None:
        self._centroids = _load_centroids(conf)

    def map(self, key, row, output, reporter):
        c = self._centroids
        d2 = ((c - np.asarray(row)[None, :]) ** 2).sum(axis=1)
        cid = int(np.argmin(d2))
        output.collect(cid, (np.asarray(row, np.float32), 1))


class KMeansAssignKernel(KernelMapper):
    name = "kmeans-assign"
    cpu_mapper_class = KMeansCpuMapper

    def map_batch_launch(self, batch, conf, task):
        """Two-phase protocol: dispatch the assign+partials program and
        hand the [k,d] sums / [k] counts back as device arrays — the
        runner fetches a whole window of tasks in one roundtrip."""
        centroids = _device_centroids(conf)
        use_pallas = conf.get_boolean("tpumr.kmeans.use.pallas", False)
        _assign, sums, counts = assign_and_partials(batch.values, centroids,
                                                    use_pallas=use_pallas)
        return (sums, counts)

    def map_batch_drain(self, fetched, conf, task) -> Iterable[tuple]:
        sums, counts = (np.asarray(a) for a in fetched)
        for cid in range(sums.shape[0]):
            if counts[cid] > 0:
                yield int(cid), (sums[cid], int(counts[cid]))

    def map_batch_cpu(self, batch, conf, task) -> Iterable[tuple]:
        """Vectorized CPU-slot path: same pre-aggregated output shape as
        the device kernel, so reduce sees identical records either way."""
        centroids = _load_centroids(conf)
        sums, counts = assign_and_partials_numpy(np.asarray(batch.values),
                                                 centroids)
        for cid in range(centroids.shape[0]):
            if counts[cid] > 0:
                yield int(cid), (sums[cid], int(counts[cid]))


register_kernel(KMeansAssignKernel())
