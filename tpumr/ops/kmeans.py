"""K-Means map kernels: nearest-centroid assignment + device-side partial
aggregation.

The flagship workload (BASELINE.json north star: 100M points, ≥5× CPU-only).
The reference ran K-Means as a CUDA pipes binary fed one point per socket
record (the Shirahata paper's job; conf/mapred-site.xml pins 1 line per map).
Here the whole split is staged as a ``DenseBatch`` and:

- distances are one MXU matmul: ``d²(x,c) = |x|² - 2x·cᵀ + |c|²``;
- the per-cluster partial sums are a second MXU matmul
  (``one_hotᵀ @ points``), so a map task emits k tiny records — the
  all-reduce over centroids rides the shuffle, not per-point traffic;
- the default compute path is fused XLA (it beats the Pallas kernel for
  narrow features — see :func:`assign_and_partials`); a Pallas kernel for
  the fused distance+argmin stays available via ``tpumr.kmeans.use.pallas``
  for wide-d inputs.
"""

from __future__ import annotations

from typing import Any, Iterable

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from tpumr.mapred.api import Mapper, Reducer
from tpumr.ops.registry import KernelMapper, register_kernel

_BIG = 1e30


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


# ----------------------------------------------------------------- XLA path


@jax.jit
def _assign_and_partials_jax(points, centroids):
    x2 = jnp.sum(points * points, axis=1, keepdims=True)
    c2 = jnp.sum(centroids * centroids, axis=1)
    d2 = x2 - 2.0 * jnp.dot(points, centroids.T,
                            preferred_element_type=jnp.float32) + c2[None, :]
    assign = jnp.argmin(d2, axis=1)
    onehot = jax.nn.one_hot(assign, centroids.shape[0], dtype=points.dtype)
    sums = jnp.dot(onehot.T, points, preferred_element_type=jnp.float32)
    counts = jnp.sum(onehot, axis=0).astype(jnp.int32)
    return assign.astype(jnp.int32), sums, counts


# ----------------------------------------------------------------- Pallas


def _assign_kernel(pts_ref, cent_ref, out_ref):
    pts = pts_ref[:]                      # [bn, d_p] VMEM
    cents = cent_ref[:]                   # [k_p, d_p] VMEM
    d2 = (jnp.sum(pts * pts, axis=1, keepdims=True)
          - 2.0 * jnp.dot(pts, cents.T, preferred_element_type=jnp.float32)
          + jnp.sum(cents * cents, axis=1)[None, :])
    out_ref[:] = jnp.argmin(d2, axis=1).astype(jnp.int32).reshape(-1, 1)


def pallas_assign(points: Any, centroids: Any, block_n: int = 2048,
                  interpret: bool = False):
    """Fused distance+argmin assign step as a Pallas TPU kernel. Inputs are
    padded to MXU-friendly tiles: feature dim to a multiple of 128 lanes,
    centroid rows to a multiple of 8 sublanes (padded rows pushed far away so
    argmin ignores them)."""
    n, d = points.shape
    k = centroids.shape[0]
    d_p = _round_up(max(d, 128), 128)
    k_p = _round_up(max(k, 8), 8)
    bn = min(block_n, _round_up(n, 8))
    n_p = _round_up(n, bn)

    pts = jnp.zeros((n_p, d_p), jnp.float32).at[:n, :d].set(points)
    cents = jnp.zeros((k_p, d_p), jnp.float32).at[:k, :d].set(centroids)
    if k_p > k:
        # push padding centroids far away in a dimension real points are 0 in
        cents = cents.at[k:, :].set(jnp.sqrt(_BIG))

    out = pl.pallas_call(
        _assign_kernel,
        grid=(n_p // bn,),
        in_specs=[pl.BlockSpec((bn, d_p), lambda i: (i, 0)),
                  pl.BlockSpec((k_p, d_p), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_p, 1), jnp.int32),
        interpret=interpret,
    )(pts, cents)
    return out[:n, 0]


def assign_and_partials(points, centroids, use_pallas: bool = False,
                        interpret: bool = False):
    """(assignments [n] i32, partial sums [k,d] f32, counts [k] i32).

    Default is the fused XLA path: measured on v5e, XLA's fusion of this op
    chain beats the Pallas kernel for narrow features (the Mosaic 128-lane
    tile forces d→128 padding, 8× the HBM traffic at d=16: 584ms vs 0.1ms
    per 1M points). The Pallas kernel stays selectable for wide-d inputs
    where the padding vanishes."""
    points = jnp.asarray(points, jnp.float32)
    centroids = jnp.asarray(centroids, jnp.float32)
    if use_pallas:
        assign = pallas_assign(points, centroids, interpret=interpret)
        onehot = jax.nn.one_hot(assign, centroids.shape[0], dtype=jnp.float32)
        sums = jnp.dot(onehot.T, points, preferred_element_type=jnp.float32)
        counts = jnp.sum(onehot, axis=0).astype(jnp.int32)
        return assign, sums, counts
    return _assign_and_partials_jax(points, centroids)


# ------------------------------------------------------------ multi-chip


def make_distributed_step(mesh, axis_name: str = "data"):
    """SPMD K-Means step over a mesh: points stay sharded along the record
    axis; every chip computes local assignments + partial sums (two MXU
    matmuls) and ONE psum over ICI yields identical new centroids on every
    chip — the centroid all-reduce that rode the reference's HTTP shuffle +
    single reduce task now costs one collective (SURVEY.md §5 'distributed
    communication backend' TPU-native mapping).

    Returns jitted ``step(points_shard [N,d] sharded, centroids [k,d]
    replicated) -> (new_centroids [k,d] replicated, counts [k])``.
    """
    import functools
    from jax.sharding import PartitionSpec as P

    from tpumr.parallel import collectives

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P(axis_name), P()), out_specs=(P(), P()))
    def step(points, centroids):
        # nested jit inlines during tracing — same program, public API
        _a, sums, counts = _assign_and_partials_jax(points, centroids)
        sums = collectives.psum(sums, axis_name)
        counts = collectives.psum(counts, axis_name)
        new = sums / jnp.maximum(counts, 1)[:, None].astype(sums.dtype)
        # empty clusters keep their old centroid
        new = jnp.where((counts > 0)[:, None], new, centroids)
        return new, counts

    return jax.jit(step)


# ----------------------------------------------------------------- mapper


_centroid_cache: dict[str, np.ndarray] = {}

#: host-cache bound: PIPELINE rounds version their centroid path (one
#: NEW entry per round, nothing invalidated), so the dict would other-
#: wise grow one k×d array per round for the life of the process
_CENTROID_CACHE_CAP = 8


def _load_centroids(conf) -> np.ndarray:
    from tpumr.fs.filesystem import FileSystem
    from tpumr.mapred.input_formats import load_dense
    path = conf.get("tpumr.kmeans.centroids")
    if not path:
        raise ValueError("tpumr.kmeans.centroids not set (path to .npy)")
    cached = _centroid_cache.get(path)
    if cached is None:
        fs = FileSystem.get(path, conf)
        while len(_centroid_cache) >= _CENTROID_CACHE_CAP:
            _centroid_cache.pop(next(iter(_centroid_cache)))
        cached = _centroid_cache[path] = load_dense(fs, path).astype(np.float32)
    return cached


def clear_centroid_cache() -> None:
    """SEQUENTIAL iterative drivers rewrite one centroid file between
    rounds, so both the host cache and the device-resident copy go
    stale and must be dropped per round. Pipeline loop nodes do NOT
    call this between rounds: their conf templates a fresh centroid
    path per round (``cents-r{round}.npy``), so every cache key stays
    valid — call :func:`clear_pipeline_caches` once at convergence or
    pipeline teardown instead."""
    from tpumr.ops.devcache import clear_device_cache
    _centroid_cache.clear()
    clear_device_cache("kmeans-centroids:")


def clear_pipeline_caches() -> None:
    """Pipeline teardown: prefix-clear the per-round centroid entries
    (host + HBM) in one sweep. During the rounds themselves nothing is
    cleared — round r+1's upload is a NEW tag, round r's entry ages out
    of the byte-budgeted device LRU naturally, and the devcache
    pre-seed in :class:`KMeansCentroidUpdateReducer` means the next
    round's centroids may never leave the device at all. Same sweep as
    :func:`clear_centroid_cache`; the distinct name is the distinct
    CONTRACT (once at teardown vs once per round)."""
    clear_centroid_cache()


def _device_centroids(conf):
    """Centroids as a DEVICE-resident array, uploaded once per
    (file, device) instead of once per map task — on a tunneled chip the
    per-task re-upload was the warm-job wall-clock (25 round-trips of
    identical bytes per job; see ops/devcache.py)."""
    from tpumr.ops.devcache import device_cached
    host = _load_centroids(conf)
    tag = f"kmeans-centroids:{conf.get('tpumr.kmeans.centroids')}"
    return device_cached(tag, host.astype(np.float32, copy=False), conf)


def assign_and_partials_numpy(points: np.ndarray, centroids: np.ndarray,
                              chunk: int = 1 << 16
                              ) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized host twin of :func:`assign_and_partials` for CPU map
    slots: chunked ``|x|² - 2x·cᵀ + |c|²`` + argmin (BLAS matmul), partial
    sums via per-dimension bincount (C-speed scatter-add). Returns
    (sums [k,d] f32, counts [k] i64)."""
    points = np.asarray(points, np.float32)
    centroids = np.asarray(centroids, np.float32)
    k, d = centroids.shape
    c2 = np.einsum("kd,kd->k", centroids, centroids)
    sums = np.zeros((k, d), np.float32)
    counts = np.zeros(k, np.int64)
    for lo in range(0, points.shape[0], chunk):
        block = points[lo:lo + chunk]
        # |x|² is constant per row — argmin doesn't need it
        d2 = c2[None, :] - 2.0 * (block @ centroids.T)
        assign = np.argmin(d2, axis=1)
        counts += np.bincount(assign, minlength=k)
        for j in range(d):
            sums[:, j] += np.bincount(assign, weights=block[:, j],
                                      minlength=k)
    return sums, counts


class KMeansCpuMapper(Mapper):
    """CPU-slot mapper for the same job: per-record nearest centroid in
    numpy — deliberately the 'slow backend' the hybrid scheduler profiles
    against (≈ running the CPU pipes binary)."""

    def configure(self, conf) -> None:
        self._centroids = _load_centroids(conf)

    def map(self, key, row, output, reporter):
        c = self._centroids
        d2 = ((c - np.asarray(row)[None, :]) ** 2).sum(axis=1)
        cid = int(np.argmin(d2))
        output.collect(cid, (np.asarray(row, np.float32), 1))


#: convergence counter the iterative driver (pipeline loop node) reads:
#: total centroid movement this round, in milli-units (counters are
#: integral) — ``converge={"group": "KMeans", "counter":
#: "CENTROID_SHIFT_MILLI", "op": "le", "value": T}``
SHIFT_COUNTER_GROUP = "KMeans"
SHIFT_COUNTER = "CENTROID_SHIFT_MILLI"


class KMeansCentroidUpdateReducer(Reducer):
    """Round-closing reducer for ITERATIVE kmeans: averages the maps'
    (partial_sum, count) records into the new centroids, writes them as
    the NEXT round's ``.npy`` (``tpumr.kmeans.centroids.out`` — a fresh
    round-templated path, so no cache is ever rewritten-under), emits
    the centroid-shift convergence counter, and pre-seeds the device
    cache under the next round's tag: on a single-host cluster the new
    centroids are HBM-resident before round r+1's first map asks —
    between rounds they never leave the device. Requires
    ``mapred.reduce.tasks=1`` (the update needs every cluster id).

    Also emits (cid, new_centroid) records like the plain
    CentroidReducer, so the round job's committed output remains the
    inspectable artifact."""

    def __init__(self) -> None:
        self._sums: "dict[int, np.ndarray]" = {}
        self._counts: "dict[int, int]" = {}
        self._conf = None
        self._reporter = None

    def configure(self, conf) -> None:
        self._conf = conf
        if int(conf.get("mapred.reduce.tasks", 1)) != 1:
            raise ValueError(
                "KMeansCentroidUpdateReducer needs mapred.reduce.tasks"
                "=1 — the centroid update must see every cluster")

    def reduce(self, key, values, output, reporter):
        self._reporter = reporter
        total, n = None, 0
        for s, c in values:
            s = np.asarray(s, dtype=np.float64)
            total = s if total is None else total + s
            n += int(c)
        cid = int(key)
        self._sums[cid] = total
        self._counts[cid] = n
        output.collect(cid, (total / max(1, n)).tolist())

    def abort(self) -> None:
        """Failed/killed attempt (reduce_task's reducer abort seam): a
        PARTIALLY-fed run must never publish next-round state — its
        rename would replace the commit winner's complete file with
        partial aggregates."""
        self._sums.clear()
        self._counts.clear()

    def close(self) -> None:
        conf = self._conf
        out_path = conf.get("tpumr.kmeans.centroids.out") if conf else None
        if not out_path:
            return   # plain (non-iterative) use: output records suffice
        prev = _load_centroids(conf)
        new = prev.copy()
        for cid, total in self._sums.items():
            if 0 <= cid < new.shape[0] and self._counts[cid] > 0:
                new[cid] = (total / self._counts[cid]).astype(np.float32)
        # write-then-rename: a twin killed MID-WRITE must never leave
        # a truncated file at the final path (fs.create truncates — a
        # direct write could corrupt a completed file). The bytes are
        # deterministic, so on filesystems whose rename replaces
        # (local os.replace, mem) the landing order is irrelevant; on
        # a DFS that REFUSES an existing destination the first writer
        # simply wins — either way the tmp must not linger.
        import io as _io

        from tpumr.fs.filesystem import FileSystem
        buf = _io.BytesIO()
        np.save(buf, np.ascontiguousarray(new))
        fs = FileSystem.get(out_path, conf)
        tmp = (f"{out_path}._"
               f"{conf.get('tpumr.task.attempt.id') or 'local'}.tmp")
        with fs.create(tmp) as f:
            f.write(buf.getvalue())
        if not fs.rename(tmp, out_path):
            try:
                fs.delete(tmp)
            except OSError:
                pass
        shift = float(np.abs(new - prev).sum())
        if self._reporter is not None:
            self._reporter.incr_counter(SHIFT_COUNTER_GROUP,
                                        SHIFT_COUNTER,
                                        int(round(shift * 1000)))
        # HBM pre-seed: register the new centroids under the NEXT
        # round's cache tag so round r+1's maps on this host hit the
        # device copy without touching storage (best-effort — a distant
        # tracker's maps just upload once, as before)
        try:
            from tpumr.ops.devcache import device_cached
            device_cached(f"kmeans-centroids:{out_path}",
                          new.astype(np.float32, copy=False), conf)
        except Exception:  # noqa: BLE001 — residency is an
            pass           # optimization, never a dependency


class KMeansAssignKernel(KernelMapper):
    name = "kmeans-assign"
    cpu_mapper_class = KMeansCpuMapper

    def map_batch_launch(self, batch, conf, task):
        """Two-phase protocol: dispatch the assign+partials program and
        hand the [k,d] sums / [k] counts back as device arrays — the
        runner fetches a whole window of tasks in one roundtrip."""
        centroids = _device_centroids(conf)
        use_pallas = conf.get_boolean("tpumr.kmeans.use.pallas", False)
        _assign, sums, counts = assign_and_partials(batch.values, centroids,
                                                    use_pallas=use_pallas)
        return (sums, counts)

    def map_batch_drain(self, fetched, conf, task) -> Iterable[tuple]:
        sums, counts = (np.asarray(a) for a in fetched)
        for cid in range(sums.shape[0]):
            if counts[cid] > 0:
                yield int(cid), (sums[cid], int(counts[cid]))

    def map_batch_cpu(self, batch, conf, task) -> Iterable[tuple]:
        """Vectorized CPU-slot path: same pre-aggregated output shape as
        the device kernel, so reduce sees identical records either way."""
        centroids = _load_centroids(conf)
        sums, counts = assign_and_partials_numpy(np.asarray(batch.values),
                                                 centroids)
        for cid in range(centroids.shape[0]):
            if counts[cid] > 0:
                yield int(cid), (sums[cid], int(counts[cid]))


register_kernel(KMeansAssignKernel())
