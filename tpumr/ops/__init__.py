"""Device map kernels — the TPU replacement for user CUDA map binaries.

In the reference, accelerator map tasks are user-supplied CUDA executables
launched through pipes (mapred/pipes/Application.java:162-181 picks
localCacheFiles[1] and passes GPUDeviceId as argv[1]); there is no GPU code
in-tree. Here the equivalent is a registry of :class:`KernelMapper`s — named
device programs a job selects with ``JobConf.set_map_kernel(name)`` — each
consuming a whole staged batch (MXU-friendly arrays) instead of a per-record
socket stream.

Importing this package registers the built-in kernels.
"""

from tpumr.ops.registry import KernelMapper, get_kernel, register_kernel, kernels

# built-ins register on import
import tpumr.ops.kmeans    # noqa: F401,E402
import tpumr.ops.matmul    # noqa: F401,E402
import tpumr.ops.pi        # noqa: F401,E402
import tpumr.ops.wordcount  # noqa: F401,E402
import tpumr.ops.grep      # noqa: F401,E402

__all__ = ["KernelMapper", "get_kernel", "register_kernel", "kernels"]
