"""Monte-Carlo π estimation map kernel.

≈ ``PiEstimator`` (reference: src/examples/org/apache/hadoop/examples/
PiEstimator.java, 353 LoC — halton-sequence sampling, one map per (offset,
size) pair). Each input record is ``"<seed> <num_samples>"``; the kernel
draws the whole sample block on device and reduces to two counters — the
map's output is 2 records regardless of sample count.
"""

from __future__ import annotations

import functools
from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np

from tpumr.mapred.api import Mapper
from tpumr.ops.registry import KernelMapper, register_kernel


@functools.partial(jax.jit, static_argnames=("n",))
def _count_inside(seed: int, n: int):
    key = jax.random.key(seed)
    pts = jax.random.uniform(key, (n, 2), dtype=jnp.float32)
    # int32: per-call n is bounded far below 2^31; totals accumulate in Python
    return jnp.sum(jnp.sum(pts * pts, axis=1) <= 1.0).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("n",))
def _count_inside_many(seeds, n: int):
    """All of a task's same-size sample blocks in ONE dispatch:
    ``lax.map`` runs the blocks sequentially on device (same transient
    memory as one block), so a task costs one small seed-array upload +
    one program launch instead of a scalar upload + dispatch per record
    — on a tunneled runtime the per-record launches were the task's
    wall-clock. Per-seed results are bit-identical to :func:`_count_inside`."""
    def one(seed):
        key = jax.random.key(seed)
        pts = jax.random.uniform(key, (n, 2), dtype=jnp.float32)
        return jnp.sum(jnp.sum(pts * pts, axis=1) <= 1.0).astype(jnp.int32)
    return jax.lax.map(one, seeds)


def _parse(value) -> tuple[int, int]:
    s = value.decode() if isinstance(value, (bytes, bytearray)) else str(value)
    seed_s, n_s = s.split()
    return int(seed_s), int(n_s)


class PiCpuMapper(Mapper):
    def map(self, key, value, output, reporter):
        seed, n = _parse(value)
        rng = np.random.default_rng(seed)
        pts = rng.random((n, 2), dtype=np.float32)
        inside = int(((pts * pts).sum(axis=1) <= 1.0).sum())
        output.collect("inside", inside)
        output.collect("total", n)


class PiSamplerKernel(KernelMapper):
    name = "pi-sampler"
    cpu_mapper_class = PiCpuMapper

    def map_batch_launch(self, batch, conf, task):
        """Group the task's records by sample count and launch ONE
        program per distinct n (usually exactly one) — the per-block
        device counters stay on device until the runner's single fetch.
        The original path synced once per record; the first batched
        version still dispatched once per record."""
        from collections import defaultdict
        groups: "dict[int, list[int]]" = defaultdict(list)
        total = 0
        for i in range(batch.num_records):
            seed, n = _parse(batch.value(i))
            groups[n].append(seed)
            total += n
        counts = [
            # mask to uint32 EXPLICITLY: numpy 2 refuses out-of-range
            # casts, and jax folds seeds to uint32 anyway (verified
            # key(-1) == key(2**32-1)) — negative/wide seeds keep the
            # per-record path's semantics instead of crashing the task
            _count_inside_many(np.asarray(
                [s & 0xFFFFFFFF for s in seeds], np.uint32), n)
            for n, seeds in groups.items()]
        return {"inside": counts, "total": total}

    def map_batch_drain(self, fetched, conf, task) -> Iterable[tuple]:
        yield "inside", sum(int(np.asarray(c).sum())
                            for c in fetched["inside"])
        yield "total", int(fetched["total"])

    def map_batch_cpu(self, batch, conf, task) -> Iterable[tuple]:
        """Vectorized host sampling — whole blocks per numpy call (CPU
        slots stay batch-speed in hybrid runs)."""
        inside = 0
        total = 0
        for i in range(batch.num_records):
            seed, n = _parse(batch.value(i))
            rng = np.random.default_rng(seed)
            pts = rng.random((n, 2), dtype=np.float32)
            inside += int(((pts * pts).sum(axis=1) <= 1.0).sum())
            total += n
        yield "inside", inside
        yield "total", total


register_kernel(PiSamplerKernel())
