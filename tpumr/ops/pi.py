"""Monte-Carlo π estimation map kernel.

≈ ``PiEstimator`` (reference: src/examples/org/apache/hadoop/examples/
PiEstimator.java, 353 LoC — halton-sequence sampling, one map per (offset,
size) pair). Each input record is ``"<seed> <num_samples>"``; the kernel
draws the whole sample block on device and reduces to two counters — the
map's output is 2 records regardless of sample count.
"""

from __future__ import annotations

import functools
from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np

from tpumr.mapred.api import Mapper
from tpumr.ops.registry import KernelMapper, register_kernel


@functools.partial(jax.jit, static_argnames=("n",))
def _count_inside(seed: int, n: int):
    key = jax.random.key(seed)
    pts = jax.random.uniform(key, (n, 2), dtype=jnp.float32)
    # int32: per-call n is bounded far below 2^31; totals accumulate in Python
    return jnp.sum(jnp.sum(pts * pts, axis=1) <= 1.0).astype(jnp.int32)


def _parse(value) -> tuple[int, int]:
    s = value.decode() if isinstance(value, (bytes, bytearray)) else str(value)
    seed_s, n_s = s.split()
    return int(seed_s), int(n_s)


class PiCpuMapper(Mapper):
    def map(self, key, value, output, reporter):
        seed, n = _parse(value)
        rng = np.random.default_rng(seed)
        pts = rng.random((n, 2), dtype=np.float32)
        inside = int(((pts * pts).sum(axis=1) <= 1.0).sum())
        output.collect("inside", inside)
        output.collect("total", n)


class PiSamplerKernel(KernelMapper):
    name = "pi-sampler"
    cpu_mapper_class = PiCpuMapper

    def map_batch_launch(self, batch, conf, task):
        """Dispatch every sample block without blocking — the per-block
        device counters stay on device until the runner's single fetch
        (the old path synced once per record: one tunnel roundtrip per
        (seed, n) line)."""
        counts = []
        total = 0
        for i in range(batch.num_records):
            seed, n = _parse(batch.value(i))
            counts.append(_count_inside(seed, n))
            total += n
        return {"inside": counts, "total": total}

    def map_batch_drain(self, fetched, conf, task) -> Iterable[tuple]:
        yield "inside", sum(int(c) for c in fetched["inside"])
        yield "total", int(fetched["total"])

    def map_batch_cpu(self, batch, conf, task) -> Iterable[tuple]:
        """Vectorized host sampling — whole blocks per numpy call (CPU
        slots stay batch-speed in hybrid runs)."""
        inside = 0
        total = 0
        for i in range(batch.num_records):
            seed, n = _parse(batch.value(i))
            rng = np.random.default_rng(seed)
            pts = rng.random((n, 2), dtype=np.float32)
            inside += int(((pts * pts).sum(axis=1) <= 1.0).sum())
            total += n
        yield "inside", inside
        yield "total", total


register_kernel(PiSamplerKernel())
