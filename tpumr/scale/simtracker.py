"""Simulated trackers: the real heartbeat wire protocol, fake execution.

A ``SimTracker`` is what a ``NodeRunner`` looks like FROM THE MASTER:
it registers with the protocol-version handshake, heartbeats its status
(slot pools, task statuses, metrics piggyback, fetch-failure reports —
full on contact, change-only deltas afterwards, exactly the NodeRunner
encoding from ``tpumr.mapred.heartbeat``) through a real ``RpcClient``
socket, honors the response-id replay protocol, and applies
launch/kill/reinit/disallowed actions. The
one thing it fakes is the work: an assigned task becomes a timed no-op
whose duration is drawn from a configurable distribution, and a
simulated reduce only completes after it has polled the master's
completion-event feed to "see" every map — so event polls (and their
master-side lag series) scale with the fleet exactly like real ones.

``SimFleet`` drives N of them from a bounded worker pool on a
fixed-rate schedule: each tracker has a due time every ``interval_s``,
and the gap between due and actual send is the CLIENT-side heartbeat
lag (the master independently measures arrival-gap lag). A saturated
master shows up here first as climbing round-trip latency, then as lag
when round trips exceed the interval.
"""

from __future__ import annotations

import heapq
import random
import threading
import time
from typing import Any, Callable

from tpumr.core import confkeys
from tpumr.ipc.rpc import RpcClient
from tpumr.mapred.heartbeat import HeartbeatEncoder
from tpumr.mapred.ids import TaskAttemptID
from tpumr.mapred.jobtracker import PROTOCOL_VERSION
from tpumr.mapred.task import TaskPhase, TaskState, TaskStatus
from tpumr.metrics.core import MetricsRegistry
from tpumr.metrics.histogram import Histogram
from tpumr.net import DEFAULT_RACK
from tpumr.utils.fi import fires


def default_task_time(rng: random.Random, is_map: bool,
                      mean_s: float = 0.1) -> float:
    """Uniform 0.5–1.5× the mean — enough spread that assignment order
    and completion order decorrelate (like real stragglers) without a
    heavy tail that would stall smoke-sized runs."""
    return rng.uniform(0.5, 1.5) * mean_s * (1.0 if is_map else 1.5)


class _SimTask:
    """One fake in-flight attempt: a deadline and a wire status."""

    __slots__ = ("job_id", "num_maps", "duration", "started", "status")

    def __init__(self, job_id: str, num_maps: int, duration: float,
                 status: TaskStatus) -> None:
        self.job_id = job_id
        self.num_maps = num_maps
        self.duration = max(1e-4, duration)
        self.started = time.monotonic()
        self.status = status


class SimTracker:
    """One simulated tracker speaking the real InterTracker protocol."""

    def __init__(self, name: str, master_host: str, master_port: int,
                 *, secret: "bytes | None" = None, cpu_slots: int = 2,
                 reduce_slots: int = 2,
                 task_time: "Callable[..., float] | None" = None,
                 task_time_mean_s: float = 0.1,
                 rng: "random.Random | None" = None,
                 fetch_failure_rate: float = 0.0,
                 piggyback: bool = True,
                 piggyback_interval_s: float = 1.0,
                 handshake: bool = True,
                 delta: bool = True,
                 rpc_timeout_s: float = 30.0,
                 index: int = -1,
                 fi_conf: Any = None) -> None:
        self.name = name
        #: fleet slot (the ``t<n>`` of the targeted ``tracker.crash.t<n>``
        #: chaos seam) — -1 when driven outside a fleet
        self.index = int(index)
        #: conf consulted for fault-injection seams (``tracker.crash``,
        #: ``task.slow``); None disables chaos entirely
        self.fi_conf = fi_conf
        self.crashed = False
        #: monotonic deadline while "partitioned away" (scenario-lab
        #: churn): the fleet skips this tracker's beats until then —
        #: the process stays alive, tasks keep finishing locally, and
        #: the master is left to expire it and adopt the rejoin
        self.paused_until = 0.0
        self.cpu_slots = cpu_slots
        self.reduce_slots = reduce_slots
        self._task_time = task_time or (
            lambda r, is_map: default_task_time(r, is_map,
                                                task_time_mean_s))
        self._rng = rng or random.Random(hash(name) & 0xFFFFFFFF)
        self._fetch_failure_rate = float(fetch_failure_rate)
        #: where this tracker's beats go — under a sharded master the
        #: fleet points each tracker at the shard that owns its name
        self.endpoint = (master_host, int(master_port))
        self.master = RpcClient(master_host, master_port, secret=secret,
                                timeout=rpc_timeout_s)
        if handshake:
            remote = self.master.call("get_protocol_version")
            if remote != PROTOCOL_VERSION:
                raise RuntimeError(f"master protocol {remote} != "
                                   f"{PROTOCOL_VERSION}")
        self._running: "dict[str, _SimTask]" = {}
        self._kill_requested: "set[str]" = set()
        self._fetch_failures: "list[dict]" = []
        self._reported_ff: "set[tuple[str, str]]" = set()
        self._response_id = 0
        self._initial_contact = True
        #: per-job completion-event cursor + live map outputs seen
        #: (OBSOLETE tombstones evict, like the real MapLocator fold)
        self._event_cursor: "dict[str, int]" = {}
        self._maps_live: "dict[str, dict[int, dict]]" = {}
        #: consecutive empty polls per starving job — rewinds the
        #: cursor like the real MapLocator (a pre-restart cursor can
        #: sit past a recovered job's shorter feed)
        self._empty_polls: "dict[str, int]" = {}
        self.stopped = False
        self.heartbeats = 0
        self.tasks_completed = 0
        # the metrics piggyback: a REAL registry shipped in the real
        # cumulative typed form, so the master's ClusterAggregator does
        # per-fleet-scale work on every beat exactly as in production
        self._reg = MetricsRegistry("tasktracker") if piggyback else None
        if self._reg is not None:
            self._task_hist = self._reg.histogram("sim_task_seconds")
        #: piggyback dirty flag + minimum ship interval: the registry
        #: only moves when a task completes, so idle beats skip
        #: building (and shipping) the typed snapshot entirely; under
        #: load the snapshot rides at most once per interval (metrics
        #: freshness is a seconds-scale concern, heartbeats are not) —
        #: mirrors the NodeRunner's tpumr.metrics.piggyback.interval.ms
        self._metrics_dirty = True
        self._piggyback_interval_s = float(piggyback_interval_s)
        self._piggyback_last = 0.0
        # the real tracker's delta encoding (tpumr.mapred.heartbeat):
        # the sim fleet must exercise the same wire protocol the master
        # optimizes for — near-empty idle beats included
        self._hb_encoder = HeartbeatEncoder(delta)
        #: RUNNING-status report-rate limit, mirroring the NodeRunner's
        #: tpumr.task.status.report.interval.ms (state transitions and
        #: terminal statuses always ship; unchanged RUNNING at most
        #: once per interval on delta beats)
        self._status_interval_s = 1.0
        self._status_shipped: "dict[str, tuple]" = {}
        #: in-flight pipelined beat (heartbeat_begin → heartbeat_finish)
        self._beat_ctx: "tuple | None" = None
        #: master-instructed heartbeat interval (adaptive cadence);
        #: None until the first response — the fleet schedules this
        #: tracker's next beat from it, exactly like a NodeRunner
        self.next_interval_s: "float | None" = None

    # ------------------------------------------------------------ protocol

    def heartbeat_once(self) -> None:
        """One full heartbeat round: advance fake work, poll completion
        events for gated reduces, send status, apply the response."""
        if self.heartbeat_begin():
            self.heartbeat_finish()

    def heartbeat_build(self) -> "tuple | None":
        """Build (but don't send) one beat: advance fake work, poll
        events, encode the wire status. Returns the heartbeat RPC args
        ``(status, initial_contact, ask, response_id)`` — the member
        shape ``heartbeat_batch`` carries — or None when stopped. The
        caller MUST follow with exactly one of :meth:`heartbeat_apply`
        (response delivered) or :meth:`heartbeat_abort` (delivery
        unknown)."""
        if self.stopped:
            return None
        self._poll_completion_events()
        self._advance_tasks()
        full = self._status_dict()
        now = time.monotonic()
        ship_metrics = (self._reg is not None and self._metrics_dirty
                        and now - self._piggyback_last
                        >= self._piggyback_interval_s)
        metrics = ({"tasktracker": self._reg.typed_snapshot()}
                   if ship_metrics else None)
        wire = full
        if self._hb_encoder.will_delta():
            wire = dict(full, task_statuses=self._suppress_statuses(
                full["task_statuses"], now))
        status = self._hb_encoder.encode(wire, metrics)
        cpu, red = self._counts()
        ask = cpu < self.cpu_slots or red < self.reduce_slots
        self._beat_ctx = (full, metrics, now)
        return (status, self._initial_contact, ask, self._response_id)

    def heartbeat_abort(self) -> None:
        """The built/sent beat's delivery is unknown (transport error
        anywhere between build and response) — same contract as
        NodeRunner: the next beat re-ships the full status."""
        self._beat_ctx = None
        self._hb_encoder.reset()

    def crash_seam_fired(self) -> bool:
        """BEHAVIORAL churn seam, checked right after a beat went on
        the wire: hard-kill mid-beat — the master may well fold the
        request, but the response is never read and the socket just
        dies, like a tracker SIGKILLed between send and receive."""
        if self.fi_conf is not None and (
                fires(f"tracker.crash.t{self.index}", self.fi_conf)
                or fires("tracker.crash", self.fi_conf)):
            self.crash()
            return True
        return False

    def heartbeat_begin(self) -> bool:
        """First half of a beat: advance fake work, poll events, SEND
        the status — without waiting for the response. Returns True
        when a request is now outstanding (pair with
        :meth:`heartbeat_finish`). The fleet pipelines many trackers'
        begins back-to-back so the master's handling overlaps the
        client side of other trackers instead of context-switching
        once per beat."""
        args = self.heartbeat_build()
        if args is None:
            return False
        try:
            self.master.call_begin("heartbeat", *args)
        except Exception:
            # delivery unknown — same contract as NodeRunner: the next
            # beat re-ships the full status
            self.heartbeat_abort()
            raise
        return not self.crash_seam_fired()

    def heartbeat_finish(self) -> None:
        """Second half: receive the response of the outstanding
        :meth:`heartbeat_begin` and apply it."""
        try:
            resp = self.master.call_finish()
        except Exception:
            # delivery unknown — same contract as NodeRunner: the next
            # beat re-ships the full status
            self._hb_encoder.reset()
            raise
        self.heartbeat_apply(resp)

    def heartbeat_apply(self, resp: dict) -> None:
        """Apply one delivered response to the beat built by
        :meth:`heartbeat_build` — the shared receive half of the
        pipelined and batched paths. A member-level error marker (a
        batch isolates member failures server-side) counts as a failed
        delivery: reset the encoder and raise."""
        full, metrics, now = self._beat_ctx
        self._beat_ctx = None
        if "error" in resp:
            self._hb_encoder.reset()
            raise RuntimeError(f"heartbeat member failed: "
                               f"{resp['error']}")
        self._hb_encoder.delivered()
        if metrics is not None:
            self._metrics_dirty = False
            self._piggyback_last = now
        self._initial_contact = False
        self._response_id = resp["response_id"]
        nxt = resp.get("next_interval_ms")
        if isinstance(nxt, (int, float)) and nxt > 0:
            self.next_interval_s = nxt / 1000.0
        self.heartbeats += 1
        if any(a.get("type") == "resend_full"
               for a in resp.get("actions", [])):
            # master folded nothing (it wants the full status first):
            # keep statuses + reports for the re-send (NodeRunner rule)
            for action in resp.get("actions", []):
                self._apply_action(action)
            return
        # delivered fetch-failure reports are done; ones appended since
        # the snapshot would stay — mirrors NodeRunner's contract
        sent_ff = len(full.get("fetch_failures", []))
        if sent_ff:
            del self._fetch_failures[:sent_ff]
        # drop statuses whose SENT snapshot was terminal (same rule as
        # the real tracker: a completion racing the RPC must survive)
        for sd in full.get("task_statuses", []):
            if sd["state"] in TaskState.TERMINAL:
                self._running.pop(sd["attempt_id"], None)
                self._kill_requested.discard(sd["attempt_id"])
                self._status_shipped.pop(sd["attempt_id"], None)
        for action in resp.get("actions", []):
            self._apply_action(action)

    def close(self) -> None:
        self.stopped = True
        self.master.close()

    def crash(self) -> None:
        """Hard kill: drop the connection with whatever was in flight,
        no deregistration, no encoder flush — exactly what the master
        sees when a tracker process dies. Master-side state (believed-
        running attempts, the replay cache entry) is left for the
        eviction sweep or the cold re-registration path to clean up."""
        self.stopped = True
        self.crashed = True
        self._beat_ctx = None
        self.master.close()

    # ------------------------------------------------------------ fake work

    def _counts(self) -> "tuple[int, int]":
        cpu = red = 0
        for t in self._running.values():
            if t.status.state != TaskState.RUNNING:
                continue
            if t.status.is_map:
                cpu += 1
            else:
                red += 1
        return cpu, red

    def _advance_tasks(self) -> None:
        now = time.monotonic()
        for aid, t in self._running.items():
            st = t.status
            if st.state != TaskState.RUNNING:
                continue
            if aid in self._kill_requested:
                st.state = TaskState.KILLED
                st.finish_time = time.time()
                st.diagnostics = "killed by master action (simulated)"
                continue
            elapsed = now - t.started
            if not st.is_map:
                live = self._maps_live.get(t.job_id, {})
                self._maybe_report_fetch_failure(t, live)
                if len(live) < t.num_maps:
                    # shuffle-gated: a reduce cannot finish before the
                    # event feed showed it every map output
                    st.progress = min(
                        0.3, 0.3 * len(live) / max(1, t.num_maps))
                    continue
                st.phase = TaskPhase.REDUCE
            if elapsed >= t.duration:
                st.state = TaskState.SUCCEEDED
                st.progress = 1.0
                st.finish_time = time.time()
                self.tasks_completed += 1
                if self._reg is not None:
                    self._reg.incr("sim_tasks_completed")
                    self._task_hist.observe(t.duration)
                    self._metrics_dirty = True
            else:
                st.progress = min(0.99, elapsed / t.duration)

    def _poll_completion_events(self) -> None:
        """Per running reduce's job, one incremental completion-event
        poll per beat — the real umbilical cadence, carried over the
        same master RPC surface (and observed by its lag series). A
        reduce that has already seen every map output stops polling,
        exactly like the real ReduceCopier once its fetch set is
        complete (OBSOLETE withdrawals can't strand it: a sim reduce
        past its shuffle gate no longer re-fetches)."""
        jobs = {t.job_id for t in self._running.values()
                if not t.status.is_map
                and t.status.state == TaskState.RUNNING
                and len(self._maps_live.get(t.job_id, {})) < t.num_maps}
        for job_id in jobs:
            cursor = self._event_cursor.get(job_id, 0)
            try:
                events = self.master.call("get_map_completion_events",
                                          job_id, cursor, 10_000)
            except Exception:  # noqa: BLE001 — purged job / master load
                continue
            self._event_cursor[job_id] = cursor + len(events)
            if events:
                self._empty_polls[job_id] = 0
            else:
                n = self._empty_polls.get(job_id, 0) + 1
                self._empty_polls[job_id] = n
                if n >= 25:
                    # starving: rewind — the cursor may predate a master
                    # restart (re-folds are idempotent, like MapLocator)
                    self._empty_polls[job_id] = 0
                    self._event_cursor[job_id] = 0
            live = self._maps_live.setdefault(job_id, {})
            for e in events:
                idx = e.get("map_index")
                if e.get("status") == "OBSOLETE":
                    cur = live.get(idx)
                    if cur is not None \
                            and cur["attempt_id"] == e["attempt_id"]:
                        del live[idx]
                else:
                    live[idx] = e

    def _maybe_report_fetch_failure(self, t: _SimTask,
                                    live: "dict[int, dict]") -> None:
        """Optional chaos: with probability ``fetch_failure_rate`` per
        beat, a running reduce reports one seen map output unfetchable —
        driving the master's withdraw/re-execute path under load. Each
        (reduce, map attempt) pair reports at most once, like a real
        copier that penalty-boxes after reporting."""
        if not self._fetch_failure_rate or not live:
            return
        if self._rng.random() >= self._fetch_failure_rate:
            return
        ev = live[self._rng.choice(list(live))]
        key = (str(t.status.attempt_id), ev["attempt_id"])
        if key in self._reported_ff:
            return
        self._reported_ff.add(key)
        self._fetch_failures.append({
            "map_attempt": ev["attempt_id"],
            "reduce_attempt": str(t.status.attempt_id)})

    # ------------------------------------------------------------ wire

    def _suppress_statuses(self, statuses: "list[dict]",
                           now: float) -> "list[dict]":
        """NodeRunner._suppress_statuses's sim twin: rate-limit
        unchanged RUNNING statuses on delta beats."""
        if not self._status_interval_s:
            return statuses
        out = []
        for sd in statuses:
            if sd["state"] != TaskState.RUNNING:
                out.append(sd)
                continue
            aid = sd["attempt_id"]
            key = (sd["state"], sd.get("phase"))
            prev = self._status_shipped.get(aid)
            if prev is not None and prev[:2] == key \
                    and now - prev[2] < self._status_interval_s:
                continue
            self._status_shipped[aid] = (*key, now)
            out.append(sd)
        return out

    def _status_dict(self) -> dict:
        cpu, red = self._counts()
        status = {
            "tracker_name": self.name,
            "host": f"sim-{self.name}",
            "shuffle_addr": f"sim-{self.name}:0",
            "shuffle_port": 0,
            "max_cpu_map_slots": self.cpu_slots,
            "max_tpu_map_slots": 0,
            "quarantined_tpu_devices": [],
            "max_reduce_slots": self.reduce_slots,
            "count_cpu_map_tasks": cpu,
            "count_tpu_map_tasks": 0,
            "count_reduce_tasks": red,
            "available_tpu_devices": [],
            "available_memory_mb": -1,
            "task_statuses": [t.status.to_dict()
                              for t in self._running.values()],
            "fetch_failures": list(self._fetch_failures),
            "rack": DEFAULT_RACK,
            "healthy": True,
            "health_report": "",
        }
        return status

    def _apply_action(self, action: dict) -> None:
        kind = action.get("type")
        if kind == "launch":
            d = action["task"]
            attempt = TaskAttemptID.parse(d["attempt_id"])
            is_map = attempt.task.is_map
            status = TaskStatus(
                attempt_id=attempt, is_map=is_map,
                state=TaskState.RUNNING,
                phase=TaskPhase.MAP if is_map else TaskPhase.SHUFFLE,
                run_on_tpu=bool(d.get("run_on_tpu", False)),
                tpu_device_id=int(d.get("tpu_device_id", -1)))
            duration = self._task_time(self._rng, is_map)
            if self.fi_conf is not None and fires("task.slow",
                                                  self.fi_conf):
                # straggler phase (scenario lab): the fake task stays
                # alive tpumr.fi.task.slow.ms longer — the sim twin of
                # the real task.slow behavioral seam in map_task
                duration += confkeys.get_int(
                    self.fi_conf, "tpumr.fi.task.slow.ms") / 1000.0
            self._running[d["attempt_id"]] = _SimTask(
                action["job_id"], int(d.get("num_maps", 0)),
                duration, status)
        elif kind == "kill_task":
            self._kill_requested.add(action["attempt_id"])
        elif kind == "reinit":
            self._running.clear()
            self._kill_requested.clear()
            self._fetch_failures.clear()
            self._initial_contact = True
            self._response_id = 0
            self._hb_encoder.reset()   # re-register with a full status
            self._status_shipped.clear()
        elif kind == "resend_full":
            # master lost our baseline (restart): re-ship the full
            # status next beat; unlike reinit, fake in-flight work
            # survives — the master adopts it (NodeRunner semantics)
            self._hb_encoder.reset()
            self._status_shipped.clear()
        elif kind == "disallowed":
            self.stopped = True


class SimFleet:
    """N ``SimTracker``s on a fixed-rate heartbeat schedule, driven by a
    bounded worker pool (hundreds of trackers don't need hundreds of
    client threads — a beat is one blocking RPC)."""

    def __init__(self, master_host: str, master_port: int,
                 n_trackers: int, *, secret: "bytes | None" = None,
                 interval_s: float = 0.2, workers: "int | None" = None,
                 name_prefix: str = "sim", seed: int = 0,
                 batch: int = 0,
                 shard_map: "list[tuple[str, int]] | None" = None,
                 stagger_s: "float | None" = None,
                 **tracker_kwargs: Any) -> None:
        self.master_host, self.master_port = master_host, master_port
        self.n = int(n_trackers)
        self.interval_s = float(interval_s)
        #: window the first beats spread over (default: one configured
        #: interval). Under adaptive cadence the steady schedule can be
        #: much coarser than the floor — spreading joins over THAT
        #: window keeps fleet start from being a synthetic herd whose
        #: full-status registrations arrive at many times the rate the
        #: master will ever instruct again.
        self.stagger_s = float(stagger_s) if stagger_s else self.interval_s
        self.secret = secret
        self.workers = workers or min(64, max(4, self.n // 4))
        self._prefix = name_prefix
        self._seed = seed
        #: members per coalesced ``heartbeat_batch`` RPC (0/1 keeps the
        #: per-tracker pipelined path) — the client twin of the
        #: master's ``tpumr.heartbeat.batch`` knob
        self.batch = int(batch)
        #: sharded master: each tracker beats the shard that owns its
        #: name (the same crc32 mapping the coordinator serves from
        #: ``get_shard_map``); None = one unsharded master
        self.shard_map = ([(str(h), int(p)) for h, p in shard_map]
                          if shard_map else None)
        self._tracker_kwargs = tracker_kwargs
        self.trackers: "list[SimTracker]" = []
        self._heap: "list[tuple[float, int]]" = []
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._threads: "list[threading.Thread]" = []
        # churn accounting (scenario lab): crashes and cold respawns
        self.trackers_crashed = 0
        self.trackers_respawned = 0
        self.trackers_partitioned = 0
        self._respawn_timers: "list[threading.Timer]" = []
        # client-side observability (the harness's own view, independent
        # of the master's): round-trip latency, schedule overrun, errors
        self.registry = MetricsRegistry("simfleet")
        self._rtt = self.registry.histogram("hb_rtt_seconds")
        self._lag = self.registry.histogram("hb_lag_seconds")

    def _endpoint(self, name: str) -> "tuple[str, int]":
        if not self.shard_map:
            return self.master_host, self.master_port
        from tpumr.mapred.shardmaster import tracker_shard
        return self.shard_map[tracker_shard(name,
                                            len(self.shard_map))]

    def start(self) -> "SimFleet":
        rng = random.Random(self._seed)
        for i in range(self.n):
            name = f"{self._prefix}_{i:04d}"
            host, port = self._endpoint(name)
            self.trackers.append(SimTracker(
                name, host, port, secret=self.secret, index=i,
                rng=random.Random(rng.randrange(1 << 30)),
                **self._tracker_kwargs))
        now = time.monotonic()
        # stagger first beats across one interval so fleet start doesn't
        # land as one synchronized thundering herd (unless saturation
        # makes it one — which is then a real measurement)
        self._heap = [(now + (i * self.stagger_s) / max(1, self.n), i)
                      for i in range(self.n)]
        heapq.heapify(self._heap)
        for w in range(self.workers):
            t = threading.Thread(target=self._worker,
                                 name=f"{self._prefix}-fleet-{w}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        return self

    #: max due beats one worker drains per wakeup: begins are PIPELINED
    #: (send all, then collect all responses) so the master handles a
    #: batch while this worker is still building the next request —
    #: at fleet rates the per-beat context-switch ping-pong was costing
    #: more CPU than the beats themselves. Bounded so one worker can't
    #: hoard a saturated heap (lag is recorded per beat either way).
    BATCH = 16

    def _worker(self) -> None:
        #: per-worker, per-endpoint batch clients: the pipelined
        #: RpcClient surface is single-threaded by contract
        clients: "dict[tuple[str, int], RpcClient]" = {}
        # a drain splits across shard endpoints (the heap orders by due
        # time, not owner), so scale it by the shard count or each
        # endpoint's RPC would only carry ~batch/shards members
        cap = max(self.BATCH, self.batch * (len(self.shard_map)
                                            if self.shard_map else 1))
        try:
            while not self._stop.is_set():
                batch: "list[tuple[float, int]]" = []
                with self._cv:
                    while not self._stop.is_set():
                        now = time.monotonic()
                        while self._heap and len(batch) < cap \
                                and self._heap[0][0] <= now:
                            batch.append(heapq.heappop(self._heap))
                        if batch:
                            break
                        wait = (self._heap[0][0] - now) if self._heap \
                            else 0.05
                        self._cv.wait(min(max(wait, 0.0), 0.05))
                    else:
                        return
                if self.batch > 1:
                    self._beat_batched(batch, clients)
                else:
                    self._beat_pipelined(batch)
                # fixed-rate schedule AGAINST THE INSTRUCTED CADENCE
                # (the master's adaptive interval, once a response
                # carried one); when more than a full interval behind,
                # skip ahead (the lag was recorded — re-queueing a
                # backlog of missed beats would only spiral the
                # overload)
                now = time.monotonic()
                with self._cv:
                    for due, idx in batch:
                        tracker = self.trackers[idx]
                        if not tracker.stopped \
                                and not self._stop.is_set():
                            iv = tracker.next_interval_s \
                                or self.interval_s
                            nxt = due + iv
                            if nxt <= now:
                                nxt = now + iv
                            if nxt < tracker.paused_until:
                                nxt = tracker.paused_until
                            heapq.heappush(self._heap, (nxt, idx))
                    self._cv.notify()
        finally:
            for client in clients.values():
                try:
                    client.close()
                except Exception:  # noqa: BLE001 — teardown
                    pass

    def _beat_pipelined(self, batch: "list[tuple[float, int]]") -> None:
        now = time.monotonic()
        begun: "list[tuple[float, int, float]]" = []
        for due, idx in batch:
            self._lag.observe(max(0.0, now - due))
            tracker = self.trackers[idx]
            if tracker.stopped:
                continue
            if now < tracker.paused_until:
                continue   # partitioned away; rescheduled by caller
            t0 = time.monotonic()
            try:
                if tracker.heartbeat_begin():
                    begun.append((due, idx, t0))
            except Exception:  # noqa: BLE001 — master down/overload
                self.registry.incr("hb_errors")
        for due, idx, t0 in begun:
            try:
                self.trackers[idx].heartbeat_finish()
                self._rtt.observe(time.monotonic() - t0)
            except Exception:  # noqa: BLE001 — master down/overload
                self.registry.incr("hb_errors")

    def _beat_batched(self, batch: "list[tuple[float, int]]",
                      clients: "dict[tuple[str, int], RpcClient]") \
            -> None:
        """Coalesce this wakeup's due beats into ONE ``heartbeat_batch``
        RPC per endpoint (per shard, under a sharded master): build all
        members first, send every endpoint's batch back-to-back
        (pipelined across endpoints), then collect and apply responses
        member-by-member. One syscall round-trip now carries up to
        ``batch`` beats — the client half of the batching win."""
        now = time.monotonic()
        by_ep: "dict[tuple[str, int], list[SimTracker]]" = {}
        for due, idx in batch:
            self._lag.observe(max(0.0, now - due))
            tracker = self.trackers[idx]
            if tracker.stopped or now < tracker.paused_until:
                continue
            by_ep.setdefault(tracker.endpoint, []).append(tracker)
        sends = []
        for ep, members in by_ep.items():
            built: "list[tuple[SimTracker, tuple]]" = []
            for tr in members:
                try:
                    args = tr.heartbeat_build()
                except Exception:  # noqa: BLE001 — event-poll hiccup
                    self.registry.incr("hb_errors")
                    continue
                if args is not None:
                    built.append((tr, args))
            if not built:
                continue
            client = clients.get(ep)
            if client is None:
                client = clients[ep] = RpcClient(
                    ep[0], ep[1], secret=self.secret)
            t0 = time.monotonic()
            try:
                client.call_begin("heartbeat_batch",
                                  [list(a) for _, a in built])
            except Exception:  # noqa: BLE001 — master down/overload
                for tr, _ in built:
                    tr.heartbeat_abort()
                self.registry.incr("hb_errors")
                continue
            for tr, _ in built:
                tr.crash_seam_fired()
            sends.append((client, built, t0))
        for client, built, t0 in sends:
            try:
                resps = client.call_finish()
            except Exception:  # noqa: BLE001 — master down/overload
                for tr, _ in built:
                    if not tr.crashed:
                        tr.heartbeat_abort()
                self.registry.incr("hb_errors")
                continue
            self._rtt.observe(time.monotonic() - t0)
            self.registry.incr("hb_batches")
            for (tr, _), resp in zip(built, resps or []):
                if tr.crashed or tr.stopped:
                    continue
                try:
                    tr.heartbeat_apply(resp)
                except Exception:  # noqa: BLE001 — member error
                    self.registry.incr("hb_errors")

    def stop(self) -> None:
        self._stop.set()
        for timer in self._respawn_timers:
            timer.cancel()
        with self._cv:
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
        for tr in self.trackers:
            tr.close()

    # ------------------------------------------------------------ churn

    def crash(self, idx: int) -> str:
        """Hard-kill tracker ``idx`` (scenario-lab churn): the socket
        drops mid-schedule, nothing deregisters, the master is left to
        notice. Returns the tracker's name."""
        tracker = self.trackers[idx]
        tracker.crash()
        self.trackers_crashed += 1
        return tracker.name

    def respawn(self, idx: int) -> SimTracker:
        """Cold-restart tracker ``idx`` under its old name: a brand-new
        process image (fresh response id, initial-contact beat, empty
        task table). The master either adopts it back through the
        rejoin/adoption path (if the old incarnation was already
        evicted) or takes the cold re-registration path (if not). The
        replacement RNG is derived from (fleet seed, slot, generation)
        so churn replays bit-identically under a pinned seed."""
        self.trackers_respawned += 1
        rng = random.Random(
            f"{self._seed}:respawn:{idx}:{self.trackers_respawned}")
        name = f"{self._prefix}_{idx:04d}"
        host, port = self._endpoint(name)
        deadline = time.monotonic() + 15.0
        while True:
            try:
                tracker = SimTracker(
                    name, host, port, secret=self.secret, index=idx,
                    rng=rng, **self._tracker_kwargs)
                break
            except OSError:
                # master mid-restart: a real tracker would retry too
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.05)
        with self._cv:
            self.trackers[idx] = tracker
            heapq.heappush(self._heap, (time.monotonic(), idx))
            self._cv.notify()
        return tracker

    def churn(self, idxs: "list[int] | None" = None, n: int = 1,
              rejoin_after_s: "float | None" = None,
              rng: "random.Random | None" = None) -> "list[str]":
        """Crash ``idxs`` (or ``n`` slots drawn from ``rng``, defaulting
        to a fleet-seed RNG) right now; when ``rejoin_after_s`` is set,
        cold-respawn each slot after that delay on daemon timers
        (cancelled by :meth:`stop`). Returns the crashed names."""
        if idxs is None:
            r = rng or random.Random(self._seed)
            idxs = sorted(r.sample(range(self.n), min(int(n), self.n)))
        names = [self.crash(i) for i in idxs]
        if rejoin_after_s is not None:
            for i in idxs:
                timer = threading.Timer(rejoin_after_s,
                                        self._respawn_quiet, args=(i,))
                timer.daemon = True
                timer.start()
                self._respawn_timers.append(timer)
        return names

    def partition(self, idxs: "list[int] | None" = None, n: int = 1,
                  duration_s: float = 2.5,
                  rng: "random.Random | None" = None) -> "list[str]":
        """Partition ``idxs`` (or ``n`` seed-drawn slots) away from the
        master for ``duration_s``: beats stop but the PROCESS survives —
        tasks keep finishing locally, state and response id intact.
        When the silence outlives the expiry sweep the master evicts
        the tracker, so the rejoin beat arrives from an \"unknown\"
        name: delta → ``resend_full`` → a full NON-initial status, the
        adoption path (``trackers_adopted``), in-flight work and all.
        Returns the partitioned names."""
        if idxs is None:
            r = rng or random.Random(self._seed)
            idxs = sorted(r.sample(range(self.n), min(int(n), self.n)))
        until = time.monotonic() + float(duration_s)
        names = []
        with self._cv:
            for i in idxs:
                self.trackers[i].paused_until = until
                names.append(self.trackers[i].name)
            self.trackers_partitioned += len(idxs)
            self._cv.notify()
        return names

    def _respawn_quiet(self, idx: int) -> None:
        if self._stop.is_set():
            return
        try:
            self.respawn(idx)
        except Exception:  # noqa: BLE001 — fleet stopping under us
            self.registry.incr("respawn_errors")

    # ------------------------------------------------------------ read side

    def stats(self) -> dict:
        """Client-side summary: heartbeat round-trip and schedule-lag
        distributions, error count, beats delivered, tasks completed."""
        snap = self.registry.snapshot()
        return {
            "heartbeats": sum(t.heartbeats for t in self.trackers),
            "tasks_completed": sum(t.tasks_completed
                                   for t in self.trackers),
            "hb_errors": snap.get("hb_errors", 0),
            "trackers_crashed": self.trackers_crashed,
            "trackers_respawned": self.trackers_respawned,
            "trackers_partitioned": self.trackers_partitioned,
            "hb_rtt": snap.get("hb_rtt_seconds",
                               Histogram("x").snapshot()),
            "hb_lag": snap.get("hb_lag_seconds",
                               Histogram("x").snapshot()),
        }
