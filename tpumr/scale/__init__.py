"""Control-plane scale harness — simulated trackers, real wire protocol.

The ROADMAP's scale-out item demands measurement before refactoring:
the JobTracker is one process absorbing every heartbeat, completion-
event poll, and fetch-failure report, and nobody ever measured where it
saturates (the reference inherited Hadoop 1.0.3's JobTracker with the
same blind spot). This package supplies the load side:

- :mod:`tpumr.scale.simtracker` — ``SimTracker``/``SimFleet``: N
  lightweight fake trackers speaking the REAL heartbeat protocol over
  the REAL RPC transport (``RpcClient`` → ``ipc/rpc.py`` → the live
  ``JobMaster.heartbeat``), executing assigned tasks as timed no-ops
  drawn from a configurable duration distribution. Everything the wire
  carries is authentic — response-id replay, metrics piggybacks,
  completion-event polls, fetch-failure reports — only task execution
  is faked, because task bytes are the data plane and this harness
  measures the control plane.
- :mod:`tpumr.scale.driver` — ``ScaleDriver``: submits synthetic
  multi-job workloads over the client RPC surface and waits for them.

The read side is the master's own saturation series (heartbeat
latency/lag/phases, ``jt_lock_wait_seconds``, ``rpc_inflight``,
completion-event lag) — see ``bench_scale.py`` at the repo root, which
ramps fleet sizes and writes the ``bench_scale.json`` baseline every
control-plane refactor must beat, and ``tpumr simulate`` in the CLI.
"""

from tpumr.scale.driver import ScaleDriver
from tpumr.scale.simtracker import SimFleet, SimTracker

__all__ = ["ScaleDriver", "SimFleet", "SimTracker"]
