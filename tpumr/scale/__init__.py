"""Control-plane scale harness — simulated trackers, real wire protocol.

The ROADMAP's scale-out item demands measurement before refactoring:
the JobTracker is one process absorbing every heartbeat, completion-
event poll, and fetch-failure report, and nobody ever measured where it
saturates (the reference inherited Hadoop 1.0.3's JobTracker with the
same blind spot). This package supplies the load side:

- :mod:`tpumr.scale.simtracker` — ``SimTracker``/``SimFleet``: N
  lightweight fake trackers speaking the REAL heartbeat protocol over
  the REAL RPC transport (``RpcClient`` → ``ipc/rpc.py`` → the live
  ``JobMaster.heartbeat``), executing assigned tasks as timed no-ops
  drawn from a configurable duration distribution. Everything the wire
  carries is authentic — response-id replay, metrics piggybacks,
  completion-event polls, fetch-failure reports — only task execution
  is faked, because task bytes are the data plane and this harness
  measures the control plane.
- :mod:`tpumr.scale.simdfs` — ``SimDFSClient``/``SimDFSFleet``: the
  storage twin — N real ``DFSClient`` instances generating a skewed
  read-dominant op mix against a live NameNode + DataNodes, the load
  side of ``bench_dfs.py`` and ``tpumr simulate -dfs``.
- :mod:`tpumr.scale.driver` — ``ScaleDriver``: submits synthetic
  multi-job workloads over the client RPC surface and waits for them.
- :mod:`tpumr.scale.scenario` — the scenario lab: named,
  seed-deterministic traffic mixes (interactive bursts, wide batch,
  iterative pipelines) replayed against a real master with chaos
  (tracker churn, master kill/restart, fi seams) and judged by
  per-traffic-class SLO verdicts from the flight recorder.

The read side is the master's own saturation series (heartbeat
latency/lag/phases, ``jt_lock_wait_seconds``, ``rpc_inflight``,
completion-event lag) — see ``bench_scale.py`` at the repo root, which
ramps fleet sizes and writes the ``bench_scale.json`` baseline every
control-plane refactor must beat, and ``tpumr simulate`` in the CLI.
"""

from tpumr.scale.driver import ScaleDriver
from tpumr.scale.scenario import (BUILTIN_SCENARIOS, ScenarioError,
                                  ScenarioRunner, list_scenarios,
                                  load_spec, plan, run_named,
                                  validate_spec)
from tpumr.scale.simdfs import SimDFSClient, SimDFSFleet
from tpumr.scale.simtracker import SimFleet, SimTracker

__all__ = ["BUILTIN_SCENARIOS", "ScaleDriver", "ScenarioError",
           "ScenarioRunner", "SimDFSClient", "SimDFSFleet", "SimFleet",
           "SimTracker", "list_scenarios", "load_spec", "plan",
           "run_named", "validate_spec"]
