"""Scenario lab: seed-deterministic traffic replay with chaos.

A scenario is a named, parameterized traffic TRACE — interactive
bursts, wide batch jobs, iterative/pipeline rounds — replayed against a
REAL ``JobMaster`` by the scale harness (``SimFleet`` heartbeats the
real wire protocol, ``ScaleDriver`` submits over the real client RPC
surface), interleaved with chaos: tracker churn (hard-kill mid-beat +
cold rejoin), a mid-mix master kill/restart, straggler phases
(fi ``task.slow``), a master-side heartbeat stall (fi
``jt.heartbeat.slow``), and fetch-failure reports. Every job carries a
traffic class (``tpumr.scenario.class``), so the master's flight
recorder windows per-class submit→first-assignment and submit→complete
latency against per-class SLOs and the run emits a machine-readable
pass/fail PER CLASS — with incident bundles as the failure artifact.

A spec with a ``dfs`` table extends the lab to the STORAGE layer: a
real ``MiniDFSCluster`` (NameNode + DataNodes over localhost RPC)
carries a ``SimDFSFleet`` of verifying ``DFSClient``s alongside the
MapReduce classes, and four storage chaos kinds drive its recovery
machinery — ``dn_crash`` (hard-kill mid-read, optional cold rejoin:
client replica failover + NN expiry + re-replication), ``dn_partition``
(heartbeat silence WITHOUT process death via the fi ``dn.partition``
seam: expiry, then rejoin through re-register + block report),
``nn_restart`` (SIGKILL-equivalent + rebind on the same port: editlog
replay, safemode entry/exit timed into the chaos log, clients riding
RPC retries with safemode refusals budgeted separately from errors),
and ``block_corrupt`` (flip bytes in one replica on disk via the fi
``dn.read.corrupt.b<id>`` seam: checksum detection, bad-block report,
drop + re-replicate — the fleet's verified reads prove readers NEVER
see the rot). The report gains a ``dfs`` section with its own SLO
verdicts (error fraction, corrupt reads == 0, read/meta p99, end-of-run
fsck heal) that feeds the overall pass.

Determinism: :func:`plan` expands a spec into a timestamped event list
using only ``(spec, seed)`` — submissions (with per-class jitter) and
chaos targets are all drawn from one seeded stream, the master's fault
seams replay from ``tpumr.fi.seed``, and every SimTracker RNG derives
from the fleet seed. Two runs under one seed produce identical job
schedules and chaos event sequences (the ``plan`` list in the report is
the comparable surface).

Specs are plain dicts — committed here as the built-in mixes, or
authored by operators as TOML files (``tpumr scenario -list`` /
``tpumr simulate -scenario NAME``); TOML loading needs Python 3.11+
(``tomllib``) or an installed ``tomli``.
"""

from __future__ import annotations

import os
import random
import shutil
import tempfile
import threading
import time
from typing import Any

from tpumr.scale.driver import ScaleDriver
from tpumr.scale.simtracker import SimFleet
from tpumr.utils import fi


class ScenarioError(ValueError):
    """A spec that cannot be replayed (unknown key, bad shape…)."""


_PRIORITIES = ("VERY_HIGH", "HIGH", "NORMAL", "LOW", "VERY_LOW")
_CHAOS_KINDS = ("tracker_crash", "tracker_partition",
                "master_restart", "shard_kill", "fi",
                "dn_crash", "dn_partition", "nn_restart",
                "block_corrupt")
#: the storage chaos kinds — only valid when the spec has a [dfs] table
_DFS_CHAOS_KINDS = ("dn_crash", "dn_partition", "nn_restart",
                    "block_corrupt")

_SPEC_KEYS = {"name", "seed", "fleet", "master", "classes", "chaos",
              "dfs", "timeout_s", "max_breach_fraction"}
_FLEET_DEFAULTS = {"trackers": 8, "interval_ms": 100, "cpu_slots": 2,
                   "reduce_slots": 1, "task_mean_ms": 250,
                   "fetch_failure_rate": 0.0, "batch": 0}
_MASTER_DEFAULTS = {"expiry_ms": 60_000, "beats_per_second": 0,
                    "interval_max_ms": 0, "brownout": False,
                    "shards": 0, "conf": {}}
_CLASS_DEFAULTS = {"jobs": 1, "maps": 2, "reduces": 0, "start_ms": 0,
                   "period_ms": 500, "jitter_ms": 0, "rounds": 1,
                   "priority": "NORMAL", "slo_assign_ms": None,
                   "slo_complete_ms": None}
#: the storage twin of the fleet table: datanode count, verifying
#: client fleet shape, seeded working set, recovery-speed knobs, and
#: the DFS-side SLO budgets the report's ``dfs`` verdict judges
_DFS_DEFAULTS = {"datanodes": 3, "clients": 4, "interval_ms": 50,
                 "files": 4, "file_kb": 64, "hot_read_p": 0.5,
                 "read_kb": 48, "replication_interval_ms": 200,
                 "expiry_ms": 1500, "slo_read_p99_ms": None,
                 "slo_meta_p99_ms": None, "max_error_fraction": 0.02,
                 "conf": {}}
_CHAOS_DEFAULTS = {
    "tracker_crash": {"count": 1, "targets": None, "rejoin_ms": None},
    "tracker_partition": {"count": 1, "targets": None,
                          "duration_ms": 2500},
    "master_restart": {},
    # SIGKILL one shard worker of a sharded master (master.shards > 0);
    # the coordinator's monitor respawns it on its pinned port and the
    # shard's trackers re-join via the adoption protocol. shard=None
    # draws the victim from the seeded stream
    "shard_kill": {"shard": None},
    "fi": {"point": None, "probability": 0.0, "max_failures": 0,
           "ms": None},
    # hard-kill datanode(s) mid-whatever; rejoin_ms=None means they
    # never come back (re-replication alone must restore the targets)
    "dn_crash": {"count": 1, "targets": None, "rejoin_ms": None},
    # heartbeat silence without process death: the NN expires the
    # node(s) while reads keep serving, then block reports rejoin them.
    # Which datanodes fall silent is whoever draws the seam first —
    # deterministic in COUNT, not in identity (the seam fires in the
    # datanodes' own heartbeat threads)
    "dn_partition": {"count": 1, "duration_ms": 2500},
    # SIGKILL-equivalent on the NameNode, rebind on the same port after
    # the outage: editlog replay + safemode, clients riding retries
    "nn_restart": {"outage_ms": 300},
    # flip bytes in ONE replica of the file's first block just before
    # a read serves it; file_index=None draws from the seeded stream
    "block_corrupt": {"file_index": None, "count": 1},
}


def _ident(value: Any, what: str) -> str:
    s = str(value or "")
    if not s or not all(c.isalnum() or c in "_-" for c in s) \
            or not s[0].isalpha():
        raise ScenarioError(f"{what} must be a simple identifier "
                            f"([a-z0-9_-], letter first): {value!r}")
    return s


def _merged(defaults: dict, given: Any, what: str) -> dict:
    if given is None:
        given = {}
    if not isinstance(given, dict):
        raise ScenarioError(f"{what} must be a table, got "
                            f"{type(given).__name__}")
    unknown = set(given) - set(defaults)
    if unknown:
        raise ScenarioError(
            f"{what} has unknown keys {sorted(unknown)} "
            f"(valid: {sorted(defaults)})")
    out = dict(defaults)
    out.update(given)
    return out


def _non_negative(row: dict, keys: "tuple[str, ...]",
                  what: str) -> None:
    for k in keys:
        v = row.get(k)
        if v is not None and (not isinstance(v, (int, float))
                              or v < 0):
            raise ScenarioError(f"{what}.{k} must be a non-negative "
                                f"number, got {v!r}")


def validate_spec(spec: Any) -> dict:
    """Normalize + validate one scenario spec (idempotent). Raises
    :class:`ScenarioError` with an author-actionable message."""
    if not isinstance(spec, dict):
        raise ScenarioError("spec must be a table/dict")
    unknown = set(spec) - _SPEC_KEYS
    if unknown:
        raise ScenarioError(f"unknown top-level keys {sorted(unknown)} "
                            f"(valid: {sorted(_SPEC_KEYS)})")
    out: "dict[str, Any]" = {
        "name": _ident(spec.get("name"), "scenario name"),
        "seed": int(spec.get("seed", 0)),
        "timeout_s": float(spec.get("timeout_s", 60.0)),
        "max_breach_fraction": float(
            spec.get("max_breach_fraction", 0.5)),
    }
    out["fleet"] = _merged(_FLEET_DEFAULTS, spec.get("fleet"), "fleet")
    out["master"] = _merged(_MASTER_DEFAULTS, spec.get("master"),
                            "master")
    _non_negative(out["fleet"], ("interval_ms", "task_mean_ms",
                                 "fetch_failure_rate", "batch"),
                  "fleet")
    if int(out["fleet"]["trackers"]) < 1:
        raise ScenarioError("fleet.trackers must be >= 1")
    _non_negative(out["master"], ("shards",), "master")
    classes = spec.get("classes")
    if not isinstance(classes, list) or not classes:
        raise ScenarioError("classes must be a non-empty list "
                            "(every job needs a traffic class)")
    out["classes"] = []
    for i, c in enumerate(classes):
        row = _merged(dict(_CLASS_DEFAULTS, name=None), c,
                      f"classes[{i}]")
        row["name"] = _ident(row["name"], f"classes[{i}].name")
        _non_negative(row, ("jobs", "maps", "reduces", "start_ms",
                            "period_ms", "jitter_ms", "rounds",
                            "slo_assign_ms", "slo_complete_ms"),
                      f"classes[{i}]")
        if int(row["jobs"]) < 1 or int(row["maps"]) < 1 \
                or int(row["rounds"]) < 1:
            raise ScenarioError(f"classes[{i}] jobs/maps/rounds "
                                "must be >= 1")
        if row["priority"] not in _PRIORITIES:
            raise ScenarioError(
                f"classes[{i}].priority {row['priority']!r} not in "
                f"{_PRIORITIES}")
        out["classes"].append(row)
    out["dfs"] = None
    if spec.get("dfs") is not None:
        d = _merged(_DFS_DEFAULTS, spec.get("dfs"), "dfs")
        _non_negative(d, ("interval_ms", "file_kb", "hot_read_p",
                          "read_kb", "replication_interval_ms",
                          "expiry_ms", "slo_read_p99_ms",
                          "slo_meta_p99_ms", "max_error_fraction"),
                      "dfs")
        # the seeded working set is written at replication=2, so a
        # single datanode loss must leave a surviving replica
        if int(d["datanodes"]) < 2:
            raise ScenarioError("dfs.datanodes must be >= 2")
        if int(d["clients"]) < 1 or int(d["files"]) < 1:
            raise ScenarioError("dfs.clients/files must be >= 1")
        out["dfs"] = d
    out["chaos"] = []
    for i, ev in enumerate(spec.get("chaos") or []):
        if not isinstance(ev, dict) or ev.get("kind") \
                not in _CHAOS_KINDS:
            raise ScenarioError(
                f"chaos[{i}].kind must be one of {_CHAOS_KINDS}")
        kind = ev["kind"]
        row = _merged(dict(_CHAOS_DEFAULTS[kind], kind=kind,
                           at_ms=None), ev, f"chaos[{i}]")
        if not isinstance(row.get("at_ms"), (int, float)) \
                or row["at_ms"] < 0:
            raise ScenarioError(f"chaos[{i}].at_ms must be a "
                                "non-negative number")
        if kind in _DFS_CHAOS_KINDS and out["dfs"] is None:
            raise ScenarioError(
                f"chaos[{i}].{kind} needs a [dfs] table (the storage "
                "chaos kinds drive the mini-DFS cluster)")
        if kind == "dn_crash" and row["targets"] is not None:
            n_dn = int(out["dfs"]["datanodes"])
            if any(not isinstance(t, int) or not 0 <= t < n_dn
                   for t in row["targets"]):
                raise ScenarioError(
                    f"chaos[{i}].targets must be datanode indexes "
                    f"in [0, {n_dn})")
        if kind == "block_corrupt" and row["file_index"] is not None:
            n_files = int(out["dfs"]["files"])
            if not isinstance(row["file_index"], int) \
                    or not 0 <= row["file_index"] < n_files:
                raise ScenarioError(
                    f"chaos[{i}].file_index must be in "
                    f"[0, {n_files})")
        if kind == "shard_kill":
            n_shards = int(out["master"]["shards"])
            if n_shards < 1:
                raise ScenarioError(
                    f"chaos[{i}].shard_kill needs master.shards >= 1 "
                    "(only a sharded master has shard workers to kill)")
            if row["shard"] is not None and (
                    not isinstance(row["shard"], int)
                    or not 0 <= row["shard"] < n_shards):
                raise ScenarioError(
                    f"chaos[{i}].shard must be a shard index in "
                    f"[0, {n_shards})")
        if kind == "master_restart" \
                and int(out["master"]["shards"]) > 0:
            raise ScenarioError(
                f"chaos[{i}].master_restart is the single-process "
                "master's chaos kind — use shard_kill against a "
                "sharded master")
        if kind == "fi":
            if not row["point"] or "tpumr" in str(row["point"]):
                raise ScenarioError(
                    f"chaos[{i}].point must be a bare seam name "
                    f"(e.g. 'jt.heartbeat.slow'), got "
                    f"{row['point']!r}")
            p = row["probability"]
            if not isinstance(p, (int, float)) or not 0 <= p <= 1:
                raise ScenarioError(
                    f"chaos[{i}].probability must be in [0, 1]")
        out["chaos"].append(row)
    return out


def plan(spec: dict) -> "list[dict]":
    """Expand a spec into the deterministic, timestamped event list a
    run replays: pure function of (spec, seed) — class jitter and
    default chaos targets come from one seeded stream, drawn in spec
    order before the final sort."""
    spec = validate_spec(spec)
    rng = random.Random(f"{spec['seed']}:{spec['name']}")
    events: "list[dict]" = []
    for ci, c in enumerate(spec["classes"]):
        for j in range(int(c["jobs"])):
            jitter = rng.randrange(int(c["jitter_ms"]) + 1) \
                if c["jitter_ms"] else 0
            events.append({
                "t_s": round((c["start_ms"] + j * c["period_ms"]
                              + jitter) / 1000.0, 4),
                "kind": "submit", "class": c["name"],
                "name": f"{c['name']}{ci}-{j}",
                "maps": int(c["maps"]), "reduces": int(c["reduces"]),
                "rounds": int(c["rounds"]),
                "priority": c["priority"]})
    n_trackers = int(spec["fleet"]["trackers"])
    for ev in spec["chaos"]:
        row: "dict[str, Any]" = {"t_s": round(ev["at_ms"] / 1000.0, 4),
                                 "kind": ev["kind"]}
        if ev["kind"] in ("tracker_crash", "tracker_partition"):
            targets = ev["targets"]
            if targets is None:
                targets = sorted(rng.sample(
                    range(n_trackers),
                    min(int(ev["count"]), n_trackers)))
            row["targets"] = [int(t) for t in targets]
            if ev["kind"] == "tracker_crash":
                row["rejoin_s"] = (
                    ev["rejoin_ms"] / 1000.0
                    if ev["rejoin_ms"] is not None else None)
            else:
                row["duration_s"] = ev["duration_ms"] / 1000.0
        elif ev["kind"] == "shard_kill":
            shard = ev["shard"]
            if shard is None:
                shard = rng.randrange(
                    int(spec["master"]["shards"]))
            row["shard"] = int(shard)
        elif ev["kind"] == "fi":
            row.update(point=str(ev["point"]),
                       probability=float(ev["probability"]),
                       max_failures=int(ev["max_failures"]),
                       ms=ev["ms"])
        elif ev["kind"] == "dn_crash":
            targets = ev["targets"]
            if targets is None:
                n_dn = int(spec["dfs"]["datanodes"])
                targets = sorted(rng.sample(
                    range(n_dn), min(int(ev["count"]), n_dn)))
            row["targets"] = [int(t) for t in targets]
            row["rejoin_s"] = (ev["rejoin_ms"] / 1000.0
                               if ev["rejoin_ms"] is not None else None)
        elif ev["kind"] == "dn_partition":
            row["count"] = int(ev["count"])
            row["duration_s"] = ev["duration_ms"] / 1000.0
        elif ev["kind"] == "nn_restart":
            row["outage_s"] = ev["outage_ms"] / 1000.0
        elif ev["kind"] == "block_corrupt":
            idx = ev["file_index"]
            if idx is None:
                idx = rng.randrange(int(spec["dfs"]["files"]))
            row["file_index"] = int(idx)
            row["count"] = int(ev["count"])
        events.append(row)
    events.sort(key=lambda e: (e["t_s"], e["kind"],
                               e.get("name", "")))
    return events


# ------------------------------------------------------------ built-ins

BUILTIN_SCENARIOS: "dict[str, dict]" = {
    # the north-star mix: interactive bursts + wide batch + an
    # iterative pipeline sharing one master, no chaos — the baseline
    # every chaos mix is judged against
    "steady_mix": {
        "name": "steady_mix",
        "fleet": {"trackers": 8, "task_mean_ms": 250},
        "classes": [
            {"name": "interactive", "jobs": 8, "maps": 2, "reduces": 0,
             "period_ms": 1200, "jitter_ms": 400, "priority": "HIGH",
             "slo_assign_ms": 1500, "slo_complete_ms": 8000},
            {"name": "batch", "jobs": 3, "maps": 16, "reduces": 2,
             "start_ms": 500, "period_ms": 3000,
             "slo_complete_ms": 45_000},
            {"name": "pipeline", "jobs": 2, "maps": 4, "reduces": 1,
             "rounds": 3, "start_ms": 1000, "period_ms": 4000},
        ],
        "timeout_s": 60,
    },
    # two tight interactive bursts landing on a master already busy
    # with wide batch work: does HIGH priority actually buy the bursts
    # their first assignments?
    "interactive_burst": {
        "name": "interactive_burst",
        "fleet": {"trackers": 8, "task_mean_ms": 300},
        "classes": [
            {"name": "batch", "jobs": 2, "maps": 24, "reduces": 2,
             "period_ms": 1000, "slo_complete_ms": 60_000},
            {"name": "interactive", "jobs": 10, "maps": 2,
             "start_ms": 2000, "period_ms": 200, "priority": "HIGH",
             "slo_assign_ms": 2000, "slo_complete_ms": 10_000},
            {"name": "interactive", "jobs": 10, "maps": 2,
             "start_ms": 8000, "period_ms": 200, "priority": "HIGH",
             "slo_assign_ms": 2000, "slo_complete_ms": 10_000},
        ],
        "timeout_s": 60,
    },
    # tracker churn under a short expiry: hard kills mid-task with cold
    # rejoins (re-registration), a partition that outlives the expiry
    # sweep so the rejoin takes the ADOPTION path, a straggler phase,
    # fetch-failure chaos — every job must still complete
    "churn_storm": {
        "name": "churn_storm",
        "fleet": {"trackers": 8, "task_mean_ms": 300,
                  "fetch_failure_rate": 0.02},
        "master": {"expiry_ms": 1200},
        "classes": [
            {"name": "interactive", "jobs": 6, "maps": 2, "reduces": 0,
             "period_ms": 1500, "jitter_ms": 300, "priority": "HIGH",
             "slo_assign_ms": 2500, "slo_complete_ms": 15_000},
            {"name": "batch", "jobs": 2, "maps": 20, "reduces": 2,
             "period_ms": 2000, "slo_complete_ms": 60_000},
        ],
        "chaos": [
            {"kind": "fi", "at_ms": 1000, "point": "task.slow",
             "probability": 0.08, "max_failures": 12, "ms": 1500},
            # targets pinned disjoint so the three churn flavors can't
            # collide on a slot: evict-then-fresh-register (rejoin
            # outlives the expiry), partition-then-ADOPT (silence
            # outlives the expiry, process survives), and crash with a
            # fast rejoin (inside the expiry: cold re-registration)
            {"kind": "tracker_crash", "at_ms": 2500,
             "targets": [2, 3], "rejoin_ms": 2500},
            {"kind": "tracker_partition", "at_ms": 3000,
             "targets": [0, 1], "duration_ms": 3000},
            {"kind": "tracker_crash", "at_ms": 6000,
             "targets": [4, 5], "rejoin_ms": 500},
            # the probabilistic seam variant: exactly one self-crash
            # drawn from the seeded fi stream, no respawn — the fleet
            # must absorb a tracker that just never comes back
            {"kind": "fi", "at_ms": 500, "point": "tracker.crash",
             "probability": 0.02, "max_failures": 1},
        ],
        "timeout_s": 90,
    },
    # sustained master-side heartbeat stall → brownout engages, sheds
    # in ranked steps, interactive recovers while batch slows, then
    # full step-down once the pressure clears
    "overload_brownout": {
        "name": "overload_brownout",
        "fleet": {"trackers": 10, "task_mean_ms": 250},
        "master": {"brownout": True, "beats_per_second": 400,
                   "interval_max_ms": 1000,
                   "conf": {"tpumr.brownout.dwell.ms": 1500}},
        "classes": [
            {"name": "interactive", "jobs": 20, "maps": 2,
             "reduces": 0, "period_ms": 700, "priority": "HIGH",
             "slo_assign_ms": 1500, "slo_complete_ms": 10_000},
            {"name": "batch", "jobs": 3, "maps": 16, "reduces": 1,
             "period_ms": 2500, "slo_complete_ms": 60_000},
        ],
        "chaos": [
            {"kind": "fi", "at_ms": 3000, "point": "jt.heartbeat.slow",
             "probability": 0.35, "max_failures": 60, "ms": 250},
        ],
        "timeout_s": 90,
    },
    # the storage churn storm: a replica corrupted under a live
    # verified-read mix (readers must NEVER see the rot), a datanode
    # hard-kill with a cold rejoin (client failover + re-replication),
    # and a heartbeat partition that outlives the expiry sweep (expire,
    # then rejoin via block report) — while MapReduce classes keep
    # completing on the same box
    "dfs_churn_storm": {
        "name": "dfs_churn_storm",
        "fleet": {"trackers": 4, "task_mean_ms": 250},
        "classes": [
            {"name": "interactive", "jobs": 4, "maps": 2, "reduces": 0,
             "period_ms": 1500, "jitter_ms": 300, "priority": "HIGH",
             "slo_assign_ms": 2500, "slo_complete_ms": 15_000},
            {"name": "batch", "jobs": 2, "maps": 8, "reduces": 1,
             "period_ms": 2000, "slo_complete_ms": 60_000},
        ],
        "dfs": {"datanodes": 3, "clients": 4, "files": 4,
                "file_kb": 64, "interval_ms": 50,
                "slo_read_p99_ms": 2500, "max_error_fraction": 0.05,
                # arm the NN flight recorder: a chaos-driven op-p99
                # breach writes nn-* bundles into the artifacts dir
                "conf": {"tpumr.nn.incident.slo.ms": 250}},
        "chaos": [
            {"kind": "block_corrupt", "at_ms": 1500},
            {"kind": "dn_crash", "at_ms": 2500, "targets": [1],
             "rejoin_ms": 3000},
            {"kind": "dn_partition", "at_ms": 5500,
             "duration_ms": 2500},
        ],
        "timeout_s": 90,
    },
    # the storage twin of master_failover: a NameNode SIGKILL mid-mix
    # (no editlog close), rebind on the same port — editlog replay +
    # safemode timed into the chaos log, DFS clients riding their RPC
    # retry policy (safemode refusals budgeted separately from
    # errors), MapReduce classes unaffected
    "dfs_nn_failover": {
        "name": "dfs_nn_failover",
        "fleet": {"trackers": 4, "task_mean_ms": 250},
        "classes": [
            {"name": "interactive", "jobs": 4, "maps": 2, "reduces": 0,
             "period_ms": 1200, "jitter_ms": 300, "priority": "HIGH",
             "slo_assign_ms": 4000, "slo_complete_ms": 20_000},
            {"name": "batch", "jobs": 2, "maps": 8, "reduces": 1,
             "period_ms": 2000, "slo_complete_ms": 60_000},
        ],
        "dfs": {"datanodes": 3, "clients": 4, "files": 4,
                "file_kb": 64, "interval_ms": 50,
                "max_error_fraction": 0.05},
        "chaos": [
            {"kind": "nn_restart", "at_ms": 3000, "outage_ms": 300},
        ],
        "timeout_s": 90,
    },
    # the sharded master's failover mix: a 2-shard master under a
    # batched fleet, one shard SIGKILLed mid-mix — the coordinator
    # respawns it on its pinned port, its trackers re-join via the
    # adoption path, the sibling shard never notices, and every job
    # (old ids polled throughout) still completes
    "shard_kill": {
        "name": "shard_kill",
        "fleet": {"trackers": 12, "task_mean_ms": 300, "batch": 4},
        "master": {"shards": 2, "expiry_ms": 60_000},
        "classes": [
            {"name": "interactive", "jobs": 6, "maps": 2, "reduces": 0,
             "period_ms": 1200, "jitter_ms": 300, "priority": "HIGH",
             "slo_assign_ms": 4000, "slo_complete_ms": 20_000},
            {"name": "batch", "jobs": 2, "maps": 16, "reduces": 2,
             "period_ms": 2000, "slo_complete_ms": 60_000},
        ],
        "chaos": [
            {"kind": "shard_kill", "at_ms": 3000},
        ],
        "timeout_s": 90,
    },
    # a mid-mix master kill/restart with journal recovery: the fleet
    # keeps beating, the driver keeps polling old job ids, every job
    # still completes
    "master_failover": {
        "name": "master_failover",
        "fleet": {"trackers": 8, "task_mean_ms": 300},
        "classes": [
            {"name": "interactive", "jobs": 6, "maps": 2, "reduces": 0,
             "period_ms": 1200, "jitter_ms": 300, "priority": "HIGH",
             "slo_assign_ms": 4000, "slo_complete_ms": 20_000},
            {"name": "batch", "jobs": 2, "maps": 16, "reduces": 2,
             "period_ms": 2000, "slo_complete_ms": 60_000},
            {"name": "pipeline", "jobs": 2, "maps": 4, "reduces": 1,
             "rounds": 2, "start_ms": 500, "period_ms": 3000},
        ],
        "chaos": [
            {"kind": "master_restart", "at_ms": 4000},
        ],
        "timeout_s": 90,
    },
}


def _read_toml(path: str) -> dict:
    try:
        import tomllib
    except ImportError:
        try:
            import tomli as tomllib  # type: ignore[no-redef]
        except ImportError as e:
            raise ScenarioError(
                "TOML scenario specs need Python 3.11+ (tomllib) or "
                "an installed tomli") from e
    try:
        with open(path, "rb") as f:
            return tomllib.load(f)
    except OSError as e:
        raise ScenarioError(f"cannot read scenario {path}: {e}") from e
    except Exception as e:  # tomllib.TOMLDecodeError
        raise ScenarioError(f"bad TOML in {path}: {e}") from e


def load_spec(source: Any,
              scenario_dir: "str | None" = None) -> dict:
    """Resolve ``source`` — a spec dict, a built-in name, or a TOML
    path / ``<scenario_dir>/<name>.toml`` — to a validated spec."""
    if isinstance(source, dict):
        return validate_spec(source)
    name = str(source)
    if name in BUILTIN_SCENARIOS:
        return validate_spec(dict(BUILTIN_SCENARIOS[name]))
    candidates = [name] if name.endswith(".toml") else []
    if scenario_dir:
        candidates.append(os.path.join(scenario_dir,
                                       f"{name}.toml"))
    for path in candidates:
        if os.path.exists(path):
            doc = _read_toml(path)
            doc.setdefault("name",
                           os.path.splitext(os.path.basename(path))[0])
            return validate_spec(doc)
    raise ScenarioError(
        f"unknown scenario {name!r} (built-ins: "
        f"{', '.join(sorted(BUILTIN_SCENARIOS))}; TOML specs load "
        f"from tpumr.scenario.dir)")


def list_scenarios(scenario_dir: "str | None" = None) -> "list[dict]":
    """Catalog rows for ``tpumr scenario -list``: built-ins plus any
    ``*.toml`` in ``scenario_dir`` (unreadable files listed with their
    error, not skipped silently)."""
    rows = []
    sources = [(name, "builtin") for name in sorted(BUILTIN_SCENARIOS)]
    if scenario_dir and os.path.isdir(scenario_dir):
        sources += [(os.path.join(scenario_dir, n), "toml")
                    for n in sorted(os.listdir(scenario_dir))
                    if n.endswith(".toml")]
    for source, origin in sources:
        try:
            spec = load_spec(source, scenario_dir)
            events = plan(spec)
            rows.append({
                "name": spec["name"], "origin": origin,
                "classes": sorted({c["name"]
                                   for c in spec["classes"]}),
                "jobs": sum(int(c["jobs"]) for c in spec["classes"]),
                "chaos": sorted({c["kind"] for c in spec["chaos"]}),
                "dfs": spec.get("dfs") is not None,
                "trace_s": events[-1]["t_s"] if events else 0.0,
            })
        except ScenarioError as e:
            rows.append({"name": str(source), "origin": origin,
                         "error": str(e)})
    return rows


# ------------------------------------------------------------ runner

class ScenarioRunner:
    """Replay one spec against a self-hosted master + sim fleet and
    emit the machine-readable report (per-class latencies + verdicts,
    chaos counters, incident artifacts)."""

    def __init__(self, spec: Any, *,
                 artifacts_dir: "str | None" = None,
                 scenario_dir: "str | None" = None) -> None:
        self.spec = load_spec(spec, scenario_dir)
        self.artifacts_dir = artifacts_dir

    # -------------------------------------------------------- conf

    def _master_conf(self, workdir: str) -> Any:
        from tpumr.mapred.jobconf import JobConf
        spec = self.spec
        fleet, mast = spec["fleet"], spec["master"]
        conf = JobConf()
        conf.set("tpumr.history.dir", os.path.join(workdir, "history"))
        # the recorder nests bundles under <dir>/incidents
        conf.set("tpumr.prof.incident.dir", workdir)
        conf.set("tpumr.prof.enabled", True)
        conf.set("tpumr.heartbeat.interval.ms",
                 int(fleet["interval_ms"]))
        conf.set("tpumr.tracker.expiry.ms", int(mast["expiry_ms"]))
        # recovery armed from the start: the first boot finds an empty
        # journal (no-op); a mid-mix restart reuses the SAME conf
        # object, so fi seam state and scenario keys survive the swap
        conf.set("mapred.jobtracker.restart.recover", True)
        conf.set("mapred.jobtracker.restart.recovery.grace.ms",
                 int(4 * fleet["interval_ms"]))
        conf.set("tpumr.fi.seed", spec["seed"])
        conf.set("tpumr.scenario.name", spec["name"])
        if mast["beats_per_second"]:
            conf.set("tpumr.heartbeat.beats.per.second",
                     int(mast["beats_per_second"]))
        if mast["interval_max_ms"]:
            conf.set("tpumr.heartbeat.interval.max.ms",
                     int(mast["interval_max_ms"]))
        if mast["brownout"]:
            conf.set("tpumr.brownout.enabled", True)
        if mast["shards"]:
            conf.set("tpumr.master.shards", int(mast["shards"]))
        if fleet["batch"]:
            # the fleet's coalescing twin of the master's batch RPC —
            # one knob in the conf so the run() fleet reads it back
            conf.set("tpumr.heartbeat.batch", int(fleet["batch"]))
        for c in spec["classes"]:
            for kind, key in (("slo_assign_ms", "assign"),
                              ("slo_complete_ms", "complete")):
                if c[kind] is not None:
                    conf.set(f"tpumr.scenario.slo.{c['name']}."
                             f"{key}.ms", int(c[kind]))
        dfs = spec.get("dfs")
        if dfs:
            # the storage lab shares THIS conf object with the master,
            # the mini-DFS cluster, and every DFSClient — one
            # tpumr.fi.seed, and chaos armed by conf.set is visible to
            # all of them immediately
            conf.set("tdfs.http.port", -1)
            conf.set("dfs.replication", 2)
            conf.set("tdfs.replication.interval.s",
                     dfs["replication_interval_ms"] / 1000.0)
            conf.set("tdfs.datanode.expiry.s",
                     dfs["expiry_ms"] / 1000.0)
            # clients must ride an nn_restart outage on transport-level
            # retries (safemode refusals are application-level and
            # counted separately by the fleet)
            conf.set("tdfs.client.nn.retries", 60)
            conf.set("tdfs.client.nn.backoff.ms", 100.0)
            for k, v in (dfs["conf"] or {}).items():
                conf.set(str(k), v)
        for k, v in (mast["conf"] or {}).items():
            conf.set(str(k), v)
        return conf

    # -------------------------------------------------------- helpers

    @staticmethod
    def _apply_fi(conf: Any, ev: dict) -> None:
        conf.set(f"tpumr.fi.{ev['point']}.probability",
                 ev["probability"])
        if ev["max_failures"]:
            conf.set(f"tpumr.fi.{ev['point']}.max.failures",
                     ev["max_failures"])
        if ev.get("ms") is not None:
            conf.set(f"tpumr.fi.{ev['point']}.ms", int(ev["ms"]))

    def _submit(self, driver: ScaleDriver, ev: dict,
                round_no: int = 1) -> str:
        name = ev["name"] if round_no <= 1 \
            else f"{ev['name']}.r{round_no}"
        ids = driver.submit(
            1, ev["maps"], ev["reduces"], name=name,
            **{"tpumr.scenario.class": ev["class"],
               "mapred.job.priority": ev["priority"]})
        return ids[0]

    def _poll_jobs(self, driver: ScaleDriver, states: dict,
                   pending: set, chains: dict,
                   job_ids: list) -> None:
        """One status sweep; completed chain rounds submit the next
        round (the iterative/pipeline stage shape — reactive, like a
        real driver resubmitting on stage completion)."""
        for jid in sorted(pending):
            try:
                st = driver.client.call("get_job_status", jid)
            except Exception:  # noqa: BLE001 — master restart window
                continue
            state = st.get("state", "RUNNING")
            states[jid] = state
            if state not in ("SUCCEEDED", "FAILED", "KILLED"):
                continue
            pending.discard(jid)
            link = chains.pop(jid, None)
            if link and state == "SUCCEEDED" \
                    and link["rounds_left"] > 0:
                nxt_round = link["round"] + 1
                njid = self._submit(driver, link, nxt_round)
                job_ids.append(njid)
                states[njid] = "RUNNING"
                pending.add(njid)
                chains[njid] = dict(link,
                                    rounds_left=link["rounds_left"] - 1,
                                    round=nxt_round)

    @staticmethod
    def _dfs_heal_wait(cluster: Any, timeout_s: float = 20.0) -> dict:
        """Bounded wait for the mini-DFS to converge after the chaos:
        safemode exited, no missing/corrupt blocks, every block back at
        its replication target (fsck clean, open files excepted — the
        fleet's in-flight writes at stop time hold leases, which is not
        damage). Returns the heal receipt for the report."""
        t0 = time.monotonic()
        deadline = t0 + timeout_s
        last: dict = {}
        while time.monotonic() < deadline:
            try:
                last = cluster.namenode.ns.fsck("/")
            except Exception:  # noqa: BLE001 — safemode window
                last = {}
            else:
                if not cluster.namenode.ns.safemode \
                        and not last["missing"] \
                        and not last["corrupt"] \
                        and not last["under_replicated"]:
                    return {"healed": True,
                            "heal_s": round(time.monotonic() - t0, 3),
                            "blocks": int(last["blocks"])}
            time.sleep(0.1)
        return {"healed": False, "heal_s": None,
                "blocks": int(last.get("blocks", 0)),
                "missing": len(last.get("missing", ())),
                "corrupt": len(last.get("corrupt", ())),
                "under_replicated": len(
                    last.get("under_replicated", ()))}

    @staticmethod
    def _class_typed(master: Any) -> "dict[tuple[str, str], dict]":
        return {key: h.typed()
                for key, h in master._class_hists.items()}

    @staticmethod
    def _merged_class_ms(states: "list[dict]") -> dict:
        """Cumulative per-class latency percentiles ACROSS master
        incarnations: fold each incarnation's typed histogram state
        into one scratch histogram per (class, kind)."""
        from tpumr.metrics.flightrec import typed_p99
        from tpumr.metrics.histogram import Histogram
        scratch: "dict[tuple[str, str], Histogram]" = {}
        for st in states:
            for (kind, cls_name), typed in st.items():
                h = scratch.setdefault(
                    (kind, cls_name), Histogram(f"{kind}_{cls_name}"))
                h.merge_typed(typed)
        out: "dict[str, dict]" = {}
        for (kind, cls_name), h in sorted(scratch.items()):
            t = h.typed()
            row = out.setdefault(cls_name, {})
            row[f"{kind}_p50_ms"] = round(
                typed_p99(t, 0.5) * 1000, 2)
            row[f"{kind}_p99_ms"] = round(
                typed_p99(t, 0.99) * 1000, 2)
            row[f"{kind}_count"] = int(t.get("count", 0))
        return out

    # -------------------------------------------------------- run

    def run(self) -> dict:
        from tpumr.mapred.jobtracker import JobMaster
        spec = self.spec
        events = plan(spec)
        fi.reset()   # counters + RNG streams replay from this run's seed
        workdir = self.artifacts_dir or tempfile.mkdtemp(
            prefix=f"tpumr-scenario-{spec['name']}-")
        own_workdir = self.artifacts_dir is None
        conf = self._master_conf(workdir)
        fleet_spec = spec["fleet"]
        interval_s = fleet_spec["interval_ms"] / 1000.0
        from tpumr.core import confkeys
        from tpumr.mapred.shardmaster import make_master
        master = make_master(conf).start()
        host, port = master.address
        masters = [master]
        fleet = SimFleet(
            host, port, int(fleet_spec["trackers"]),
            interval_s=interval_s, seed=spec["seed"],
            cpu_slots=int(fleet_spec["cpu_slots"]),
            reduce_slots=int(fleet_spec["reduce_slots"]),
            task_time_mean_s=fleet_spec["task_mean_ms"] / 1000.0,
            fetch_failure_rate=fleet_spec["fetch_failure_rate"],
            batch=confkeys.get_int(conf, "tpumr.heartbeat.batch"),
            shard_map=(master.shard_map()
                       if hasattr(master, "shard_map") else None),
            fi_conf=conf).start()
        driver = ScaleDriver(host, port)
        cluster = dfs_fleet = None
        dfs_files: "list[str]" = []
        dfs_timers: "list[threading.Timer]" = []
        dfs_fi_points: "list[str]" = []
        dfs_spec = spec.get("dfs")
        if dfs_spec:
            from tpumr.dfs.mini_cluster import MiniDFSCluster
            from tpumr.scale.simdfs import SimDFSFleet, seed_files
            cluster = MiniDFSCluster(int(dfs_spec["datanodes"]),
                                     conf=conf)
            dfs_files = seed_files(
                cluster.nn_host, cluster.nn_port, conf,
                n_files=int(dfs_spec["files"]),
                file_bytes=int(dfs_spec["file_kb"]) * 1024,
                root="/scenario/data")
            dfs_fleet = SimDFSFleet(
                cluster.nn_host, cluster.nn_port,
                int(dfs_spec["clients"]), conf,
                interval_s=dfs_spec["interval_ms"] / 1000.0,
                seed=spec["seed"], files=dfs_files,
                hot_read_p=dfs_spec["hot_read_p"],
                read_bytes=int(dfs_spec["read_kb"]) * 1024,
                verify=True).start()
        job_ids: "list[str]" = []
        states: "dict[str, str]" = {}
        pending: "set[str]" = set()
        chains: "dict[str, dict]" = {}
        chaos_log: "list[dict]" = []
        dead_class_states: "list[dict]" = []
        t0 = time.monotonic()
        ok = False
        dfs_heal: "dict | None" = None
        try:
            for ev in events:
                while time.monotonic() - t0 < ev["t_s"]:
                    time.sleep(min(
                        0.1, max(0.0, ev["t_s"]
                                 - (time.monotonic() - t0))))
                    self._poll_jobs(driver, states, pending, chains,
                                    job_ids)
                if ev["kind"] == "submit":
                    jid = self._submit(driver, ev)
                    job_ids.append(jid)
                    states[jid] = "RUNNING"
                    pending.add(jid)
                    if ev["rounds"] > 1:
                        chains[jid] = dict(
                            ev, rounds_left=ev["rounds"] - 1, round=1)
                elif ev["kind"] == "tracker_crash":
                    names = fleet.churn(idxs=ev["targets"],
                                        rejoin_after_s=ev["rejoin_s"])
                    chaos_log.append({
                        "t_s": round(time.monotonic() - t0, 3),
                        "kind": "tracker_crash", "crashed": names,
                        "rejoin_s": ev["rejoin_s"]})
                elif ev["kind"] == "tracker_partition":
                    names = fleet.partition(idxs=ev["targets"],
                                            duration_s=ev["duration_s"])
                    chaos_log.append({
                        "t_s": round(time.monotonic() - t0, 3),
                        "kind": "tracker_partition",
                        "partitioned": names,
                        "duration_s": ev["duration_s"]})
                elif ev["kind"] == "master_restart":
                    dead_class_states.append(
                        self._class_typed(masters[-1]))
                    masters[-1].stop()
                    m2 = None
                    for _ in range(250):
                        try:
                            m2 = JobMaster(conf, host=host,
                                           port=port).start()
                            break
                        except OSError:
                            time.sleep(0.02)
                    if m2 is None:
                        raise RuntimeError(
                            "could not rebind the master port")
                    masters.append(m2)
                    chaos_log.append({
                        "t_s": round(time.monotonic() - t0, 3),
                        "kind": "master_restart"})
                elif ev["kind"] == "shard_kill":
                    t_kill = time.monotonic()
                    info = masters[-1].kill_shard(ev["shard"])
                    respawned = masters[-1].wait_shard_ready(
                        ev["shard"], 30.0)
                    chaos_log.append({
                        "t_s": round(time.monotonic() - t0, 3),
                        "kind": "shard_kill",
                        "shard": int(ev["shard"]),
                        "pid": info.get("pid"),
                        "respawned": bool(respawned),
                        "respawn_s": round(
                            time.monotonic() - t_kill, 3)})
                elif ev["kind"] == "fi":
                    self._apply_fi(conf, ev)
                    chaos_log.append({
                        "t_s": round(time.monotonic() - t0, 3),
                        "kind": "fi", "point": ev["point"],
                        "probability": ev["probability"]})
                elif ev["kind"] == "dn_crash":
                    for t in ev["targets"]:
                        cluster.kill_datanode(t)
                        if ev["rejoin_s"] is not None:
                            timer = threading.Timer(
                                ev["rejoin_s"],
                                cluster.restart_datanode, args=(t,))
                            timer.daemon = True
                            timer.start()
                            dfs_timers.append(timer)
                    chaos_log.append({
                        "t_s": round(time.monotonic() - t0, 3),
                        "kind": "dn_crash",
                        "targets": list(ev["targets"]),
                        "rejoin_s": ev["rejoin_s"]})
                elif ev["kind"] == "dn_partition":
                    # armed via conf, drawn by the datanodes' own
                    # heartbeat threads: max.failures is cumulative
                    # against the process-global fired counter so a
                    # second partition event silences `count` MORE
                    conf.set("tpumr.fi.dn.partition.ms",
                             int(ev["duration_s"] * 1000))
                    conf.set("tpumr.fi.dn.partition.probability", 1.0)
                    conf.set("tpumr.fi.dn.partition.max.failures",
                             fi.fired("dn.partition")
                             + int(ev["count"]))
                    dfs_fi_points.append("dn.partition")
                    chaos_log.append({
                        "t_s": round(time.monotonic() - t0, 3),
                        "kind": "dn_partition",
                        "count": int(ev["count"]),
                        "duration_s": ev["duration_s"]})
                elif ev["kind"] == "nn_restart":
                    t_kill = time.monotonic()
                    cluster.kill_namenode()
                    until = t_kill + ev["outage_s"]
                    while time.monotonic() < until:
                        self._poll_jobs(driver, states, pending,
                                        chains, job_ids)
                        time.sleep(min(0.05, max(
                            0.0, until - time.monotonic())))
                    cluster.restart_killed_namenode()
                    # time safemode exit (the recovery headline); the
                    # fleet is retrying meanwhile, refusals counted
                    # separately from errors
                    sm_deadline = time.monotonic() + 30.0
                    while cluster.namenode.ns.safemode \
                            and time.monotonic() < sm_deadline:
                        self._poll_jobs(driver, states, pending,
                                        chains, job_ids)
                        time.sleep(0.05)
                    chaos_log.append({
                        "t_s": round(time.monotonic() - t0, 3),
                        "kind": "nn_restart",
                        "outage_s": ev["outage_s"],
                        "safemode_exit_s": round(
                            time.monotonic() - t_kill, 3),
                        "safemode_exited":
                            not cluster.namenode.ns.safemode})
                elif ev["kind"] == "block_corrupt":
                    path = dfs_files[ev["file_index"]
                                     % len(dfs_files)]
                    inode = cluster.namenode.ns.namespace.get(
                        path) or {}
                    blocks = inode.get("blocks") or []
                    if blocks:
                        bid = int(blocks[0][0])
                        point = f"dn.read.corrupt.b{bid}"
                        conf.set(f"tpumr.fi.{point}.probability", 1.0)
                        conf.set(f"tpumr.fi.{point}.max.failures",
                                 int(ev["count"]))
                        dfs_fi_points.append(point)
                        chaos_log.append({
                            "t_s": round(time.monotonic() - t0, 3),
                            "kind": "block_corrupt", "path": path,
                            "block_id": bid,
                            "count": int(ev["count"])})
                    else:
                        chaos_log.append({
                            "t_s": round(time.monotonic() - t0, 3),
                            "kind": "block_corrupt", "path": path,
                            "block_id": None, "skipped": True})
            trace_end = events[-1]["t_s"] if events else 0.0
            deadline = t0 + trace_end + spec["timeout_s"]
            while pending and time.monotonic() < deadline:
                self._poll_jobs(driver, states, pending, chains,
                                job_ids)
                if pending:
                    time.sleep(0.1)
            # drain ticks: the flight recorder windows at 1 Hz — give
            # it a beat to fold the last completions, and let an active
            # brownout finish stepping down after the pressure cleared
            brown = masters[-1].brownout
            settle_until = time.monotonic() + 2.5
            time.sleep(max(0.0, settle_until - time.monotonic()))
            if brown is not None:
                step_down_cap = time.monotonic() + 30.0
                while brown.level > 0 \
                        and time.monotonic() < step_down_cap:
                    time.sleep(0.25)
            if cluster is not None:
                # freeze DFS traffic, let pending rejoin timers land,
                # then demand the cluster self-heal to a clean fsck —
                # the chaos kinds all promise convergence, this is
                # where the promise is checked
                dfs_fleet.stop()
                for timer in dfs_timers:
                    timer.join(timeout=15.0)
                dfs_heal = self._dfs_heal_wait(cluster)
            ok = True
        finally:
            fleet.stop()
            if dfs_fleet is not None:
                dfs_fleet.stop()
            for timer in dfs_timers:
                timer.cancel()
            driver.close()
            try:
                masters[-1].stop()
            except Exception:  # noqa: BLE001
                pass
            if cluster is not None:
                try:
                    cluster.shutdown()
                except Exception:  # noqa: BLE001
                    pass
        report = self._report(spec, events, masters, fleet, states,
                              pending, chaos_log, dead_class_states,
                              workdir, time.monotonic() - t0,
                              dfs_fleet=dfs_fleet, dfs_heal=dfs_heal,
                              dfs_fi_points=dfs_fi_points)
        if own_workdir and ok and report["pass"]:
            shutil.rmtree(workdir, ignore_errors=True)
            report["artifacts_dir"] = None
        return report

    @staticmethod
    def _dfs_section(spec: dict, dfs_fleet: Any,
                     dfs_heal: "dict | None") -> "dict | None":
        """The storage layer's own verdict block: error budget,
        corrupt-read invariant (== 0, always), optional client-side
        p99 SLOs, and the end-of-run heal receipt."""
        if dfs_fleet is None:
            return None
        d = spec["dfs"]
        st = dfs_fleet.stats()
        ops = sum(st["op_counts"].values()) or 1
        err_frac = st["errors"] / ops
        read_p99_ms = round(float(
            (st["read_rtt"] or {}).get("p99", 0.0)) * 1000, 2)
        meta_p99_ms = round(float(
            (st["meta_rtt"] or {}).get("p99", 0.0)) * 1000, 2)
        verdicts = {
            "errors_ok": err_frac <= float(d["max_error_fraction"]),
            "corrupt_reads_ok": int(st["corrupt_reads"]) == 0,
            "read_p99_ok": (d["slo_read_p99_ms"] is None
                            or read_p99_ms <= d["slo_read_p99_ms"]),
            "meta_p99_ok": (d["slo_meta_p99_ms"] is None
                            or meta_p99_ms <= d["slo_meta_p99_ms"]),
            "healed": bool(dfs_heal and dfs_heal.get("healed")),
        }
        return {
            "clients": int(d["clients"]),
            "datanodes": int(d["datanodes"]),
            "ops": int(st["ops"]),
            "op_counts": st["op_counts"],
            "bytes_read": int(st["bytes_read"]),
            "errors": int(st["errors"]),
            "error_fraction": round(err_frac, 4),
            "corrupt_reads": int(st["corrupt_reads"]),
            "safemode_refusals": int(st["safemode_refusals"]),
            "read_p99_ms": read_p99_ms,
            "meta_p99_ms": meta_p99_ms,
            "heal": dfs_heal,
            "verdicts": verdicts,
            "pass": all(verdicts.values()),
        }

    def _report(self, spec: dict, events: list, masters: list,
                fleet: SimFleet, states: dict, pending: set,
                chaos_log: list, dead_class_states: list,
                workdir: str, wall_s: float, *,
                dfs_fleet: Any = None,
                dfs_heal: "dict | None" = None,
                dfs_fi_points: "list[str] | None" = None) -> dict:
        final = masters[-1]
        jt = final.metrics.snapshot().get("jobtracker", {})
        fr = final.flightrec
        verdicts = fr.class_report() if fr is not None else {}
        history = fr.window_history() if fr is not None else []
        # re-judge with the SPEC's breach-fraction budget (the
        # recorder's class_report uses its default majority rule)
        mbf = spec["max_breach_fraction"]
        for row in verdicts.values():
            ok = True
            for kind in ("assign", "complete"):
                entry = row.get(kind) or {}
                if entry.get("slo_ms") is None:
                    continue
                if entry.get("ok") is False \
                        or entry.get("breach_fraction", 0.0) > mbf:
                    ok = False
            row["pass"] = ok
        class_ms = self._merged_class_ms(
            dead_class_states + [self._class_typed(final)])
        succeeded = sorted(j for j, s in states.items()
                           if s == "SUCCEEDED")
        failed = sorted(j for j, s in states.items()
                        if s in ("FAILED", "KILLED"))
        chaos_points = sorted({ev["point"] for ev in spec["chaos"]
                               if ev["kind"] == "fi"}
                              | {"tracker.crash"}
                              | set(dfs_fi_points or ()))
        dfs_section = self._dfs_section(spec, dfs_fleet, dfs_heal)
        all_pass = (not failed and not pending
                    and all(v.get("pass") for v in verdicts.values())
                    and (dfs_section is None or dfs_section["pass"]))
        return {
            "scenario": spec["name"],
            "seed": spec["seed"],
            "wall_s": round(wall_s, 2),
            "plan": events,
            "jobs": {"submitted": len(states),
                     "succeeded": len(succeeded),
                     "failed": len(failed),
                     "unfinished": len(pending)},
            "classes": class_ms,
            "verdicts": verdicts,
            "chaos": {
                "trackers_crashed": fleet.trackers_crashed,
                "trackers_respawned": fleet.trackers_respawned,
                "trackers_partitioned": fleet.trackers_partitioned,
                "trackers_adopted": int(
                    jt.get("trackers_adopted", 0)),
                "trackers_restarted": int(
                    jt.get("trackers_restarted", 0)),
                "attempts_adopted": int(
                    jt.get("attempts_adopted", 0)),
                "master_restarts": len(masters) - 1,
                "shards_killed": int(jt.get("shards_killed", 0)),
                "shard_restarts": int(jt.get("shard_restarts", 0)),
                "datanodes_killed": sum(
                    len(r.get("targets", ())) for r in chaos_log
                    if r["kind"] == "dn_crash"),
                "nn_restarts": sum(1 for r in chaos_log
                                   if r["kind"] == "nn_restart"),
                "fi_fired": {p: fi.fired(p) for p in chaos_points},
            },
            "dfs": dfs_section,
            "chaos_log": chaos_log,
            "brownout": (final.brownout.snapshot()
                         if final.brownout is not None
                         else {"level": 0}),
            "brownout_max_level": max(
                [r["brownout_level"] for r in history] or [0]),
            "window_history": history,
            "incidents": [r["name"]
                          for r in (fr.list_incidents()
                                    if fr is not None else [])],
            "artifacts_dir": workdir,
            "pass": all_pass,
        }


def run_named(name: Any, seed: "int | None" = None,
              scenario_dir: "str | None" = None,
              artifacts_dir: "str | None" = None) -> dict:
    """Load + replay one scenario (the CLI/bench entry). ``seed``
    overrides the spec's."""
    spec = load_spec(name, scenario_dir)
    if seed is not None:
        spec = dict(spec, seed=int(seed))
    return ScenarioRunner(spec,
                          artifacts_dir=artifacts_dir).run()
