"""Synthetic workload driver for the control-plane scale harness.

Submits multi-job workloads to a live ``JobMaster`` over the real
client RPC surface (``submit_job`` / ``get_job_status`` — the same
calls ``JobClient`` makes) and waits for them. The jobs are pure
control-plane load: M map splits and R reduces with no mapper class, no
input bytes, and no output dir — the ``SimTracker`` fleet "executes"
them as timed no-ops, so every scheduling decision, completion event,
and history append is real while zero task bytes move.
"""

from __future__ import annotations

import time
from typing import Any

from tpumr.ipc.rpc import RpcClient


def synthetic_job_conf(name: str, reduces: int,
                       **overrides: Any) -> dict:
    """A submit-ready job conf for a no-op scale job. Speculation is off
    (fake tasks complete fast and twins would only blur the scheduling
    accounting the harness measures); attempts are generous so injected
    fetch failures re-execute instead of failing the job."""
    conf = {
        "mapred.job.name": name,
        "user.name": "scale-harness",
        "mapred.reduce.tasks": int(reduces),
        "mapred.speculative.execution": False,
        "mapred.map.max.attempts": 8,
        "mapred.reduce.max.attempts": 8,
    }
    conf.update(overrides)
    return conf


class ScaleDriver:
    """Submit/await synthetic jobs against one master, over the wire."""

    def __init__(self, master_host: str, master_port: int,
                 secret: "bytes | None" = None,
                 timeout_s: float = 30.0) -> None:
        self.client = RpcClient(master_host, master_port, secret=secret,
                                timeout=timeout_s)

    def submit(self, n_jobs: int, maps_per_job: int,
               reduces_per_job: int = 1, name: str = "scale",
               **conf_overrides: Any) -> "list[str]":
        """Submit ``n_jobs`` no-op jobs; returns their job ids. Splits
        are empty dicts — a split with no locations schedules on any
        tracker, which is exactly right for a fleet of fake hosts."""
        ids = []
        for j in range(n_jobs):
            conf = synthetic_job_conf(f"{name}-{j}", reduces_per_job,
                                      **conf_overrides)
            splits = [{} for _ in range(int(maps_per_job))]
            ids.append(self.client.call("submit_job", conf, splits))
        return ids

    def wait(self, job_ids: "list[str]", timeout_s: float = 60.0,
             poll_s: float = 0.2) -> dict:
        """Poll ``get_job_status`` until every job is terminal (or the
        deadline passes). Returns ``{"succeeded": [...], "failed":
        [...], "unfinished": [...], "states": {id: state}}`` — an
        unfinished job under a generous deadline is itself a saturation
        datum, so the caller gets the partial truth, not an exception."""
        deadline = time.monotonic() + timeout_s
        states: "dict[str, str]" = {jid: "RUNNING" for jid in job_ids}
        pending = set(job_ids)
        while pending and time.monotonic() < deadline:
            for jid in list(pending):
                try:
                    st = self.client.call("get_job_status", jid)
                except Exception:  # noqa: BLE001 — overloaded master
                    continue
                states[jid] = st.get("state", "RUNNING")
                if states[jid] in ("SUCCEEDED", "FAILED", "KILLED"):
                    pending.discard(jid)
            if pending:
                time.sleep(poll_s)
        return {
            "succeeded": sorted(j for j, s in states.items()
                                if s == "SUCCEEDED"),
            "failed": sorted(j for j, s in states.items()
                             if s in ("FAILED", "KILLED")),
            "unfinished": sorted(pending),
            "states": states,
        }

    def run_workload(self, n_jobs: int, maps_per_job: int,
                     reduces_per_job: int = 1, timeout_s: float = 60.0,
                     poll_s: float = 0.2,
                     **conf_overrides: Any) -> dict:
        """submit + wait, one call (the bench/CLI entry)."""
        ids = self.submit(n_jobs, maps_per_job, reduces_per_job,
                          **conf_overrides)
        return self.wait(ids, timeout_s=timeout_s, poll_s=poll_s)

    def close(self) -> None:
        self.client.close()
