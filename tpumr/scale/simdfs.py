"""Simulated DFS clients: real DFSClient traffic, synthetic workload.

``SimTracker``'s storage twin: where the scale lab's ``SimFleet`` beats
a real JobTracker with the real heartbeat protocol, ``SimDFSFleet``
drives a real NameNode + DataNodes with real ``DFSClient`` instances —
every namespace op is a genuine RPC through the instrumented
``NameNode._op`` seam, every block read moves real bytes off a real
DataNode (and into its SpaceSaving hot-block sketch). Nothing is
mocked, so what bench_dfs measures is the actual serving stack.

The workload is the mix a MapReduce cluster's storage layer sees:

- **reads dominate** and are SKEWED — with probability ``hot_read_p``
  a client reads the designated hot file (everyone's job config /
  shared side input), otherwise a uniform draw over the working set.
  The skew is what makes ``/hotblocks`` testable: the hot file's
  block must surface as the cluster-wide top entry.
- **metadata ops** (exists / get_status / list_status) — the
  lightweight chatter of job setup and polling.
- **writes** roll small per-client files (task output commit), with
  renames and deletes bounding each client's namespace footprint —
  so create/complete/rename/delete all show op latency under load.

``SimDFSFleet`` schedules N clients from a bounded worker pool on a
fixed-rate heap (same skeleton as ``SimFleet``): each client has a due
time every ``interval_s``; the due-vs-actual gap is the client-side
scheduling lag, and per-op round trips are the client-side latency
view that bench_dfs compares against the NameNode's own
``nn_op_seconds`` attribution.
"""

from __future__ import annotations

import heapq
import random
import threading
import time
from typing import Any

from tpumr.dfs.client import DFSClient
from tpumr.metrics.core import MetricsRegistry
from tpumr.metrics.histogram import Histogram

#: op mix (weights, normalized at draw time): reads dominate, metadata
#: chatter second, a steady trickle of write/rename/delete churn
DEFAULT_MIX = (("read", 0.66), ("stat", 0.18), ("write", 0.10),
               ("rename", 0.03), ("delete", 0.03))

#: the seeded working-set payload is ``bytes(range(256))`` repeated, so
#: byte ``i`` of every file is ``i % 256`` — a read of any prefix is
#: verifiable without shipping the expectation around
_PAYLOAD_TEMPLATE = bytes(range(256))


class CorruptReadError(IOError):
    """A verified read returned bytes that differ from the seeded
    payload — the checksum/bad-block-report defense FAILED and rot
    reached an application. The one counter that must stay at zero
    under ``block_corrupt`` chaos."""


def seed_files(nn_host: str, nn_port: int, conf: Any = None,
               n_files: int = 8, file_bytes: int = 1 << 18,
               root: str = "/bench/data") -> "list[str]":
    """Create the shared read working set (``f_0`` is the hot file).
    Returns the paths. Idempotent: existing files are reused so a
    ramp's later rungs don't re-write the set."""
    cli = DFSClient(nn_host, nn_port, conf)
    try:
        cli.mkdirs(root)
        paths = []
        payload = bytes(range(256)) * (max(1, file_bytes) // 256 + 1)
        for i in range(n_files):
            path = f"{root}/f_{i}"
            if not cli.exists(path):
                # replication=2 on a 3-DN rung leaves the hot-block
                # policy headroom to prove itself: the hot file's
                # replica count visibly climbs 2 -> 3 under skew
                with cli.create(path, replication=2) as out:
                    out.write(payload[:file_bytes])
            paths.append(path)
        return paths
    finally:
        close_client(cli)


def close_client(cli: DFSClient) -> None:
    """Drop the client's sockets (renewer, NN conn, DN pool) so a
    ramp's retired rungs don't leak fds into the next."""
    try:
        cli.close()
    except Exception:  # noqa: BLE001
        pass


class SimDFSClient:
    """One synthetic client: a real ``DFSClient`` plus a seeded op
    generator. ``step()`` performs exactly one operation drawn from
    the mix and returns ``(op, bytes_read)``."""

    def __init__(self, name: str, nn_host: str, nn_port: int,
                 conf: Any = None, *,
                 files: "list[str] | None" = None,
                 hot_read_p: float = 0.5,
                 read_bytes: int = 1 << 16,
                 mix: "tuple | None" = None,
                 home: str = "/user",
                 verify: bool = False,
                 rng: "random.Random | None" = None) -> None:
        self.name = name
        self.cli = DFSClient(nn_host, nn_port, conf)
        self.files = list(files or [])
        self.hot_read_p = float(hot_read_p)
        self.read_bytes = int(read_bytes)
        # verify=True checks every working-set read against the seeded
        # seed_files payload (byte i == i % 256) and raises
        # CorruptReadError on mismatch — the block_corrupt invariant
        self.verify = bool(verify)
        self._expected = (_PAYLOAD_TEMPLATE
                          * (self.read_bytes // 256 + 1))[
                              :self.read_bytes] if verify else b""
        self.mix = tuple(mix or DEFAULT_MIX)
        self._weights = [w for _op, w in self.mix]
        self._rng = rng or random.Random(hash(name) & 0xFFFFFFFF)
        # /user/<name>/... gives every client its own depth-2 stripe
        # prefix, so write/rename/delete churn spreads across the
        # namenode's striped locks instead of serializing on one
        self.home = f"{home}/{name}"
        # the directory the listing op sweeps: the working set's own
        # parent (NOT a hardcoded root — the scenario lab seeds under a
        # different tree than bench_dfs)
        self._data_root = (self.files[0].rsplit("/", 1)[0] or "/") \
            if self.files else "/"
        self._made_home = False
        self._seq = 0
        self._mine: "list[str]" = []   # my rolled files, oldest first
        self.ops = 0
        self.stopped = False

    def step(self) -> "tuple[str, int]":
        op = self._rng.choices([o for o, _w in self.mix],
                               weights=self._weights)[0]
        n = getattr(self, f"_op_{op}")()
        self.ops += 1
        return op, n

    # ------------------------------------------------------------ ops

    def _op_read(self) -> int:
        if not self.files:
            return 0
        # the skew: hot file with probability hot_read_p, else uniform
        if self._rng.random() < self.hot_read_p:
            path = self.files[0]
        else:
            path = self._rng.choice(self.files)
        with self.cli.open(path) as f:
            data = f.read(self.read_bytes)
        if self.verify and data != self._expected[:len(data)]:
            raise CorruptReadError(
                f"{self.name}: {path} returned {len(data)} bytes that "
                f"do not match the seeded payload")
        return len(data)

    def _op_stat(self) -> int:
        which = self._rng.randrange(3)
        if which == 0:
            self.cli.exists(self.files[0] if self.files else "/")
        elif which == 1 and self.files:
            self.cli.get_status(self._rng.choice(self.files))
        else:
            self.cli.list_status(self._data_root)
        return 0

    def _op_write(self) -> int:
        if not self._made_home:
            self.cli.mkdirs(self.home)
            self._made_home = True
        self._seq += 1
        path = f"{self.home}/w_{self._seq}.dat"
        with self.cli.create(path) as out:
            out.write(b"x" * 4096)
        self._mine.append(path)
        # bound the per-client namespace footprint (and generate
        # steady delete traffic): at most 8 rolled files live
        if len(self._mine) > 8:
            self.cli.delete(self._mine.pop(0))
        return 0

    def _op_rename(self) -> int:
        if not self._mine:
            return self._op_write()
        src = self._mine.pop(self._rng.randrange(len(self._mine)))
        dst = src + ".r"
        if self.cli.rename(src, dst):
            self._mine.append(dst)
        return 0

    def _op_delete(self) -> int:
        if not self._mine:
            return self._op_stat()
        self.cli.delete(self._mine.pop(0))
        return 0

    def close(self) -> None:
        self.stopped = True
        close_client(self.cli)


class SimDFSFleet:
    """N ``SimDFSClient``s on a fixed-rate op schedule, driven by a
    bounded worker pool (the ``SimFleet`` skeleton: due-time heap,
    staggered start, skip-ahead when saturated)."""

    def __init__(self, nn_host: str, nn_port: int, n_clients: int,
                 conf: Any = None, *, interval_s: float = 0.05,
                 workers: "int | None" = None, seed: int = 0,
                 name_prefix: str = "sdfs",
                 **client_kwargs: Any) -> None:
        self.nn_host, self.nn_port = nn_host, int(nn_port)
        self.conf = conf
        self.n = int(n_clients)
        self.interval_s = float(interval_s)
        self.workers = workers or min(32, max(4, self.n // 2))
        self._prefix = name_prefix
        self._seed = seed
        self._client_kwargs = client_kwargs
        self.clients: "list[SimDFSClient]" = []
        self._heap: "list[tuple[float, int]]" = []
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._threads: "list[threading.Thread]" = []
        # the client-side view, independent of the NameNode's own
        # nn_op_seconds attribution: read round trips (the end-to-end
        # NN-locate + DN-fetch path), metadata/write round trips, and
        # schedule lag (how far behind the intended op rate we run)
        self.registry = MetricsRegistry("simdfs")
        self._read_rtt = self.registry.histogram("dfs_read_rtt_seconds")
        self._meta_rtt = self.registry.histogram("dfs_meta_rtt_seconds")
        self._lag = self.registry.histogram("op_lag_seconds")
        self.bytes_read = 0
        self.op_counts: "dict[str, int]" = {}

    def start(self) -> "SimDFSFleet":
        rng = random.Random(self._seed)
        for i in range(self.n):
            self.clients.append(SimDFSClient(
                f"{self._prefix}_{i:04d}", self.nn_host, self.nn_port,
                self.conf, rng=random.Random(rng.randrange(1 << 30)),
                **self._client_kwargs))
        now = time.monotonic()
        # stagger first ops across one interval: fleet start must not
        # land as a synchronized herd unless saturation makes it one
        self._heap = [(now + (i * self.interval_s) / max(1, self.n), i)
                      for i in range(self.n)]
        heapq.heapify(self._heap)
        for w in range(self.workers):
            t = threading.Thread(target=self._worker,
                                 name=f"{self._prefix}-fleet-{w}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def _worker(self) -> None:
        while not self._stop.is_set():
            with self._cv:
                while not self._stop.is_set():
                    now = time.monotonic()
                    if self._heap and self._heap[0][0] <= now:
                        due, idx = heapq.heappop(self._heap)
                        break
                    wait = (self._heap[0][0] - now) if self._heap \
                        else 0.05
                    self._cv.wait(min(max(wait, 0.0), 0.05))
                else:
                    return
            self._lag.observe(max(0.0, time.monotonic() - due))
            client = self.clients[idx]
            if client.stopped:
                continue
            t0 = time.monotonic()
            try:
                op, nbytes = client.step()
            except CorruptReadError:
                self.registry.incr("dfs_corrupt_reads")
                op, nbytes = "corrupt_read", 0
            except Exception as e:  # noqa: BLE001 — NN/DN down or overloaded
                if "safe mode" in str(e).lower():
                    # a freshly restarted NameNode refusing ops until
                    # block reports land: an availability event, not a
                    # data error — budgeted separately (the SLO is
                    # time-to-safemode-exit, judged by the scenario)
                    self.registry.incr("dfs_safemode_refusals")
                    op, nbytes = "safemode", 0
                else:
                    self.registry.incr("dfs_errors")
                    op, nbytes = "error", 0
            else:
                rtt = time.monotonic() - t0
                (self._read_rtt if op == "read"
                 else self._meta_rtt).observe(rtt)
            with self._cv:
                self.bytes_read += nbytes
                self.op_counts[op] = self.op_counts.get(op, 0) + 1
                if not client.stopped and not self._stop.is_set():
                    # fixed-rate against the intended cadence; when a
                    # full interval behind, skip ahead (the lag was
                    # recorded — queueing missed ops would spiral)
                    nxt = due + self.interval_s
                    now = time.monotonic()
                    if nxt <= now:
                        nxt = now + self.interval_s
                    heapq.heappush(self._heap, (nxt, idx))
                self._cv.notify()

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
        for c in self.clients:
            c.close()

    # ------------------------------------------------------------ read side

    def stats(self) -> dict:
        """Client-side summary for one measurement window's rung."""
        snap = self.registry.snapshot()
        with self._cv:
            counts = dict(self.op_counts)
            bytes_read = self.bytes_read
        return {
            "ops": sum(c.ops for c in self.clients),
            "op_counts": counts,
            "bytes_read": bytes_read,
            "errors": snap.get("dfs_errors", 0),
            "corrupt_reads": snap.get("dfs_corrupt_reads", 0),
            "safemode_refusals": snap.get("dfs_safemode_refusals", 0),
            "read_rtt": snap.get("dfs_read_rtt_seconds",
                                 Histogram("x").snapshot()),
            "meta_rtt": snap.get("dfs_meta_rtt_seconds",
                                 Histogram("x").snapshot()),
            "lag": snap.get("op_lag_seconds",
                            Histogram("x").snapshot()),
        }


# ---------------------------------------------------------------- harness


def _p(h: "dict | None", q: str) -> float:
    return float((h or {}).get(q, 0.0))


def run_dfs_step(n_clients: int, *, conf: Any = None,
                 interval_s: float = 0.05, measure_s: float = 6.0,
                 num_datanodes: int = 3, n_files: int = 8,
                 file_bytes: int = 1 << 18, hot_read_p: float = 0.5,
                 read_bytes: int = 1 << 16, seed: int = 0,
                 prom_out: "str | None" = None,
                 hot_top_n: int = 8) -> dict:
    """One DFS saturation rung: a FRESH in-process MiniDFSCluster, a
    fleet of ``n_clients`` real DFSClients on a fixed op cadence for
    ``measure_s``, then one joined snapshot of both sides — the
    NameNode's own op/lock/editlog attribution and the fleet's
    client-side round trips. Shared by ``bench_dfs.py`` (the ramp) and
    ``tpumr simulate -dfs`` (one rung, operator-driven).

    ``prom_out`` additionally scrapes the NameNode's live
    ``/metrics/prom`` at the end of the window and writes the body
    there (the CI artifact proving the exposition renders under load).
    """
    from tpumr.dfs.mini_cluster import MiniDFSCluster
    from tpumr.mapred.jobconf import JobConf

    conf = conf or JobConf()
    # the scrape/hotblocks surface rides the rung on an ephemeral port
    conf.set_if_unset("tdfs.http.port", 0)
    t0 = time.monotonic()
    with MiniDFSCluster(num_datanodes, conf=conf) as cluster:
        files = seed_files(cluster.nn_host, cluster.nn_port, conf,
                           n_files=n_files, file_bytes=file_bytes)
        nn = cluster.namenode
        fleet = SimDFSFleet(cluster.nn_host, cluster.nn_port, n_clients,
                            conf, interval_s=interval_s, seed=seed,
                            files=files, hot_read_p=hot_read_p,
                            read_bytes=read_bytes).start()
        try:
            time.sleep(measure_s)
        finally:
            fleet.stop()
        # let the last datanode heartbeats land so the cluster
        # hot-block table holds every sketch slice
        from tpumr.core import confkeys
        time.sleep(2 * confkeys.get_float(
            conf, "tdfs.datanode.heartbeat.s") + 0.1)
        wall = time.monotonic() - t0
        fl = fleet.stats()
        snap = nn.metrics.snapshot()
        reg = snap.get("namenode", {})
        merged = Histogram("nn_op_seconds")
        for h in nn._op_hists.values():
            merged.merge_typed(h.typed())
        ops_merged = merged.snapshot()
        hot_top = nn.ns.get_hot_blocks(hot_top_n)
        hot_total = nn.ns.hot_blocks.total_reads()
        row = {
            "clients": n_clients,
            "interval_s": interval_s,
            "wall_s": round(wall, 3),
            "ops": fl["ops"],
            "op_counts": fl["op_counts"],
            "errors": int(fl["errors"]),
            "completed": int(fl["errors"]) == 0,
            # the NameNode's own attribution (nn_op_seconds merged
            # across every op family, plus the per-op p99 map)
            "nn_op_count": int(_p(ops_merged, "count")),
            "nn_op_p50_s": round(_p(ops_merged, "p50"), 6),
            "nn_op_p99_s": round(_p(ops_merged, "p99"), 6),
            "nn_op_p99_by_op": {
                op: round(_p(h.snapshot(), "p99"), 6)
                for op, h in sorted(nn._op_hists.items())},
            # the striped namenode reports three lock families
            # (namespace = structural/global, namespace-stripe,
            # namespace-blocks); the headline wait/hold p99 is the
            # worst family — the one gating op latency at this rung
            "lock_wait_p99_s": round(max(
                (_p(h, "p99") for k, h in reg.items()
                 if k.startswith("nn_lock_wait_seconds|")),
                default=0.0), 6),
            "lock_hold_p99_s": round(max(
                (_p(h, "p99") for k, h in reg.items()
                 if k.startswith("nn_lock_hold_seconds|")),
                default=0.0), 6),
            "lock_wait_p99_by_lock": {
                k.split("lock=", 1)[1]: round(_p(h, "p99"), 6)
                for k, h in sorted(reg.items())
                if k.startswith("nn_lock_wait_seconds|")},
            "editlog_sync_p99_s": round(_p(reg.get(
                "nn_editlog_sync_seconds"), "p99"), 6),
            # fsyncs absorbed per group commit: mean ops covered by
            # one sync (1.0 = no batching; >1 = the editlog is
            # coalescing concurrent mutations into shared fsyncs)
            "editlog_group_ops_mean": round(_p(reg.get(
                "nn_editlog_group_ops"), "mean"), 3),
            # data-plane throughput + tails, both sides
            "read_mb_s": round(fl["bytes_read"] / wall / 1e6, 3),
            "read_rtt_p50_s": round(_p(fl["read_rtt"], "p50"), 6),
            "read_rtt_p99_s": round(_p(fl["read_rtt"], "p99"), 6),
            "meta_rtt_p99_s": round(_p(fl["meta_rtt"], "p99"), 6),
            "lag_p99_s": round(_p(fl["lag"], "p99"), 6),
            "dn_read_p99_s": round(max(
                (_p(dn.metrics.snapshot().get("datanode", {})
                    .get("dn_read_seconds"), "p99")
                 for dn in cluster.datanodes), default=0.0), 6),
            # hot-block skew: share of all sketched reads landing on
            # the cluster-wide top block (the /hotblocks headline)
            "hot_total_reads": hot_total,
            "hot_top": [{"block": r["block"], "path": r.get("path", ""),
                         "reads": r["reads"],
                         "replicas": r.get("replicas", 0),
                         "boost": r.get("boost", 0)}
                        for r in hot_top[:3]],
            "hot_top1_share": round(
                hot_top[0]["reads"] / hot_total, 4)
                if hot_top and hot_total else 0.0,
            # the auto-replication receipt: the top block's live
            # replica count and the boost the policy assigned it
            "hot_top1_replicas": int(hot_top[0].get("replicas", 0))
                if hot_top else 0,
            "hot_top1_boost": int(hot_top[0].get("boost", 0))
                if hot_top else 0,
        }
        # lock wait p99 as a share of op p99: ~1.0 means the namespace
        # lock IS the op latency (the saturation signature the
        # fine-grained-locking roadmap item would have to move)
        p99 = row["nn_op_p99_s"]
        row["lock_wait_share"] = round(
            row["lock_wait_p99_s"] / p99, 3) if p99 > 0 else 0.0
        if prom_out and nn.http_url:
            from urllib.request import urlopen
            with urlopen(f"{nn.http_url}/metrics/prom",
                         timeout=10) as resp:
                body = resp.read()
            with open(prom_out, "wb") as f:
                f.write(body)
        return row


# ------------------------------------------------------ recovery steps


def _recovery_conf() -> "tuple[Any, dict]":
    """One conf + the registered recovery SLOs for the timed kill
    steps. Fast monitor/expiry cadences: the rows measure the
    detection + repair MACHINERY, not production timer defaults."""
    from tpumr.core import confkeys
    from tpumr.mapred.jobconf import JobConf
    conf = JobConf()
    conf.set("tdfs.http.port", -1)
    conf.set("dfs.replication", 2)
    conf.set("tdfs.replication.interval.s", 0.2)
    conf.set("tdfs.datanode.expiry.s", 1.5)
    # clients ride a NameNode outage on transport retries; safemode
    # refusals are application-retried by the probe
    conf.set("tdfs.client.nn.retries", 60)
    conf.set("tdfs.client.nn.backoff.ms", 100.0)
    slos = {
        "safemode": confkeys.get_float(
            conf, "tpumr.dfs.bench.recovery.safemode.slo.s"),
        "client": confkeys.get_float(
            conf, "tpumr.dfs.bench.recovery.client.slo.s"),
        "replication": confkeys.get_float(
            conf, "tpumr.dfs.bench.recovery.replication.slo.s"),
    }
    return conf, slos


def run_nn_kill_recovery(*, num_datanodes: int = 3, n_files: int = 8,
                         file_bytes: int = 1 << 18,
                         outage_s: float = 0.3) -> "list[dict]":
    """SIGKILL the NameNode mid-traffic and time the two recovery
    headlines from the moment of the kill: safemode exit (editlog
    replay + enough block reports) and the first client op that
    SUCCEEDS again (a probe riding transport retries across the
    outage and application-retrying safemode refusals — the HDFS
    SafeModeException loop). Returns the two bench rows with SLO
    verdicts (``bench_dfs.py --recovery-only``)."""
    from tpumr.dfs.mini_cluster import MiniDFSCluster

    conf, slos = _recovery_conf()
    base = {"kind": "", "datanodes": num_datanodes, "files": n_files,
            "outage_s": outage_s}
    with MiniDFSCluster(num_datanodes, conf=conf) as c:
        files = seed_files(c.nn_host, c.nn_port, conf,
                           n_files=n_files, file_bytes=file_bytes)
        result: dict = {}

        def probe() -> None:
            cli = c.client()
            try:
                deadline = time.monotonic() + 25.0
                while time.monotonic() < deadline:
                    try:
                        with cli.open(files[0]) as f:
                            f.read(1024)
                        result["t"] = time.monotonic()
                        return
                    except Exception as e:  # noqa: BLE001
                        if "safe mode" not in str(e).lower():
                            result["error"] = str(e)
                            return
                        time.sleep(0.1)
                result["error"] = "probe timed out"
            finally:
                close_client(cli)

        t_kill = time.monotonic()
        c.kill_namenode()
        t = threading.Thread(target=probe, daemon=True)
        t.start()
        time.sleep(outage_s)
        c.restart_killed_namenode()
        sm_deadline = time.monotonic() + 30.0
        while c.namenode.ns.safemode \
                and time.monotonic() < sm_deadline:
            time.sleep(0.02)
        safemode_s = time.monotonic() - t_kill
        t.join(timeout=30.0)
        rows = [dict(base, kind="nn_kill_safemode_exit",
                     recovery_s=round(safemode_s, 3),
                     slo_s=slos["safemode"],
                     ok=(not c.namenode.ns.safemode
                         and safemode_s <= slos["safemode"]))]
        if "t" in result:
            client_s = result["t"] - t_kill
            rows.append(dict(base,
                             kind="nn_kill_first_client_success",
                             recovery_s=round(client_s, 3),
                             slo_s=slos["client"],
                             ok=client_s <= slos["client"]))
        else:
            rows.append(dict(base,
                             kind="nn_kill_first_client_success",
                             error=result.get("error", "no result")))
        return rows


def run_dn_kill_recovery(*, num_datanodes: int = 4, n_files: int = 8,
                         file_bytes: int = 1 << 18) -> dict:
    """Hard-kill one datanode holding seeded replicas and time the
    NameNode's expiry + re-replication loop restoring EVERY block to
    its replication target on the survivors. Returns the bench row
    with its SLO verdict."""
    from tpumr.dfs.mini_cluster import MiniDFSCluster

    conf, slos = _recovery_conf()
    with MiniDFSCluster(num_datanodes, conf=conf) as c:
        seed_files(c.nn_host, c.nn_port, conf,
                   n_files=n_files, file_bytes=file_bytes)
        ns = c.namenode.ns
        dead = c.datanodes[0].addr
        n_blocks = len(ns.block_locations)
        t_kill = time.monotonic()
        c.kill_datanode(0)

        def restored() -> bool:
            for locs in ns.block_locations.values():
                if dead in locs or len(locs) < 2:
                    return False
            return True

        deadline = time.monotonic() + slos["replication"] + 10.0
        while not restored() and time.monotonic() < deadline:
            time.sleep(0.05)
        recovery_s = time.monotonic() - t_kill
        return {"kind": "dn_kill_replication_restored",
                "datanodes": num_datanodes, "files": n_files,
                "blocks": n_blocks,
                "recovery_s": round(recovery_s, 3),
                "slo_s": slos["replication"],
                "ok": (restored()
                       and recovery_s <= slos["replication"])}
