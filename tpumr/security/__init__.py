"""Security-lite: identity + RPC authentication.

≈ the reference's ``org.apache.hadoop.security`` tier (UserGroupInformation,
SaslRpcServer digest auth, delegation tokens — 10k LoC of Kerberos/SASL
machinery, SURVEY.md §2.2). Scoped to what a single-operator TPU cluster
needs: a process identity (UGI), and shared-secret HMAC request signing
on every RPC (≈ the DIGEST-MD5 token path, with HMAC-SHA256). Kerberos
is out of scope — documented divergence.

Config: ``tpumr.rpc.secret`` (inline secret) or ``tpumr.rpc.secret.file``
(path to a secret file; trailing whitespace ignored). All daemons and
clients of one cluster must share it. Unset = auth off (the reference's
``simple`` auth mode).
"""

from __future__ import annotations

import contextlib
import getpass
import os
import threading
from typing import Any, Iterator

_local = threading.local()

#: memoized process login (see get_current_user)
_process_login: "str | None" = None


class UserGroupInformation:
    """≈ UserGroupInformation.getCurrentUser / doAs (simple-auth mode:
    identity is asserted, not cryptographically proven — exactly the
    reference's non-Kerberos default)."""

    def __init__(self, user: str, groups: "list[str] | None" = None) -> None:
        self.user = user
        self.groups = groups or []

    @staticmethod
    def get_current_user(conf: Any = None) -> "UserGroupInformation":
        override = getattr(_local, "ugi", None)
        if override is not None:
            return override
        if conf is not None and conf.get("user.name"):
            return UserGroupInformation(str(conf.get("user.name")))
        # the process login is resolved once: this sits on the RPC
        # client's per-call path (identity rides every request) and
        # getpass walks env/passwd each time — measurable at thousands
        # of heartbeats per second
        global _process_login
        if _process_login is None:
            try:
                _process_login = getpass.getuser()
            except Exception:  # no passwd entry (containers)
                _process_login = os.environ.get("USER", "nobody")
        return UserGroupInformation(_process_login)

    @contextlib.contextmanager
    def do_as(self) -> Iterator["UserGroupInformation"]:
        """≈ ugi.doAs: run a block under this identity."""
        prev = getattr(_local, "ugi", None)
        _local.ugi = self
        try:
            yield self
        finally:
            _local.ugi = prev


def server_side_ugi(user: str, conf: Any = None) -> UserGroupInformation:
    """Build a UGI for an asserted remote username with groups resolved
    SERVER-side (≈ the reference's Groups/ShellBasedUnixGroupsMapping:
    group membership is never trusted from the wire). Resolution order:
    static conf mapping ``tpumr.user.groups.<user> = g1,g2``, then the
    local OS group database; empty ``user`` falls back to the current
    process identity (in-process callers)."""
    if not user:
        return UserGroupInformation.get_current_user()
    groups: "list[str]" = []
    if conf is not None:
        static = conf.get(f"tpumr.user.groups.{user}")
        if static:
            groups = [g.strip() for g in str(static).split(",") if g.strip()]
    if not groups:
        try:
            import grp
            import pwd
            pw = pwd.getpwnam(user)
            groups = [g.gr_name for g in grp.getgrall()
                      if user in g.gr_mem]
            primary = grp.getgrgid(pw.pw_gid).gr_name
            if primary not in groups:
                groups.insert(0, primary)
        except (KeyError, ImportError, OSError):
            pass
    return UserGroupInformation(user, groups)


def rpc_secret(conf: Any) -> "bytes | None":
    """Resolve the cluster RPC secret from conf (None = auth disabled)."""
    if conf is None:
        return None
    inline = conf.get("tpumr.rpc.secret")
    if inline:
        return str(inline).encode()
    path = conf.get("tpumr.rpc.secret.file")
    if path:
        with open(path, "rb") as f:
            return f.read().strip()
    return None


def client_credentials(conf: Any, service: "str | None" = None) \
        -> "tuple[bytes | None, str | None]":
    """(signing_secret, scope) for an RPC client. Personal credentials
    win over the cluster secret: a user configured with their own key or
    a delegation token signs as a VERIFIED identity and never needs (or
    touches) the cluster secret — the trust split the reference draws
    between service keytabs and user tokens. ``service`` selects the
    right token from a per-service token file ("jobtracker",
    "namenode")."""
    from tpumr.security.tokens import user_signing_credentials
    personal = user_signing_credentials(conf, service)
    if personal is not None:
        return personal
    return rpc_secret(conf), None
