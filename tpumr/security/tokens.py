# tpulint: disable=clock-arith — token lifetimes are ABSOLUTE wall-clock
# instants shared across daemons and credential files; one process's
# monotonic clock means nothing to another.
"""Per-user signing keys + delegation tokens — verified identity.

≈ the reference's token tier (src/core/org/apache/hadoop/security/token/
``Token``, ``SecretManager``, ``delegation/AbstractDelegationTokenSecretManager``
and ``DelegationTokenIdentifier``; SaslRpcServer's DIGEST-MD5 uses the
token password as the digest secret). Re-designed on the framework's
HMAC-SHA256 request signing instead of SASL:

**The trust structure.** The round-3 flat model let any cluster-secret
holder sign as any user, so queue ACLs authenticated *assertions*. This
module fixes the client side of that: a user holds only a PERSONAL key
(or a time-bounded delegation token) and can sign only as themselves —
while daemons, which hold the cluster secret, can derive/verify every
key server-side with zero per-user state (exactly the reference's
masterKey -> token-password derivation, SecretManager.createPassword).
Cluster-secret holders remain omnipotent — they are the daemons; that
boundary is the same one the reference draws with its service keytabs.

- ``derive_user_key(cluster_secret, user)``: the user's personal signing
  key. Provisioned out-of-band by an operator (``tpumr keys user-key``);
  config ``tpumr.rpc.user.key`` / ``tpumr.rpc.user.key.file``.
- ``DelegationToken``: (owner, renewer, issue_ts, max_ts, seq) ident
  whose password is HMAC(master_key, ident) — self-authenticating to any
  daemon holding the cluster secret, with LIVENESS tracked server-side
  in a ``TokenStore`` (issue/renew/cancel with a renew interval capped
  by max lifetime, ≈ AbstractDelegationTokenSecretManager's
  currentTokens map).

An RPC signed with either rides scope ``user:<name>`` / ``token:<hex>``
(tpumr/ipc/rpc.py) and reaches handlers as a **verified** identity
(``current_rpc_verified()``); ``tpumr.acls.require.verified`` lets a
cluster demand that for ACL-relevant operations.
"""

from __future__ import annotations

import hashlib
import hmac
import threading
import time
from dataclasses import dataclass
from typing import Any

from tpumr.io.writable import deserialize, serialize

_USER_KEY_CTX = b"tpumr user-key v1:"
_MASTER_CTX = b"tpumr token-master v1"

#: default renew interval / max lifetime (s) — the reference's
#: delegation.token.renew-interval (24h) and max-lifetime (7d), scaled
#: for job-scoped clusters; both overridable in conf
RENEW_INTERVAL_S = 24 * 3600.0
MAX_LIFETIME_S = 7 * 24 * 3600.0


def derive_user_key(cluster_secret: bytes, user: str) -> bytes:
    """The user's personal RPC signing key. Deterministic from the
    cluster secret, so daemons verify with no key database; users hold
    only their own key and cannot compute anyone else's."""
    return hmac.new(cluster_secret, _USER_KEY_CTX + user.encode(),
                    "sha256").digest()


def master_key(cluster_secret: bytes) -> bytes:
    """Token-password master key (domain-separated from user keys)."""
    return hmac.new(cluster_secret, _MASTER_CTX, "sha256").digest()


@dataclass(frozen=True)
class DelegationToken:
    """Token ident + password. The ident travels as the RPC scope; the
    password is the request-signing secret (never sent — proven by the
    HMAC on each request, like the reference's DIGEST password)."""

    owner: str
    renewer: str
    issue_ts: float
    max_ts: float
    seq: int
    password: bytes = b""

    def ident_bytes(self) -> bytes:
        return serialize([self.owner, self.renewer, self.issue_ts,
                          self.max_ts, self.seq])

    def digest(self) -> str:
        return hashlib.sha256(self.ident_bytes()).hexdigest()

    def scope(self) -> str:
        return "token:" + self.ident_bytes().hex()

    def to_wire(self) -> dict:
        """Client-side credential (≈ Token.encodeToUrlString)."""
        return {"ident": self.ident_bytes().hex(),
                "password": self.password.hex()}

    @staticmethod
    def from_wire(d: dict) -> "DelegationToken":
        tok = parse_ident(bytes.fromhex(d["ident"]))
        object.__setattr__(tok, "password", bytes.fromhex(d["password"]))
        return tok


def parse_ident(ident: bytes) -> DelegationToken:
    owner, renewer, issue_ts, max_ts, seq = deserialize(ident)
    return DelegationToken(owner=str(owner), renewer=str(renewer),
                           issue_ts=float(issue_ts), max_ts=float(max_ts),
                           seq=int(seq))


def token_password(cluster_secret: bytes, ident: bytes) -> bytes:
    """password = HMAC(masterKey, ident) ≈ SecretManager.createPassword."""
    return hmac.new(master_key(cluster_secret), ident, "sha256").digest()


class TokenStore:
    """Server-side token liveness (≈ AbstractDelegationTokenSecretManager
    currentTokens): a token's signature proves it was issued by this
    cluster; the store decides whether it is still GOOD — within its
    tracked expiry, not canceled. Local to the issuing daemon, like the
    reference's per-service token managers."""

    def __init__(self, conf: Any = None) -> None:
        get = (lambda k, d: float(conf.get(k, d))) if conf is not None \
            else (lambda k, d: d)
        self.renew_interval = get("tpumr.token.renew.interval.s",
                                  RENEW_INTERVAL_S)
        self.max_lifetime = get("tpumr.token.max.lifetime.s",
                                MAX_LIFETIME_S)
        self._lock = threading.Lock()
        self._seq = 0
        #: digest -> tracked expiry_ts
        self._live: dict[str, float] = {}

    def issue(self, cluster_secret: bytes, owner: str,
              renewer: str = "") -> DelegationToken:
        now = time.time()
        with self._lock:
            self._seq += 1
            tok = DelegationToken(owner=owner, renewer=renewer,
                                  issue_ts=now,
                                  max_ts=now + self.max_lifetime,
                                  seq=self._seq)
            ident = tok.ident_bytes()
            object.__setattr__(tok, "password",
                               token_password(cluster_secret, ident))
            self._live[tok.digest()] = min(now + self.renew_interval,
                                           tok.max_ts)
            return tok

    def check(self, tok: DelegationToken) -> "str | None":
        """None when good; else the rejection reason."""
        with self._lock:
            expiry = self._live.get(tok.digest())
        now = time.time()
        if expiry is None:
            return "token is not known to this daemon (canceled, " \
                   "expired out of the store, or issued elsewhere)"
        if now > expiry:
            return "token expired (renewable until its max lifetime)"
        if now > tok.max_ts:
            return "token past max lifetime"
        return None

    def renew(self, tok: DelegationToken, caller: str) -> float:
        """≈ renewToken: only the designated renewer or the owner may;
        extends by one renew interval, capped at max lifetime."""
        if caller not in (tok.renewer, tok.owner) or not caller:
            raise PermissionError(
                f"user {caller!r} may not renew a token owned by "
                f"{tok.owner!r} (renewer {tok.renewer!r})")
        now = time.time()
        if now > tok.max_ts:
            raise PermissionError("token past max lifetime")
        with self._lock:
            if tok.digest() not in self._live:
                raise PermissionError("token unknown (canceled?)")
            expiry = min(now + self.renew_interval, tok.max_ts)
            self._live[tok.digest()] = expiry
            return expiry

    def cancel(self, tok: DelegationToken, caller: str) -> None:
        """≈ cancelToken: owner or renewer only."""
        if caller not in (tok.renewer, tok.owner) or not caller:
            raise PermissionError(
                f"user {caller!r} may not cancel a token owned by "
                f"{tok.owner!r}")
        with self._lock:
            self._live.pop(tok.digest(), None)

    def purge_expired(self) -> None:
        now = time.time()
        with self._lock:
            dead = [d for d, exp in self._live.items() if now > exp]
            for d in dead:
                del self._live[d]


def issue_for_caller(store: TokenStore, cluster_secret: "bytes | None",
                     renewer: str) -> dict:
    """Shared issuance gate for token-service daemons (JobTracker and
    NameNode RPCs): the caller's verified or cluster-secret-asserted
    identity gets a token — EXCEPT a token-authenticated caller, which
    must not mint successors (the reference forbids getDelegationToken
    over token-authenticated connections precisely so cancellation and
    max lifetime actually bound access)."""
    from tpumr.ipc.rpc import current_rpc_scope, current_rpc_user
    if cluster_secret is None:
        raise PermissionError("delegation tokens need an authenticated "
                              "cluster (tpumr.rpc.secret unset)")
    scope = current_rpc_scope()
    if isinstance(scope, str) and scope.startswith("token:"):
        raise PermissionError(
            "a delegation token cannot be used to obtain further "
            "tokens — authenticate with a user key")
    user = current_rpc_user()
    if not user:
        raise PermissionError("no caller identity to issue a token for")
    return store.issue(cluster_secret, str(user),
                       str(renewer or "")).to_wire()


def verify_wire(cluster_secret: "bytes | None",
                wire: dict) -> DelegationToken:
    """Parse + password-check a client-presented token: possession of
    the PASSWORD (not just the guessable ident) is what renew/cancel
    authorize on, like the reference's retrievePassword."""
    if cluster_secret is None:
        raise PermissionError("tokens need an authenticated cluster")
    tok = DelegationToken.from_wire(dict(wire))
    if not hmac.compare_digest(
            tok.password, token_password(cluster_secret,
                                         tok.ident_bytes())):
        raise PermissionError("token password mismatch")
    return tok


# ------------------------------------------------------------ block access


_DN_CTX = b"tpumr dn-access v1"

#: default stamp lifetime — the revocation horizon for direct DataNode
#: access by personal-credential holders (≈ the reference's block tokens,
#: which are hours-lived and not individually revocable either)
BLOCK_ACCESS_LIFETIME_S = 3600.0


def dn_access_key(cluster_secret: bytes) -> bytes:
    return hmac.new(cluster_secret, _DN_CTX, "sha256").digest()


def mint_block_access(cluster_secret: bytes, user: str, block_id: int,
                      mode: str,
                      lifetime_s: float = BLOCK_ACCESS_LIFETIME_S) -> dict:
    """NameNode-side: a short-lived bearer stamp authorizing ``user`` to
    ``mode`` ('r'/'w') one block on any DataNode (≈ BlockTokenSecret-
    Manager.generateToken). Minted only by block-id-granting RPCs
    (get_block_locations, add_block), so a canceled/expired delegation
    token stops yielding fresh stamps — cancellation reaches the DN
    within the stamp lifetime."""
    exp = time.time() + lifetime_s
    canon = serialize([user, int(block_id), mode, exp])
    return {"u": user, "b": int(block_id), "m": mode, "e": exp,
            "sig": hmac.new(dn_access_key(cluster_secret), canon,
                            "sha256").hexdigest()}


def check_block_access(cluster_secret: bytes, stamp: Any, user: str,
                       block_id: int, mode: str) -> bool:
    """DataNode-side verification: signature, binding, expiry."""
    try:
        if not isinstance(stamp, dict):
            return False
        if stamp["u"] != user or int(stamp["b"]) != int(block_id):
            return False
        if mode not in str(stamp["m"]):
            return False
        exp = float(stamp["e"])
        if time.time() > exp:
            return False
        canon = serialize([stamp["u"], int(stamp["b"]), str(stamp["m"]),
                           exp])
        want = hmac.new(dn_access_key(cluster_secret), canon,
                        "sha256").hexdigest()
        return hmac.compare_digest(str(stamp["sig"]), want)
    except (KeyError, TypeError, ValueError):
        return False


def user_signing_credentials(conf: Any, service: "str | None" = None) \
        -> "tuple[bytes, str] | None":
    """(signing_key, scope) for a client configured with a PERSONAL
    credential — a user key (``tpumr.rpc.user.key``/``.file``, hex) or a
    delegation token (``tpumr.rpc.token.file``). The token file is
    either one flat wire dict {ident, password} (single-service) or
    keyed by service name ({"jobtracker": {...}, "namenode": {...}} —
    tokens are per-issuing-daemon, like the reference's per-service
    Token<?> credentials). A token file with no entry for ``service``
    falls through to the user key. None when nothing personal is
    configured (cluster-secret or simple auth)."""
    if conf is None:
        return None
    tok_file = conf.get("tpumr.rpc.token.file")
    if tok_file:
        import json
        with open(tok_file) as f:
            data = json.load(f)
        wire = None
        if isinstance(data, dict) and "ident" in data:
            wire = data                       # flat single-service file
        elif isinstance(data, dict) and service and service in data:
            wire = data[service]
        if wire is not None:
            tok = DelegationToken.from_wire(wire)
            return tok.password, tok.scope()
    key_hex = conf.get("tpumr.rpc.user.key")
    if not key_hex:
        path = conf.get("tpumr.rpc.user.key.file")
        if path:
            with open(path) as f:
                key_hex = f.read().strip()
    if key_hex:
        from tpumr.security import UserGroupInformation
        user = UserGroupInformation.get_current_user(conf).user
        return bytes.fromhex(str(key_hex)), f"user:{user}"
    return None
