"""Service-level authorization ≈ the reference's ``hadoop-policy.xml``
tier (src/core/org/apache/hadoop/security/authorize/
``ServiceAuthorizationManager``, ``PolicyProvider``, ``Service`` and the
per-daemon providers ``MapReducePolicyProvider``/``HDFSPolicyProvider``;
refresh RPC ≈ ``RefreshAuthorizationPolicyProtocol.refreshServiceAcl``).

Who may talk to which PROTOCOL at all — a coarser gate than job/queue
ACLs, checked before dispatch. The reference authorizes at connection
time per protocol interface; tpumr's RPC servers dispatch per-method on
one handler per daemon, so each daemon declares a method→service-key
policy map and a method is authorized when ANY of its service keys
admits the caller (a method reachable from two protocols — e.g.
completion events for both clients and reduce children — accepts
callers of either).

Config (reference key names kept):

- ``tpumr.security.authorization`` (≈ ``hadoop.security.authorization``,
  default false) — master switch.
- ``security.<service>.protocol.acl`` — the reference's per-service ACL
  spec (``"user1,user2 group1"`` / ``*`` / blank); unset = ``*``, the
  stock hadoop-policy.xml default.
- ``tpumr.policy.file`` — optional separate hot-reloadable policy file
  (≈ hadoop-policy.xml as its own resource), re-read by
  ``mradmin|dfsadmin -refreshServiceAcl``.
"""

from __future__ import annotations

from typing import Any

from tpumr.security import UserGroupInformation, server_side_ugi

AUTHORIZATION_KEY = "tpumr.security.authorization"
POLICY_FILE_KEY = "tpumr.policy.file"


class AuthorizationError(PermissionError):
    """≈ org.apache.hadoop.security.authorize.AuthorizationException."""


def authorize_proxy(conf: Any, real_user: str, effective_user: str,
                    remote_addr: str) -> None:
    """≈ ProxyUsers.authorize (hadoop.proxyuser.<real>.groups/.hosts):
    may ``real_user`` impersonate ``effective_user`` from
    ``remote_addr``? BOTH rules must pass, both default CLOSED (an
    unset key denies — impersonation is opt-in per superuser). ``*``
    is accepted in either key (a convenience the reference's 1.0.3
    ProxyUsers lacks but its successors added). Rules are read from
    conf on every call, so edits via a reloaded daemon conf apply
    without a dedicated refresh RPC."""
    if not str(effective_user).strip() or not str(real_user).strip():
        # defense in depth with the RPC-layer check: an empty identity
        # on either side of a proxy decision must never pass (empty
        # users resolve to the daemon's own UGI downstream)
        raise AuthorizationError("empty identity in proxy authorization")
    groups_spec = str(conf.get(f"hadoop.proxyuser.{real_user}.groups",
                               "") or "")
    hosts_spec = str(conf.get(f"hadoop.proxyuser.{real_user}.hosts",
                              "") or "")
    allowed_groups = {g.strip() for g in groups_spec.split(",")
                      if g.strip()}
    if "*" not in allowed_groups:
        effective = server_side_ugi(effective_user, conf)
        if not allowed_groups & set(effective.groups):
            raise AuthorizationError(
                f"User: {real_user} is not allowed to impersonate "
                f"{effective_user}")
    allowed_hosts = {h.strip() for h in hosts_spec.split(",")
                     if h.strip()}
    if "*" not in allowed_hosts and remote_addr not in allowed_hosts:
        raise AuthorizationError(
            f"Unauthorized connection for super-user {real_user} "
            f"from IP {remote_addr}")


class ServiceAuthorizationManager:
    def __init__(self, conf: Any, policy_map: "dict[str, list[str]]",
                 default_key: str) -> None:
        """``policy_map``: method name → service keys that reach it;
        methods absent from the map fall back to ``default_key`` (the
        daemon's client-protocol key — the safe default for new client
        RPCs; service/admin surfaces must be mapped explicitly)."""
        self.policy_map = policy_map
        self.default_key = default_key
        policy_file = conf.get(POLICY_FILE_KEY)
        if policy_file:
            from tpumr.core.configuration import Configuration
            eff = Configuration(conf)
            eff.add_resource(str(policy_file))   # unreadable: fail loudly
            conf = eff
        self.conf = conf
        self.enabled = bool(conf.get_boolean(AUTHORIZATION_KEY, False)) \
            if hasattr(conf, "get_boolean") else \
            str(conf.get(AUTHORIZATION_KEY, "false")).lower() == "true"
        # parse every referenced ACL once at construction (refresh =
        # rebuild, the queue-manager pattern), so a syntax problem
        # surfaces at refresh time, not on some later request
        from tpumr.mapred.queue_manager import AccessControlList
        keys = {k for keys in policy_map.values() for k in keys}
        keys.add(default_key)
        self._acls = {k: AccessControlList(
            "*" if conf.get(k) is None else str(conf.get(k)))
            for k in keys}

    def acl_specs(self) -> "dict[str, str]":
        """Current specs per service key (for -refreshServiceAcl's
        confirmation output)."""
        return {k: acl.spec if not acl.all else "*"
                for k, acl in sorted(self._acls.items())}

    def check(self, method: str, user: Any) -> None:
        """Raise AuthorizationError unless ``user`` may invoke
        ``method`` via at least one of its declared services. ``user``
        is the rpc-layer identity (verified when the caller signed with
        a personal credential, else the asserted simple-auth name —
        the reference's simple-auth posture); groups resolve
        server-side, never from the wire."""
        if not self.enabled:
            return
        keys = self.policy_map.get(method) or [self.default_key]
        ugi = server_side_ugi(str(user), self.conf) if user else \
            UserGroupInformation("anonymous", [])
        for key in keys:
            if self._acls[key].allows(ugi):
                return
        raise AuthorizationError(
            f"user {ugi.user!r} is not authorized for protocol of "
            f"{method!r} ({' / '.join(keys)})")
