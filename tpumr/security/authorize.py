"""Service-level authorization ≈ the reference's ``hadoop-policy.xml``
tier (src/core/org/apache/hadoop/security/authorize/
``ServiceAuthorizationManager``, ``PolicyProvider``, ``Service`` and the
per-daemon providers ``MapReducePolicyProvider``/``HDFSPolicyProvider``;
refresh RPC ≈ ``RefreshAuthorizationPolicyProtocol.refreshServiceAcl``).

Who may talk to which PROTOCOL at all — a coarser gate than job/queue
ACLs, checked before dispatch. The reference authorizes at connection
time per protocol interface; tpumr's RPC servers dispatch per-method on
one handler per daemon, so each daemon declares a method→service-key
policy map and a method is authorized when ANY of its service keys
admits the caller (a method reachable from two protocols — e.g.
completion events for both clients and reduce children — accepts
callers of either).

Config (reference key names kept):

- ``tpumr.security.authorization`` (≈ ``hadoop.security.authorization``,
  default false) — master switch.
- ``security.<service>.protocol.acl`` — the reference's per-service ACL
  spec (``"user1,user2 group1"`` / ``*`` / blank); unset = ``*``, the
  stock hadoop-policy.xml default.
- ``tpumr.policy.file`` — optional separate hot-reloadable policy file
  (≈ hadoop-policy.xml as its own resource), re-read by
  ``mradmin|dfsadmin -refreshServiceAcl``.
"""

from __future__ import annotations

from typing import Any

from tpumr.security import UserGroupInformation, server_side_ugi

AUTHORIZATION_KEY = "tpumr.security.authorization"
POLICY_FILE_KEY = "tpumr.policy.file"


class AuthorizationError(PermissionError):
    """≈ org.apache.hadoop.security.authorize.AuthorizationException."""


def authorize_proxy(conf: Any, real_user: str, effective_user: str,
                    remote_addr: str) -> None:
    """≈ ProxyUsers.authorize (hadoop.proxyuser.<real>.groups/.hosts):
    may ``real_user`` impersonate ``effective_user`` from
    ``remote_addr``? BOTH rules must pass, both default CLOSED (an
    unset key denies — impersonation is opt-in per superuser). ``*``
    is accepted in either key (a convenience the reference's 1.0.3
    ProxyUsers lacks but its successors added). Rules are read from
    conf on every call, so edits via a reloaded daemon conf apply
    without a dedicated refresh RPC."""
    if not str(effective_user).strip() or not str(real_user).strip():
        # defense in depth with the RPC-layer check: an empty identity
        # on either side of a proxy decision must never pass (empty
        # users resolve to the daemon's own UGI downstream)
        raise AuthorizationError("empty identity in proxy authorization")
    groups_spec = str(conf.get(f"hadoop.proxyuser.{real_user}.groups",
                               "") or "")
    hosts_spec = str(conf.get(f"hadoop.proxyuser.{real_user}.hosts",
                              "") or "")
    allowed_groups = {g.strip() for g in groups_spec.split(",")
                      if g.strip()}
    if "*" not in allowed_groups:
        effective = server_side_ugi(effective_user, conf)
        if not allowed_groups & set(effective.groups):
            raise AuthorizationError(
                f"User: {real_user} is not allowed to impersonate "
                f"{effective_user}")
    allowed_hosts = {h.strip() for h in hosts_spec.split(",")
                     if h.strip()}
    if "*" not in allowed_hosts and remote_addr not in allowed_hosts:
        # entries may be hostnames (the reference resolves each via
        # InetAddress.getByName before comparing, ProxyUsers.authorize) —
        # a config listing "localhost" must match a 127.0.0.1 peer.
        # ALL addresses of a multi-homed entry count, resolutions are
        # TTL-cached (a DNS outage must not stall every doas RPC for the
        # resolver timeout), and failures are tolerated per-entry
        # (fail closed).
        for h in allowed_hosts:
            if remote_addr in _resolve_host(h):
                return
        raise AuthorizationError(
            f"Unauthorized connection for super-user {real_user} "
            f"from IP {remote_addr}")


#: hostname -> (monotonic deadline, frozenset of addresses); negative
#: results cache too — a dead resolver stalls each name once per TTL,
#: not once per RPC
_HOST_CACHE: "dict[str, tuple[float, frozenset]]" = {}
_HOST_CACHE_TTL_S = 300.0


def _resolve_host(name: str) -> frozenset:
    """Every address ``name`` resolves to (A/AAAA — a round-robin or
    multi-homed gateway must match whichever address the peer arrives
    from), empty on resolution failure."""
    import socket
    import time
    hit = _HOST_CACHE.get(name)
    now = time.monotonic()
    if hit is not None and now < hit[0]:
        return hit[1]
    try:
        addrs = frozenset(
            info[4][0] for info in socket.getaddrinfo(name, None))
    except OSError:
        addrs = frozenset()
    if len(_HOST_CACHE) > 1024:     # bound: entries come from config,
        _HOST_CACHE.clear()         # but stay safe against abuse
    _HOST_CACHE[name] = (now + _HOST_CACHE_TTL_S, addrs)
    return addrs


class ServiceAuthorizationManager:
    def __init__(self, conf: Any, policy_map: "dict[str, list[str]]",
                 default_key: str) -> None:
        """``policy_map``: method name → service keys that reach it;
        methods absent from the map fall back to ``default_key`` (the
        daemon's client-protocol key — the safe default for new client
        RPCs; service/admin surfaces must be mapped explicitly)."""
        self.policy_map = policy_map
        self.default_key = default_key
        policy_file = conf.get(POLICY_FILE_KEY)
        if policy_file:
            from tpumr.core.configuration import Configuration
            eff = Configuration(conf)
            eff.add_resource(str(policy_file))   # unreadable: fail loudly
            conf = eff
        self.conf = conf
        self.enabled = bool(conf.get_boolean(AUTHORIZATION_KEY, False)) \
            if hasattr(conf, "get_boolean") else \
            str(conf.get(AUTHORIZATION_KEY) or "").lower() == "true"
        # parse every referenced ACL once at construction (refresh =
        # rebuild, the queue-manager pattern), so a syntax problem
        # surfaces at refresh time, not on some later request
        from tpumr.mapred.queue_manager import AccessControlList
        keys = {k for keys in policy_map.values() for k in keys}
        keys.add(default_key)
        self._acls = {k: AccessControlList(
            "*" if conf.get(k) is None else str(conf.get(k)))
            for k in keys}
        # user→UGI TTL cache ≈ the reference's Groups cache
        # (hadoop.security.groups.cache.secs, default 300): without it
        # every authorized RPC pays a full group-database scan
        # (grp.getgrall() inside server_side_ugi). Per-manager, so a
        # -refreshServiceAcl (which rebuilds the manager) also drops
        # stale memberships.
        self._ugi_ttl = float(conf.get(
            "hadoop.security.groups.cache.secs", 300) or 300)
        self._ugi_cache: "dict[str, tuple[float, Any]]" = {}
        # the RPC server dispatches check() from concurrent handler
        # threads; the eviction sweep iterates the dict, so lookups and
        # inserts must serialize (group resolution itself stays outside
        # the lock — it can hit the OS group database)
        self._ugi_lock = __import__("threading").Lock()

    def acl_specs(self) -> "dict[str, str]":
        """Current specs per service key (for -refreshServiceAcl's
        confirmation output)."""
        return {k: acl.spec if not acl.all else "*"
                for k, acl in sorted(self._acls.items())}

    def check(self, method: str, user: Any) -> None:
        """Raise AuthorizationError unless ``user`` may invoke
        ``method`` via at least one of its declared services. ``user``
        is the rpc-layer identity (verified when the caller signed with
        a personal credential, else the asserted simple-auth name —
        the reference's simple-auth posture); groups resolve
        server-side, never from the wire."""
        if not self.enabled:
            return
        keys = self.policy_map.get(method) or [self.default_key]
        if user:
            import time
            name = str(user)
            now = time.monotonic()
            with self._ugi_lock:
                hit = self._ugi_cache.get(name)
            if hit is not None and now - hit[0] < self._ugi_ttl:
                ugi = hit[1]
            else:
                ugi = server_side_ugi(name, self.conf)
                with self._ugi_lock:
                    if len(self._ugi_cache) >= 4096:
                        # names are CALLER-asserted under simple auth: a
                        # client spraying distinct users must not grow a
                        # daemon-lifetime dict without bound. Drop
                        # expired entries first; full-clear if they were
                        # all live.
                        live = {k: v for k, v in self._ugi_cache.items()
                                if now - v[0] < self._ugi_ttl}
                        self._ugi_cache = live if len(live) < 4096 else {}
                    self._ugi_cache[name] = (now, ugi)
        else:
            ugi = UserGroupInformation("anonymous", [])
        for key in keys:
            if self._acls[key].allows(ugi):
                return
        raise AuthorizationError(
            f"user {ugi.user!r} is not authorized for protocol of "
            f"{method!r} ({' / '.join(keys)})")
