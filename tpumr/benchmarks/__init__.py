"""Benchmark harnesses ≈ the reference's ``src/benchmarks`` tree
(gridmix/gridmix2: synthetic mixed workloads — SURVEY.md §2.4)."""
