"""Gridmix-lite — synthetic mixed-workload benchmark.

≈ ``src/benchmarks/gridmix{,2}`` (reference README: "runs a mix of
small/medium/large jobs", sized there for a 480-500 node cluster —
SURVEY.md §6). This harness generates synthetic inputs and runs a
representative mix through the real job path — text jobs (wordcount,
grep), a sort over random SequenceFile records, the device-kernel
K-Means assignment, and Monte-Carlo pi — reporting per-job wall clock
and aggregate throughput as one JSON object.

Scales: ``small`` (seconds, CI-sized), ``medium``, ``large``.
"""

from __future__ import annotations

import argparse
import io
import json
import sys
import time

import numpy as np

from tpumr.cli import main as cli_main
from tpumr.fs import get_filesystem

SCALES = {
    #           text_mb  sort_mb  kmeans_pts  pi_samples
    "small":   (1,       1,       50_000,     20_000),
    "medium":  (32,      32,      2_000_000,  2_000_000),
    "large":   (256,     128,     20_000_000, 20_000_000),
}

_WORDS = ("alpha bravo charlie delta echo foxtrot golf hotel india "
          "juliet kilo lima mike november oscar papa").split()


def _gen_text(fs, path: str, mb: int, seed: int) -> None:
    rng = np.random.default_rng(seed)
    target = mb << 20
    out = io.BytesIO()
    while out.tell() < target:
        line = b" ".join(rng.choice(_WORDS).encode()
                         for _ in range(12)) + b"\n"
        out.write(line * 256)
    fs.write_bytes(path, out.getvalue()[:target])


def _gen_points(fs, path: str, n: int, seed: int) -> None:
    rng = np.random.default_rng(seed)
    pts = rng.normal(size=(n, 16)).astype(np.float32)
    buf = io.BytesIO()
    np.save(buf, pts)
    fs.write_bytes(path, buf.getvalue())


def _timed(name: str, argv: list[str], results: dict) -> bool:
    t0 = time.monotonic()
    rc = cli_main(argv)
    results[name] = {"wall_s": round(time.monotonic() - t0, 3), "ok": rc == 0}
    return rc == 0


def run(scale: str = "small", root: str = "mem:///gridmix",
        cpu_only: bool = False) -> dict:
    text_mb, sort_mb, kmeans_pts, pi_samples = SCALES[scale]
    fs = get_filesystem(root)
    base = root.rstrip("/")
    results: dict = {}
    t_all = time.monotonic()

    _gen_text(fs, f"{base}/text.txt", text_mb, 1)
    _gen_points(fs, f"{base}/points.npy", kmeans_pts, 2)
    flags = ["--cpu-only"] if cpu_only else []

    ok = True
    ok &= _timed("wordcount", ["examples", "wordcount", f"{base}/text.txt",
                               f"{base}/wc-out", "-r", "2", *flags],
                 results)
    ok &= _timed("grep", ["examples", "grep", f"{base}/text.txt",
                          f"{base}/grep-out", r"al\w+", *flags], results)
    ok &= _timed("randomwriter", ["examples", "randomwriter",
                                  f"{base}/rand", "-m", "2",
                                  "--bytes-per-map",
                                  str((sort_mb << 20) // 2)], results)
    ok &= _timed("sort", ["examples", "sort", f"{base}/rand",
                          f"{base}/sorted", "-r", "2", "--total-order"],
                 results)
    ok &= _timed("kmeans", ["examples", "kmeans", f"{base}/points.npy",
                            f"{base}/km-out", "-k", "8", "-i", "2",
                            *flags], results)
    ok &= _timed("pi", ["examples", "pi", "4", str(pi_samples // 4),
                        "--work", f"{base}/pi", *flags], results)

    return {
        "benchmark": "gridmix-lite",
        "scale": scale,
        "cpu_only": cpu_only,
        "jobs": results,
        "total_wall_s": round(time.monotonic() - t_all, 3),
        "succeeded": ok,
    }


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr gridmix")
    ap.add_argument("--scale", choices=sorted(SCALES), default="small")
    ap.add_argument("--root", default="mem:///gridmix",
                    help="working URI (use tdfs:// for cluster runs)")
    ap.add_argument("--cpu-only", action="store_true")
    args = ap.parse_args(argv)
    report = run(args.scale, args.root, args.cpu_only)
    print(json.dumps(report, indent=2))
    return 0 if report["succeeded"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
