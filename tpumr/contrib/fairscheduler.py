"""Fair scheduler — pool-based fair sharing.

≈ ``src/contrib/fairscheduler/.../FairScheduler.java`` (pools, weights,
minimum shares, deficit-style ordering). Jobs are grouped into pools (job
conf ``mapred.fairscheduler.pool``, falling back to ``user.name``); each
free slot is offered to the most-starved pool first:

1. *map pass only*: pools running below their map minimum share
   (``tpumr.fairscheduler.pool.<name>.minmaps``) come before satisfied
   pools (≈ the reference's minMaps guarantee);
2. ties break on running-tasks-to-weight ratio (lower = more starved,
   ``tpumr.fairscheduler.pool.<name>.weight``, default 1.0);
3. within a pool, FIFO by start time (the reference's default ordering
   inside a pool before fair-share-within-pool was added).

The reduce pass ranks pools purely by running-reduces/weight — map
min-shares do not leak into reduce ordering.

Unlike the reference's contrib scheduler — which had no GPU awareness at
all (SURVEY.md §2.4) — this subclasses the hybrid scheduler, so CPU/TPU
placement, optional-scheduling starvation, and device-id assignment all
apply within the fair ordering.
"""

from __future__ import annotations

import time
from typing import Any, Callable

from tpumr.mapred.job_in_progress import JobInProgress, JobState
from tpumr.mapred.scheduler import (HybridQueueScheduler,
                                    _priority_fifo)

POOL_KEY = "mapred.fairscheduler.pool"


def pool_of(job: JobInProgress) -> str:
    return str(job.conf.get(POOL_KEY)
               or job.conf.get("user.name")
               or "default")


class FairScheduler(HybridQueueScheduler):
    def __init__(self) -> None:
        super().__init__()
        self._pool_cache: dict[tuple[str, str], Any] = {}
        #: pool -> wall time it first fell below its map min share
        self._starved_since: dict[str, float] = {}
        self._last_preempt_check = 0.0

    def _begin_assignment(self, tts: dict) -> None:
        # weights/min-shares are heartbeat-invariant; the order hooks run
        # once per free slot — don't re-parse config each time
        self._pool_cache.clear()

    def before_heartbeat(self, tts: dict) -> None:
        # preemption runs on EVERY heartbeat — not inside assign_tasks,
        # which a saturated cluster (the one case preemption exists for)
        # never reaches because full trackers don't ask for work
        if self.conf is not None and self.conf.get_boolean(
                "tpumr.fairscheduler.preemption", False):
            self._pool_cache.clear()
            self._preempt_if_starved()

    # -------------------------------------------------------- preemption

    def _preempt_if_starved(self, now: float | None = None) -> None:
        """≈ FairScheduler.preemptTasksIfNecessary (reference
        src/contrib/fairscheduler): a pool below its map min share with
        pending work for longer than ``tpumr.fairscheduler.preemption.
        timeout.ms`` reclaims its guarantee by killing the NEWEST running
        map attempts of pools above their own min share. Kills requeue the
        victims (KILLED, not FAILED — no attempt budget burned)."""
        assert self.manager is not None and self.conf is not None
        now = time.monotonic() if now is None else now
        interval = self.conf.get_int(
            "tpumr.fairscheduler.preemption.interval.ms", 1000) / 1000.0
        if now - self._last_preempt_check < interval:
            return
        self._last_preempt_check = now
        timeout = self.conf.get_int(
            "tpumr.fairscheduler.preemption.timeout.ms", 15_000) / 1000.0

        jobs = [j for j in self.manager.running_jobs()
                if j.state == JobState.RUNNING]
        pools: dict[str, list[JobInProgress]] = {}
        for j in jobs:
            pools.setdefault(pool_of(j), []).append(j)

        usage = {p: sum(j.running_map_count() for j in members)
                 for p, members in pools.items()}
        pending = {p: sum(j.pending_map_count() for j in members)
                   for p, members in pools.items()}
        minshare = {p: int(self._pool_conf(p, "minmaps", 0)) for p in pools}
        pool_in_flight = {p: sum(len(j.preempt_pending()) for j in members)
                          for p, members in pools.items()}

        # drop starvation clocks of pools that no longer have running jobs
        # — a stale timestamp would let a future job in that pool preempt
        # instantly, skipping the configured timeout
        for p in list(self._starved_since):
            if p not in pools:
                del self._starved_since[p]

        starved: set[str] = set()
        deficit = 0
        for p in pools:
            if usage[p] < minshare[p] and pending[p] > 0:
                since = self._starved_since.setdefault(p, now)
                if now - since >= timeout:
                    starved.add(p)
                    deficit += min(minshare[p] - usage[p], pending[p])
            else:
                self._starved_since.pop(p, None)
        # kills already in flight count toward the coming free slots
        deficit -= sum(pool_in_flight.values())
        if deficit <= 0:
            return

        # victims: newest attempts of pools strictly above their OWN min
        # share (never push a pool below its guarantee — in-flight kills
        # already count against the pool's surplus), newest-first so the
        # least sunk work is lost (the reference's victim order)
        victims: list[tuple[float, str, JobInProgress, str]] = []
        for p, members in pools.items():
            over = usage[p] - max(minshare[p], 0) - pool_in_flight[p]
            if over <= 0 or p in starved:
                continue
            cand = []
            for j in members:
                already = j.preempt_pending()
                cand.extend((start, p, j, aid)
                            for aid, start in j.running_map_attempts()
                            if aid not in already)
            cand.sort(key=lambda t: t[0], reverse=True)
            victims.extend(cand[:over])
        victims.sort(key=lambda t: t[0], reverse=True)  # newest first

        for _start, _p, job, aid in victims[:deficit]:
            job.request_preempt(aid)

    def _pool_conf(self, pool: str, suffix: str, default: Any) -> Any:
        if self.conf is None:
            return default
        key = (pool, suffix)
        if key not in self._pool_cache:
            self._pool_cache[key] = self.conf.get(
                f"tpumr.fairscheduler.pool.{pool}.{suffix}", default)
        return self._pool_cache[key]

    def _ordered(self, jobs: list[JobInProgress],
                 running_of: Callable[[JobInProgress], int],
                 use_min_share: bool) -> list[JobInProgress]:
        pools: dict[str, list[JobInProgress]] = {}
        for j in jobs:
            pools.setdefault(pool_of(j), []).append(j)

        def pool_rank(item: tuple[str, list[JobInProgress]]):
            name, members = item
            running = sum(running_of(j) for j in members)
            weight = float(self._pool_conf(name, "weight", 1.0))
            below_min = False
            if use_min_share:
                min_share = int(self._pool_conf(name, "minmaps", 0))
                below_min = running < min_share
            # most starved first: below-min pools, then lowest usage/weight
            return (0 if below_min else 1,
                    running / max(weight, 1e-9),
                    name)

        out: list[JobInProgress] = []
        for _name, members in sorted(pools.items(), key=pool_rank):
            out.extend(_priority_fifo(members))
        return out

    def _map_job_order(self, jobs: list[JobInProgress]) -> list[JobInProgress]:
        return self._ordered(jobs, JobInProgress.running_map_count,
                             use_min_share=True)

    def _reduce_job_order(self,
                          jobs: list[JobInProgress]) -> list[JobInProgress]:
        return self._ordered(jobs, JobInProgress.running_reduce_count,
                             use_min_share=False)
