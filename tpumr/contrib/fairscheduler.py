"""Fair scheduler — pool-based fair sharing.

≈ ``src/contrib/fairscheduler/.../FairScheduler.java`` (pools, weights,
minimum shares, deficit-style ordering). Jobs are grouped into pools (job
conf ``mapred.fairscheduler.pool``, falling back to ``user.name``); each
free slot is offered to the most-starved pool first:

1. *map pass only*: pools running below their map minimum share
   (``tpumr.fairscheduler.pool.<name>.minmaps``) come before satisfied
   pools (≈ the reference's minMaps guarantee);
2. ties break on running-tasks-to-weight ratio (lower = more starved,
   ``tpumr.fairscheduler.pool.<name>.weight``, default 1.0);
3. within a pool, FIFO by start time (the reference's default ordering
   inside a pool before fair-share-within-pool was added).

The reduce pass ranks pools purely by running-reduces/weight — map
min-shares do not leak into reduce ordering.

Unlike the reference's contrib scheduler — which had no GPU awareness at
all (SURVEY.md §2.4) — this subclasses the hybrid scheduler, so CPU/TPU
placement, optional-scheduling starvation, and device-id assignment all
apply within the fair ordering.
"""

from __future__ import annotations

from typing import Any, Callable

from tpumr.mapred.job_in_progress import JobInProgress
from tpumr.mapred.scheduler import HybridQueueScheduler

POOL_KEY = "mapred.fairscheduler.pool"


def pool_of(job: JobInProgress) -> str:
    return str(job.conf.get(POOL_KEY)
               or job.conf.get("user.name")
               or "default")


class FairScheduler(HybridQueueScheduler):
    def __init__(self) -> None:
        super().__init__()
        self._pool_cache: dict[tuple[str, str], Any] = {}

    def _begin_assignment(self, tts: dict) -> None:
        # weights/min-shares are heartbeat-invariant; the order hooks run
        # once per free slot — don't re-parse config each time
        self._pool_cache.clear()

    def _pool_conf(self, pool: str, suffix: str, default: Any) -> Any:
        if self.conf is None:
            return default
        key = (pool, suffix)
        if key not in self._pool_cache:
            self._pool_cache[key] = self.conf.get(
                f"tpumr.fairscheduler.pool.{pool}.{suffix}", default)
        return self._pool_cache[key]

    def _ordered(self, jobs: list[JobInProgress],
                 running_of: Callable[[JobInProgress], int],
                 use_min_share: bool) -> list[JobInProgress]:
        pools: dict[str, list[JobInProgress]] = {}
        for j in jobs:
            pools.setdefault(pool_of(j), []).append(j)

        def pool_rank(item: tuple[str, list[JobInProgress]]):
            name, members = item
            running = sum(running_of(j) for j in members)
            weight = float(self._pool_conf(name, "weight", 1.0))
            below_min = False
            if use_min_share:
                min_share = int(self._pool_conf(name, "minmaps", 0))
                below_min = running < min_share
            # most starved first: below-min pools, then lowest usage/weight
            return (0 if below_min else 1,
                    running / max(weight, 1e-9),
                    name)

        out: list[JobInProgress] = []
        for _name, members in sorted(pools.items(), key=pool_rank):
            out.extend(sorted(members, key=lambda j: j.start_time))
        return out

    def _map_job_order(self, jobs: list[JobInProgress]) -> list[JobInProgress]:
        return self._ordered(jobs, JobInProgress.running_map_count,
                             use_min_share=True)

    def _reduce_job_order(self,
                          jobs: list[JobInProgress]) -> list[JobInProgress]:
        return self._ordered(jobs, JobInProgress.running_reduce_count,
                             use_min_share=False)
