"""Reduce-side data join framework.

≈ ``src/contrib/data_join`` (reference: contrib/utils/join/
{DataJoinMapperBase,DataJoinReducerBase,TaggedMapOutput,DataJoinJob}.java):
a generic framework for joining records from several sources on a shared
key. Each source's mapper tags its records with the source name; the
reducer groups each key's values by tag and emits one output per tuple of
the cross product over the tag groups — subclasses implement ``combine``
to build (or filter, by returning None) the joined record, exactly the
reference's contract. The per-group value cap
(``datajoin.maxNumOfValuesPerGroup``, reference DataJoinReducerBase's
maxNumOfValuesPerGroup, default 100) bounds the cross-product blow-up.

Usage::

    class OrderMapper(DataJoinMapper):
        def input_tag(self, conf):  # one mapper class per source
            return "orders"
        def extract_key(self, key, value):
            return value.split(",")[0]

    class Joiner(DataJoinReducer):
        def combine(self, key, tags, values, output, reporter):
            return ",".join(values)  # one joined record, or None to drop

    conf = make_datajoin_conf([("orders", "mem:///o", OrderMapper),
                               ("users", "mem:///u", UserMapper)],
                              Joiner, "mem:///joined")
"""

from __future__ import annotations

import itertools
from typing import Any, Iterable

from tpumr.mapred.api import Mapper, Reducer

MAX_VALUES_KEY = "datajoin.maxNumOfValuesPerGroup"


class TaggedValue:
    """A record tagged with its source ≈ TaggedMapOutput. Serialized as a
    (tag, payload) tuple on the wire."""

    __slots__ = ("tag", "value")

    def __init__(self, tag: str, value: Any) -> None:
        self.tag = tag
        self.value = value


class DataJoinMapper(Mapper):
    """≈ DataJoinMapperBase: tag every record of this source and re-key it
    by the join key. Subclasses implement :meth:`input_tag` (the source
    name) and :meth:`extract_key` (the join key for one record);
    :meth:`extract_value` defaults to the record's value."""

    def configure(self, conf) -> None:
        self._conf = conf
        self._tag = self.input_tag(conf)

    def input_tag(self, conf) -> str:
        raise NotImplementedError

    def extract_key(self, key, value) -> Any:
        raise NotImplementedError

    def extract_value(self, key, value) -> Any:
        return value

    def map(self, key, value, output, reporter):
        join_key = self.extract_key(key, value)
        if join_key is None:
            return  # unjoinable record (reference: null key → dropped)
        output.collect(join_key,
                       (self._tag, self.extract_value(key, value)))


class DataJoinReducer(Reducer):
    """≈ DataJoinReducerBase: regroup one key's values by source tag, walk
    the cross product over the tag groups, and call :meth:`combine` once
    per tuple. ``combine`` returns the joined output value (collected
    under the join key) or None to filter the tuple out. Groups larger
    than ``datajoin.maxNumOfValuesPerGroup`` are truncated (with a
    counter) to bound the cross product, as the reference does."""

    COUNTER_GROUP = "tpumr.DataJoin"

    def configure(self, conf) -> None:
        self._max_per_group = conf.get_int(MAX_VALUES_KEY, 100)

    #: override for inner/outer behavior: tags that MUST be present for a
    #: key to produce output (empty = every tag seen for the key suffices,
    #: i.e. the reference's default cross product over present groups)
    required_tags: "tuple[str, ...]" = ()

    def combine(self, key, tags: "tuple[str, ...]", values: "tuple[Any, ...]",
                output, reporter) -> Any:
        raise NotImplementedError

    def reduce(self, key, values, output, reporter):
        groups: "dict[str, list[Any]]" = {}
        truncated = 0
        for v in values:
            tag, payload = v
            group = groups.setdefault(tag, [])
            if len(group) >= self._max_per_group:
                truncated += 1
                continue
            group.append(payload)
        if truncated:
            reporter.incr_counter(self.COUNTER_GROUP,
                                  "VALUES_TRUNCATED", truncated)
        if self.required_tags and any(t not in groups
                                      for t in self.required_tags):
            reporter.incr_counter(self.COUNTER_GROUP, "KEYS_UNMATCHED")
            return
        tags = tuple(sorted(groups))
        for tup in itertools.product(*(groups[t] for t in tags)):
            joined = self.combine(key, tags, tup, output, reporter)
            if joined is not None:
                output.collect(key, joined)
                reporter.incr_counter(self.COUNTER_GROUP, "TUPLES_JOINED")


def make_datajoin_conf(sources: "Iterable[tuple[str, str, type]]",
                       reducer_cls: type, output_path: str,
                       base_conf: Any = None):
    """Build a join job over several (tag, input_path, mapper_cls)
    sources ≈ DataJoinJob.createDataJoinJob. Each source's mapper runs
    over its own input paths via per-path mapper dispatch."""
    from tpumr.mapred.jobconf import JobConf
    conf = JobConf(base_conf) if base_conf is not None else JobConf()
    paths, tag_map = [], {}
    for tag, path, mapper_cls in sources:
        if not issubclass(mapper_cls, DataJoinMapper):
            raise TypeError(f"{mapper_cls.__name__} is not a DataJoinMapper")
        paths.append(path)
        tag_map[path] = f"{mapper_cls.__module__}.{mapper_cls.__qualname__}"
    conf.set_job_name("datajoin")
    conf.set_input_paths(*paths)
    conf.set_output_path(output_path)
    conf.set("tpumr.datajoin.mappers", tag_map)
    conf.set_mapper_class(PerSourceDispatchMapper)
    conf.set_reducer_class(reducer_cls)
    return conf


class PerSourceDispatchMapper(Mapper):
    """Routes each split's records to the mapper registered for the
    split's input path prefix (the DataJoinJob role: one mapper class per
    source directory). The split path arrives via the task-localized
    conf."""

    def configure(self, conf) -> None:
        from tpumr.utils.reflection import resolve_class
        self._conf = conf
        self._by_prefix = {
            prefix.rstrip("/"): resolve_class(cls_name)
            for prefix, cls_name in
            (conf.get("tpumr.datajoin.mappers") or {}).items()
        }
        self._delegate: "Mapper | None" = None

    def _resolve(self, reporter) -> Mapper:
        if self._delegate is None:
            path = str(self._conf.get("tpumr.task.input.path") or "")
            best = None
            for prefix, cls in self._by_prefix.items():
                # boundary-respecting match: 'in/users' must not claim
                # 'in/users_extra/part-0'
                if (path == prefix or path.startswith(prefix + "/")) and \
                        (best is None or len(prefix) > len(best[0])):
                    best = (prefix, cls)
            if best is None:
                raise ValueError(
                    f"no datajoin mapper registered for split path {path!r}"
                    f" (sources: {sorted(self._by_prefix)})")
            self._delegate = best[1]()
            self._delegate.configure(self._conf)
        return self._delegate

    def map(self, key, value, output, reporter):
        self._resolve(reporter).map(key, value, output, reporter)

    def close(self) -> None:
        if self._delegate is not None:
            self._delegate.close()
