"""Capacity scheduler — queue-based capacity guarantees with elasticity.

≈ ``src/contrib/capacity-scheduler/.../CapacityTaskScheduler.java``:
operators define queues with capacity percentages; each queue is
guaranteed its share of cluster slots, can elastically exceed it while
other queues are idle (bounded by an optional maximum capacity), and
jobs pick a queue with ``mapred.job.queue.name`` (the reference's key).

Config:
  tpumr.capacity.queues                 = default,prod,adhoc
  tpumr.capacity.<queue>.capacity       = percent of cluster slots (int)
  tpumr.capacity.<queue>.max-capacity   = elastic ceiling percent (optional)
  tpumr.capacity[.<queue>].supports-priority = honor job priority within
                                          the queue (default false, the
                                          reference's default)

Queues most below their guaranteed capacity are offered slots first;
within a queue, FIFO. Map and reduce passes each rank against their own
slot pool (map usage / map-slot capacity, reduce usage / reduce-slot
capacity — the reference's TaskSchedulingMgr per-type split). A job
naming an undefined queue is scheduled LAST (zero guaranteed capacity,
elastic only) rather than rejected at submit time like the reference —
divergence documented: submission stays non-blocking and configured
queues' guarantees stay intact.

TPU-aware through the hybrid base class, unlike the reference contrib
(SURVEY.md §2.4: "no GPU awareness — verified by grep").
"""

from __future__ import annotations

from typing import Callable

from tpumr.mapred.job_in_progress import JobInProgress
from tpumr.mapred.scheduler import (HybridQueueScheduler,
                                    _priority_fifo)

QUEUE_KEY = "mapred.job.queue.name"
_PHANTOM = "\x00undefined"  # bucket for jobs naming a queue not configured


def queue_of(job: JobInProgress) -> str:
    return str(job.conf.get(QUEUE_KEY) or "default")


class CapacityScheduler(HybridQueueScheduler):
    def __init__(self) -> None:
        super().__init__()
        self._caps: dict[str, float] = {"default": 1.0}
        self._map_slot_total = 1
        self._reduce_slot_total = 1

    def _parse_queues(self) -> dict[str, float]:
        """queue -> capacity fraction (normalized; unset = equal split)."""
        if self.conf is None:
            return {"default": 1.0}
        names = [q.strip() for q in
                 str(self.conf.get("tpumr.capacity.queues",
                                   "default")).split(",") if q.strip()]
        caps = {}
        for q in names:
            caps[q] = float(self.conf.get(f"tpumr.capacity.{q}.capacity",
                                          100.0 / len(names)))
        total = sum(caps.values()) or 1.0
        return {q: c / total for q, c in caps.items()}

    def _max_capacity(self, queue: str) -> float | None:
        if self.conf is None or queue == _PHANTOM:
            return None
        v = self.conf.get(f"tpumr.capacity.{queue}.max-capacity")
        return float(v) / 100.0 if v is not None else None

    def _begin_assignment(self, tts: dict) -> None:
        """Heartbeat-invariant context, computed once (the order hooks run
        per free slot and must not re-parse config or re-lock the master)."""
        assert self.manager is not None
        self._caps = self._parse_queues()
        slots = self.manager.total_slots()
        self._map_slot_total = max(1, int(slots.get("cpu", 0))
                                   + int(slots.get("tpu", 0)))
        self._reduce_slot_total = max(1, int(slots.get("reduce", 0)))

    def _order(self, jobs: list[JobInProgress],
               running_of: Callable[[JobInProgress], int],
               slot_total: int) -> list[JobInProgress]:
        caps = self._caps
        by_queue: dict[str, list[JobInProgress]] = {}
        for j in jobs:
            q = queue_of(j)
            if q not in caps:
                q = _PHANTOM
            by_queue.setdefault(q, []).append(j)

        def rank(item):
            name, members = item
            running = sum(running_of(j) for j in members)
            cap = caps.get(name, 0.0)
            # queues with guaranteed capacity always outrank the phantom
            # bucket (jobs naming an unconfigured queue: elastic only)
            if cap <= 0.0:
                return (1, float(running), name)
            return (0, running / (cap * slot_total), name)

        out: list[JobInProgress] = []
        for name, members in sorted(by_queue.items(), key=rank):
            # elastic ceiling against THIS pass's slot pool
            ceiling = self._max_capacity(name)
            if ceiling is not None:
                running = sum(running_of(j) for j in members)
                if running >= ceiling * slot_total:
                    continue
            # within-queue priority order is OPT-IN, matching the
            # reference's supports-priority default (off -> submit
            # order): mapred.capacity-scheduler...supports-priority
            if self._supports_priority(name):
                out.extend(_priority_fifo(members))
            else:
                out.extend(sorted(members, key=lambda j: j.start_time))
        return out

    def _supports_priority(self, queue: str) -> bool:
        assert self.conf is not None
        v = self.conf.get(f"tpumr.capacity.{queue}.supports-priority")
        if v is None:
            v = self.conf.get("tpumr.capacity.supports-priority", False)
        return str(v).lower() in ("true", "1")

    def _map_job_order(self, jobs: list[JobInProgress]) -> list[JobInProgress]:
        return self._order(jobs, JobInProgress.running_map_count,
                           self._map_slot_total)

    def _reduce_job_order(self,
                          jobs: list[JobInProgress]) -> list[JobInProgress]:
        return self._order(jobs, JobInProgress.running_reduce_count,
                           self._reduce_slot_total)
