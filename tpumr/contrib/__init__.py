"""Contrib tier ≈ the reference's ``src/contrib``: pluggable schedulers
(fairscheduler, capacity-scheduler) and other optional components that sit
on public SPIs rather than in the core."""
