"""Threaded HTTP status server.

≈ ``org.apache.hadoop.http.HttpServer`` (839 LoC Jetty wrapper): daemons
register handlers; ``/json/*`` endpoints return JSON, ``/`` renders an
HTML dashboard from the same handlers. Stdlib http.server — the status
plane is low-traffic (humans + scrapers), unlike the shuffle path.
"""

from __future__ import annotations

import html
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable
from urllib.parse import parse_qs, urlparse

#: A handler takes the query dict and returns a JSON-able object.
Handler = Callable[[dict], Any]

#: A page handler takes the query dict and returns an HTML body fragment.
PageHandler = Callable[[dict], str]

_STYLE = """
body{font-family:sans-serif;margin:1.5em;color:#222}
h1{font-size:1.4em}h2{font-size:1.1em;border-bottom:1px solid #aaa;
padding-bottom:.2em;margin-top:1.4em}
table{border-collapse:collapse;margin:.5em 0;font-size:.92em}
th,td{border:1px solid #bbb;padding:.25em .6em;text-align:left}
th{background:#eee}tr:nth-child(even){background:#f7f7f7}
nav a{margin-right:1em}.num{text-align:right}
.ok{color:#060}.bad{color:#a00}.dim{color:#777}
progress{width:8em;vertical-align:middle}
pre{background:#f4f4f4;padding:.6em;overflow-x:auto}
"""


def html_escape(v: Any) -> str:
    return html.escape(str(v))


class RawHtml(str):
    """Explicit marker for a trusted, caller-built HTML fragment. ONLY
    RawHtml cells skip escaping in html_table — user-controlled strings
    (job names, counter names) can never smuggle markup by merely
    starting with '<'."""


def html_table(headers: "list[str]", rows: "list[list[Any]]") -> str:
    """Render a table; every cell is escaped unless it is a RawHtml
    fragment the caller explicitly built (links, progress bars)."""
    out = ["<table><tr>"]
    out += [f"<th>{html_escape(h)}</th>" for h in headers]
    out.append("</tr>")
    for row in rows:
        out.append("<tr>")
        for cell in row:
            s = cell if isinstance(cell, RawHtml) else html_escape(cell)
            out.append(f"<td>{s}</td>")
        out.append("</tr>")
    out.append("</table>")
    return "".join(out)


def progress_bar(fraction: float) -> RawHtml:
    pct = max(0.0, min(1.0, float(fraction))) * 100
    return RawHtml(f"<progress max='100' value='{pct:.0f}'></progress> "
                   f"{pct:.0f}%")


def _log_level(query: "dict[str, str]") -> dict:
    """/json/logLevel?log=NAME[&level=LEVEL] — read or set a logger's
    level at runtime (≈ LogLevel.Servlet: same get/set semantics, JSON
    instead of HTML). Empty/omitted ``log`` addresses the root logger.
    The server only routes the ``level`` mutation here on POST — a GET
    (browser, <img> drive-by, monitoring scrape) can never change a
    daemon's logging, unlike the reference servlet."""
    import logging
    name = query.get("log", "")
    logger = logging.getLogger(name) if name else logging.getLogger()
    if "level" in query:
        level = query["level"].upper()
        # the reference's daemonlog accepts log4j names — operators
        # porting runbooks send WARN/FATAL, which Python spells
        # WARNING/CRITICAL
        level = {"WARN": "WARNING", "FATAL": "CRITICAL"}.get(level, level)
        # str->int mapping check that exists on 3.10 (getLevelName
        # returns the int for a known name, "Level X" otherwise)
        if not isinstance(logging.getLevelName(level), int):
            raise ValueError(
                f"unknown level {query['level']!r}; try DEBUG, INFO, "
                f"WARN(ING), ERROR, FATAL or CRITICAL")
        logger.setLevel(level)
    return {"log": name or "root",
            "level": (logging.getLevelName(logger.level)
                      if logger.level else "UNSET"),
            "effective": logging.getLevelName(
                logger.getEffectiveLevel())}


def _threads(query: "dict[str, str]") -> str:
    """/threads — one-shot dump of every live thread's stack with
    InstrumentedRLock holder/waiter annotations (tpumr/metrics/locks.py
    + tpumr/metrics/sampler.py). Lazy import: the http package must not
    pull the metrics package at import time."""
    from tpumr.metrics.sampler import threads_dump
    return threads_dump()


class StatusHttpServer:
    def __init__(self, name: str, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.name = name
        self._handlers: dict[str, Handler] = {}
        self._pages: dict[str, PageHandler] = {}
        #: top-level raw endpoints (/<path>): handler returns a str body
        #: (served verbatim) or any JSON-able object
        self._raw: dict[str, tuple[Handler, str]] = {}
        self._parameterized: set[str] = set()
        #: endpoint -> query param whose presence requires POST
        self._mutating_param: dict[str, str] = {}
        #: pages that need query params (not linked from the nav)
        self._page_params: set[str] = set()
        outer = self

        class _Req(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self) -> None:
                outer._serve(self)

            def do_POST(self) -> None:
                # POST exists solely for mutating endpoints (logLevel
                # set); handlers read params from the query string
                # either way
                outer._serve(self)

        self._server = ThreadingHTTPServer((host, port), _Req)
        self._thread: threading.Thread | None = None
        # every daemon gets the log-level endpoint ≈ the reference's
        # org.apache.hadoop.log.LogLevel servlet on every HttpServer
        # (bin/hadoop daemonlog -getlevel/-setlevel)
        self.add_json("logLevel", _log_level, parameterized=True,
                      mutating_param="level")
        # ... and the instant stack dump (≈ the reference's
        # StackServlet on every HttpServer / `kill -QUIT`): all live
        # threads annotated with instrumented-lock holder/waiter state.
        # Needs no sampler and no daemon lock — the "is it deadlocked
        # right now" page works precisely when everything else doesn't.
        self.add_raw("threads", _threads, content_type="text/plain")

    # ------------------------------------------------------------ wiring

    def add_json(self, path: str, handler: Handler,
                 parameterized: bool = False,
                 mutating_param: "str | None" = None) -> None:
        """Register ``/json/<path>``. ``parameterized`` endpoints require
        query args — the dashboard links them but doesn't invoke them.
        ``mutating_param`` names a query arg whose presence makes the
        request a MUTATION: such requests are rejected on GET (405) so a
        browser/drive-by GET can never change daemon state."""
        self._handlers[path] = handler
        if parameterized:
            self._parameterized.add(path)
        if mutating_param is not None:
            self._mutating_param[path] = mutating_param

    def add_raw(self, path: str, handler: Handler,
                content_type: str = "application/json") -> None:
        """Register a TOP-LEVEL endpoint at ``/<path>`` (no /json prefix,
        no HTML chrome): tool-facing surfaces whose path is part of the
        operational contract — ``/metrics`` for scrapers, ``/tracejson``
        for chrome://tracing / Perfetto. A str return is served verbatim;
        anything else is JSON-encoded."""
        self._raw[path] = (handler, content_type)

    def attach_metrics(self, metrics_system: Any) -> None:
        """The uniform ``/metrics`` endpoint every daemon exposes: one
        JSON document of every registered MetricsRegistry's snapshot
        (``{source: {metric: value}}``) — same payload shape on the
        jobtracker, trackers, and the namenode, so one scraper config
        covers the whole cluster. Also registered at ``/json/metrics``
        when the daemon didn't already wire it there, and at
        ``/metrics/prom`` as Prometheus text exposition (v0.0.4) —
        counters/gauges/histograms from the same typed snapshot, so a
        stock Prometheus scrapes every daemon with one job config."""
        handler = lambda q: metrics_system.snapshot()  # noqa: E731
        self.add_raw("metrics", handler)
        if "metrics" not in self._handlers:
            self.add_json("metrics", handler)

        def prom(q: dict) -> str:
            from tpumr.metrics.prometheus import render_exposition
            return render_exposition(metrics_system.typed_snapshot())

        self.add_raw("metrics/prom", prom,
                     content_type="text/plain; version=0.0.4")

    def add_page(self, path: str, handler: PageHandler,
                 parameterized: bool = False) -> None:
        """Register a human-readable HTML view at ``/<path>`` (≈ one JSP
        of webapps/{job,task,hdfs}). ``"index"`` becomes ``/``; the raw
        JSON dump moves to ``/raw``. The handler returns a body fragment;
        the server wraps it with the chrome (title, nav, style).
        ``parameterized`` pages need query args and stay out of the nav."""
        self._pages[path] = handler
        if parameterized:
            self._page_params.add(path)

    @property
    def address(self) -> tuple[str, int]:
        return self._server.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "StatusHttpServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name=f"http-{self.name}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()

    # ------------------------------------------------------------ serving

    def _serve(self, req: BaseHTTPRequestHandler) -> None:
        parsed = urlparse(req.path)
        query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        path = parsed.path.rstrip("/")
        try:
            if path in ("", "/"):
                if "index" in self._pages:
                    self._send(req, 200,
                               self._page("index", query), "text/html")
                else:
                    self._send(req, 200, self._dashboard(), "text/html")
            elif path == "/raw":
                self._send(req, 200, self._dashboard(), "text/html")
            elif path.lstrip("/") in self._raw:
                handler, ctype = self._raw[path.lstrip("/")]
                body = handler(query)
                if not isinstance(body, str):
                    body = json.dumps(body, indent=2, default=str)
                self._send(req, 200, body, ctype)
            elif path.lstrip("/") in self._pages:
                self._send(req, 200,
                           self._page(path.lstrip("/"), query), "text/html")
            elif path.startswith("/json/"):
                name = path[len("/json/"):]
                mut = self._mutating_param.get(name)
                if mut is not None and mut in query \
                        and req.command != "POST":
                    self._send(req, 405, json.dumps(
                        {"error": f"{name}: mutating requests "
                                  f"({mut}=...) require POST "
                                  f"(GET is read-only)"}),
                        "application/json")
                    return
                handler = self._handlers.get(name)
                if handler is None:
                    self._send(req, 404, json.dumps(
                        {"error": f"no endpoint {name!r}",
                         "endpoints": sorted(self._handlers)}),
                        "application/json")
                else:
                    body = json.dumps(handler(query), indent=2, default=str)
                    self._send(req, 200, body, "application/json")
            else:
                self._send(req, 404, "not found", "text/plain")
        except Exception as e:
            self._send(req, 500, json.dumps({"error": str(e)}),
                       "application/json")

    @staticmethod
    def _send(req: BaseHTTPRequestHandler, code: int, body: str,
              ctype: str) -> None:
        data = body.encode()
        req.send_response(code)
        req.send_header("Content-Type", ctype + "; charset=utf-8")
        req.send_header("Content-Length", str(len(data)))
        req.end_headers()
        req.wfile.write(data)

    def _page(self, name: str, query: dict) -> str:
        """Wrap a page handler's body fragment with the shared chrome."""
        try:
            body = self._pages[name](query)
        except KeyError as e:
            body = f"<p class='bad'>missing parameter/entity: {html_escape(e)}</p>"
        except Exception as e:  # noqa: BLE001 — render, don't 500
            body = f"<p class='bad'>error: {html_escape(e)}</p>"
        nav = "".join(f"<a href='/{'' if p == 'index' else html_escape(p)}'>"
                      f"{html_escape(p)}</a>"
                      for p in sorted(self._pages)
                      if p not in self._page_params)
        return (f"<html><head><title>{html_escape(self.name)}</title>"
                f"<style>{_STYLE}</style></head><body>"
                f"<nav>{nav}<a href='/raw'>raw json</a></nav>"
                f"{body}</body></html>")

    def _dashboard(self) -> str:
        """One-page HTML: each JSON endpoint rendered as a <pre> block
        (≈ the JSP dashboards' information, minus the JSP)."""
        parts = [f"<html><head><title>{html.escape(self.name)}</title>",
                 "<style>body{font-family:monospace;margin:2em}"
                 "h2{border-bottom:1px solid #888}</style></head><body>",
                 f"<h1>{html.escape(self.name)}</h1>"]
        for name in sorted(self._handlers):
            if name in self._parameterized:
                parts.append(f"<h2>/json/{name}?…</h2>"
                             "<pre>(takes query parameters)</pre>")
                continue
            try:
                body = json.dumps(self._handlers[name]({}), indent=2,
                                  default=str)
            except Exception as e:
                body = f"error: {e}"
            parts.append(f"<h2><a href='/json/{name}'>{name}</a></h2>"
                         f"<pre>{html.escape(body)}</pre>")
        parts.append("</body></html>")
        return "".join(parts)
