"""Threaded HTTP status server.

≈ ``org.apache.hadoop.http.HttpServer`` (839 LoC Jetty wrapper): daemons
register handlers; ``/json/*`` endpoints return JSON, ``/`` renders an
HTML dashboard from the same handlers. Stdlib http.server — the status
plane is low-traffic (humans + scrapers), unlike the shuffle path.
"""

from __future__ import annotations

import html
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable
from urllib.parse import parse_qs, urlparse

#: A handler takes the query dict and returns a JSON-able object.
Handler = Callable[[dict], Any]


class StatusHttpServer:
    def __init__(self, name: str, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.name = name
        self._handlers: dict[str, Handler] = {}
        self._parameterized: set[str] = set()
        outer = self

        class _Req(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self) -> None:
                outer._serve(self)

        self._server = ThreadingHTTPServer((host, port), _Req)
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ wiring

    def add_json(self, path: str, handler: Handler,
                 parameterized: bool = False) -> None:
        """Register ``/json/<path>``. ``parameterized`` endpoints require
        query args — the dashboard links them but doesn't invoke them."""
        self._handlers[path] = handler
        if parameterized:
            self._parameterized.add(path)

    @property
    def address(self) -> tuple[str, int]:
        return self._server.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "StatusHttpServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name=f"http-{self.name}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()

    # ------------------------------------------------------------ serving

    def _serve(self, req: BaseHTTPRequestHandler) -> None:
        parsed = urlparse(req.path)
        query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        path = parsed.path.rstrip("/")
        try:
            if path in ("", "/"):
                self._send(req, 200, self._dashboard(), "text/html")
            elif path.startswith("/json/"):
                name = path[len("/json/"):]
                handler = self._handlers.get(name)
                if handler is None:
                    self._send(req, 404, json.dumps(
                        {"error": f"no endpoint {name!r}",
                         "endpoints": sorted(self._handlers)}),
                        "application/json")
                else:
                    body = json.dumps(handler(query), indent=2, default=str)
                    self._send(req, 200, body, "application/json")
            else:
                self._send(req, 404, "not found", "text/plain")
        except Exception as e:
            self._send(req, 500, json.dumps({"error": str(e)}),
                       "application/json")

    @staticmethod
    def _send(req: BaseHTTPRequestHandler, code: int, body: str,
              ctype: str) -> None:
        data = body.encode()
        req.send_response(code)
        req.send_header("Content-Type", ctype + "; charset=utf-8")
        req.send_header("Content-Length", str(len(data)))
        req.end_headers()
        req.wfile.write(data)

    def _dashboard(self) -> str:
        """One-page HTML: each JSON endpoint rendered as a <pre> block
        (≈ the JSP dashboards' information, minus the JSP)."""
        parts = [f"<html><head><title>{html.escape(self.name)}</title>",
                 "<style>body{font-family:monospace;margin:2em}"
                 "h2{border-bottom:1px solid #888}</style></head><body>",
                 f"<h1>{html.escape(self.name)}</h1>"]
        for name in sorted(self._handlers):
            if name in self._parameterized:
                parts.append(f"<h2>/json/{name}?…</h2>"
                             "<pre>(takes query parameters)</pre>")
                continue
            try:
                body = json.dumps(self._handlers[name]({}), indent=2,
                                  default=str)
            except Exception as e:
                body = f"error: {e}"
            parts.append(f"<h2><a href='/json/{name}'>{name}</a></h2>"
                         f"<pre>{html.escape(body)}</pre>")
        parts.append("</body></html>")
        return "".join(parts)
