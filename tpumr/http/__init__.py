"""Embedded HTTP status tier ≈ the reference's Jetty ``HttpServer`` +
JSP webapps (src/core/org/apache/hadoop/http/HttpServer.java;
webapps/{job,task,hdfs,history}). JSON endpoints are the machine
interface (the MXBean/``/jmx`` analog); daemons additionally register
HTML pages (jobs table, task drill-down, datanode table) filling the
JSP dashboards' role."""

from tpumr.http.server import (RawHtml, StatusHttpServer, html_escape,
                               html_table, progress_bar)

__all__ = ["RawHtml", "StatusHttpServer", "html_escape", "html_table",
           "progress_bar"]
