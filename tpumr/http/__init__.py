"""Embedded HTTP status tier ≈ the reference's Jetty ``HttpServer`` +
JSP webapps (src/core/org/apache/hadoop/http/HttpServer.java;
webapps/{job,task,hdfs,history}). JSON endpoints are the primary
interface (the MXBean/``/jmx`` analog); a minimal HTML dashboard renders
the same JSON for humans."""

from tpumr.http.server import StatusHttpServer

__all__ = ["StatusHttpServer"]
