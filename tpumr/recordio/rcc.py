"""``rcc`` — the Record I/O DDL compiler (≈ ``bin/rcc`` driving
``org.apache.hadoop.record.compiler.generated.Rcc`` + JavaGenerator/
CppGenerator, src/core/org/apache/hadoop/record/compiler/).

Grammar (the reference's .jr files, src/test/ddl/*.jr):

    include "other.jr"
    module some.dotted.name {
        class RecName {
            <type> <field>;
            ...
        }
    }

with types ``byte boolean int long float double ustring buffer``,
``vector<T>``, ``map<K,V>``, and references to other record classes
(bare or module-qualified). ``//``, ``/* */`` comments anywhere.

Where the reference generates per-field Java/C++ method bodies, this
generator emits a Python module of :class:`tpumr.recordio.runtime.Record`
subclasses carrying declarative ``FIELDS`` typespecs — the runtime
walker does the rest, for all three wire formats.

CLI: ``tpumr rcc <file.jr …> [--dest DIR]`` writes ``<module>.py`` per
DDL module (dots → underscores), mirroring bin/rcc's per-language
destdir layout.
"""

from __future__ import annotations

import re
from typing import Any

PRIMS = {"byte", "boolean", "int", "long", "float", "double",
         "ustring", "buffer"}


class DdlError(ValueError):
    pass


def _strip_comments(text: str) -> str:
    text = re.sub(r"/\*.*?\*/", " ", text, flags=re.S)
    return re.sub(r"//[^\n]*", " ", text)


class _Tokens:
    _TOK = re.compile(r'"[^"]*"|[A-Za-z_][\w.]*|[{}<>,;]')

    def __init__(self, text: str) -> None:
        self.toks = self._TOK.findall(_strip_comments(text))
        self.pos = 0

    def peek(self) -> "str | None":
        return self.toks[self.pos] if self.pos < len(self.toks) else None

    def next(self) -> str:
        tok = self.peek()
        if tok is None:
            raise DdlError("unexpected end of DDL")
        self.pos += 1
        return tok

    def expect(self, want: str) -> str:
        tok = self.next()
        if tok != want:
            raise DdlError(f"expected {want!r}, found {tok!r}")
        return tok


def parse_type(toks: _Tokens) -> Any:
    """Typespec tree: primitive name, ('vector', t), ('map', k, v), or
    ('ref', name) for record references resolved at generation time."""
    tok = toks.next()
    if tok in PRIMS:
        return tok
    if tok == "vector":
        toks.expect("<")
        elem = parse_type(toks)
        toks.expect(">")
        return ("vector", elem)
    if tok == "map":
        toks.expect("<")
        key = parse_type(toks)
        toks.expect(",")
        val = parse_type(toks)
        toks.expect(">")
        return ("map", key, val)
    if re.fullmatch(r"[A-Za-z_][\w.]*", tok):
        return ("ref", tok)
    raise DdlError(f"bad type token {tok!r}")


def parse_ddl(text: str) -> "list[dict]":
    """[{module, classes: [(name, [(field, typespec), …]), …],
    includes: [path, …]}, …]"""
    toks = _Tokens(text)
    modules = []
    includes = []
    while toks.peek() is not None:
        tok = toks.next()
        if tok == "include":
            path = toks.next()
            if not (path.startswith('"') and path.endswith('"')):
                raise DdlError(f"include needs a quoted path, got {path!r}")
            includes.append(path[1:-1])
            continue
        if tok != "module":
            raise DdlError(f"expected 'module' or 'include', got {tok!r}")
        name = toks.next()
        toks.expect("{")
        classes = []
        while toks.peek() != "}":
            toks.expect("class")
            cname = toks.next()
            toks.expect("{")
            fields = []
            while toks.peek() != "}":
                ts = parse_type(toks)
                fname = toks.next()
                if not re.fullmatch(r"[A-Za-z_]\w*", fname):
                    raise DdlError(f"bad field name {fname!r}")
                toks.expect(";")
                fields.append((fname, ts))
            toks.expect("}")
            classes.append((cname, fields))
        toks.expect("}")
        modules.append({"module": name, "classes": classes,
                        "includes": list(includes)})
        includes = []
    return modules


def _pyspec(ts: Any, resolve) -> str:
    """Typespec literal for the generated module; record references go
    through ``resolve`` (local name, or cross-module via imports)."""
    if isinstance(ts, str):
        return repr(ts)
    if ts[0] == "vector":
        return f"(\"vector\", {_pyspec(ts[1], resolve)})"
    if ts[0] == "map":
        return (f"(\"map\", {_pyspec(ts[1], resolve)}, "
                f"{_pyspec(ts[2], resolve)})")
    return resolve(ts[1])


def generate_python(modules: "list[dict]",
                    registry: "dict[str, set] | None" = None
                    ) -> "dict[str, str]":
    """module-name → generated Python source.

    Forward references inside a module are legal DDL (the reference
    resolves them at link time), so FIELDS referencing a later class are
    assigned after all classes exist. ``registry`` maps every module IN
    SCOPE (this compile run + includes) to its class names: a
    module-qualified reference (``other.mod.Rec``) — or a bare name
    defined in exactly one other in-scope module — becomes a Python
    import of the sibling generated module (dots → underscores, so all
    generated files in one --dest dir import each other)."""
    registry = dict(registry or {})
    for mod in modules:
        registry.setdefault(mod["module"], set()).update(
            c for c, _ in mod["classes"])
    out = {}
    for mod in modules:
        known = {c for c, _ in mod["classes"]}
        imports: "set[tuple[str, str]]" = set()

        def resolve(ref: str, known=known, mod=mod, imports=imports) -> str:
            name = ref.rsplit(".", 1)[-1]
            if "." in ref:
                src_mod = ref.rsplit(".", 1)[0]
                if src_mod == mod["module"] and name in known:
                    return name
                if name in registry.get(src_mod, ()):
                    imports.add((src_mod, name))
                    return name
                raise DdlError(f"unknown record type {ref!r} (module "
                               f"{src_mod!r} not in scope — missing "
                               f"include?)")
            if name in known:
                return name
            homes = [m for m, cs in registry.items()
                     if name in cs and m != mod["module"]]
            if len(homes) == 1:
                imports.add((homes[0], name))
                return name
            raise DdlError(
                f"unknown record type {ref!r}" if not homes else
                f"ambiguous record type {ref!r} (in modules {homes}); "
                f"qualify it")

        body: "list[str]" = []
        for cname, _fields in mod["classes"]:
            body += [f"class {cname}(Record):", "    FIELDS = []", "", ""]
        for cname, fields in mod["classes"]:
            specs = ", ".join(
                f"(\"{fname}\", {_pyspec(ts, resolve)})"
                for fname, ts in fields)
            body.append(f"{cname}.FIELDS = [{specs}]")
        lines = [
            '"""Generated by tpumr rcc — do not edit.',
            "",
            f"DDL module: {mod['module']}",
            '"""',
            "",
            "from tpumr.recordio.runtime import Record",
        ]
        for src_mod, name in sorted(imports):
            lines.append(
                f"from {src_mod.replace('.', '_')} import {name}")
        out[mod["module"]] = "\n".join(lines + [""] + body + [""])
    return out


def _parse_tree(path: str, seen: "dict[str, list]") -> None:
    """Parse ``path`` and, recursively, everything it includes (relative
    to the including file — bin/rcc's include semantics)."""
    import os
    real = os.path.realpath(path)
    if real in seen:
        return
    with open(path) as f:
        modules = parse_ddl(f.read())
    seen[real] = modules
    for mod in modules:
        for inc in mod["includes"]:
            _parse_tree(os.path.join(os.path.dirname(path), inc), seen)


def compile_files(paths: "list[str]", dest: str = ".") -> "list[str]":
    import os
    seen: "dict[str, list]" = {}
    roots = []
    for path in paths:
        _parse_tree(path, seen)
        roots.append(os.path.realpath(path))
    registry: "dict[str, set]" = {}
    for modules in seen.values():
        for mod in modules:
            registry.setdefault(mod["module"], set()).update(
                c for c, _ in mod["classes"])
    written = []
    # included-only modules generate too: they are the import targets
    for real, modules in seen.items():
        for name, src in generate_python(modules, registry).items():
            target = os.path.join(dest, name.replace(".", "_") + ".py")
            with open(target, "w") as f:
                f.write(src)
            written.append(target)
    return written


def main(argv: "list[str]") -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="tpumr rcc",
        description="compile Record I/O DDL (.jr) to Python record "
                    "classes (= bin/rcc --language python)")
    ap.add_argument("files", nargs="+", help=".jr DDL files")
    ap.add_argument("--dest", default=".", help="output directory")
    args = ap.parse_args(argv)
    for target in compile_files(args.files, args.dest):
        print(target)
    return 0
