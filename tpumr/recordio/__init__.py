"""Record I/O — DDL-driven serialization (≈ org.apache.hadoop.record +
bin/rcc + src/c++/librecordio; deprecated upstream but part of the
1.0.3 surface, so implemented rather than gated).

- :mod:`tpumr.recordio.runtime` — Record base + Binary/Csv/Xml record
  streams, wire-compatible with the reference's three formats.
- :mod:`tpumr.recordio.rcc` — the DDL compiler (``tpumr rcc``).
- ``native/recordio`` — C codec for the binary wire format (librecordio
  role): validate/skip records without a Python runtime, fuzz-hardened
  like the tree's other native parsers.
"""

from tpumr.recordio.runtime import (BinaryRecordInput, BinaryRecordOutput,
                                    CsvRecordInput, CsvRecordOutput,
                                    Record, XmlRecordInput,
                                    XmlRecordOutput, read_vlong,
                                    write_vlong)

__all__ = ["Record", "BinaryRecordInput", "BinaryRecordOutput",
           "CsvRecordInput", "CsvRecordOutput", "XmlRecordInput",
           "XmlRecordOutput", "read_vlong", "write_vlong"]
