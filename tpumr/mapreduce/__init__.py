"""New-style context-object API.

≈ the reference's ``org.apache.hadoop.mapreduce`` package (Job.java,
Mapper.java, Reducer.java — the context-object API added alongside the
old ``mapred`` interfaces): users subclass ``Mapper``/``Reducer`` with
``setup/map|reduce/cleanup(context)`` lifecycles and drive jobs through a
``Job`` facade. Implemented as adapters over the mapred execution engine —
one engine, two user APIs, exactly the reference's layering
(mapreduce/** delegates to mapred core, SURVEY.md §2.4).

Unlike the reference — where the new API was NOT GPU-wired (the GPU path
was old-API pipes only, SURVEY.md §2.4) — device kernels work here too:
``job.set_map_kernel(name)`` passes straight through to the TPU runner.
"""

from __future__ import annotations

from typing import Any, Iterator

from tpumr.mapred import api as old_api
from tpumr.mapred.job_client import run_job as _run_job
from tpumr.mapred.jobconf import JobConf

__all__ = ["Job", "Mapper", "Reducer", "Partitioner", "Context"]


class _Counter:
    __slots__ = ("_reporter", "_group", "_name")

    def __init__(self, reporter, group: str, name: str) -> None:
        self._reporter = reporter
        self._group = group
        self._name = name

    def increment(self, amount: int = 1) -> None:
        self._reporter.incr_counter(self._group, self._name, amount)


class Context:
    """≈ TaskInputOutputContext: write + counters + conf + progress."""

    def __init__(self, conf: Any, output: old_api.OutputCollector,
                 reporter: old_api.Reporter) -> None:
        self.conf = conf
        self._output = output
        self._reporter = reporter
        #: current key/value, visible during map() ≈ getCurrentKey/Value
        self.current_key: Any = None
        self.current_value: Any = None

    def write(self, key: Any, value: Any) -> None:
        self._output.collect(key, value)

    def get_counter(self, group: str, name: str) -> _Counter:
        return _Counter(self._reporter, group, name)

    def set_status(self, status: str) -> None:
        self._reporter.set_status(status)

    def progress(self) -> None:
        self._reporter.progress()


class Mapper:
    """≈ org.apache.hadoop.mapreduce.Mapper: setup/map/cleanup, and an
    overridable run() for whole-split control (the reference's
    Mapper.run(Context))."""

    def setup(self, context: Context) -> None:
        pass

    def map(self, key: Any, value: Any, context: Context) -> None:
        context.write(key, value)  # identity default, as in the reference

    def cleanup(self, context: Context) -> None:
        pass

    def run(self, records: Iterator[tuple], context: Context) -> None:
        self.setup(context)
        try:
            for key, value in records:
                context.current_key, context.current_value = key, value
                self.map(key, value, context)
        finally:
            self.cleanup(context)


class Reducer:
    """≈ org.apache.hadoop.mapreduce.Reducer."""

    def setup(self, context: Context) -> None:
        pass

    def reduce(self, key: Any, values: Iterator[Any],
               context: Context) -> None:
        for v in values:
            context.write(key, v)

    def cleanup(self, context: Context) -> None:
        pass


class Partitioner:
    """≈ org.apache.hadoop.mapreduce.Partitioner."""

    def get_partition(self, key: Any, value: Any, num_partitions: int) -> int:
        raise NotImplementedError


# ------------------------------------------------------------ adapters
# Bridge new-API classes onto the mapred engine's runner/reducer seams.


class _NewApiMapRunner(old_api.MapRunnable):
    """Old-engine MapRunnable that drives a new-API Mapper.run()."""

    def __init__(self) -> None:
        self.conf: Any = None
        self.mapper: Mapper | None = None

    def configure(self, conf: Any) -> None:
        self.conf = conf
        from tpumr.utils.reflection import new_instance
        cls = conf.get_class("tpumr.mapreduce.mapper.class", Mapper)
        self.mapper = new_instance(cls)

    def run(self, reader, output, reporter, task_ctx=None) -> None:
        assert self.mapper is not None
        self.mapper.run(iter(reader), Context(self.conf, output, reporter))


class _NewApiReducerAdapter(old_api.Reducer):
    """Old-engine Reducer wrapping a new-API Reducer. The engine's
    ``begin_task`` seam hands over the collector before grouping, so
    setup()/cleanup() run even for partitions with zero groups (the
    reference's Reducer.run semantics)."""

    _key = "tpumr.mapreduce.reducer.class"

    def configure(self, conf: Any) -> None:
        from tpumr.utils.reflection import new_instance
        cls = conf.get_class(self._key, Reducer)
        self._new = new_instance(cls)
        self._conf = conf
        self._ctx: Context | None = None

    def _ensure_ctx(self, output, reporter) -> Context:
        if self._ctx is None:
            self._ctx = Context(self._conf, output, reporter)
            self._new.setup(self._ctx)
        else:
            self._ctx._output = output
            self._ctx._reporter = reporter
        return self._ctx

    def begin_task(self, output, reporter) -> None:
        self._ensure_ctx(output, reporter)

    def reduce(self, key, values, output, reporter):
        self._new.reduce(key, values, self._ensure_ctx(output, reporter))

    def close(self) -> None:
        if self._ctx is not None:
            self._new.cleanup(self._ctx)


class _NewApiPartitionerAdapter(old_api.Partitioner):
    def configure(self, conf: Any) -> None:
        from tpumr.utils.reflection import new_instance
        cls = conf.get_class("tpumr.mapreduce.partitioner.class", None)
        self._new = new_instance(cls, conf) if cls else old_api.HashPartitioner()

    def get_partition(self, key, value, num_partitions):
        return self._new.get_partition(key, value, num_partitions)


# ------------------------------------------------------------ Job facade


class Job:
    """≈ org.apache.hadoop.mapreduce.Job: configure + submit + wait."""

    def __init__(self, conf: JobConf | None = None, name: str = "") -> None:
        self.conf = conf or JobConf()
        if name:
            self.conf.set_job_name(name)

    # configuration ------------------------------------------------------

    def set_mapper_class(self, cls: type) -> None:
        self.conf.set_class("tpumr.mapreduce.mapper.class", cls)
        self.conf.set_map_runner_class(_NewApiMapRunner)

    def set_reducer_class(self, cls: type) -> None:
        self.conf.set_class("tpumr.mapreduce.reducer.class", cls)
        self.conf.set_reducer_class(_NewApiReducerAdapter)

    def set_combiner_class(self, cls: type) -> None:
        # combiner runs through the old-API seam; new-API combiners are
        # plain Reducer subclasses so the adapter applies unchanged
        self.conf.set_class("tpumr.mapreduce.combiner.class", cls)
        self.conf.set_combiner_class(_NewApiCombinerAdapter)

    def set_partitioner_class(self, cls: type) -> None:
        self.conf.set_class("tpumr.mapreduce.partitioner.class", cls)
        self.conf.set_partitioner_class(_NewApiPartitionerAdapter)

    def set_map_kernel(self, name: str) -> None:
        """Device-kernel map — works with the new API here, unlike the
        reference where GPU was old-API pipes only."""
        self.conf.set_map_kernel(name)

    def set_input_format(self, cls: type) -> None:
        self.conf.set_input_format(cls)

    def set_output_format(self, cls: type) -> None:
        self.conf.set_output_format(cls)

    def set_num_reduce_tasks(self, n: int) -> None:
        self.conf.set_num_reduce_tasks(n)

    def add_input_path(self, path: str) -> None:
        self.conf.add_input_path(path)

    def set_output_path(self, path: str) -> None:
        self.conf.set_output_path(path)

    # execution ----------------------------------------------------------

    def wait_for_completion(self, verbose: bool = False) -> bool:
        """Runs the job; returns False on job failure (the reference's
        boolean contract — task errors surface via ``job.error``)."""
        import sys
        try:
            result = _run_job(self.conf)
        except Exception as e:  # engine raises on failed jobs
            self.error = str(e)
            if verbose:
                print(f"job failed: {e}", file=sys.stderr)
            return False
        self._result = result
        self.error = "" if result.successful else "job failed"
        return result.successful

    @property
    def counters(self):
        return getattr(self, "_result", None) and self._result.counters


class _NewApiCombinerAdapter(_NewApiReducerAdapter):
    _key = "tpumr.mapreduce.combiner.class"
