"""The new-API helper library ≈ ``org.apache.hadoop.mapreduce.lib``.

The reference ships a second copy of the helper tier for the context-
object API (src/mapred/org/apache/hadoop/mapreduce/lib/{input,output,
partition,map,reduce,jobcontrol,...}). Here the ENGINE-level pieces
(input/output formats, the total-order machinery) are shared with the old
API — one engine, two user APIs — so this module provides:

- new-API-NATIVE mappers/reducers/partitioners (lib/map/InverseMapper.
  java, TokenCounterMapper.java, RegexMapper.java, MultithreadedMapper.
  java; lib/reduce/IntSumReducer.java, LongSumReducer.java; lib/
  partition/{HashPartitioner,BinaryPartitioner,KeyFieldBasedPartitioner,
  TotalOrderPartitioner}.java);
- re-exports of the shared formats under their new-API names
  (lib/input/*.java, lib/output/*.java) plus :class:`LazyOutputFormat`;
- :class:`ControlledJob` / :class:`JobControl` (lib/jobcontrol/
  {ControlledJob,JobControl}.java) — dependency-ordered multi-job
  execution, shared by both APIs.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Iterator

from tpumr.mapred import api as old_api
# shared engine formats, re-exported under their mapreduce.lib names
from tpumr.mapred.input_formats import (CombineFileInputFormat,
                                        DenseInputFormat, FileInputFormat,
                                        KeyValueTextInputFormat,
                                        NLineInputFormat,
                                        SequenceFileInputFormat,
                                        TextInputFormat, WholeFileInputFormat)
from tpumr.mapred.output_formats import (NullOutputFormat,
                                         SequenceFileOutputFormat,
                                         TextOutputFormat)
from tpumr.mapreduce import Context, Job, Mapper, Partitioner, Reducer

__all__ = [
    # input (≈ lib/input)
    "FileInputFormat", "TextInputFormat", "KeyValueTextInputFormat",
    "NLineInputFormat", "SequenceFileInputFormat", "CombineFileInputFormat",
    "WholeFileInputFormat", "DenseInputFormat",
    # output (≈ lib/output)
    "TextOutputFormat", "SequenceFileOutputFormat", "NullOutputFormat",
    "LazyOutputFormat",
    # map (≈ lib/map)
    "InverseMapper", "TokenCounterMapper", "RegexMapper",
    "MultithreadedMapper",
    # reduce (≈ lib/reduce)
    "IntSumReducer", "LongSumReducer",
    # partition (≈ lib/partition)
    "HashPartitioner", "BinaryPartitioner", "KeyFieldBasedPartitioner",
    "TotalOrderPartitioner",
    # jobcontrol (≈ lib/jobcontrol)
    "ControlledJob", "JobControl",
]


# ------------------------------------------------------------------ map


class InverseMapper(Mapper):
    """(k, v) → (v, k) ≈ lib/map/InverseMapper.java."""

    def map(self, key: Any, value: Any, context: Context) -> None:
        context.write(value, key)


class TokenCounterMapper(Mapper):
    """(_, text) → (token, 1) ≈ lib/map/TokenCounterMapper.java."""

    def map(self, key: Any, value: Any, context: Context) -> None:
        text = value.decode("utf-8", "replace") \
            if isinstance(value, (bytes, bytearray)) else str(value)
        for tok in text.split():
            context.write(tok, 1)


class RegexMapper(Mapper):
    """(_, text) → (match, 1) per regex group match ≈ lib/map/RegexMapper.
    java; pattern from ``mapreduce.mapper.regex`` (reference key
    ``mapred.mapper.regex`` is honoured too), group from
    ``mapreduce.mapper.regex.group``."""

    def setup(self, context: Context) -> None:
        import re
        pat = (context.conf.get("mapreduce.mapper.regex")
               or context.conf.get("mapred.mapper.regex") or r"\w+")
        self._re = re.compile(pat)
        self._group = int(context.conf.get("mapreduce.mapper.regex.group",
                                           context.conf.get(
                                               "mapred.mapper.regex.group",
                                               0)))

    def map(self, key: Any, value: Any, context: Context) -> None:
        text = value.decode("utf-8", "replace") \
            if isinstance(value, (bytes, bytearray)) else str(value)
        for m in self._re.finditer(text):
            context.write(m.group(self._group), 1)


class MultithreadedMapper(Mapper):
    """N worker threads drive an inner new-API mapper within one slot
    ≈ lib/map/MultithreadedMapper.java — for mappers that block on
    external IO, not CPU parallelism (GIL; CPU-bound batching belongs to
    the kernel/batch runners). Inner class from
    ``mapreduce.mapper.multithreadedmapper.class``; thread count from
    ``mapreduce.mapper.multithreadedmapper.threads`` (default 10).
    Contracts kept from the reference: one shared inner mapper (map()
    must be thread-safe), serialized writes, first worker error aborts."""

    def run(self, records: Iterator[tuple], context: Context) -> None:
        import queue as _queue

        from tpumr.utils.reflection import new_instance
        conf = context.conf
        inner_cls = conf.get_class(
            "mapreduce.mapper.multithreadedmapper.class", Mapper)
        inner: Mapper = new_instance(inner_cls)
        n_threads = max(1, int(conf.get(
            "mapreduce.mapper.multithreadedmapper.threads", 10)))
        lock = threading.Lock()
        raw_write = context.write

        def locked_write(k: Any, v: Any) -> None:
            with lock:
                raw_write(k, v)

        context.write = locked_write  # type: ignore[method-assign]
        work: _queue.Queue = _queue.Queue(maxsize=n_threads * 2)
        errors: list[BaseException] = []
        err_lock = threading.Lock()

        def worker() -> None:
            while True:
                item = work.get()
                if item is None:
                    return
                try:
                    inner.map(item[0], item[1], context)
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    with err_lock:
                        errors.append(e)

        inner.setup(context)
        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        try:
            for key, value in records:
                with err_lock:
                    if errors:
                        break
                work.put((key, value))
        finally:
            for _ in threads:
                work.put(None)
            for t in threads:
                t.join()
            context.write = raw_write  # type: ignore[method-assign]
            inner.cleanup(context)
        if errors:
            raise errors[0]


# ---------------------------------------------------------------- reduce


class IntSumReducer(Reducer):
    """(k, [n...]) → (k, sum) ≈ lib/reduce/IntSumReducer.java."""

    def reduce(self, key: Any, values: Iterator[Any],
               context: Context) -> None:
        context.write(key, sum(int(v) for v in values))


class LongSumReducer(IntSumReducer):
    """Python ints are arbitrary precision — same as IntSumReducer;
    both names kept for API parity (lib/reduce/LongSumReducer.java)."""


# ------------------------------------------------------------- partition


class HashPartitioner(Partitioner):
    """Stable hash of the key ≈ lib/partition/HashPartitioner.java."""

    def get_partition(self, key: Any, value: Any,
                      num_partitions: int) -> int:
        return old_api.HashPartitioner().get_partition(key, value,
                                                       num_partitions)


class BinaryPartitioner(Partitioner):
    """Partitions on a byte range of a bytes key ≈ lib/partition/
    BinaryPartitioner.java: ``left``/``right`` offsets (negative =
    from-end, defaults 0/-1 = whole key)."""

    def __init__(self, left: int = 0, right: int = -1) -> None:
        self.left = left
        self.right = right

    def get_partition(self, key: Any, value: Any,
                      num_partitions: int) -> int:
        import zlib
        b = key if isinstance(key, (bytes, bytearray)) else \
            str(key).encode()
        n = len(b)
        lo = self.left if self.left >= 0 else n + self.left
        hi = (self.right if self.right >= 0 else n + self.right) + 1
        return zlib.crc32(bytes(b[lo:hi])) % num_partitions


class KeyFieldBasedPartitioner(Partitioner):
    """New-API face of the field partitioner (lib/partition/
    KeyFieldBasedPartitioner.java) — delegates to the engine's."""

    def __init__(self, num_fields: int = 1, separator: str = "\t") -> None:
        self._inner = old_api.KeyFieldBasedPartitioner(num_fields, separator)

    def get_partition(self, key: Any, value: Any,
                      num_partitions: int) -> int:
        return self._inner.get_partition(key, value, num_partitions)


class TotalOrderPartitioner(Partitioner):
    """New-API face of the total-order partitioner (lib/partition/
    TotalOrderPartitioner.java): reads the sampled partition file named
    by the same conf key the engine's uses. Instantiated reflectively —
    no-arg ctor + ``configure(conf)`` (≈ Configurable.setConf)."""

    def __init__(self) -> None:
        self._inner: Any = None

    def configure(self, conf: Any) -> None:
        from tpumr.mapred.total_order import TotalOrderPartitioner as _Engine
        self._inner = _Engine()
        self._inner.configure(conf)

    def get_partition(self, key: Any, value: Any,
                      num_partitions: int) -> int:
        if self._inner is None:
            raise RuntimeError("TotalOrderPartitioner not configured "
                               "(no partition file conf)")
        return self._inner.get_partition(key, value, num_partitions)


# ------------------------------------------------------------ jobcontrol


class ControlledJob:
    """One job plus its dependencies ≈ lib/jobcontrol/ControlledJob.java.
    States: WAITING → READY → RUNNING → SUCCESS | FAILED |
    DEPENDENT_FAILED."""

    WAITING = "WAITING"
    READY = "READY"
    RUNNING = "RUNNING"
    SUCCESS = "SUCCESS"
    FAILED = "FAILED"
    DEPENDENT_FAILED = "DEPENDENT_FAILED"

    def __init__(self, job: Job, depending: "list[ControlledJob] | None"
                 = None, name: str = "") -> None:
        self.job = job
        self.name = name or job.conf.job_name or f"job-{id(job) & 0xffff}"
        self.depending: "list[ControlledJob]" = list(depending or [])
        self.state = self.WAITING
        self.message = ""

    def add_depending_job(self, dep: "ControlledJob") -> None:
        self.depending.append(dep)

    def _check_state(self) -> str:
        if self.state != self.WAITING:
            return self.state
        if any(d.state in (self.FAILED, self.DEPENDENT_FAILED)
               for d in self.depending):
            self.state = self.DEPENDENT_FAILED
            self.message = "a depending job failed"
        elif all(d.state == self.SUCCESS for d in self.depending):
            self.state = self.READY
        return self.state


class JobControl:
    """Dependency-ordered runner ≈ lib/jobcontrol/JobControl.java: call
    :meth:`run` (synchronous) or drive a background thread with
    ``threading.Thread(target=jc.run)`` and poll :attr:`all_finished` —
    the reference's Thread-subclass usage. Jobs run one at a time here
    (the engine parallelizes WITHIN a job; concurrent jobs would fight
    over the one-core host this targets)."""

    def __init__(self, group_name: str = "jobcontrol") -> None:
        self.group_name = group_name
        self.jobs: "list[ControlledJob]" = []
        self._stop = threading.Event()

    def add_job(self, cj: ControlledJob) -> ControlledJob:
        self.jobs.append(cj)
        return cj

    def add_jobs(self, cjs: "list[ControlledJob]") -> None:
        for cj in cjs:
            self.add_job(cj)

    @property
    def all_finished(self) -> bool:
        return all(cj.state in (ControlledJob.SUCCESS, ControlledJob.FAILED,
                                ControlledJob.DEPENDENT_FAILED)
                   for cj in self.jobs)

    def failed_jobs(self) -> "list[ControlledJob]":
        return [cj for cj in self.jobs
                if cj.state in (ControlledJob.FAILED,
                                ControlledJob.DEPENDENT_FAILED)]

    def successful_jobs(self) -> "list[ControlledJob]":
        return [cj for cj in self.jobs if cj.state == ControlledJob.SUCCESS]

    def stop(self) -> None:
        self._stop.set()

    def run(self, poll_s: float = 0.05) -> None:
        """Run jobs as their dependencies succeed, until all settle."""
        while not self.all_finished and not self._stop.is_set():
            progressed = False
            for cj in self.jobs:
                if cj._check_state() == ControlledJob.READY:
                    cj.state = ControlledJob.RUNNING
                    ok = False
                    try:
                        ok = cj.job.wait_for_completion()
                    except Exception as e:  # noqa: BLE001 — job failure
                        cj.message = str(e)
                    cj.state = (ControlledJob.SUCCESS if ok
                                else ControlledJob.FAILED)
                    if not ok and not cj.message:
                        cj.message = getattr(cj.job, "error", "job failed")
                    progressed = True
            if not progressed and not self.all_finished:
                time.sleep(poll_s)


# ---------------------------------------------------------------- output


class LazyOutputFormat:
    """≈ lib/output/LazyOutputFormat.java: the real writer is created on
    the FIRST write, so tasks that emit nothing produce no part file.
    Configure with :meth:`set_output_format_class`."""

    KEY = "mapreduce.output.lazyoutputformat.outputformat"

    @classmethod
    def set_output_format_class(cls, job_or_conf: Any,
                                fmt: type) -> None:
        conf = getattr(job_or_conf, "conf", job_or_conf)
        conf.set_class(cls.KEY, fmt)
        conf.set_class("mapred.output.format.class", cls)

    def __init__(self, conf: Any = None) -> None:
        self._conf = conf

    def _inner(self, conf: Any):
        from tpumr.utils.reflection import new_instance
        fmt = conf.get_class(self.KEY, TextOutputFormat)
        return new_instance(fmt, conf)

    def check_output_specs(self, conf: Any) -> None:
        self._inner(conf).check_output_specs(conf)

    def get_record_writer(self, conf: Any, work_dir: str, partition: int,
                          prefix: str = "part"):
        from tpumr.mapred.output_formats import RecordWriter
        inner_fmt = self._inner(conf)

        class _Lazy(RecordWriter):
            _writer: "RecordWriter | None" = None

            def write(self, key: Any, value: Any) -> None:
                if self._writer is None:
                    self._writer = inner_fmt.get_record_writer(
                        conf, work_dir, partition, prefix)
                self._writer.write(key, value)

            def close(self) -> None:
                if self._writer is not None:
                    self._writer.close()

        return _Lazy()
