"""Continuous in-process profiler: stack sampling, subsystem CPU
attribution, and a GIL-contention proxy — stdlib only.

After the lock decomposition the master saturates on one Python
process's throughput (the GIL), not on locking — and nothing in the
tree says WHERE that CPU goes, so the sharded-master boundary (ROADMAP)
would be chosen blind. The reference's answer was offline profiling of
dev clusters; ours is a daemon thread that samples every live thread's
stack via ``sys._current_frames()`` at ``tpumr.prof.hz`` (default 19 —
deliberately co-prime with the 1 Hz heartbeat cadence and common 10/100
ms timer grids, so periodic work can't hide between samples), folds the
frames into a bounded trie, and classifies every sample into a
subsystem (reactor loop, rpc handler pool, heartbeat fold/assign,
history/deferred I/O, shuffle, merger, other) so ``cpu_share``
gauges land in the owning daemon's MetricsRegistry and ``/metrics/prom``.

The GIL itself is measured by proxy: a sentinel thread sleeps 5 ms in a
loop and observes its scheduling OVERSHOOT (wakeup lateness) into a
``gil_delay_seconds`` histogram. A healthy process wakes the sentinel
within a few hundred µs; a GIL convoy (one thread holding the
interpreter through its switch interval while runnable threads queue)
shows up directly as overshoot p99 — the cheapest honest contention
signal a pure-Python process can produce about itself.

Costs are measured, not asserted: the sampler times its own passes and
publishes ``prof_overhead_share`` (fraction of one core it consumes),
and excludes its own two threads from every sample.

HTTP surface (``attach_http``): ``/stacks?seconds=N`` returns
flamegraph-compatible collapsed folded-stack text (``a;b;c count``,
rooted at the thread name), ``/flame?seconds=N`` a self-contained SVG
flame graph (same in-repo-SVG approach as the trace swimlane);
``/threads`` (served by StatusHttpServer on every daemon, sampler or
not) is the one-shot dump with InstrumentedRLock holder/waiter
annotations.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Any, Callable

from tpumr.metrics.core import MetricsRegistry

#: sentinel sleep quantum — small enough to sample scheduling latency
#: many times per second, large enough that the sentinel itself stays
#: far below 1% of a core
SENTINEL_SLEEP_S = 0.005

#: stacks deeper than this truncate at the root end — a runaway
#: recursion must not make one sample allocate unboundedly
MAX_STACK_DEPTH = 64

#: the canonical subsystem labels every sample classifies into (the
#: bench's cpu_share columns group these further)
SUBSYSTEMS = ("reactor", "rpc", "fold", "assign", "history",
              "shuffle", "merger", "other")

#: ordered (module prefix, function prefixes, subsystem): the FIRST
#: table row matching any frame, walking the stack innermost-out, wins
#: — so a heartbeat that is currently inside the scheduler pass counts
#: as "assign" (the scheduler module frame is deeper) while the fold
#: loop around it counts as "fold".
_MODULE_TABLE: "tuple[tuple[str, tuple, str], ...]" = (
    ("tpumr.mapred.scheduler", (), "assign"),
    ("tpumr.mapred.jobtracker", ("heartbeat", "_heartbeat"), "fold"),
    ("tpumr.mapred.history", (), "history"),
    ("tpumr.mapred.shuffle_copier", (), "shuffle"),
    ("tpumr.mapred.fetch_batcher", (), "shuffle"),
    ("tpumr.mapred.device_shuffle", (), "shuffle"),
    ("tpumr.io.merger", (), "merger"),
)

#: thread-name roles, consulted when no module frame matched: the
#: reactor spends its life in the selector/dispatch loop (ipc.rpc
#: frames, which deliberately have NO module-table row so handler-pool
#: work doesn't masquerade as reactor time), the pool threads own
#: everything dispatched into daemon code the table doesn't name
_THREAD_ROLES: "tuple[tuple[str, str], ...]" = (
    ("rpc-reactor", "reactor"),
    ("rpc-handler", "rpc"),
    ("rpc-server", "rpc"),
    ("shuffle-inmem-merger", "merger"),
    ("shuffle-disk-merger", "merger"),
    ("shuffle-copier", "shuffle"),
)


#: idle-leaf detection (the py-spy approach): a sample whose INNERMOST
#: frame is a known blocking call is parked, not burning CPU. Idle
#: samples stay in the folded stacks (the wait is the interesting fact
#: when diagnosing a hang) but are excluded from cpu_share — counting
#: them would measure thread population, not CPU (a daemon has dozens
#: of parked threads per busy one). C-level blocking (socket recv,
#: time.sleep) shows the CALLER as the leaf, so the repo's own blocking
#: read helpers are named here alongside the stdlib wait primitives.
_IDLE_LEAF_MODULES = ("selectors", "socketserver")
_IDLE_LEAVES = frozenset((
    ("threading", "wait"), ("threading", "_wait_for_tstate_lock"),
    ("threading", "join"),
    ("queue", "get"), ("queue", "put"),
    ("concurrent.futures.thread", "_worker"),
))
_IDLE_LEAF_FUNCS = frozenset(
    ("select", "poll", "accept", "_read_exact", "_fill"))


def is_idle(stack: "tuple[str, ...]") -> bool:
    """True when the innermost frame of a sampled stack (labels
    root-first, ``module:function``) is a known blocking call."""
    if not stack:
        return True
    mod, _, func = stack[-1].partition(":")
    return (mod in _IDLE_LEAF_MODULES
            or (mod, func) in _IDLE_LEAVES
            or func in _IDLE_LEAF_FUNCS)


def classify(stack: "tuple[str, ...]", thread_name: str) -> str:
    """Subsystem for one sampled stack (labels root-first,
    ``module:function``). Reactor wins by thread identity — its
    dispatch loop must never be attributed to the code it dispatches."""
    if thread_name.startswith("rpc-reactor"):
        return "reactor"
    for label in reversed(stack):
        mod, _, func = label.partition(":")
        for mprefix, funcs, sub in _MODULE_TABLE:
            if mod.startswith(mprefix) and (
                    not funcs or func.startswith(funcs)):
                return sub
    for prefix, sub in _THREAD_ROLES:
        if thread_name.startswith(prefix):
            return sub
    return "other"


class StackTrie:
    """Bounded prefix tree of sampled stacks. Each ``add`` walks the
    stack root-first, creating nodes up to ``max_nodes``; past the
    budget, unseen branches collapse into a per-level ``(other)`` child
    and the stack truncates there — memory stays bounded no matter how
    pathological the code under the profiler is, and the overflow is
    visible in the output rather than silently dropped."""

    OTHER = "(other)"

    def __init__(self, max_nodes: int = 20000) -> None:
        self.max_nodes = int(max_nodes)
        self.nodes = 0
        #: label -> [leaf_count, children_dict]
        self.root: "dict[str, list]" = {}

    def add(self, stack: "tuple[str, ...]") -> "tuple[str, ...]":
        """Record one sample; returns the canonical stack actually
        stored (identical to the input unless the node budget forced a
        ``(other)`` truncation)."""
        out: "list[str]" = []
        children = self.root
        node = None
        for label in stack:
            nd = children.get(label)
            if nd is None:
                if self.nodes >= self.max_nodes:
                    nd = children.get(self.OTHER)
                    if nd is None:
                        # the overflow child is always grantable: one
                        # per existing node bounds the total at 2x
                        nd = children[self.OTHER] = [0, {}]
                        self.nodes += 1
                    out.append(self.OTHER)
                    node = nd
                    break
                nd = children[label] = [0, {}]
                self.nodes += 1
            out.append(label)
            node = nd
            children = nd[1]
        if node is not None:
            node[0] += 1
        return tuple(out)

    def folded(self) -> "list[tuple[tuple[str, ...], int]]":
        """Lifetime (stack, count) pairs for every stack observed."""
        out: "list[tuple[tuple[str, ...], int]]" = []

        def walk(children: dict, prefix: "tuple[str, ...]") -> None:
            for label, (count, kids) in children.items():
                path = prefix + (label,)
                if count:
                    out.append((path, count))
                walk(kids, path)

        walk(self.root, ())
        return out


def parse_folded(text: str) -> "list[tuple[tuple[str, ...], int]]":
    """Inverse of the collapsed folded-stack rendering: ``a;b;c N``
    lines back into (stack, count) pairs (blank lines skipped)."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        path, _, count = line.rpartition(" ")
        out.append((tuple(path.split(";")), int(count)))
    return out


def render_folded(pairs: "list[tuple[tuple[str, ...], int]]") -> str:
    return "\n".join(f"{';'.join(stack)} {count}"
                     for stack, count in sorted(pairs)) + (
                         "\n" if pairs else "")


class StackSampler:
    """The continuous profiler: one sampling thread + one GIL sentinel.

    Samples land in three places — a bounded :class:`StackTrie`
    (lifetime aggregate), a time-pruned window of per-tick samples
    (``/stacks?seconds=N`` queries), and per-subsystem rolling totals
    feeding the ``cpu_share`` gauges. All three mutate under one plain
    lock held for microseconds per tick; HTTP readers take the same
    lock, never the daemon's."""

    def __init__(self, hz: int = 19, window_s: float = 120.0,
                 max_trie_nodes: int = 20000,
                 registry: "MetricsRegistry | None" = None) -> None:
        self.hz = max(1, int(hz))
        self.window_s = float(window_s)
        self.registry = registry if registry is not None \
            else MetricsRegistry("prof")
        self.trie = StackTrie(max_trie_nodes)
        self._lock = threading.Lock()
        #: deque-ish list of (monotonic ts, [(ident, tname, stack,
        #: subsystem)], {subsystem: busy count}) ticks inside the
        #: window; list+del beats deque here because pruning is
        #: amortized batch work. Entry tuples are SHARED across ticks
        #: while a thread stays parked (see _frame_cache) so a
        #: fleet-scale window holds millions of references but only
        #: thousands of tuples — without the sharing, allocation + GC
        #: scan cost of the window dominates the profiler's overhead.
        self._ticks: "list[tuple[float, list, dict]]" = []
        self._sub_totals: "dict[str, int]" = {s: 0 for s in SUBSYSTEMS}
        self._total = 0
        self._busy_s = 0.0
        self._started_at = 0.0
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self._sentinel: "threading.Thread | None" = None
        self._own_idents: "set[int]" = set()
        #: code object -> "module:function" — frame labeling without a
        #: per-frame f_globals lookup + string build (the dominant cost
        #: of a sampling pass once a process has hundreds of threads)
        self._label_cache: "dict[Any, str]" = {}
        #: ident -> ((id(frame), f_lasti), entry, sub) where entry is
        #: the shared (ident, tname, stack, sub) tuple: a thread whose
        #: leaf frame object AND instruction pointer are unchanged since
        #: the last tick is parked in the same place — reuse last tick's
        #: walk AND its entry tuple instead of re-walking/re-allocating
        #: (a frame's f_back chain is immutable for its lifetime, so an
        #: unchanged leaf implies an unchanged label stack). On a
        #: fleet-scale daemon ~99% of threads hit this cache every tick;
        #: without it sampling cost scales with thread COUNT instead of
        #: thread ACTIVITY.
        self._frame_cache: "dict[int, tuple]" = {}
        #: ident -> thread name; threading.enumerate() walks a lock and
        #: two properties per thread, so it only reruns when an unknown
        #: ident shows up (or the cache holds mostly-dead idents)
        self._name_cache: "dict[int, str]" = {}
        self.gil_delay = self.registry.histogram("gil_delay_seconds")
        for sub in SUBSYSTEMS:
            self.registry.set_gauge(f"cpu_share|subsystem={sub}",
                                    lambda s=sub: self._share(s))
        self.registry.set_gauge("prof_overhead_share", self._overhead)

    # ------------------------------------------------------------ wiring

    @classmethod
    def from_conf(cls, conf: Any,
                  metrics: Any = None) -> "StackSampler | None":
        """The daemon entry point: None when ``tpumr.prof.enabled`` is
        off (the default — profiling is opt-in), else a ready-to-start
        sampler whose registry is registered into ``metrics`` (a
        MetricsSystem) when one is given."""
        from tpumr.core import confkeys
        if not confkeys.get_boolean(conf, "tpumr.prof.enabled"):
            return None
        sampler = cls(
            hz=confkeys.get_int(conf, "tpumr.prof.hz"),
            window_s=confkeys.get_float(conf, "tpumr.prof.window.s"),
            max_trie_nodes=confkeys.get_int(
                conf, "tpumr.prof.trie.max.nodes"))
        if metrics is not None:
            metrics.register(sampler.registry)
        return sampler

    def start(self) -> "StackSampler":
        if self._thread is not None:
            return self
        self._started_at = time.monotonic()
        self._thread = threading.Thread(
            target=self._loop, name="prof-sampler", daemon=True)
        self._sentinel = threading.Thread(
            target=self._sentinel_loop, name="prof-gil-sentinel",
            daemon=True)
        self._thread.start()
        self._sentinel.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in (self._thread, self._sentinel):
            if t is not None:
                t.join(timeout=2.0)
        self._thread = self._sentinel = None

    # ------------------------------------------------------------ loops

    def _loop(self) -> None:
        self._own_idents.add(threading.get_ident())
        period = 1.0 / self.hz
        next_t = time.monotonic()
        while not self._stop.is_set():
            next_t += period
            delay = next_t - time.monotonic()
            if delay > 0:
                if self._stop.wait(delay):
                    return
            else:
                # fell behind (suspend, GIL convoy): resync instead of
                # bursting to catch up — burst samples are biased
                next_t = time.monotonic()
            self._sample_once()

    def _sentinel_loop(self) -> None:
        self._own_idents.add(threading.get_ident())
        observe = self.gil_delay.observe
        while not self._stop.is_set():
            t0 = time.monotonic()
            time.sleep(SENTINEL_SLEEP_S)
            overshoot = time.monotonic() - t0 - SENTINEL_SLEEP_S
            if overshoot > 0:
                observe(overshoot)

    def _walk(self, frame: Any) -> "tuple[str, ...]":
        labels: "list[str]" = []
        cache = self._label_cache
        f = frame
        while f is not None and len(labels) < MAX_STACK_DEPTH:
            code = f.f_code
            label = cache.get(code)
            if label is None:
                if len(cache) > 100_000:   # runaway dynamic code
                    cache.clear()
                mod = f.f_globals.get("__name__", "?")
                label = cache[code] = f"{mod}:{code.co_name}"
            labels.append(label)
            f = f.f_back
        labels.reverse()
        return tuple(labels)

    def _sample_once(self) -> None:
        t0 = time.monotonic()
        frames = sys._current_frames()
        own = self._own_idents
        cache = self._frame_cache
        names = self._name_cache
        if (len(names) > len(frames) + 64
                or any(i not in names for i in frames)):
            names = self._name_cache = {
                t.ident: t.name for t in threading.enumerate()
                if t.ident is not None}
        entries: "list[tuple[int, str, tuple, str]]" = []
        tick_subs: "dict[str, int]" = {}
        busy = 0
        with self._lock:
            for ident, frame in frames.items():
                if ident in own:
                    continue
                key = (id(frame), frame.f_lasti)
                hit = cache.get(ident)
                if hit is not None and hit[0] == key:
                    _, entry, sub = hit
                else:
                    tname = names.get(ident) or f"tid-{ident}"
                    stack = self.trie.add(self._walk(frame))
                    # sub=None marks a parked thread: kept in the folded
                    # output, excluded from the cpu_share totals
                    sub = (None if is_idle(stack)
                           else classify(stack, tname))
                    entry = (ident, tname, stack, sub)
                    cache[ident] = (key, entry, sub)
                entries.append(entry)
                if sub is not None:
                    tick_subs[sub] = tick_subs.get(sub, 0) + 1
                    busy += 1
            if len(cache) > len(entries) + len(own):
                for ident in [i for i in cache if i not in frames]:
                    del cache[ident]
            now = time.monotonic()
            self._ticks.append((now, entries, tick_subs))
            for sub, n in tick_subs.items():
                self._sub_totals[sub] = self._sub_totals.get(sub, 0) + n
            self._total += busy
            self._prune_locked(now)
            self._busy_s += time.monotonic() - t0
        self.registry.incr("prof_samples", len(entries))

    def _prune_locked(self, now: float) -> None:
        cutoff = now - self.window_s
        drop = 0
        for ts, _entries, tick_subs in self._ticks:
            if ts >= cutoff:
                break
            drop += 1
            for sub, n in tick_subs.items():
                self._sub_totals[sub] -= n
                self._total -= n
        if drop:
            del self._ticks[:drop]

    # ------------------------------------------------------------ reads

    def _share(self, sub: str) -> float:
        with self._lock:
            total = self._total
            return self._sub_totals.get(sub, 0) / total if total else 0.0

    def _overhead(self) -> float:
        elapsed = time.monotonic() - self._started_at
        with self._lock:
            busy = self._busy_s
        return busy / elapsed if elapsed > 0 else 0.0

    def subsystem_shares(
            self, seconds: "float | None" = None) -> "dict[str, float]":
        """Per-subsystem CPU share over the last ``seconds`` (whole
        window when None), over BUSY samples only (idle-leaf samples
        don't burn CPU). Shares sum to 1.0 by construction whenever any
        busy sample exists."""
        with self._lock:
            if seconds is None:
                counts = dict(self._sub_totals)
                total = self._total
            else:
                cutoff = time.monotonic() - float(seconds)
                counts = {}
                total = 0
                for ts, _entries, tick_subs in self._ticks:
                    if ts < cutoff:
                        continue
                    for sub, n in tick_subs.items():
                        counts[sub] = counts.get(sub, 0) + n
                        total += n
        if not total:
            return {s: 0.0 for s in SUBSYSTEMS}
        return {s: counts.get(s, 0) / total for s in SUBSYSTEMS}

    def folded(self, seconds: "float | None" = None,
               thread_prefix: "str | None" = None) -> str:
        """Collapsed folded-stack text over the last ``seconds`` (whole
        window when None), each stack rooted at its thread name;
        ``thread_prefix`` narrows to matching thread names (the
        tracker's per-attempt view — task threads are ``task-<id>``)."""
        agg: "dict[tuple[str, ...], int]" = {}
        with self._lock:
            cutoff = None if seconds is None \
                else time.monotonic() - float(seconds)
            for ts, entries, _subs in self._ticks:
                if cutoff is not None and ts < cutoff:
                    continue
                for _ident, tname, stack, _sub in entries:
                    if thread_prefix is not None \
                            and not tname.startswith(thread_prefix):
                        continue
                    key = (tname,) + stack
                    agg[key] = agg.get(key, 0) + 1
        return render_folded(list(agg.items()))

    def flame_svg(self, seconds: "float | None" = None,
                  title: str = "tpumr flame graph") -> str:
        return flame_svg(self.folded(seconds), title=title)

    # ------------------------------------------------------------ http

    def attach_http(self, srv: Any,
                    attempt_thread_prefix:
                    "Callable[[str], str] | None" = None) -> None:
        """Register ``/stacks`` and ``/flame`` on a StatusHttpServer.
        ``attempt_thread_prefix`` maps an ``attempt=`` query arg to the
        thread-name prefix running it (tracker in-process attempts)."""

        def _window(q: dict) -> "float | None":
            return float(q["seconds"]) if "seconds" in q else None

        def _prefix(q: dict) -> "str | None":
            if attempt_thread_prefix is not None and "attempt" in q:
                return attempt_thread_prefix(q["attempt"])
            return None

        def stacks(q: dict) -> str:
            return self.folded(_window(q), thread_prefix=_prefix(q))

        def flame(q: dict) -> str:
            return flame_svg(
                self.folded(_window(q), thread_prefix=_prefix(q)),
                title=f"{srv.name} flame graph")

        srv.add_raw("stacks", stacks, content_type="text/plain")
        srv.add_raw("flame", flame, content_type="image/svg+xml")


# ---------------------------------------------------------------- /threads


def threads_dump() -> str:
    """One-shot plain-text dump of every live thread's stack, prefixed
    by the InstrumentedRLock holder/waiter table — the "is it
    deadlocked right now" page. Needs no sampler and takes no daemon
    lock: reading ``sys._current_frames`` and the racy lock fields is
    safe from any thread at any time."""
    from tpumr.metrics.locks import lock_table
    out: "list[str]" = []
    rows = lock_table()
    out.append("== locks (rank order) ==")
    if not rows:
        out.append("(no named instrumented locks)")
    for r in rows:
        held = (f"held by {r['holder']} for {r['held_for_s']:.3f}s"
                if r["holder"] else "free")
        waiters = (f"; waiters: {', '.join(r['waiters'])} "
                   f"(longest {r['longest_wait_s']:.3f}s)"
                   if r["waiters"] else "")
        out.append(f"{r['name']} (rank {r['rank']}): {held}{waiters}")
    holder_of: "dict[str, list[str]]" = {}
    waiting_on: "dict[str, list[str]]" = {}
    for r in rows:
        if r["holder"]:
            holder_of.setdefault(r["holder"], []).append(r["name"])
        for w in r["waiters"]:
            waiting_on.setdefault(w, []).append(r["name"])
    out.append("")
    out.append("== threads ==")
    frames = sys._current_frames()
    threads = {t.ident: t for t in threading.enumerate()}
    def _key(item):  # stable, named threads first
        t = threads.get(item[0])
        return (t.name if t else f"~tid-{item[0]}")
    for ident, frame in sorted(frames.items(), key=_key):
        t = threads.get(ident)
        name = t.name if t else f"tid-{ident}"
        flags = " daemon" if (t is not None and t.daemon) else ""
        ann = ""
        if name in holder_of:
            ann += f" [holds: {', '.join(holder_of[name])}]"
        if name in waiting_on:
            ann += f" [waiting on: {', '.join(waiting_on[name])}]"
        out.append(f"--- {name} (ident {ident}{flags}){ann}")
        out.append("".join(traceback.format_stack(frame)).rstrip())
    return "\n".join(out) + "\n"


# ------------------------------------------------------------- flame SVG

_FLAME_ROW_H = 17
_FLAME_PALETTE = ("#e05038", "#e07038", "#e09038", "#e0b038",
                  "#d0a030", "#c8883a", "#e06048", "#d07840")


def _flame_color(label: str) -> str:
    return _FLAME_PALETTE[hash(label) % len(_FLAME_PALETTE)]


def flame_svg(folded_text: str, title: str = "tpumr flame graph",
              width: int = 1200) -> str:
    """A self-contained SVG flame graph from collapsed folded-stack
    text — no scripts, no external assets, loadable straight from
    ``/flame`` in any browser (the same in-repo-SVG stance as the trace
    swimlane: the artifact must render decades from now). Frame width
    is proportional to sample count; ``<title>`` elements carry the
    full label + counts for hover inspection."""
    from html import escape
    pairs = parse_folded(folded_text)
    total = sum(c for _s, c in pairs)
    # fold the flat pairs back into a tree: label -> [count, children]
    root: "dict[str, list]" = {}
    maxdepth = 0
    for stack, count in pairs:
        children = root
        maxdepth = max(maxdepth, len(stack))
        for label in stack:
            nd = children.get(label)
            if nd is None:
                nd = children[label] = [0, {}]
            nd[0] += count
            children = nd[1]
    height = (maxdepth + 1) * _FLAME_ROW_H + 40
    out = [
        f"<svg xmlns='http://www.w3.org/2000/svg' width='{width}' "
        f"height='{height}' font-family='monospace' font-size='11'>",
        f"<rect width='100%' height='100%' fill='#fffdf7'/>",
        f"<text x='8' y='16' font-size='13'>{escape(title)} "
        f"&#8212; {total} samples</text>",
    ]

    def layout(children: dict, x: float, depth: int) -> None:
        y = height - (depth + 1) * _FLAME_ROW_H - 8
        for label, (count, kids) in sorted(
                children.items(), key=lambda kv: (-kv[1][0], kv[0])):
            w = count / total * width
            if w >= 0.4:
                pct = 100.0 * count / total
                lab = escape(label)
                out.append(
                    f"<g><rect x='{x:.2f}' y='{y}' width='{w:.2f}' "
                    f"height='{_FLAME_ROW_H - 1}' "
                    f"fill='{_flame_color(label)}' rx='1'>"
                    f"<title>{lab} &#8212; {count} samples "
                    f"({pct:.1f}%)</title></rect>")
                if w > 40:
                    shown = escape(label[: max(1, int(w / 7))])
                    out.append(
                        f"<text x='{x + 3:.2f}' y='{y + 12}' "
                        f"fill='#222'>{shown}</text>")
                out.append("</g>")
                layout(kids, x, depth + 1)
            x += w

    if total:
        layout(root, 0.0, 0)
    else:
        out.append(f"<text x='8' y='40'>(no samples in window)</text>")
    out.append("</svg>")
    return "\n".join(out)
