"""Flight recorder: automatic postmortem bundles when the master
breaches its own latency SLO.

bench_scale.json enshrines a dual-p99 SLO (heartbeat handling and
per-tracker lag under 250 ms) that CI gates on — but a breach in a LIVE
cluster evaporates before anyone can attach a profiler: by the time an
operator reads the page, the convoy that caused it is gone. The
recorder closes that gap. A watchdog thread on the master derives a
WINDOWED p99 each tick from the cumulative ``heartbeat_seconds`` /
``heartbeat_lag_seconds`` histograms (``typed()`` state diffed with
``typed_delta`` — the same mechanism the heartbeat cluster merge uses),
and on a breach writes one incident bundle: the profiler's folded
stacks for the breach window, the live InstrumentedRLock holder/waiter
table plus per-lock wait/hold distributions, rpc saturation and
heartbeat-phase snapshots, and the most recent buffered trace spans —
everything a postmortem needs, captured AT the breach, as one JSON file
under ``tpumr.prof.incident.dir``.

Bundles are rate-limited (``tpumr.prof.incident.cooldown.ms``): a
sustained breach produces exactly one bundle per cooldown window, not a
disk-filling stream. ``/incidents`` on the master lists them;
``validate_incident`` is the schema checker the e2e test (and any
external consumer) holds bundles against.

The scenario lab grew the watchdog two surfaces. Per-TRAFFIC-CLASS
windowed percentiles: the master's lazily-created
``class_assign_seconds`` / ``class_complete_seconds`` histograms are
windowed the same way each tick and judged against per-class SLOs
(``tpumr.scenario.slo.<class>.{assign,complete}.ms``), yielding an
online per-class verdict (``class_report``) plus a bounded per-tick
window history the overload e2e asserts recovery against. And the tick
is the master BROWNOUT's clock: every tick folds one pressure bit
(any windowed breach, heartbeat or class) into
``JobMaster.brownout_tick``, so sustained pressure engages ranked load
shedding and sustained calm releases it. Bundles carry the workload
context — active scenario name, per-class breakdown at breach time,
brownout level and recent transitions — so a bundle alone answers
"degrading for whom, and what was already shed".
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any

from tpumr.metrics.histogram import typed_delta

#: bundle schema tag — bump on incompatible shape changes
#: (2: reason rows carry per-row slo_s; workload context section)
SCHEMA = "tpumr-incident-2"

#: watchdog cadence: 1 s ticks make the breach window ~1 s, matching
#: the heartbeat cadence the SLO is defined over
TICK_S = 1.0


def typed_p99(t: "dict | None", q: float = 0.99) -> float:
    """Interpolated quantile of a ``Histogram.typed()`` (or
    ``typed_delta``) state — the windowed read the watchdog runs on,
    where no Histogram object exists to ask."""
    if not t or not t.get("count"):
        return 0.0
    bounds = list(t.get("bounds") or [])
    buckets = {int(k): int(v) for k, v in (t.get("buckets") or {}).items()}
    total = int(t["count"])
    rank = q * total
    seen = 0.0
    for i in range(len(bounds) + 1):
        c = buckets.get(i, 0)
        if not c:
            continue
        if seen + c >= rank:
            if i >= len(bounds):
                return float(t.get("max") or (bounds[-1] if bounds else 0.0))
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            frac = (rank - seen) / c
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
        seen += c
    return float(t.get("max") or 0.0)


class FlightRecorder:
    """The master's SLO watchdog + incident writer. Owns one daemon
    thread; reads only racy-safe surfaces (cumulative histogram state,
    the metrics snapshot, the lock table, buffered spans) so arming it
    adds nothing to the heartbeat path."""

    def __init__(self, master: Any, sampler: Any, slo_ms: int,
                 cooldown_ms: int, incident_dir: str,
                 conf: Any = None) -> None:
        self.master = master
        self.sampler = sampler
        self.conf = conf
        self.slo_s = slo_ms / 1000.0
        self.cooldown_s = cooldown_ms / 1000.0
        self.incident_dir = incident_dir
        self._registry = sampler.registry if sampler is not None \
            else getattr(master, "_mreg", None)
        self._prev: "dict[str, dict]" = {}
        #: per-class online verdict state, keyed by class name
        self._class_state: "dict[str, dict]" = {}
        self._class_slo_cache: \
            "dict[str, tuple[float | None, float | None]]" = {}
        #: bounded per-tick history: per-class windowed p99s + brownout
        #: level — the overload e2e proves "interactive recovered WHILE
        #: brownout was active" from this, not from cumulative state
        self._window_history: "deque[dict]" = deque(maxlen=900)
        self._last_write_mono: "float | None" = None
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    @classmethod
    def from_conf(cls, conf: Any, master: Any,
                  sampler: Any) -> "FlightRecorder | None":
        """None unless an incident dir can be derived
        (``tpumr.prof.incident.dir``, else next to the job history) AND
        something wants the watchdog: the profiler (folded stacks in
        every bundle) or brownout mode (the tick is the brownout's
        clock — a stacks-less recorder still windows SLOs, judges
        classes, and writes bundles with empty ``folded_stacks``)."""
        from tpumr.core import confkeys
        if sampler is None and not confkeys.get_boolean(
                conf, "tpumr.brownout.enabled"):
            return None
        d = conf.get("tpumr.prof.incident.dir") \
            or conf.get("tpumr.history.dir")
        if not d:
            return None
        return cls(
            master, sampler,
            slo_ms=confkeys.get_int(conf, "tpumr.prof.incident.slo.ms"),
            cooldown_ms=confkeys.get_int(
                conf, "tpumr.prof.incident.cooldown.ms"),
            incident_dir=os.path.join(str(d), "incidents"),
            conf=conf)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "FlightRecorder":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="prof-flightrec", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(TICK_S):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — the watchdog must never
                pass           # take the master down with it

    # ------------------------------------------------------------ watchdog

    def _windowed_p99s(self) -> "list[tuple[str, float]]":
        """(metric, windowed p99 seconds) for each watched histogram —
        the delta since the previous tick, so a breach long past can't
        keep the cumulative p99 pinned above the SLO forever."""
        out = []
        for metric, hist in (
                ("heartbeat_seconds", self.master._hb_seconds),
                ("heartbeat_lag_seconds", self.master._hb_lag)):
            cur = hist.typed()
            delta = typed_delta(cur, self._prev.get(metric))
            self._prev[metric] = cur
            if delta and delta.get("count"):
                out.append((metric, typed_p99(delta)))
        return out

    def _class_slos(self, cls_name: str) \
            -> "tuple[float | None, float | None]":
        """(assign_slo_s, complete_slo_s) for one traffic class, from
        ``tpumr.scenario.slo.<class>.{assign,complete}.ms`` — None when
        unset (that side is observed but never judged)."""
        cached = self._class_slo_cache.get(cls_name)
        if cached is not None:
            return cached
        out = []
        for kind in ("assign", "complete"):
            raw = self.conf.get(
                f"tpumr.scenario.slo.{cls_name}.{kind}.ms") \
                if self.conf is not None else None
            try:
                out.append(float(raw) / 1000.0 if raw not in
                           (None, "") else None)
            except (TypeError, ValueError):
                out.append(None)
        self._class_slo_cache[cls_name] = (out[0], out[1])
        return self._class_slo_cache[cls_name]

    def _fold_classes(self) -> "list[tuple[str, str, float,"\
            " float | None, bool]]":
        """Window the master's per-class latency histograms (same
        typed-delta mechanism as the heartbeat SLOs) and fold the
        online verdict state. Returns (class, kind, p99_s, slo_s,
        breach) rows for windows that carried data."""
        rows: "list[tuple[str, str, float, float | None, bool]]" = []
        hists = getattr(self.master, "_class_hists", None) or {}
        for (kind, cls_name), hist in list(hists.items()):
            key = f"class_{kind}|{cls_name}"
            cur = hist.typed()
            delta = typed_delta(cur, self._prev.get(key))
            self._prev[key] = cur
            if not delta or not delta.get("count"):
                continue
            p99 = typed_p99(delta)
            slo = self._class_slos(cls_name)[0 if kind == "assign"
                                             else 1]
            breach = slo is not None and p99 > slo
            st = self._class_state.setdefault(cls_name, {})
            st[f"{kind}_windows"] = st.get(f"{kind}_windows", 0) + 1
            if breach:
                st[f"{kind}_breach_windows"] = \
                    st.get(f"{kind}_breach_windows", 0) + 1
            st[f"{kind}_last_p99_s"] = round(p99, 6)
            st[f"{kind}_ok"] = (not breach) if slo is not None else None
            rows.append((cls_name, kind, p99, slo, breach))
        return rows

    def _tick(self) -> None:
        hb = self._windowed_p99s()
        class_rows = self._fold_classes()
        breaches = [(m, p99, self.slo_s) for m, p99 in hb
                    if p99 > self.slo_s]
        breaches += [(f"class_{kind}_seconds|class={cls_name}", p99,
                      slo)
                     for cls_name, kind, p99, slo, breach in class_rows
                     if breach]
        # the brownout's clock: one pressure bit per tick — any
        # windowed breach, heartbeat or class, counts as pressure
        if getattr(self.master, "brownout", None) is not None:
            self.master.brownout_tick(bool(breaches))
        self._record_window(hb, class_rows)
        if not breaches:
            return
        now = time.monotonic()
        if self._last_write_mono is not None \
                and now - self._last_write_mono < self.cooldown_s:
            if self._registry is not None:
                self._registry.incr("incidents_suppressed")
            return
        self._last_write_mono = now
        self.write_incident(breaches)

    def _record_window(self, hb: "list[tuple[str, float]]",
                       class_rows: "list") -> None:
        brown = getattr(self.master, "brownout", None)
        rec: "dict[str, Any]" = {
            "t_mono": round(time.monotonic(), 3),
            "brownout_level": brown.level if brown is not None else 0,
            "heartbeat": {m: round(p, 6) for m, p in hb},
            "classes": {},
        }
        for cls_name, kind, p99, slo, breach in class_rows:
            c = rec["classes"].setdefault(cls_name, {})
            c[f"{kind}_p99_s"] = round(p99, 6)
            if slo is not None:
                c[f"{kind}_ok"] = not breach
        self._window_history.append(rec)

    def window_history(self) -> "list[dict]":
        """The bounded per-tick record (copy) — per-class windowed
        p99s, verdict bits, and the brownout level at each tick."""
        return list(self._window_history)

    def class_report(self) -> dict:
        """Machine-readable per-class verdicts: cumulative p50/p99 plus
        the online windowed state for both latency kinds, and one
        ``pass`` bit per class — the last data-carrying window must be
        under SLO and breached windows must stay a minority, so a class
        that RECOVERED under brownout passes while one still drowning
        fails. Classes without SLOs report latencies with ``pass``
        True (observed, never judged)."""
        hists = getattr(self.master, "_class_hists", None) or {}
        by_cls: "dict[str, dict]" = {}
        for (kind, cls_name), hist in list(hists.items()):
            by_cls.setdefault(cls_name, {})[kind] = hist
        out: "dict[str, dict]" = {}
        for cls_name in sorted(by_cls):
            slo_assign, slo_complete = self._class_slos(cls_name)
            st = self._class_state.get(cls_name, {})
            row: "dict[str, Any]" = {}
            ok = True
            for kind, slo in (("assign", slo_assign),
                              ("complete", slo_complete)):
                hist = by_cls[cls_name].get(kind)
                snap = hist.snapshot() if hist is not None else {}
                windows = st.get(f"{kind}_windows", 0)
                breach_w = st.get(f"{kind}_breach_windows", 0)
                entry: "dict[str, Any]" = {
                    "count": snap.get("count", 0),
                    "p50_s": snap.get("p50", 0.0),
                    "p99_s": snap.get("p99", 0.0),
                    "slo_ms": int(slo * 1000) if slo is not None
                    else None,
                    "windows": windows,
                    "breach_windows": breach_w,
                    "last_window_p99_s": st.get(f"{kind}_last_p99_s"),
                    "ok": st.get(f"{kind}_ok"),
                }
                if slo is not None and windows:
                    frac = breach_w / windows
                    entry["breach_fraction"] = round(frac, 4)
                    if entry["ok"] is False or frac > 0.5:
                        ok = False
                row[kind] = entry
            row["pass"] = ok
            out[cls_name] = row
        return out

    # ------------------------------------------------------------ bundles

    def bundle(self, breaches: "list[tuple]") -> dict:
        """Assemble the incident document (pure read — the e2e test and
        ``write_incident`` share it). ``breaches`` rows are (metric,
        p99_s) judged against the heartbeat SLO, or (metric, p99_s,
        slo_s) carrying their own — per-class SLOs differ."""
        from tpumr.metrics.locks import lock_table
        m = self.master
        snaps = m.metrics.snapshot()
        jt = snaps.get("jobtracker", {})
        rpc = snaps.get("rpc", {})
        brown = getattr(m, "brownout", None)
        wait_hold = {
            name: val for name, val in jt.items()
            if name.startswith(("jt_lock_wait_seconds|",
                                "jt_lock_hold_seconds|"))}
        phases = {name.split("phase=", 1)[-1]: val
                  for name, val in jt.items()
                  if name.startswith("heartbeat_phase_seconds|")}
        spans = [s.to_dict() for s in m.tracer.pending()[-200:]] \
            if getattr(m, "tracer", None) is not None else []
        return {
            "schema": SCHEMA,
            "ts": time.time(),
            "role": "jobtracker",
            "slo_ms": int(self.slo_s * 1000),
            "reason": [{"metric": b[0], "p99_s": round(b[1], 6),
                        "slo_s": round(b[2] if len(b) > 2
                                       else self.slo_s, 6)}
                       for b in breaches],
            # workload context: WHO was degrading and what the master
            # had already shed when this bundle was cut
            "workload": {
                "scenario": getattr(m, "scenario_name", "") or "",
                "brownout": brown.snapshot() if brown is not None
                else {"level": 0},
                "classes": {
                    cls_name: dict(st)
                    for cls_name, st in self._class_state.items()},
            },
            "folded_stacks": self.sampler.folded(
                max(2 * TICK_S, 5.0)) if self.sampler else "",
            "subsystem_shares": self.sampler.subsystem_shares()
            if self.sampler else {},
            "locks": {"live": lock_table(), "wait_hold": wait_hold},
            "rpc": {k: rpc.get(k) for k in
                    ("rpc_inflight", "rpc_inflight_peak",
                     "rpc_handler_threads") if k in rpc},
            "heartbeat": {
                "seconds": jt.get("heartbeat_seconds", {}),
                "lag": jt.get("heartbeat_lag_seconds", {}),
                "phases": phases,
                "trackers": len(getattr(m, "trackers", ()) or ()),
            },
            "spans": spans,
        }

    def write_incident(self, breaches: "list[tuple]") -> "str | None":
        """Write one bundle; returns its path (None on I/O failure —
        the recorder must outlive a full disk)."""
        doc = self.bundle(breaches)
        try:
            os.makedirs(self.incident_dir, exist_ok=True)
            name = f"incident-{int(doc['ts'] * 1000)}.json"
            path = os.path.join(self.incident_dir, name)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, default=str)
            os.replace(tmp, path)
        except OSError:
            return None
        if self._registry is not None:
            self._registry.incr("incidents_written")
        return path

    # ------------------------------------------------------------ listing

    def list_incidents(self) -> "list[dict]":
        """Newest-first {name, bytes, reason…} rows for /incidents."""
        try:
            names = sorted(
                (n for n in os.listdir(self.incident_dir)
                 if n.startswith("incident-") and n.endswith(".json")),
                reverse=True)
        except OSError:
            return []
        rows = []
        for n in names:
            path = os.path.join(self.incident_dir, n)
            row: "dict[str, Any]" = {"name": n}
            try:
                row["bytes"] = os.path.getsize(path)
                with open(path) as f:
                    doc = json.load(f)
                row["ts"] = doc.get("ts")
                row["reason"] = doc.get("reason", [])
            except (OSError, ValueError):
                row["reason"] = [{"metric": "(unreadable)"}]
            rows.append(row)
        return rows

    def read_incident(self, name: str) -> dict:
        """One bundle by basename — path-traversal-proof (the name must
        be exactly a listing entry)."""
        base = os.path.basename(name)
        if not (base.startswith("incident-") and base.endswith(".json")):
            raise ValueError(f"not an incident bundle name: {name!r}")
        with open(os.path.join(self.incident_dir, base)) as f:
            return json.load(f)


class NNFlightRecorder(FlightRecorder):
    """The NameNode's SLO watchdog — same tick/cooldown/bundle machinery
    as the master's recorder, but the watched distributions are the
    per-RPC-op latencies (``nn_op_seconds{op=}``) judged against
    ``tpumr.nn.incident.slo.ms``. A breach bundle carries the namespace
    lock's live holder/waiter row and wait/hold distributions plus every
    op's cumulative latency — the "which op convoyed the namespace lock"
    postmortem, cut at the breach."""

    @classmethod
    def from_conf(cls, conf: Any, namenode: Any,
                  sampler: Any) -> "NNFlightRecorder | None":
        """None unless ``tpumr.nn.incident.slo.ms`` > 0 (off by default —
        unlike the master there is no committed-bench SLO to re-derive
        yet; bench_dfs.py declares one explicitly). The incident dir
        falls back to the name dir, which always exists."""
        from tpumr.core import confkeys
        slo_ms = confkeys.get_int(conf, "tpumr.nn.incident.slo.ms")
        if slo_ms <= 0:
            return None
        d = conf.get("tpumr.prof.incident.dir") or namenode.ns.name_dir
        return cls(
            namenode, sampler, slo_ms=slo_ms,
            cooldown_ms=confkeys.get_int(
                conf, "tpumr.prof.incident.cooldown.ms"),
            incident_dir=os.path.join(str(d), "incidents"),
            conf=conf)

    def _windowed_p99s(self) -> "list[tuple[str, float]]":
        out = []
        for op, hist in list(getattr(self.master,
                                     "_op_hists", {}).items()):
            metric = f"nn_op_seconds|op={op}"
            cur = hist.typed()
            delta = typed_delta(cur, self._prev.get(metric))
            self._prev[metric] = cur
            if delta and delta.get("count"):
                out.append((metric, typed_p99(delta)))
        return out

    def bundle(self, breaches: "list[tuple]") -> dict:
        from tpumr.metrics.histogram import Histogram
        from tpumr.metrics.locks import lock_table
        nn = self.master
        snaps = nn.metrics.snapshot()
        reg = snaps.get("namenode", {})
        rpc = snaps.get("rpc", {})
        wait_hold = {
            name: val for name, val in reg.items()
            if name.startswith(("nn_lock_wait_seconds|",
                                "nn_lock_hold_seconds|"))}
        ops = {name.split("op=", 1)[-1]: val
               for name, val in reg.items()
               if name.startswith("nn_op_seconds|")}
        # one all-ops distribution (the master bundle's "seconds"
        # slot); the per-op breakdown rides in "phases", mirroring the
        # heartbeat-phase layout so bundle consumers read both roles
        # the same way
        merged = Histogram("nn_op_seconds")
        for h in list(getattr(nn, "_op_hists", {}).values()):
            merged.merge_typed(h.typed())
        return {
            "schema": SCHEMA,
            "ts": time.time(),
            "role": "namenode",
            "slo_ms": int(self.slo_s * 1000),
            "reason": [{"metric": b[0], "p99_s": round(b[1], 6),
                        "slo_s": round(b[2] if len(b) > 2
                                       else self.slo_s, 6)}
                       for b in breaches],
            "workload": {"scenario": "", "brownout": {"level": 0},
                         "classes": {}},
            "folded_stacks": self.sampler.folded(
                max(2 * TICK_S, 5.0)) if self.sampler else "",
            "subsystem_shares": self.sampler.subsystem_shares()
            if self.sampler else {},
            "locks": {"live": lock_table(), "wait_hold": wait_hold},
            "rpc": {k: rpc.get(k) for k in
                    ("rpc_inflight", "rpc_inflight_peak",
                     "rpc_handler_threads") if k in rpc},
            "heartbeat": {"seconds": merged.snapshot(), "phases": ops,
                          "datanodes": len(nn.ns.datanodes)},
            "spans": [],
        }


class ShardFlightRecorder(FlightRecorder):
    """The sharded-master coordinator's SLO watchdog. The base tick
    windows the COORDINATOR-MERGED ``heartbeat_seconds`` /
    ``heartbeat_lag_seconds`` (folded from every shard's deltas) and
    the merged per-class hists, so cluster-wide breach judgement is
    unchanged; on top of that it windows each shard's own heartbeat
    distributions, so a breach driven by ONE hot or dying shard shows
    up as ``heartbeat_seconds|shard=k`` in the bundle's reason — the
    incident names the breaching shard instead of blaming the whole
    master. No sampler of its own: the coordinator does no fold work
    worth profiling; per-shard CPU shares ride in the ``shards``
    section instead."""

    @classmethod
    def from_conf(cls, conf: Any,
                  coordinator: Any) -> "ShardFlightRecorder | None":
        from tpumr.core import confkeys
        if not (confkeys.get_boolean(conf, "tpumr.prof.enabled")
                or confkeys.get_boolean(conf, "tpumr.brownout.enabled")):
            return None
        d = conf.get("tpumr.prof.incident.dir") \
            or conf.get("tpumr.history.dir")
        if not d:
            return None
        return cls(
            coordinator, None,
            slo_ms=confkeys.get_int(conf, "tpumr.prof.incident.slo.ms"),
            cooldown_ms=confkeys.get_int(
                conf, "tpumr.prof.incident.cooldown.ms"),
            incident_dir=os.path.join(str(d), "incidents"),
            conf=conf)

    def _windowed_p99s(self) -> "list[tuple[str, float]]":
        rows = super()._windowed_p99s()
        hists = getattr(self.master, "_shard_hists", None) or {}
        for (k, name), hist in sorted(hists.items()):
            metric = f"{name}|shard={k}"
            cur = hist.typed()
            delta = typed_delta(cur, self._prev.get(metric))
            self._prev[metric] = cur
            if delta and delta.get("count"):
                rows.append((metric, typed_p99(delta)))
        return rows

    def bundle(self, breaches: "list[tuple]") -> dict:
        doc = super().bundle(breaches)
        doc["role"] = "coordinator"
        stats = self.master.shard_stats() \
            if hasattr(self.master, "shard_stats") else {}
        doc["shards"] = stats
        return doc


def validate_incident(doc: Any) -> "list[str]":
    """Schema check for one incident bundle — same stance as the trace
    module's ``validate_chrome_trace``: an empty list means the bundle
    holds everything a postmortem consumer may rely on."""
    errs: "list[str]" = []
    if not isinstance(doc, dict):
        return ["bundle is not an object"]
    if doc.get("schema") != SCHEMA:
        errs.append(f"schema is {doc.get('schema')!r}, want {SCHEMA!r}")
    if not isinstance(doc.get("ts"), (int, float)):
        errs.append("ts missing or non-numeric")
    reason = doc.get("reason")
    if not isinstance(reason, list) or not reason:
        errs.append("reason missing or empty")
    else:
        for i, r in enumerate(reason):
            if not isinstance(r, dict) or "metric" not in r \
                    or not isinstance(r.get("p99_s"), (int, float)):
                errs.append(f"reason[{i}] lacks metric/p99_s")
    if not isinstance(doc.get("slo_ms"), int):
        errs.append("slo_ms missing")
    if not isinstance(doc.get("folded_stacks"), str):
        errs.append("folded_stacks missing (must be a string)")
    locks = doc.get("locks")
    if not isinstance(locks, dict) or not isinstance(
            locks.get("live"), list) \
            or not isinstance(locks.get("wait_hold"), dict):
        errs.append("locks.live / locks.wait_hold missing")
    if not isinstance(doc.get("rpc"), dict):
        errs.append("rpc snapshot missing")
    hb = doc.get("heartbeat")
    if not isinstance(hb, dict) or "seconds" not in hb \
            or "phases" not in hb:
        errs.append("heartbeat snapshot missing seconds/phases")
    if not isinstance(doc.get("spans"), list):
        errs.append("spans missing (must be a list)")
    wl = doc.get("workload")
    if not isinstance(wl, dict) \
            or not isinstance(wl.get("scenario"), str) \
            or not isinstance(wl.get("brownout"), dict) \
            or not isinstance(wl.get("classes"), dict):
        errs.append("workload context missing "
                    "(scenario/brownout/classes)")
    return errs
