"""Prometheus text exposition (format v0.0.4) over typed snapshots.

The scraper-facing twin of the JSON ``/metrics`` surface: every daemon
serves ``/metrics/prom`` rendering its MetricsSystem's typed snapshot —
counters as ``counter``, numeric gauges as ``gauge`` (one level of
dict-valued composite gauges is flattened to ``name_key``), histograms
as cumulative-``le`` ``_bucket``/``_sum``/``_count`` series. Sources
become a ``{source="..."}`` label so one metric name aggregates across
registries (and, on the master, across the heartbeat-merged ``cluster``
source). Non-numeric gauge values are skipped — the exposition format
has no place for them, and the registry already counts gauge failures
instead of snapshotting poison strings.

``validate_exposition`` is the in-repo format checker the tests and the
CI e2e run against scraped bodies, so a renderer regression fails a
test — not a production Prometheus.
"""

from __future__ import annotations

import re
from typing import Any

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")

#: default metric-name namespace — fixed across daemon types so one
#: dashboard query covers JT, trackers, and the namenode (the daemon
#: identity is the scrape target / instance label, not the name)
NAMESPACE = "tpumr"


def sanitize_name(name: str) -> str:
    """Metric-name charset enforcement: every illegal char becomes
    ``_`` (dots in RPC method names, dashes in tracker names)."""
    out = _SANITIZE.sub("_", str(name))
    if not out or not _NAME_OK.match(out):
        out = "_" + out
    return out


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def split_extra_labels(name: str) -> "tuple[str, tuple]":
    """Registry-name convention for labeled series: a metric registered
    as ``family|k=v[,k2=v2]`` renders as family ``family`` with extra
    labels ``{k="v"}`` next to the standard ``source`` label — how the
    heartbeat phase breakdown ships as one
    ``heartbeat_phase_seconds{phase=...}`` family instead of N
    disconnected names. Plain names pass through untouched."""
    base, sep, rest = str(name).partition("|")
    if not sep:
        return base, ()
    labels = []
    for part in rest.split(","):
        k, eq, v = part.partition("=")
        if eq and k.strip():
            labels.append((sanitize_name(k.strip()), v.strip()))
    return base, tuple(labels)


def _fmt(v: Any) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _flatten_gauges(gauges: dict) -> "dict[str, float]":
    """Numeric gauges, with one level of dict-valued composites
    flattened (``slots`` -> ``slots_cpu`` …); everything else skipped."""
    out: dict[str, float] = {}
    for name, v in gauges.items():
        if _is_num(v):
            out[name] = float(v)
        elif isinstance(v, bool):
            out[name] = float(v)
        elif isinstance(v, dict):
            for k, sub in v.items():
                if _is_num(sub):
                    out[f"{name}_{k}"] = float(sub)
    return out


def render_exposition(typed_snapshot: "dict[str, dict]",
                      namespace: str = NAMESPACE) -> str:
    """Render ``MetricsSystem.typed_snapshot()`` as exposition text.

    Metric families are grouped across sources: the same metric name in
    two registries becomes one ``# TYPE`` block with two ``source``-
    labeled samples. A name claimed with conflicting kinds is qualified
    by its source instead — a valid exposition beats a pretty one.
    """
    # family name -> (kind, [(source, extra-labels, payload)])
    families: "dict[str, tuple[str, list]]" = {}

    def claim(name: str, kind: str, source: str, payload: Any) -> None:
        base, extra = split_extra_labels(name)
        full = f"{namespace}_{sanitize_name(base)}"
        if full in families and families[full][0] != kind:
            full = f"{namespace}_{sanitize_name(source)}_" \
                   f"{sanitize_name(base)}"
            if full in families and families[full][0] != kind:
                return  # still conflicting: drop rather than corrupt
        families.setdefault(full, (kind, []))[1].append(
            (source, extra, payload))

    for source in sorted(typed_snapshot):
        t = typed_snapshot[source] or {}
        for name, v in sorted((t.get("counters") or {}).items()):
            if _is_num(v):
                claim(name, "counter", source, float(v))
        for name, v in sorted(_flatten_gauges(
                t.get("gauges") or {}).items()):
            claim(name, "gauge", source, v)
        for name, h in sorted((t.get("histograms") or {}).items()):
            claim(name, "histogram", source, h)

    lines: list[str] = []
    for full in sorted(families):
        kind, samples = families[full]
        lines.append(f"# HELP {full} tpumr metric {full}")
        lines.append(f"# TYPE {full} {kind}")
        for source, extra, payload in samples:
            label = f'source="{_escape_label(source)}"' + "".join(
                f',{k}="{_escape_label(v)}"' for k, v in extra)
            if kind != "histogram":
                lines.append(f"{full}{{{label}}} {_fmt(payload)}")
                continue
            bounds = list(payload.get("bounds") or [])
            sparse = payload.get("buckets") or {}
            counts = [0] * (len(bounds) + 1)
            for i, c in sparse.items():
                i = int(i)
                if 0 <= i < len(counts):
                    counts[i] = int(c)
            cum = 0
            for i, bound in enumerate(bounds):
                cum += counts[i]
                lines.append(f"{full}_bucket{{{label},"
                             f'le="{_fmt(bound)}"}} {cum}')
            total = int(payload.get("count", cum + counts[-1]))
            lines.append(f'{full}_bucket{{{label},le="+Inf"}} {total}')
            lines.append(f"{full}_sum{{{label}}} "
                         f"{_fmt(payload.get('sum', 0.0))}")
            lines.append(f"{full}_count{{{label}}} {total}")
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------- validator

_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"           # metric name
    r"(?:\{(.*)\})?"                          # optional label set
    r" (-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)|NaN|[+-]Inf)"
    r"(?: -?[0-9]+)?$")                       # optional timestamp
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_KINDS = {"counter", "gauge", "histogram", "summary", "untyped"}


def validate_exposition(text: str) -> None:
    """Raise ``ValueError`` on the first format violation. Checks the
    contract a real Prometheus scrape depends on: parseable samples,
    legal names, TYPE-before-samples, one TYPE per family, and for
    histograms cumulative (non-decreasing) ``le`` buckets ending in a
    ``+Inf`` bucket that equals ``_count``."""
    types: dict[str, str] = {}
    seen_samples: set[str] = set()
    # (family, labelset-ex-le) -> [(le, value)] in line order
    hist_buckets: dict[tuple, list] = {}
    hist_counts: dict[tuple, float] = {}

    def family_of(name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and types.get(base) == "histogram":
                return base
        return name

    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                continue  # free-form comment — legal
            name = parts[2]
            if not _NAME_OK.match(name):
                raise ValueError(f"line {ln}: illegal metric name "
                                 f"{name!r} in {parts[1]}")
            if parts[1] == "TYPE":
                kind = parts[3].strip() if len(parts) > 3 else ""
                if kind not in _KINDS:
                    raise ValueError(f"line {ln}: unknown TYPE {kind!r}")
                if name in types:
                    raise ValueError(f"line {ln}: duplicate TYPE for "
                                     f"{name}")
                if name in seen_samples:
                    raise ValueError(f"line {ln}: TYPE for {name} after "
                                     f"its samples")
                types[name] = kind
            continue
        m = _SAMPLE.match(line)
        if m is None:
            raise ValueError(f"line {ln}: unparseable sample {line!r}")
        name, labels_raw, value = m.group(1), m.group(2) or "", m.group(3)
        labels = dict(_LABEL.findall(labels_raw))
        if labels_raw and _LABEL.sub("", labels_raw).strip(", ") != "":
            raise ValueError(f"line {ln}: malformed labels {labels_raw!r}")
        family = family_of(name)
        if family not in types:
            raise ValueError(f"line {ln}: sample {name} has no # TYPE")
        seen_samples.add(family)
        if types[family] == "histogram":
            key = (family, tuple(sorted((k, v) for k, v in labels.items()
                                        if k != "le")))
            if name == f"{family}_bucket":
                if "le" not in labels:
                    raise ValueError(f"line {ln}: {name} without le label")
                hist_buckets.setdefault(key, []).append(
                    (labels["le"], float(value)))
            elif name == f"{family}_count":
                hist_counts[key] = float(value)
            elif name != f"{family}_sum":
                raise ValueError(f"line {ln}: sample {name} under "
                                 f"histogram family {family}")
        elif name != family:
            raise ValueError(f"line {ln}: sample {name} does not match "
                             f"declared family {family}")

    for (family, labelset), buckets in hist_buckets.items():
        prev = -1.0
        inf = None
        for le, v in buckets:
            if v < prev:
                raise ValueError(
                    f"{family}{dict(labelset)}: bucket le={le} count {v} "
                    f"decreased (not cumulative)")
            prev = v
            if le == "+Inf":
                inf = v
        if inf is None:
            raise ValueError(f"{family}{dict(labelset)}: no +Inf bucket")
        count = hist_counts.get((family, labelset))
        if count is None:
            raise ValueError(f"{family}{dict(labelset)}: no _count sample")
        if count != inf:
            raise ValueError(
                f"{family}{dict(labelset)}: _count {count} != +Inf "
                f"bucket {inf}")
