"""Heartbeat-aggregated cluster metrics — the master-side merge.

Trackers piggyback a compact snapshot of their MetricsSystem on every
heartbeat (``NodeRunner._metrics_piggyback``): cumulative counter values
and cumulative histogram bucket state, numeric gauges by value. The
master folds each tracker's piggyback into ONE ``cluster`` registry, so
a single scrape of the master's ``/metrics/prom`` yields cluster-wide
series (TPU utilization, shuffle fetch percentiles, demotion totals)
without a per-tracker scrape fleet — the Hadoop-era answer was "run
Ganglia next to the cluster"; here the control plane already carries a
periodic all-trackers RPC, so the aggregation rides it.

Cumulative-state-with-derived-increments (not sender-side deltas) is
deliberate: heartbeats are retried and replayed (response-id protocol),
and re-applying a cumulative snapshot is idempotent where re-applying a
delta double-counts. A tracker restart shows as shrunk cumulative values
and is folded as a fresh baseline.
"""

from __future__ import annotations

import threading
from typing import Any

from tpumr.metrics.core import MetricsRegistry
from tpumr.metrics.histogram import typed_delta


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


class ClusterAggregator:
    """Folds per-tracker metric piggybacks into a shared registry.

    Metric naming: the tracker's own source arrives pre-renamed to
    ``tasktracker`` (tracker instance names would explode the cluster
    namespace); other sources prefix their metrics (``shuffle`` →
    ``shuffle_fetch_seconds``) unless the metric already carries the
    prefix (the ``rpc`` source's ``rpc_*`` histograms).
    """

    def __init__(self, registry: MetricsRegistry) -> None:
        self.registry = registry
        # per-tracker state STRIPED by tracker name: merges arrive from
        # every heartbeat handler thread, and one shared lock here was
        # a measurable cross-tracker convoy on the decomposed master
        # (each tracker's baselines are private to it anyway; only the
        # read-side aggregations walk all stripes)
        self._stripes = [threading.Lock() for _ in range(16)]
        #: tracker -> {("c", key): value, ("h", key): typed} baselines
        self._prev: dict[str, dict] = {}
        #: tracker -> {key: value} last-reported numeric gauges
        self._gauges: dict[str, dict[str, float]] = {}

    @staticmethod
    def _key(source: str, name: str) -> str:
        if source == "tasktracker" or name.startswith(source + "_"):
            return name
        return f"{source}_{name}"

    def merge(self, tracker: str, piggyback: "dict | None") -> None:
        """Fold one tracker's heartbeat piggyback. Idempotent per
        snapshot; malformed payloads are dropped whole (a tracker on a
        newer/older build must not corrupt the cluster registry)."""
        if not isinstance(piggyback, dict) or not piggyback:
            return
        try:
            self._merge(tracker, piggyback)
        except Exception:  # noqa: BLE001 — observability must not
            pass           # break heartbeats

    def _stripe(self, tracker: str) -> threading.Lock:
        return self._stripes[hash(tracker) & 15]

    def _merge(self, tracker: str, piggyback: dict) -> None:
        gauges_out: dict[str, float] = {}
        with self._stripe(tracker):
            prev = self._prev.setdefault(tracker, {})
            for source in sorted(piggyback):
                t = piggyback[source]
                if not isinstance(t, dict):
                    continue
                for name, v in (t.get("counters") or {}).items():
                    if not _is_num(v):
                        continue
                    key = self._key(source, name)
                    base = prev.get(("c", key), 0)
                    inc = v - base if v >= base else v  # restart: re-base
                    prev[("c", key)] = v
                    if inc > 0:
                        self.registry.incr(key, inc)
                for name, h in (t.get("histograms") or {}).items():
                    if not isinstance(h, dict):
                        continue
                    key = self._key(source, name)
                    delta = typed_delta(h, prev.get(("h", key)))
                    prev[("h", key)] = h
                    if delta:
                        self.registry.histogram(
                            key, delta.get("bounds") or None
                        ).merge_typed(delta)
                for name, v in (t.get("gauges") or {}).items():
                    key = self._key(source, name)
                    if _is_num(v):
                        gauges_out[key] = float(v)
                    elif isinstance(v, dict):
                        for k, sub in v.items():
                            if _is_num(sub):
                                gauges_out[f"{key}_{k}"] = float(sub)
            self._gauges[tracker] = gauges_out

    def forget(self, tracker: str) -> None:
        """Evicted/expired tracker: drop its baselines and gauge rows
        (already-merged counter/histogram increments stay — they
        happened)."""
        with self._stripe(tracker):
            self._prev.pop(tracker, None)
            self._gauges.pop(tracker, None)

    def gauge_rows(self) -> "dict[str, dict[str, float]]":
        """Per-tracker last-reported numeric gauges (the /cluster page's
        tracker table)."""
        # per-stripe-consistent walk (dict views are GIL-safe; each
        # row is copied under its owner stripe's lock)
        out: "dict[str, dict[str, float]]" = {}
        for t in list(self._gauges):
            with self._stripe(t):
                g = self._gauges.get(t)
                if g is not None:
                    out[t] = dict(g)
        return out

    def gauge_totals(self) -> "dict[str, float]":
        """Summed numeric gauges across live trackers — right for
        count-like gauges (running tasks, quarantined devices); ratio
        gauges are recomputed master-side from slot totals instead."""
        out: dict[str, float] = {}
        for t in list(self._gauges):
            with self._stripe(t):
                g = self._gauges.get(t)
                if g is None:
                    continue
                for k, v in g.items():
                    out[k] = out.get(k, 0.0) + v
        return out
