"""Typed latency/size distributions for the metrics registry.

≈ the metrics2 ``MutableQuantiles``/``MutableStat`` role (reference:
metrics2/lib/MutableQuantiles.java — sampled estimation over a rolling
window), re-designed as fixed exponential-bucket histograms: constant
memory, lock-held O(1) observe, mergeable across processes (bucket
counts add), and directly renderable as Prometheus cumulative-``le``
``_bucket`` series. The paper's hybrid scheduler is profiling-driven;
means hide exactly the tail behavior placement decisions need
(PAPERS.md "It's the Critical Path!"), so distributions — not flat
counters — are the unit of measurement here.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Sequence


def exponential_bounds(base: float, factor: float, count: int) -> "tuple[float, ...]":
    """``count`` upper bounds: base, base*factor, … (the +Inf bucket is
    implicit — every histogram has ``count + 1`` counters)."""
    if base <= 0 or factor <= 1 or count < 1:
        raise ValueError(f"invalid bucket spec ({base}, {factor}, {count})")
    out, b = [], float(base)
    for _ in range(count):
        out.append(b)
        b *= factor
    return tuple(out)


#: default ladder for wall-time observations: 100µs … ~1.7 hours at
#: factor 2 — heartbeat handling, RPC dispatch, shuffle fetches, and
#: whole-task runtimes all land inside it with <2x relative error
SECONDS = exponential_bounds(1e-4, 2.0, 26)

#: default ladder for payload/transfer sizes: 64 B … ~4 GiB at factor 4
BYTES = exponential_bounds(64, 4.0, 13)

#: default ladder for small-integer counts (queue depths, events pending
#: per completion-event poll): 1 … ~1M at factor 2, with the first
#: bucket isolating the healthy "nothing pending" case exactly
COUNTS = exponential_bounds(1, 2.0, 20)


class Histogram:
    """Thread-safe exponential-bucket histogram with count/sum/min/max
    and interpolated percentile estimation.

    Estimation error is bounded by the bucket ratio (``factor``): a
    reported p99 is within one bucket of the true value — plenty for
    "did heartbeat p99 regress 10x", useless noise for "did it regress
    3%", which is the honest trade fixed buckets make.
    """

    __slots__ = ("name", "bounds", "_counts", "count", "sum",
                 "min", "max", "_lock")

    def __init__(self, name: str,
                 bounds: "Sequence[float] | None" = None) -> None:
        self.name = name
        self.bounds: "tuple[float, ...]" = tuple(bounds) if bounds \
            else SECONDS
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError("histogram bounds must be strictly increasing")
        self._counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self.count = 0
        self.sum = 0.0
        self.min = 0.0
        self.max = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum += v
            if self.count == 1 or v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def time(self) -> "Timer":
        """``with hist.time(): ...`` — observe the block's wall time."""
        return Timer(self)

    # -------------------------------------------------------- read side

    def _state(self) -> tuple:
        with self._lock:
            return (list(self._counts), self.count, self.sum,
                    self.min, self.max)

    def percentile(self, q: float, counts: "list[int] | None" = None,
                   count: "int | None" = None) -> float:
        """Estimated q-quantile (q in [0, 1]) by linear interpolation
        inside the bucket holding the target rank; the +Inf bucket
        reports the observed max (the only honest bound we have)."""
        if counts is None or count is None:
            counts, count, _s, _mn, _mx = self._state()
        if count == 0:
            return 0.0
        rank = q * count
        seen = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if seen + c >= rank:
                if i >= len(self.bounds):
                    return self.max
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                frac = (rank - seen) / c
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            seen += c
        return self.max

    def snapshot(self) -> dict:
        """Flat summary for the long-standing ``/metrics`` JSON surface
        (dict-valued like the existing composite gauges)."""
        counts, count, total, mn, mx = self._state()
        if count == 0:
            return {"count": 0, "sum": 0.0, "mean": 0.0, "min": 0.0,
                    "max": 0.0, "p50": 0.0, "p95": 0.0, "p99": 0.0}
        return {
            "count": count, "sum": total, "mean": total / count,
            "min": mn, "max": mx,
            "p50": self.percentile(0.50, counts, count),
            "p95": self.percentile(0.95, counts, count),
            "p99": self.percentile(0.99, counts, count),
        }

    def typed(self) -> dict:
        """Full typed form: sparse cumulative state for sinks that can
        use the distribution itself (Prometheus exposition, the
        heartbeat cluster merge). ``buckets`` is sparse {index: count}
        over ``bounds`` plus index len(bounds) for +Inf — compact on the
        wire, mergeable by addition."""
        counts, count, total, mn, mx = self._state()
        return {
            "bounds": list(self.bounds),
            "buckets": {i: c for i, c in enumerate(counts) if c},
            "count": count, "sum": total, "min": mn, "max": mx,
        }

    def merge_typed(self, delta: dict) -> None:
        """Fold another histogram's (partial) typed state into this one
        — the master-side cluster merge. Bucket ladders must match;
        mismatched deltas are dropped (a tracker running older code must
        not corrupt the cluster distribution)."""
        if list(delta.get("bounds", [])) != list(self.bounds):
            return
        count = int(delta.get("count", 0))
        if count <= 0:
            return
        with self._lock:
            for i, c in (delta.get("buckets") or {}).items():
                i = int(i)
                if 0 <= i < len(self._counts):
                    self._counts[i] += int(c)
            first = self.count == 0
            self.count += count
            self.sum += float(delta.get("sum", 0.0))
            dmin = float(delta.get("min", 0.0))
            dmax = float(delta.get("max", 0.0))
            if first or dmin < self.min:
                self.min = dmin
            if dmax > self.max:
                self.max = dmax


def typed_delta(cur: dict, prev: "dict | None") -> "dict | None":
    """The increment between two cumulative ``Histogram.typed()`` states
    of the SAME histogram (the heartbeat cluster merge: trackers ship
    cumulative state — idempotent under replays — and the master derives
    increments). A shrunk count or changed ladder means the source
    restarted: the full current state is the delta. None = nothing new."""
    if not cur or not cur.get("count"):
        return None
    if prev is None or prev.get("count", 0) > cur["count"] \
            or list(prev.get("bounds", [])) != list(cur.get("bounds", [])):
        return cur
    count = cur["count"] - prev["count"]
    if count <= 0:
        return None
    pb = prev.get("buckets") or {}
    buckets = {}
    for i, c in (cur.get("buckets") or {}).items():
        d = int(c) - int(pb.get(i, pb.get(str(i), 0)))
        if d > 0:
            buckets[i] = d
    return {"bounds": list(cur.get("bounds", [])), "buckets": buckets,
            "count": count,
            "sum": float(cur.get("sum", 0.0)) - float(prev.get("sum", 0.0)),
            # cumulative extrema are correct merge inputs: the cluster
            # min/max folds of per-tracker lifetime min/max
            "min": cur.get("min", 0.0), "max": cur.get("max", 0.0)}


class Timer:
    """Context manager observing a block's wall time into a histogram.
    Monotonic clock — an NTP step mid-block must not record a negative
    (or hour-long) latency. Exceptions still observe: a failing RPC's
    latency is data, not noise."""

    __slots__ = ("hist", "_t0", "elapsed")

    def __init__(self, hist: Histogram) -> None:
        self.hist = hist
        self._t0 = 0.0
        self.elapsed = 0.0

    def __enter__(self) -> "Timer":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.elapsed = time.monotonic() - self._t0
        self.hist.observe(self.elapsed)


def exact_percentiles(values: "Sequence[float]",
                      qs: "Sequence[float]" = (0.50, 0.95, 0.99)) -> dict:
    """Exact quantiles of a finished sample (the per-job rollup path —
    the job kept every task runtime, so no estimation is needed).
    Nearest-rank on the sorted sample; {} for an empty one."""
    if not values:
        return {}
    import math
    s = sorted(float(v) for v in values)
    out = {}
    for q in qs:
        # nearest-rank: the smallest value with at least q of the sample
        # at or below it
        idx = min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))
        out[f"p{int(q * 100)}"] = s[idx]
    out["count"] = len(s)
    out["mean"] = sum(s) / len(s)
    out["max"] = s[-1]
    return out
