"""Typed latency/size distributions for the metrics registry.

≈ the metrics2 ``MutableQuantiles``/``MutableStat`` role (reference:
metrics2/lib/MutableQuantiles.java — sampled estimation over a rolling
window), re-designed as fixed exponential-bucket histograms: constant
memory, lock-held O(1) observe, mergeable across processes (bucket
counts add), and directly renderable as Prometheus cumulative-``le``
``_bucket`` series. The paper's hybrid scheduler is profiling-driven;
means hide exactly the tail behavior placement decisions need
(PAPERS.md "It's the Critical Path!"), so distributions — not flat
counters — are the unit of measurement here.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Sequence


def exponential_bounds(base: float, factor: float, count: int) -> "tuple[float, ...]":
    """``count`` upper bounds: base, base*factor, … (the +Inf bucket is
    implicit — every histogram has ``count + 1`` counters)."""
    if base <= 0 or factor <= 1 or count < 1:
        raise ValueError(f"invalid bucket spec ({base}, {factor}, {count})")
    out, b = [], float(base)
    for _ in range(count):
        out.append(b)
        b *= factor
    return tuple(out)


#: default ladder for wall-time observations: 100µs … ~1.7 hours at
#: factor 2 — heartbeat handling, RPC dispatch, shuffle fetches, and
#: whole-task runtimes all land inside it with <2x relative error
SECONDS = exponential_bounds(1e-4, 2.0, 26)

#: default ladder for payload/transfer sizes: 64 B … ~4 GiB at factor 4
BYTES = exponential_bounds(64, 4.0, 13)

#: default ladder for small-integer counts (queue depths, events pending
#: per completion-event poll): 1 … ~1M at factor 2, with the first
#: bucket isolating the healthy "nothing pending" case exactly
COUNTS = exponential_bounds(1, 2.0, 20)


class Histogram:
    """Thread-safe exponential-bucket histogram with count/sum/min/max
    and interpolated percentile estimation.

    Estimation error is bounded by the bucket ratio (``factor``): a
    reported p99 is within one bucket of the true value — plenty for
    "did heartbeat p99 regress 10x", useless noise for "did it regress
    3%", which is the honest trade fixed buckets make.

    The WRITE path is lock-free: ``observe`` is a single
    ``list.append`` (atomic under the GIL), and pending observations
    fold into the bucket state lazily on the read side (snapshots,
    percentiles, typed exports — all of which drain under the lock).
    The eager-fold original held a mutex for a few field updates, which
    looks harmless until hundreds of handler threads share one hot
    histogram on one core: a holder preempted mid-section (the GIL
    switch interval) convoys EVERY observer behind it — measured as the
    dominant wait on the master's heartbeat path at fleet scale. An
    append can neither be lost nor block, so the hot path has no convoy
    to form. Readers pay the fold cost instead, off the hot path (every
    owning daemon's metrics loop reads at least once per period, which
    also bounds pending growth; a very hot histogram additionally
    self-drains past a high-water mark).
    """

    __slots__ = ("name", "bounds", "_counts", "_count", "_sum",
                 "_min", "_max", "_lock", "_pending")

    #: pending-observation high-water mark: past this, the OBSERVING
    #: thread try-locks and folds (never blocks) so an unread histogram
    #: cannot grow without bound
    PENDING_HWM = 65536

    def __init__(self, name: str,
                 bounds: "Sequence[float] | None" = None) -> None:
        self.name = name
        self.bounds: "tuple[float, ...]" = tuple(bounds) if bounds \
            else SECONDS
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError("histogram bounds must be strictly increasing")
        self._counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self._count = 0
        self._sum = 0.0
        self._min = 0.0
        self._max = 0.0
        self._lock = threading.Lock()
        self._pending: "list[float]" = []

    def observe(self, value: float) -> None:
        p = self._pending
        p.append(float(value))
        if len(p) >= self.PENDING_HWM and self._lock.acquire(False):
            try:
                self._drain_locked()
            finally:
                self._lock.release()

    def _drain_locked(self) -> None:
        """Fold pending observations into the bucket state. Caller
        holds ``_lock``. Concurrent appends are safe: the length is
        snapshotted first, the copied prefix is folded, and the single
        ``del`` of that prefix is one atomic bytecode — late appends
        land past the deleted prefix and survive for the next drain."""
        p = self._pending
        n = len(p)
        if not n:
            return
        vals = p[:n]
        del p[:n]
        bounds, counts = self.bounds, self._counts
        bl = bisect.bisect_left
        count, total = self._count, self._sum
        mn, mx = self._min, self._max
        for v in vals:
            counts[bl(bounds, v)] += 1
            count += 1
            total += v
            if count == 1 or v < mn:
                mn = v
            if v > mx:
                mx = v
        self._count, self._sum = count, total
        self._min, self._max = mn, mx

    # folded totals (drain-on-read so the attributes stay exact)
    @property
    def count(self) -> int:
        with self._lock:
            self._drain_locked()
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            self._drain_locked()
            return self._sum

    @property
    def min(self) -> float:
        with self._lock:
            self._drain_locked()
            return self._min

    @property
    def max(self) -> float:
        with self._lock:
            self._drain_locked()
            return self._max

    def time(self) -> "Timer":
        """``with hist.time(): ...`` — observe the block's wall time."""
        return Timer(self)

    # -------------------------------------------------------- read side

    def _state(self) -> tuple:
        with self._lock:
            self._drain_locked()
            return (list(self._counts), self._count, self._sum,
                    self._min, self._max)

    def percentile(self, q: float, counts: "list[int] | None" = None,
                   count: "int | None" = None) -> float:
        """Estimated q-quantile (q in [0, 1]) by linear interpolation
        inside the bucket holding the target rank; the +Inf bucket
        reports the observed max (the only honest bound we have)."""
        if counts is None or count is None:
            counts, count, _s, _mn, _mx = self._state()
        if count == 0:
            return 0.0
        rank = q * count
        seen = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if seen + c >= rank:
                if i >= len(self.bounds):
                    return self.max
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                frac = (rank - seen) / c
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            seen += c
        return self.max

    def snapshot(self) -> dict:
        """Flat summary for the long-standing ``/metrics`` JSON surface
        (dict-valued like the existing composite gauges)."""
        counts, count, total, mn, mx = self._state()
        if count == 0:
            return {"count": 0, "sum": 0.0, "mean": 0.0, "min": 0.0,
                    "max": 0.0, "p50": 0.0, "p95": 0.0, "p99": 0.0}
        return {
            "count": count, "sum": total, "mean": total / count,
            "min": mn, "max": mx,
            "p50": self.percentile(0.50, counts, count),
            "p95": self.percentile(0.95, counts, count),
            "p99": self.percentile(0.99, counts, count),
        }

    def typed(self) -> dict:
        """Full typed form: sparse cumulative state for sinks that can
        use the distribution itself (Prometheus exposition, the
        heartbeat cluster merge). ``buckets`` is sparse {index: count}
        over ``bounds`` plus index len(bounds) for +Inf — compact on the
        wire, mergeable by addition."""
        counts, count, total, mn, mx = self._state()
        return {
            "bounds": list(self.bounds),
            "buckets": {i: c for i, c in enumerate(counts) if c},
            "count": count, "sum": total, "min": mn, "max": mx,
        }

    def merge_typed(self, delta: dict) -> None:
        """Fold another histogram's (partial) typed state into this one
        — the master-side cluster merge. Bucket ladders must match;
        mismatched deltas are dropped (a tracker running older code must
        not corrupt the cluster distribution)."""
        if list(delta.get("bounds", [])) != list(self.bounds):
            return
        count = int(delta.get("count", 0))
        if count <= 0:
            return
        with self._lock:
            self._drain_locked()
            for i, c in (delta.get("buckets") or {}).items():
                i = int(i)
                if 0 <= i < len(self._counts):
                    self._counts[i] += int(c)
            first = self._count == 0
            self._count += count
            self._sum += float(delta.get("sum", 0.0))
            dmin = float(delta.get("min", 0.0))
            dmax = float(delta.get("max", 0.0))
            if first or dmin < self._min:
                self._min = dmin
            if dmax > self._max:
                self._max = dmax


def typed_delta(cur: dict, prev: "dict | None") -> "dict | None":
    """The increment between two cumulative ``Histogram.typed()`` states
    of the SAME histogram (the heartbeat cluster merge: trackers ship
    cumulative state — idempotent under replays — and the master derives
    increments). A shrunk count or changed ladder means the source
    restarted: the full current state is the delta. None = nothing new."""
    if not cur or not cur.get("count"):
        return None
    if prev is None or prev.get("count", 0) > cur["count"] \
            or list(prev.get("bounds", [])) != list(cur.get("bounds", [])):
        return cur
    count = cur["count"] - prev["count"]
    if count <= 0:
        return None
    pb = prev.get("buckets") or {}
    buckets = {}
    for i, c in (cur.get("buckets") or {}).items():
        d = int(c) - int(pb.get(i, pb.get(str(i), 0)))
        if d > 0:
            buckets[i] = d
    return {"bounds": list(cur.get("bounds", [])), "buckets": buckets,
            "count": count,
            "sum": float(cur.get("sum", 0.0)) - float(prev.get("sum", 0.0)),
            # cumulative extrema are correct merge inputs: the cluster
            # min/max folds of per-tracker lifetime min/max
            "min": cur.get("min", 0.0), "max": cur.get("max", 0.0)}


class Timer:
    """Context manager observing a block's wall time into a histogram.
    Monotonic clock — an NTP step mid-block must not record a negative
    (or hour-long) latency. Exceptions still observe: a failing RPC's
    latency is data, not noise."""

    __slots__ = ("hist", "_t0", "elapsed")

    def __init__(self, hist: Histogram) -> None:
        self.hist = hist
        self._t0 = 0.0
        self.elapsed = 0.0

    def __enter__(self) -> "Timer":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.elapsed = time.monotonic() - self._t0
        self.hist.observe(self.elapsed)


def exact_percentiles(values: "Sequence[float]",
                      qs: "Sequence[float]" = (0.50, 0.95, 0.99)) -> dict:
    """Exact quantiles of a finished sample (the per-job rollup path —
    the job kept every task runtime, so no estimation is needed).
    Nearest-rank on the sorted sample; {} for an empty one."""
    if not values:
        return {}
    import math
    s = sorted(float(v) for v in values)
    out = {}
    for q in qs:
        # nearest-rank: the smallest value with at least q of the sample
        # at or below it
        idx = min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))
        out[f"p{int(q * 100)}"] = s[idx]
    out["count"] = len(s)
    out["mean"] = sum(s) / len(s)
    out["max"] = s[-1]
    return out
