"""Registry, system, and sinks — see package docstring.

≈ metrics2 concepts: MetricsRegistry (metrics2/lib/MetricsRegistry.java),
MetricsSystemImpl (register/start/publish loop), MetricsSink SPI
(metrics2/MetricsSink.java), FileSink (metrics2/sink/FileSink.java).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Protocol


class MetricsRegistry:
    """Thread-safe named counters + gauges for one source."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, Callable[[], Any]] = {}

    def incr(self, name: str, amount: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount

    def set_gauge(self, name: str, fn_or_value: Any) -> None:
        """A callable is sampled at snapshot time; a value is stored."""
        fn = fn_or_value if callable(fn_or_value) else (lambda: fn_or_value)
        with self._lock:
            self._gauges[name] = fn

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            out: dict[str, Any] = dict(self._counters)
            gauges = list(self._gauges.items())
        for name, fn in gauges:
            try:
                out[name] = fn()
            except Exception as e:  # a broken gauge must not kill publish
                out[name] = f"<error: {e}>"
        return out


class MetricsSink(Protocol):
    def put_metrics(self, record: dict) -> None: ...


class FileSink:
    """JSON-lines metrics log ≈ metrics2/sink/FileSink.java.

    Every record is stamped with the writing host and a per-sink
    monotonic sequence number: daemons across a cluster append to
    per-host files that later get concatenated for analysis, and
    wall-clock ``ts`` alone cannot order records across hosts (clock
    skew) or even within one host across a clock step — ``(host, seq)``
    can, and a gap in ``seq`` is a dropped-record tell."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._seq = 0
        import socket
        self._host = socket.gethostname()

    def put_metrics(self, record: dict) -> None:
        with self._lock:
            self._seq += 1
            stamped = {**record, "host": self._host, "seq": self._seq}
            with open(self.path, "a") as f:
                f.write(json.dumps(stamped) + "\n")


class UdpSink:
    """Push metrics to a monitoring daemon over UDP ≈ the GangliaSink
    role (metrics2/sink/ganglia/*) with statsd gauge lines as the
    2026-era wire format: ``<prefix>.<source>.<name>:<value>|g``, one
    datagram per publish (batched, newline-separated). Fire-and-forget:
    a down collector costs nothing."""

    MAX_DATAGRAM = 1400  # stay under typical MTU

    def __init__(self, host: str, port: int) -> None:
        import socket
        self.addr = (host, int(port))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def put_metrics(self, record: dict) -> None:
        prefix = record.get("prefix", "tpumr")
        lines: "list[str]" = []
        for source, metrics in (record.get("sources") or {}).items():
            for name, value in metrics.items():
                if isinstance(value, (int, float)) and not isinstance(
                        value, bool):
                    lines.append(f"{prefix}.{source}.{name}:{value}|g")
        batch = ""
        for line in lines:
            if batch and len(batch) + 1 + len(line) > self.MAX_DATAGRAM:
                self._sock.sendto(batch.encode(), self.addr)
                batch = ""
            batch = f"{batch}\n{line}" if batch else line
        if batch:
            self._sock.sendto(batch.encode(), self.addr)


def sinks_from_conf(conf: Any) -> "list[Any]":
    """Conf-driven sink wiring shared by every daemon:
    ``tpumr.metrics.file`` = JSONL path, ``tpumr.metrics.udp`` =
    host:port for the statsd/Ganglia-role push."""
    sinks: "list[Any]" = []
    path = conf.get("tpumr.metrics.file")
    if path:
        sinks.append(FileSink(str(path)))
    udp = conf.get("tpumr.metrics.udp")
    if udp:
        host, _, port = str(udp).rpartition(":")
        try:
            sinks.append(UdpSink(host or "127.0.0.1", int(port)))
        except (ValueError, OSError):
            # a typo'd observability knob must not kill the daemon —
            # same resilience posture as broken gauges/sinks elsewhere
            import logging
            logging.getLogger("tpumr.metrics").warning(
                "ignoring malformed tpumr.metrics.udp=%r "
                "(expected host:port)", udp)
    return sinks


class MetricsSystem:
    """Holds sources (registries), publishes snapshots to sinks on a
    period, and serves pull-based snapshots (the /json/metrics endpoint)."""

    def __init__(self, prefix: str, period_s: float = 10.0) -> None:
        self.prefix = prefix
        self.period_s = period_s
        self._lock = threading.Lock()
        self._sources: dict[str, MetricsRegistry] = {}
        self._sinks: list[MetricsSink] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def register(self, registry: MetricsRegistry) -> MetricsRegistry:
        with self._lock:
            self._sources[registry.name] = registry
        return registry

    def new_registry(self, name: str) -> MetricsRegistry:
        return self.register(MetricsRegistry(name))

    def add_sink(self, sink: MetricsSink) -> None:
        with self._lock:
            self._sinks.append(sink)

    def snapshot(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            sources = list(self._sources.items())
        return {name: reg.snapshot() for name, reg in sources}

    # ------------------------------------------------------------ publish

    def start(self) -> "MetricsSystem":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name=f"metrics-{self.prefix}",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            has_sinks = bool(self._sinks)
        if has_sinks:
            # final flush so counters bumped since the last period aren't
            # lost (the reference MetricsSystemImpl flushes on stop)
            self.publish_once()

    def publish_once(self) -> None:
        record = {"prefix": self.prefix, "ts": time.time(),
                  "sources": self.snapshot()}
        with self._lock:
            sinks = list(self._sinks)
        for sink in sinks:
            try:
                sink.put_metrics(record)
            except Exception:
                pass  # a broken sink must not kill the publish loop

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            self.publish_once()
