"""Registry, system, and sinks — see package docstring.

≈ metrics2 concepts: MetricsRegistry (metrics2/lib/MetricsRegistry.java),
MetricsSystemImpl (register/start/publish loop), MetricsSink SPI
(metrics2/MetricsSink.java), FileSink (metrics2/sink/FileSink.java).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Protocol, Sequence

from tpumr.metrics.histogram import Histogram

#: counter bumped (in the registry owning the gauge) when a gauge
#: callable raises at sample time — the failure is counted, never
#: snapshotted as a poison string that numeric sinks must dodge
GAUGE_ERRORS = "metrics_gauge_errors"


class MetricsRegistry:
    """Thread-safe named counters + gauges + histograms for one source.

    The counter WRITE path is lock-free (one GIL-atomic list append,
    folded into the counter table lazily on the read side) for the same
    reason ``Histogram.observe`` is: per-beat counters on the master's
    heartbeat path are bumped from hundreds of handler threads, and a
    mutex holder preempted mid-increment convoys all of them on one
    core. Appends can neither be lost nor block; snapshots drain."""

    #: pending-increment high-water mark — past it, the incrementing
    #: thread try-locks and folds (never blocks)
    INCR_HWM = 65536

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._counter_ops: "list[tuple[str, float]]" = []
        self._gauges: dict[str, Callable[[], Any]] = {}
        self._histograms: dict[str, Histogram] = {}

    def incr(self, name: str, amount: float = 1) -> None:
        ops = self._counter_ops
        ops.append((name, amount))
        if len(ops) >= self.INCR_HWM and self._lock.acquire(False):
            try:
                self._drain_locked()
            finally:
                self._lock.release()

    def _drain_locked(self) -> None:
        """Fold pending increments (caller holds ``_lock``). The
        snapshotted-prefix copy + single atomic ``del`` make concurrent
        appends safe — a late append lands past the deleted prefix."""
        ops = self._counter_ops
        n = len(ops)
        if not n:
            return
        batch = ops[:n]
        del ops[:n]
        counters = self._counters
        for name, amount in batch:
            counters[name] = counters.get(name, 0) + amount

    def set_gauge(self, name: str, fn_or_value: Any) -> None:
        """A callable is sampled at snapshot time; a value is stored."""
        fn = fn_or_value if callable(fn_or_value) else (lambda: fn_or_value)
        with self._lock:
            self._gauges[name] = fn

    def histogram(self, name: str,
                  bounds: "Sequence[float] | None" = None) -> Histogram:
        """Get-or-create the named distribution (callers at hot sites
        hoist the returned object; lookups here stay cheap for the lazy
        per-method RPC path). ``bounds`` only applies on creation."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, bounds)
            return h

    def _sample_gauges(self, gauges: "list[tuple[str, Any]]",
                       out: dict, counters: dict) -> None:
        errors = 0
        for name, fn in gauges:
            try:
                out[name] = fn()
            except Exception:  # a broken gauge must not kill publish —
                errors += 1    # counted, not snapshotted as a string
        if errors:
            self.incr(GAUGE_ERRORS, errors)
            with self._lock:   # surface the bump in THIS snapshot too
                self._drain_locked()
                counters[GAUGE_ERRORS] = self._counters[GAUGE_ERRORS]

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            self._drain_locked()
            out: dict[str, Any] = dict(self._counters)
            gauges = list(self._gauges.items())
            hists = list(self._histograms.items())
        self._sample_gauges(gauges, out, out)
        for name, h in hists:
            out[name] = h.snapshot()
        return out

    def typed_snapshot(self) -> dict[str, dict]:
        """Kind-separated view so sinks can tell counters from gauges
        from distributions: ``{"counters": {...}, "gauges": {...},
        "histograms": {name: typed}}``. Histograms ride in their full
        typed (bucketed, mergeable) form."""
        with self._lock:
            self._drain_locked()
            counters = dict(self._counters)
            gauges = list(self._gauges.items())
            hists = list(self._histograms.items())
        sampled: dict[str, Any] = {}
        self._sample_gauges(gauges, sampled, counters)
        return {"counters": counters, "gauges": sampled,
                "histograms": {name: h.typed() for name, h in hists}}


class MetricsSink(Protocol):
    def put_metrics(self, record: dict) -> None: ...


# ---------------------------------------------------------------- process
# Data-plane instrumentation sites (shuffle fetchers, the TPU runner)
# live far below any daemon object, so their registries are process-wide
# singletons. A daemon CLAIMS a registry to publish it: exactly one
# MetricsSystem per process may own each source — co-located trackers
# (mini clusters) would otherwise each piggyback the same process-wide
# cumulative values and the master would double-count the increments.

_process_registries: dict[str, MetricsRegistry] = {}
_process_claims: dict[str, str] = {}
_process_lock = threading.Lock()


def process_registry(name: str) -> MetricsRegistry:
    """The process-wide registry for ``name`` (created on first use) —
    instrumentation sites call this; claiming is the publisher's job."""
    with _process_lock:
        reg = _process_registries.get(name)
        if reg is None:
            reg = _process_registries[name] = MetricsRegistry(name)
        return reg


def claim_process_registry(name: str,
                           owner: str) -> "MetricsRegistry | None":
    """Claim ``name`` for publication by ``owner`` (idempotent per
    owner). Returns the registry, or None when another live owner in
    this process already publishes it."""
    with _process_lock:
        holder = _process_claims.get(name)
        if holder is not None and holder != owner:
            return None
        _process_claims[name] = owner
        reg = _process_registries.get(name)
        if reg is None:
            reg = _process_registries[name] = MetricsRegistry(name)
        return reg


def release_process_registry(name: str, owner: str) -> None:
    """Drop ``owner``'s claim (daemon shutdown) so a later daemon in the
    same process can publish the source."""
    with _process_lock:
        if _process_claims.get(name) == owner:
            del _process_claims[name]


class FileSink:
    """JSON-lines metrics log ≈ metrics2/sink/FileSink.java.

    Every record is stamped with the writing host and a per-sink
    monotonic sequence number: daemons across a cluster append to
    per-host files that later get concatenated for analysis, and
    wall-clock ``ts`` alone cannot order records across hosts (clock
    skew) or even within one host across a clock step — ``(host, seq)``
    can, and a gap in ``seq`` is a dropped-record tell."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._seq = 0
        #: one append handle for the sink's lifetime, flushed per record
        #: — reopening per publish cost an open/close syscall pair every
        #: period on every daemon and made each record a separate dentry
        #: walk; flush (not just close) is what readers actually need
        self._f: Any = None
        import socket
        self._host = socket.gethostname()

    def put_metrics(self, record: dict) -> None:
        with self._lock:
            self._seq += 1
            stamped = {**record, "host": self._host, "seq": self._seq}
            if self._f is None:
                self._f = open(self.path, "a")
            self._f.write(json.dumps(stamped) + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


class UdpSink:
    """Push metrics to a monitoring daemon over UDP ≈ the GangliaSink
    role (metrics2/sink/ganglia/*) with statsd gauge lines as the
    2026-era wire format: ``<prefix>.<source>.<name>:<value>|g``, one
    datagram per publish (batched, newline-separated). Fire-and-forget:
    a down collector costs nothing."""

    MAX_DATAGRAM = 1400  # stay under typical MTU

    def __init__(self, host: str, port: int) -> None:
        import socket
        self.addr = (host, int(port))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def put_metrics(self, record: dict) -> None:
        prefix = record.get("prefix", "tpumr")
        lines: "list[str]" = []
        for source, metrics in (record.get("sources") or {}).items():
            for name, value in metrics.items():
                if isinstance(value, (int, float)) and not isinstance(
                        value, bool):
                    lines.append(f"{prefix}.{source}.{name}:{value}|g")
        batch = ""
        for line in lines:
            if batch and len(batch) + 1 + len(line) > self.MAX_DATAGRAM:
                self._sock.sendto(batch.encode(), self.addr)
                batch = ""
            batch = f"{batch}\n{line}" if batch else line
        if batch:
            self._sock.sendto(batch.encode(), self.addr)


def sinks_from_conf(conf: Any) -> "list[Any]":
    """Conf-driven sink wiring shared by every daemon:
    ``tpumr.metrics.file`` = JSONL path, ``tpumr.metrics.udp`` =
    host:port for the statsd/Ganglia-role push."""
    sinks: "list[Any]" = []
    path = conf.get("tpumr.metrics.file")
    if path:
        sinks.append(FileSink(str(path)))
    udp = conf.get("tpumr.metrics.udp")
    if udp:
        host, _, port = str(udp).rpartition(":")
        try:
            sinks.append(UdpSink(host or "127.0.0.1", int(port)))
        except (ValueError, OSError):
            # a typo'd observability knob must not kill the daemon —
            # same resilience posture as broken gauges/sinks elsewhere
            import logging
            logging.getLogger("tpumr.metrics").warning(
                "ignoring malformed tpumr.metrics.udp=%r "
                "(expected host:port)", udp)
    return sinks


class MetricsSystem:
    """Holds sources (registries), publishes snapshots to sinks on a
    period, and serves pull-based snapshots (the /json/metrics endpoint)."""

    def __init__(self, prefix: str, period_s: float = 10.0) -> None:
        self.prefix = prefix
        self.period_s = period_s
        self._lock = threading.Lock()
        self._sources: dict[str, MetricsRegistry] = {}
        self._sinks: list[MetricsSink] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def register(self, registry: MetricsRegistry) -> MetricsRegistry:
        with self._lock:
            self._sources[registry.name] = registry
        return registry

    def new_registry(self, name: str) -> MetricsRegistry:
        return self.register(MetricsRegistry(name))

    def add_sink(self, sink: MetricsSink) -> None:
        with self._lock:
            self._sinks.append(sink)

    def snapshot(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            sources = list(self._sources.items())
        return {name: reg.snapshot() for name, reg in sources}

    def typed_snapshot(self) -> dict[str, dict]:
        """Every source's kind-separated snapshot — the input shape the
        Prometheus renderer and the heartbeat cluster merge consume."""
        with self._lock:
            sources = list(self._sources.items())
        return {name: reg.typed_snapshot() for name, reg in sources}

    # ------------------------------------------------------------ publish

    def start(self) -> "MetricsSystem":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name=f"metrics-{self.prefix}",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        # join the publish thread so stop() means STOPPED: an orphaned
        # loop mid-publish could interleave with (or outlive) the final
        # flush below and write to sinks the caller is about to close.
        # Bounded join — a sink wedged in I/O must not hang daemon
        # shutdown (the thread is a daemon thread either way).
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        with self._lock:
            sinks = list(self._sinks)
        if sinks:
            # final flush so counters bumped since the last period aren't
            # lost (the reference MetricsSystemImpl flushes on stop)
            self.publish_once()
        for sink in sinks:
            close = getattr(sink, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 — shutdown best-effort
                    pass

    def publish_once(self) -> None:
        record = {"prefix": self.prefix, "ts": time.time(),
                  "sources": self.snapshot()}
        with self._lock:
            sinks = list(self._sinks)
        for sink in sinks:
            try:
                sink.put_metrics(record)
            except Exception:
                pass  # a broken sink must not kill the publish loop

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            self.publish_once()
