"""Instrumented, rank-ordered locking — contention as a first-class
distribution, deadlocks as assertion failures.

The JobTracker began as one process behind one RLock; every heartbeat,
completion-event poll, and status page serialized on it. The reference
never measured that (its global synchronized heartbeat monitor was a
known scaling wall nobody could see coming — SURVEY.md §3.2); here
every master lock is wrapped so wait time (how long callers queue) and
hold time (how long the winner keeps everyone else out) land in
histograms (``jt_lock_wait_seconds{lock=...}`` /
``jt_lock_hold_seconds{lock=...}``). Wait p99 climbing while hold p99
stays flat = more contenders; both climbing = the work under the lock
grew. These are the first series the control-plane scale-out refactor
is judged against (ROADMAP, bench_scale.py).

Since the lock decomposition (PR 8) the master runs on a fixed set of
lock classes with a fixed acquisition order, ascending by rank (the
``namespace*`` classes are the NameNode's — a separate process,
slotted into the one table so tooling sees every ranked lock)::

    tracker-beat(5) -> scheduler(10) -> pipeline(15) -> global(20)
        -> namespace(25) -> namespace-stripe(26) -> namespace-blocks(27)
        -> trackers(30) -> job(40)

The NameNode's three classes mirror the master's decomposition: the
``namespace`` global (25) is held only for cross-stripe structural
ops (rename/delete on shallow paths, fsck, checkpoints), the
``namespace-stripe`` stripes (26) partition the path tree so
same-rank sorted-index multi-acquisition is legal, and
``namespace-blocks`` (27) guards the block/datanode plane (locations,
heartbeats, leases) in short critical sections that never journal.

The ``pipeline`` rank (the DAG engine's state lock) sits below
``global`` because recording a stage submission and reading member-job
outcomes happen while the engine plans — but every BLOCKING part of a
stage submission (split computation, conf hooks, submit_job's history
write) runs outside it: pipeline advancement lives in the heartbeat's
deferred phase, off the fast path, and must stay there.

A thread may acquire a lock only when every lock it already holds has a
rank <= the new lock's (same-lock re-entrancy always allowed). The one
rule worth memorizing: **scheduler -> job, never the reverse** — the
scheduler pass obtains tasks under per-job locks, so a job-lock holder
calling back into the scheduler would deadlock the control plane. The
order is asserted in debug mode: violations raise ``AssertionError``
with both lock names. ``python -O`` or ``TPUMR_LOCK_ORDER_CHECK=0``
disables the check (the bookkeeping is a thread-local list append/pop
per outermost acquire — cheap, but not free).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Any

#: canonical lock ranks (ascending = legal acquisition order). The
#: numbers are spaced so a future lock class can slot between tiers.
RANK_TRACKER_BEAT = 5    # one tracker's heartbeat processing
RANK_SCHEDULER = 10      # scheduler passes (before_heartbeat / assign)
RANK_PIPELINE = 15       # DAG engine state (PipelineInProgress tables)
RANK_COORDINATOR = 18    # sharded-master coordinator tables (job→shard
#                          routing, shard records, merged snapshots) —
#                          its own process; every blocking edge (shard
#                          RPC, Popen, wait) runs OUTSIDE it by rule
RANK_GLOBAL = 20         # job table, commit grants, admin swaps
RANK_NAMESPACE = 25      # the NameNode's structural/global lock (DFS
#                          control plane; its own process — co-held
#                          with no master lock today, ranked so the
#                          analyzer and /threads see it like any
#                          master class)
RANK_NAMESPACE_STRIPE = 26  # NameNode path-tree stripes (acquired in
#                          ascending stripe-index order; equal-rank
#                          multi-acquisition is legal by design)
RANK_NAMESPACE_BLOCKS = 27  # NameNode block/datanode plane (locations,
#                          heartbeats, leases, pending commands) —
#                          short sections, never journals under it
RANK_TRACKERS = 30       # tracker registry stripes
RANK_JOB = 40            # one JobInProgress's task bookkeeping

_ORDER_NAMES = "tracker-beat(5) -> scheduler(10) -> pipeline(15) " \
               "-> coordinator(18) -> global(20) -> namespace(25) " \
               "-> namespace-stripe(26) -> namespace-blocks(27) " \
               "-> trackers(30) -> job(40)"

#: debug-mode ordering assertion: on under ``__debug__`` (plain
#: ``python``), off under ``python -O`` or TPUMR_LOCK_ORDER_CHECK=0
ORDER_CHECK = __debug__ and os.environ.get(
    "TPUMR_LOCK_ORDER_CHECK", "1").lower() not in ("0", "false", "no")

_held = threading.local()


def _held_stack() -> "list[InstrumentedRLock]":
    s = getattr(_held, "stack", None)
    if s is None:
        s = _held.stack = []
    return s


#: every NAMED InstrumentedRLock self-registers here so live-state pages
#: (/threads) and incident bundles can enumerate the master's lock
#: classes without threading a list through every constructor. Weak so
#: per-job locks die with their JobInProgress.
_named_locks: "weakref.WeakSet[InstrumentedRLock]" = weakref.WeakSet()


def lock_table(now: "float | None" = None) -> "list[dict[str, Any]]":
    """Live holder/waiter rows for every named instrumented lock, sorted
    by (rank, name) — the "is it deadlocked right now" view. Lock-free
    read of racy-by-design fields: a row may be a few microseconds
    stale, which is exactly good enough for a human or a postmortem
    bundle (the alternative — taking each lock to report on it — would
    make the reporter a contender)."""
    if now is None:
        now = time.monotonic()
    rows = []
    for lk in list(_named_locks):
        holder = lk._holder          # racy read: grab one reference
        waiters = list(lk._waiters.values())
        rows.append({
            "name": lk.name, "rank": lk.rank,
            "holder": holder[0] if holder else None,
            "held_for_s": round(now - holder[1], 6) if holder else None,
            "waiters": sorted(w[0] for w in waiters),
            "longest_wait_s": round(
                max((now - w[1] for w in waiters), default=0.0), 6),
        })
    rows.sort(key=lambda r: (r["rank"], r["name"]))
    return rows


class InstrumentedRLock:
    """A re-entrant lock recording acquisition wait and outermost hold
    durations into histograms, optionally participating in the master's
    rank-ordered deadlock assertion.

    Drop-in for ``threading.RLock`` at the ``acquire``/``release``/
    context-manager surface. Only the OUTERMOST acquire measures wait
    (a re-entrant acquire by the owner never blocks) and only the
    outermost release records hold — nested ``with`` blocks must not
    turn one hold into N overlapping observations. Histograms may be
    bound after construction (:meth:`bind`) so the lock can exist
    before the metrics registry does.

    Named locks additionally publish LIVE state — who holds me, since
    when, who is queued — via :func:`lock_table` (/threads, incident
    bundles). The bookkeeping is deliberately lock-free: the holder
    field is one GIL-atomic tuple store per outermost acquire/release,
    and only a caller that LOST the uncontended try-acquire ever
    touches the waiter dict, so the uncontended path costs two clock
    reads and never a second lock.
    """

    def __init__(self, wait_hist: Any = None, hold_hist: Any = None,
                 *, name: str = "", rank: int = 0) -> None:
        self._lock = threading.RLock()
        self._wait = wait_hist
        self._hold = hold_hist
        self.name = name
        self.rank = int(rank)
        self._tl = threading.local()
        #: (thread name, monotonic since) of the current outermost
        #: holder, or None — racy by design, read by lock_table()
        self._holder: "tuple[str, float] | None" = None
        #: ident -> (thread name, monotonic since) of blocked acquirers
        self._waiters: "dict[int, tuple[str, float]]" = {}
        if name:
            _named_locks.add(self)

    def bind(self, wait_hist: Any, hold_hist: Any) -> "InstrumentedRLock":
        self._wait = wait_hist
        self._hold = hold_hist
        return self

    def _assert_order(self) -> None:
        stack = _held_stack()
        if not stack:
            return
        # acquisition ranks are enforced ascending, so the top of the
        # held stack is the max held rank
        top = stack[-1]
        if top.rank > self.rank:
            raise AssertionError(
                f"lock-order violation: acquiring "
                f"{self.name or 'lock'} (rank {self.rank}) while "
                f"holding {top.name or 'lock'} (rank {top.rank}); "
                f"the master's order is {_ORDER_NAMES}")

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        depth = getattr(self._tl, "depth", 0)
        if depth:
            # re-entrant: the owner never waits, the hold already runs
            ok = self._lock.acquire(blocking, timeout)
            if ok:
                self._tl.depth = depth + 1
            return ok
        if ORDER_CHECK and self.rank:
            self._assert_order()
        t0 = time.monotonic()
        # uncontended try first: only a caller that LOSES this race
        # registers in the waiter table, so the fast path never mutates
        # shared state beyond the underlying lock itself
        ok = self._lock.acquire(False)
        if not ok:
            if not blocking:
                return False
            ident = threading.get_ident()
            self._waiters[ident] = (threading.current_thread().name, t0)
            try:
                ok = self._lock.acquire(True, timeout)
            finally:
                self._waiters.pop(ident, None)
            if not ok:
                return False
        now = time.monotonic()
        if self._wait is not None:
            self._wait.observe(now - t0)
        self._tl.depth = 1
        self._tl.acquired_at = now
        self._holder = (threading.current_thread().name, now)
        if ORDER_CHECK and self.rank:
            _held_stack().append(self)
        return True

    def release(self) -> None:
        depth = getattr(self._tl, "depth", 0)
        if depth == 1:
            self._holder = None
            if self._hold is not None:
                t0 = getattr(self._tl, "acquired_at", None)
                if t0 is not None:
                    self._hold.observe(time.monotonic() - t0)
            if ORDER_CHECK and self.rank:
                stack = _held_stack()
                if stack and stack[-1] is self:
                    stack.pop()
                else:  # released out of acquisition order — still legal
                    try:
                        stack.remove(self)
                    except ValueError:
                        pass
        if depth:
            self._tl.depth = depth - 1
        self._lock.release()

    def __enter__(self) -> "InstrumentedRLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()
