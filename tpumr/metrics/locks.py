"""Instrumented, rank-ordered locking — contention as a first-class
distribution, deadlocks as assertion failures.

The JobTracker began as one process behind one RLock; every heartbeat,
completion-event poll, and status page serialized on it. The reference
never measured that (its global synchronized heartbeat monitor was a
known scaling wall nobody could see coming — SURVEY.md §3.2); here
every master lock is wrapped so wait time (how long callers queue) and
hold time (how long the winner keeps everyone else out) land in
histograms (``jt_lock_wait_seconds{lock=...}`` /
``jt_lock_hold_seconds{lock=...}``). Wait p99 climbing while hold p99
stays flat = more contenders; both climbing = the work under the lock
grew. These are the first series the control-plane scale-out refactor
is judged against (ROADMAP, bench_scale.py).

Since the lock decomposition (PR 8) the master runs on SIX lock
classes with a fixed acquisition order, ascending by rank::

    tracker-beat(5) -> scheduler(10) -> pipeline(15) -> global(20)
        -> trackers(30) -> job(40)

The ``pipeline`` rank (the DAG engine's state lock) sits below
``global`` because recording a stage submission and reading member-job
outcomes happen while the engine plans — but every BLOCKING part of a
stage submission (split computation, conf hooks, submit_job's history
write) runs outside it: pipeline advancement lives in the heartbeat's
deferred phase, off the fast path, and must stay there.

A thread may acquire a lock only when every lock it already holds has a
rank <= the new lock's (same-lock re-entrancy always allowed). The one
rule worth memorizing: **scheduler -> job, never the reverse** — the
scheduler pass obtains tasks under per-job locks, so a job-lock holder
calling back into the scheduler would deadlock the control plane. The
order is asserted in debug mode: violations raise ``AssertionError``
with both lock names. ``python -O`` or ``TPUMR_LOCK_ORDER_CHECK=0``
disables the check (the bookkeeping is a thread-local list append/pop
per outermost acquire — cheap, but not free).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

#: canonical lock ranks (ascending = legal acquisition order). The
#: numbers are spaced so a future lock class can slot between tiers.
RANK_TRACKER_BEAT = 5    # one tracker's heartbeat processing
RANK_SCHEDULER = 10      # scheduler passes (before_heartbeat / assign)
RANK_PIPELINE = 15       # DAG engine state (PipelineInProgress tables)
RANK_GLOBAL = 20         # job table, commit grants, admin swaps
RANK_TRACKERS = 30       # tracker registry stripes
RANK_JOB = 40            # one JobInProgress's task bookkeeping

_ORDER_NAMES = "tracker-beat(5) -> scheduler(10) -> pipeline(15) " \
               "-> global(20) -> trackers(30) -> job(40)"

#: debug-mode ordering assertion: on under ``__debug__`` (plain
#: ``python``), off under ``python -O`` or TPUMR_LOCK_ORDER_CHECK=0
ORDER_CHECK = __debug__ and os.environ.get(
    "TPUMR_LOCK_ORDER_CHECK", "1").lower() not in ("0", "false", "no")

_held = threading.local()


def _held_stack() -> "list[InstrumentedRLock]":
    s = getattr(_held, "stack", None)
    if s is None:
        s = _held.stack = []
    return s


class InstrumentedRLock:
    """A re-entrant lock recording acquisition wait and outermost hold
    durations into histograms, optionally participating in the master's
    rank-ordered deadlock assertion.

    Drop-in for ``threading.RLock`` at the ``acquire``/``release``/
    context-manager surface. Only the OUTERMOST acquire measures wait
    (a re-entrant acquire by the owner never blocks) and only the
    outermost release records hold — nested ``with`` blocks must not
    turn one hold into N overlapping observations. Histograms may be
    bound after construction (:meth:`bind`) so the lock can exist
    before the metrics registry does; unbound and unranked, it costs
    one thread-local read over a plain RLock (no clock calls).
    """

    def __init__(self, wait_hist: Any = None, hold_hist: Any = None,
                 *, name: str = "", rank: int = 0) -> None:
        self._lock = threading.RLock()
        self._wait = wait_hist
        self._hold = hold_hist
        self.name = name
        self.rank = int(rank)
        self._tl = threading.local()

    def bind(self, wait_hist: Any, hold_hist: Any) -> "InstrumentedRLock":
        self._wait = wait_hist
        self._hold = hold_hist
        return self

    def _assert_order(self) -> None:
        stack = _held_stack()
        if not stack:
            return
        # acquisition ranks are enforced ascending, so the top of the
        # held stack is the max held rank
        top = stack[-1]
        if top.rank > self.rank:
            raise AssertionError(
                f"lock-order violation: acquiring "
                f"{self.name or 'lock'} (rank {self.rank}) while "
                f"holding {top.name or 'lock'} (rank {top.rank}); "
                f"the master's order is {_ORDER_NAMES}")

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        depth = getattr(self._tl, "depth", 0)
        if depth:
            # re-entrant: the owner never waits, the hold already runs
            ok = self._lock.acquire(blocking, timeout)
            if ok:
                self._tl.depth = depth + 1
            return ok
        if ORDER_CHECK and self.rank:
            self._assert_order()
        if self._wait is None:
            ok = self._lock.acquire(blocking, timeout)
            if ok:
                self._tl.depth = 1
                if self._hold is not None:
                    self._tl.acquired_at = time.monotonic()
        else:
            t0 = time.monotonic()
            ok = self._lock.acquire(blocking, timeout)
            if ok:
                now = time.monotonic()
                self._wait.observe(now - t0)
                self._tl.depth = 1
                self._tl.acquired_at = now
        if ok and ORDER_CHECK and self.rank:
            _held_stack().append(self)
        return ok

    def release(self) -> None:
        depth = getattr(self._tl, "depth", 0)
        if depth == 1:
            if self._hold is not None:
                t0 = getattr(self._tl, "acquired_at", None)
                if t0 is not None:
                    self._hold.observe(time.monotonic() - t0)
            if ORDER_CHECK and self.rank:
                stack = _held_stack()
                if stack and stack[-1] is self:
                    stack.pop()
                else:  # released out of acquisition order — still legal
                    try:
                        stack.remove(self)
                    except ValueError:
                        pass
        if depth:
            self._tl.depth = depth - 1
        self._lock.release()

    def __enter__(self) -> "InstrumentedRLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()
