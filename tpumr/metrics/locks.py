"""Instrumented locking — contention as a first-class distribution.

The JobTracker is one process behind one RLock; every heartbeat,
completion-event poll, and status page serializes on it. The reference
never measured that (its global synchronized heartbeat monitor was a
known scaling wall nobody could see coming — SURVEY.md §3.2); here the
master lock is wrapped so wait time (how long callers queue) and hold
time (how long the winner keeps everyone else out) land in histograms
(``jt_lock_wait_seconds`` / ``jt_lock_hold_seconds``). Wait p99 climbing
while hold p99 stays flat = more contenders; both climbing = the work
under the lock grew. These are the first series the control-plane
scale-out refactor is judged against (ROADMAP, bench_scale.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any


class InstrumentedRLock:
    """A re-entrant lock recording acquisition wait and outermost hold
    durations into histograms.

    Drop-in for ``threading.RLock`` at the ``acquire``/``release``/
    context-manager surface. Only the OUTERMOST acquire measures wait
    (a re-entrant acquire by the owner never blocks) and only the
    outermost release records hold — nested ``with`` blocks must not
    turn one hold into N overlapping observations. Histograms may be
    bound after construction (:meth:`bind`) so the lock can exist
    before the metrics registry does; unbound, it costs one thread-local
    read over a plain RLock.
    """

    def __init__(self, wait_hist: Any = None, hold_hist: Any = None) -> None:
        self._lock = threading.RLock()
        self._wait = wait_hist
        self._hold = hold_hist
        self._tl = threading.local()

    def bind(self, wait_hist: Any, hold_hist: Any) -> "InstrumentedRLock":
        self._wait = wait_hist
        self._hold = hold_hist
        return self

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        depth = getattr(self._tl, "depth", 0)
        if depth:
            # re-entrant: the owner never waits, the hold already runs
            ok = self._lock.acquire(blocking, timeout)
            if ok:
                self._tl.depth = depth + 1
            return ok
        t0 = time.monotonic()
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            now = time.monotonic()
            if self._wait is not None:
                self._wait.observe(now - t0)
            self._tl.depth = 1
            self._tl.acquired_at = now
        return ok

    def release(self) -> None:
        depth = getattr(self._tl, "depth", 0)
        if depth == 1 and self._hold is not None:
            self._hold.observe(time.monotonic() - self._tl.acquired_at)
        if depth:
            self._tl.depth = depth - 1
        self._lock.release()

    def __enter__(self) -> "InstrumentedRLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()
