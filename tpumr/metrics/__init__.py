"""Metrics system ≈ the reference's ``metrics2`` framework.

(src/core/org/apache/hadoop/metrics2/impl/MetricsSystemImpl.java: named
sources publish records to sinks on a period; sinks are pluggable —
FileSink, Ganglia.) Here: a registry of counters/gauges per source, a
`MetricsSystem` that snapshots all sources either on demand (the HTTP
``/json/metrics`` endpoint — the MXBean analog) or on a period into
sinks. Backend (CPU vs TPU) placement counts are first-class metrics —
the reference's GPU observability was log-grep only (SURVEY.md §5).
"""

from tpumr.metrics.core import (FileSink, MetricsRegistry, MetricsSystem,
                                UdpSink, sinks_from_conf,
                                MetricsSink)
from tpumr.metrics.flightrec import FlightRecorder, validate_incident
from tpumr.metrics.histogram import (BYTES, SECONDS, Histogram, Timer,
                                     exact_percentiles, exponential_bounds)
from tpumr.metrics.prometheus import render_exposition, validate_exposition
from tpumr.metrics.sampler import (StackSampler, flame_svg, parse_folded,
                                   threads_dump)

__all__ = ["BYTES", "FileSink", "FlightRecorder", "Histogram",
           "MetricsRegistry", "MetricsSink", "MetricsSystem", "SECONDS",
           "StackSampler", "Timer", "UdpSink", "exact_percentiles",
           "exponential_bounds", "flame_svg", "parse_folded",
           "render_exposition", "sinks_from_conf", "threads_dump",
           "validate_exposition", "validate_incident"]
