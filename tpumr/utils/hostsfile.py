"""Include/exclude host list files ≈ the reference's ``HostsFileReader``
(src/core/org/apache/hadoop/util/HostsFileReader.java): one hostname
per line, ``#`` comments, re-read by the refreshNodes admin ops of both
masters (``mapred.hosts[.exclude]`` on the JobTracker,
``dfs.hosts[.exclude]`` on the NameNode)."""

from __future__ import annotations

from typing import Any


def read_hosts_file(path: Any) -> "set[str]":
    """Hostname entries of one file — whitespace-separated tokens, a
    ``#`` token ending its line (the reference HostsFileReader's
    grammar, so ported files parse identically: ``hostA hostB`` and
    ``hostC  # drained 2026-07`` both work). Unreadable files raise (a
    misconfigured admission list must fail loudly, never silently admit
    everyone)."""
    out: "set[str]" = set()
    with open(str(path)) as f:
        for ln in f:
            for tok in ln.split():
                if tok.startswith("#"):
                    break                # comment: rest of line ignored
                out.add(tok)
    return out


def read_hosts_lists(conf: Any, include_key: str,
                     exclude_key: str) -> "tuple[set | None, set]":
    """(include, exclude) from the files named by the two conf keys.
    include=None means no include file → every host may join (the
    reference's semantics: an EMPTY or absent include list admits
    all)."""
    inc_path = conf.get(include_key)
    exc_path = conf.get(exclude_key)
    include = read_hosts_file(inc_path) if inc_path else None
    if include is not None and not include:
        include = None           # empty include file = admit all
    return include, read_hosts_file(exc_path) if exc_path else set()
