"""Class resolution helpers ≈ ``org.apache.hadoop.util.ReflectionUtils``
(reference: src/core/org/apache/hadoop/util/ReflectionUtils.java): turn dotted
class names from configuration into classes and construct configured
instances.
"""

from __future__ import annotations

import importlib
from typing import Any


def resolve_class(name: str) -> type:
    """Resolve 'pkg.mod.Class' or 'pkg.mod.Outer.Inner' to the class object."""
    parts = name.split(".")
    for split in range(len(parts) - 1, 0, -1):
        mod_name = ".".join(parts[:split])
        try:
            mod = importlib.import_module(mod_name)
        except ImportError:
            continue
        obj: Any = mod
        try:
            for attr in parts[split:]:
                obj = getattr(obj, attr)
        except AttributeError:
            continue
        if isinstance(obj, type):
            return obj
        raise TypeError(f"{name} resolved to non-class {obj!r}")
    raise ImportError(f"cannot resolve class {name!r}")


def new_instance(cls: "type | str", conf: Any = None) -> Any:
    """Instantiate, passing conf if the class accepts it (≈
    ReflectionUtils.newInstance + setConf on Configurable)."""
    if isinstance(cls, str):
        cls = resolve_class(cls)
    obj = cls()
    if conf is not None:
        if hasattr(obj, "configure"):       # JobConfigurable.configure
            obj.configure(conf)
        elif hasattr(obj, "set_conf"):      # Configurable.setConf
            obj.set_conf(conf)
    return obj


def class_name(cls: type) -> str:
    return f"{cls.__module__}.{cls.__qualname__}"
