"""Lazy loader for the repo's native tiers (native/<kit>/build/<so>).

One pattern, used by the textkit tokenizer and the tlz codec: build on
first use via the kit's Makefile, serialized against concurrent THREADS
(per-kit lock) and concurrent PROCESSES (flock on a build lockfile —
cc links the .so in place, so an unserialized reader could dlopen a
truncated artifact and silently pin the process to its fallback path).
Returns None when the toolchain is unavailable; callers fall back.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable

_libs: dict[str, Any] = {}      # kit -> CDLL | False (permanent miss)
_lock = threading.Lock()


def repo_native_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "native")


def load_native_lib(kit: str, so_name: str,
                    configure: "Callable[[Any], None] | None" = None):
    """CDLL for ``native/<kit>/build/<so_name>``, building it on first
    use; ``configure`` sets restype/argtypes exactly once."""
    cached = _libs.get(kit)
    if cached is not None:
        return cached or None
    with _lock:
        cached = _libs.get(kit)
        if cached is not None:
            return cached or None
        import ctypes
        kit_dir = os.path.join(repo_native_dir(), kit)
        so = os.path.join(kit_dir, "build", so_name)
        if not os.path.exists(so):
            import fcntl
            import subprocess
            try:
                with open(os.path.join(kit_dir, ".build.lock"),
                          "w") as lf:
                    fcntl.flock(lf, fcntl.LOCK_EX)
                    if not os.path.exists(so):  # lost the build race?
                        r = subprocess.run(["make"], cwd=kit_dir,
                                           capture_output=True,
                                           timeout=60)
                        if r.returncode != 0:
                            _libs[kit] = False
                            return None
            except Exception:  # noqa: BLE001 — no toolchain/locked FS
                _libs[kit] = False
                return None
        try:
            lib = ctypes.CDLL(so)
            if configure is not None:
                configure(lib)
            _libs[kit] = lib
        except OSError:
            _libs[kit] = False
            return None
    return _libs[kit] or None
