from tpumr.utils.reflection import resolve_class, new_instance

__all__ = ["resolve_class", "new_instance"]
