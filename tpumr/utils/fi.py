"""Fault injection ≈ the reference's fi/AspectJ framework.

(src/test/aop/org/apache/hadoop/fi/{FiConfig,ProbabilityModel}.java +
weave targets, SURVEY.md §4.5: probabilistic faults at named join
points.) No bytecode weaving here — seams call ``maybe_fail(point,
conf)`` directly; production cost is one dict lookup returning None.

Config per point:
  tpumr.fi.<point>.probability   fault probability (0 disables, default)
  tpumr.fi.<point>.max.failures  stop injecting after N fires (per
                                 process; 0 = unlimited) — lets tests
                                 fail the first attempt and watch the
                                 retry succeed.
"""

from __future__ import annotations

import random
import threading
from typing import Any

_lock = threading.Lock()
_fired: dict[str, int] = {}


class InjectedFault(RuntimeError):
    """Raised at a join point when the probability model fires."""


def reset() -> None:
    with _lock:
        _fired.clear()


def maybe_fail(point: str, conf: Any = None) -> None:
    """≈ ProbabilityModel.injectCriteria + the woven fault advice."""
    if conf is None:
        return
    p = conf.get(f"tpumr.fi.{point}.probability")
    if not p:
        return
    if random.random() >= float(p):
        return
    limit = int(conf.get(f"tpumr.fi.{point}.max.failures", 0) or 0)
    with _lock:
        if limit and _fired.get(point, 0) >= limit:
            return
        _fired[point] = _fired.get(point, 0) + 1
    raise InjectedFault(f"injected fault at {point}")
