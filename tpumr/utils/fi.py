"""Fault injection ≈ the reference's fi/AspectJ framework.

(src/test/aop/org/apache/hadoop/fi/{FiConfig,ProbabilityModel}.java +
weave targets, SURVEY.md §4.5: probabilistic faults at named join
points.) No bytecode weaving here — seams call ``maybe_fail(point,
conf)`` directly; production cost is one dict lookup returning None.

Config per point:
  tpumr.fi.<point>.probability   fault probability (0 disables, default)
  tpumr.fi.<point>.max.failures  stop injecting after N fires (per
                                 process; 0 = unlimited) — lets tests
                                 fail the first attempt and watch the
                                 retry succeed.
Global:
  tpumr.fi.seed                  seed per-process, PER-POINT RNG streams
                                 so chaos runs replay deterministically
                                 (unset = the global unseeded ``random``
                                 module). A point exercised from one
                                 thread replays bit-identically; points
                                 hit by concurrent threads draw from
                                 their own stream, so other points'
                                 sequences stay reproducible even then.

Shuffle seams (the lost-map-output recovery loop) fire at qualified
point names so one map's output — or one attempt generation — can be
targeted deterministically:
  shuffle.serve / shuffle.serve.m<map_index> / shuffle.serve.a<attempt>
  shuffle.fetch / shuffle.fetch.m<map_index>

Accelerator-fault seams (the TPU→CPU demotion / device-quarantine /
hung-task-reaping loop):
  tpu.compile                    raises classed ``compile`` at dispatch
  tpu.execute / tpu.execute.d<id>  raises classed ``device`` (optionally
                                 targeting one physical device)
  task.hang / task.hang.m<idx>   BEHAVIORAL fault — the task stops
                                 reporting progress forever (drawn via
                                 :func:`fires`, nothing raised); the
                                 tracker's reaper is the quarry's
                                 predator
  task.slow / task.slow.m<idx>   BEHAVIORAL fault — a straggler: the
                                 task stays alive, reporting slowly-
                                 advancing progress for ``tpumr.fi.
                                 task.slow.ms`` before the real work
                                 runs; targeted speculation is the
                                 quarry's predator

Churn seams (the scenario lab's tracker-churn / cold-rejoin chaos
loop):
  tracker.crash / tracker.crash.t<n>  BEHAVIORAL fault — a SimTracker
                                 hard-kills itself mid-beat: the
                                 request may be on the wire but the
                                 response is never read and the socket
                                 just dies, with no deregistration;
                                 the master's eviction sweep plus the
                                 adoption / cold re-registration
                                 rejoin paths are the quarry's
                                 predator

Storage churn seams (the DFS chaos-certification loop — scenario kinds
``dn_crash`` / ``dn_partition`` / ``nn_restart`` / ``block_corrupt``):
  dn.crash / dn.crash.d<n>       BEHAVIORAL fault — a DataNode
                                 hard-kills itself mid-beat (no
                                 deregistration, storage dir survives);
                                 client replica failover, NN expiry and
                                 re-replication are the quarry's
                                 predator
  dn.partition                   BEHAVIORAL fault — heartbeat silence
                                 for ``tpumr.fi.dn.partition.ms``
                                 (default 3000) WITHOUT process death:
                                 reads keep serving while the NN
                                 expires the node; the rejoin rides the
                                 re-register + block report path
  dn.read.corrupt / dn.read.corrupt.b<id>  BEHAVIORAL fault — flips a
                                 byte in the on-disk replica just
                                 before a read serves it; CRC
                                 verification, bad-block reporting and
                                 NN drop-and-re-replicate are the
                                 quarry's predator (readers must never
                                 see the rot)
  nn.crash                       BEHAVIORAL fault — the NameNode dies
                                 SIGKILL-style between monitor sweeps
                                 (no editlog close); restart via
                                 image + editlog replay, safemode
                                 re-entry/exit and clients riding RPC
                                 retries are the quarry's predator

Observability seams (the flight-recorder / continuous-profiler loop):
  jt.heartbeat.slow              BEHAVIORAL fault — master heartbeat
                                 handling stalls ``tpumr.fi.jt.
                                 heartbeat.slow.ms`` (default 400)
                                 before the real fold runs, breaching
                                 the windowed heartbeat p99 SLO; the
                                 flight recorder's incident bundle is
                                 the quarry's predator
  nn.op.slow                     BEHAVIORAL fault — NameNode op
                                 handling stalls ``tpumr.fi.nn.op.
                                 slow.ms`` (default 400) before the
                                 real op runs, breaching the windowed
                                 nn_op_seconds p99 SLO; the NN flight
                                 recorder's incident bundle is the
                                 quarry's predator

Control-plane partition seams (``RpcClient`` with ``fi_conf`` set —
the master-restart / partition-tolerance chaos loop):
  rpc.drop                       the request is lost before the wire
                                 (ConnectionError; exercises the
                                 client retry policy)
  rpc.delay                      the call stalls ``tpumr.fi.rpc.delay.
                                 ms`` (default 100) before sending
  rpc.reset                      the connection resets AFTER the send —
                                 delivery unknown; the resent id must
                                 hit the server's replay cache, never
                                 re-execute
"""

from __future__ import annotations

import random
import threading
from typing import Any

_lock = threading.Lock()
_fired: dict[str, int] = {}
#: per-process seeded RNGs, one per (seed, point) — separate streams per
#: join point so concurrent threads exercising DIFFERENT points can't
#: perturb each other's replay sequence (the determinism contract chaos
#: tests rely on)
_rngs: dict[tuple[str, str], random.Random] = {}


class InjectedFault(RuntimeError):
    """Raised at a join point when the probability model fires."""


def reset() -> None:
    with _lock:
        _fired.clear()
        _rngs.clear()


def _random(point: str, conf: Any) -> float:
    """One draw from the (seed, point) stream when ``tpumr.fi.seed`` is
    set, else the global unseeded module RNG."""
    seed = conf.get("tpumr.fi.seed") if conf is not None else None
    if seed in (None, ""):
        return random.random()
    key = (str(seed), point)
    with _lock:
        rng = _rngs.get(key)
        if rng is None:
            rng = _rngs[key] = random.Random(f"{seed}:{point}")
        return rng.random()


def fired(point: str) -> int:
    """How many times ``point`` has fired in this process (observability
    for chaos tests asserting a fault actually happened)."""
    with _lock:
        return _fired.get(point, 0)


def fires(point: str, conf: Any = None) -> bool:
    """Draw the probability model for ``point`` WITHOUT raising — for
    seams whose fault is behavioral (a hang, a silence) rather than an
    exception. Same config keys, counting, and determinism contract as
    :func:`maybe_fail`."""
    if conf is None:
        return False
    p = conf.get(f"tpumr.fi.{point}.probability")
    if not p:
        return False
    if _random(point, conf) >= float(p):
        return False
    limit = int(conf.get(f"tpumr.fi.{point}.max.failures", 0) or 0)
    with _lock:
        if limit and _fired.get(point, 0) >= limit:
            return False
        _fired[point] = _fired.get(point, 0) + 1
    return True


def maybe_fail(point: str, conf: Any = None,
               failure_class: str = "") -> None:
    """≈ ProbabilityModel.injectCriteria + the woven fault advice.
    ``failure_class`` stamps the raised fault for the accelerator
    failure-classification pipeline (task.classify_exception honors the
    attribute), so a seam can impersonate a device/compile/oom error."""
    if fires(point, conf):
        e = InjectedFault(f"injected fault at {point}")
        if failure_class:
            e.failure_class = failure_class
        raise e
