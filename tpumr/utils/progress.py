"""Device-liveness ticks for external watchdogs.

A tunneled TPU runtime can wedge *inside* an XLA call — the host parks on
a futex with zero CPU and no Python-level timeout can preempt it (seen
live in bench rounds 2–4). A watchdog outside the process can only tell
"slow but alive" from "wedged" if the process leaves a heartbeat at every
completed device transfer. That is what :func:`tick` is: each finished
``device_put`` / ``device_get`` (the tunnel roundtrips) rewrites the file
named by ``TPUMR_DEVICE_PROGRESS_FILE``, so the file's mtime is a
monotone "last proven device roundtrip" clock readable by any supervisor
(``bench.py``'s stall watchdog is the consumer in-tree).

Unset env (the default, and all normal production use) disables ticks
entirely — one dict lookup per transfer, no I/O.

The file is shared by every process of a job tree (tasks inherit the
env); each writer overwrites rather than appends because the watchdog
only reads the mtime — contents are a small debugging aid, not a log.
"""

from __future__ import annotations

import os
import time

_count = 0


def tick(nbytes: int = 0, what: str = "") -> None:
    """Record one completed device transfer (best-effort, never raises)."""
    path = os.environ.get("TPUMR_DEVICE_PROGRESS_FILE")
    if not path:
        return
    global _count
    _count += 1
    try:
        with open(path, "w") as f:
            f.write(f"{os.getpid()} {_count} {nbytes} {what} "
                    f"{time.time():.1f}\n")
    except OSError:
        pass
