"""Network topology ≈ ``org.apache.hadoop.net``."""

from tpumr.net.topology import (DEFAULT_RACK, NetworkTopology,
                                resolver_from_conf)

__all__ = ["DEFAULT_RACK", "NetworkTopology", "resolver_from_conf"]
