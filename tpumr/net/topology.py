"""Rack awareness.

≈ ``org.apache.hadoop.net.NetworkTopology`` + ``DNSToSwitchMapping``
(src/core/org/apache/hadoop/net/, SURVEY.md §2.2): hosts map to racks via
either a static table (``tpumr.topology.map`` = ``host=\\/rack1,host2=\\/rack2``)
or an operator script (``topology.script.file.name`` — invoked with
hostnames, prints one rack per line, the reference's ScriptBasedMapping).
Unresolvable hosts land in ``/default-rack``. Resolutions are cached.
"""

from __future__ import annotations

import subprocess
import threading
from typing import Callable

DEFAULT_RACK = "/default-rack"

Resolver = Callable[[str], str]


def _host_only(name: str) -> str:
    """Strip a :port suffix so tracker/datanode addresses resolve."""
    return name.rsplit(":", 1)[0] if ":" in name else name


def static_resolver(table: dict[str, str]) -> Resolver:
    def resolve(host: str) -> str:
        return table.get(_host_only(host), DEFAULT_RACK)
    return resolve


#: process-wide script-resolution cache — rack mappings are stable, and
#: per-consumer caches would re-exec the script for every job/daemon
_script_cache: dict[tuple[str, str], str] = {}
_script_cache_lock = threading.Lock()


def script_resolver(script: str, timeout_s: float = 30.0) -> Resolver:
    """≈ ScriptBasedMapping: run the script with the hostname, read the
    rack from stdout. Resolutions cache process-wide; still, callers must
    not invoke this while holding a control-plane lock on a cold cache."""

    def resolve(host: str) -> str:
        h = _host_only(host)
        with _script_cache_lock:
            if (script, h) in _script_cache:
                return _script_cache[(script, h)]
        try:
            # argv form, never a shell: host strings come from job
            # submissions and must not be interpretable; shlex keeps
            # interpreter-style configs ("python3 /opt/rack.py") working
            import shlex
            proc = subprocess.run(shlex.split(script) + [h],
                                  capture_output=True, text=True,
                                  timeout=timeout_s)
            rack = (proc.stdout or "").strip().splitlines()
            result = rack[0].strip() if rack else DEFAULT_RACK
        except Exception:  # noqa: BLE001 — resolution failure ≠ crash
            result = DEFAULT_RACK
        with _script_cache_lock:
            _script_cache[(script, h)] = result
        return result

    return resolve


def resolver_from_conf(conf) -> Resolver:
    """Pick the mapping strategy from configuration (static table wins)."""
    if conf is not None:
        table_s = conf.get("tpumr.topology.map")
        if table_s:
            table = {}
            for pair in str(table_s).split(","):
                host, _, rack = pair.partition("=")
                if host.strip() and rack.strip():
                    table[host.strip()] = rack.strip()
            return static_resolver(table)
        script = conf.get("topology.script.file.name")
        if script:
            return script_resolver(str(script))
    return lambda host: DEFAULT_RACK


class NetworkTopology:
    """Rack membership tracking ≈ NetworkTopology.add/getRack — the
    placement-policy input for tdfs and the scheduler's rack-local tier."""

    def __init__(self, resolver: Resolver | None = None) -> None:
        self.resolver = resolver or (lambda host: DEFAULT_RACK)
        self._lock = threading.Lock()
        self._rack_of: dict[str, str] = {}

    def add(self, host: str) -> str:
        rack = self.resolver(host)
        with self._lock:
            self._rack_of[host] = rack
        return rack

    def remove(self, host: str) -> None:
        with self._lock:
            self._rack_of.pop(host, None)

    def rack_of(self, host: str) -> str:
        with self._lock:
            cached = self._rack_of.get(host)
        return cached if cached is not None else self.resolver(host)

    def on_same_rack(self, a: str, b: str) -> bool:
        return self.rack_of(a) == self.rack_of(b)

    def racks(self) -> dict[str, list[str]]:
        out: dict[str, list[str]] = {}
        with self._lock:
            for host, rack in self._rack_of.items():
                out.setdefault(rack, []).append(host)
        return out
