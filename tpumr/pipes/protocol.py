"""Framed binary socket protocol between framework and pipes child.

≈ ``org.apache.hadoop.mapred.pipes.BinaryProtocol`` (reference: src/mapred/
org/apache/hadoop/mapred/pipes/BinaryProtocol.java:50,67-84 — downward codes
START=0..ABORT=9, AUTHENTICATION_REQ=10; upward OUTPUT=50..DONE=54,
REGISTER_COUNTER=55, INCREMENT_COUNTER=56) and the C++ twin
(src/c++/pipes/impl/HadoopPipes.cc:296). The message set and lifecycle are
preserved; the wire format is a clean re-design: unsigned LEB128 varints for
ints/lengths, length-prefixed byte strings, IEEE-754 big-endian doubles —
no Java Writable framing.

Every message: ``varint(code)`` followed by the fields listed next to each
code below. Authentication is a mutual HMAC-SHA1 challenge/response over a
shared per-task secret (≈ the job-token digest handshake,
BinaryProtocol.java:264-299).
"""

from __future__ import annotations

import hashlib
import hmac
import struct
from typing import BinaryIO

from tpumr.io.writable import read_vint, write_vint

PROTOCOL_VERSION = 0

# downward (framework -> child), BinaryProtocol.java:67-78
START = 0                # version:int
SET_JOB_CONF = 1         # n:int, then n*(key:str, value:str)
SET_INPUT_TYPES = 2      # key_type:str, value_type:str
RUN_MAP = 3              # split:bytes, num_reduces:int, piped_input:int
MAP_ITEM = 4             # key:bytes, value:bytes
RUN_REDUCE = 5           # partition:int, piped_output:int
REDUCE_KEY = 6           # key:bytes
REDUCE_VALUE = 7         # value:bytes
CLOSE = 8                # -
ABORT = 9                # -
AUTHENTICATION_REQ = 10  # digest:bytes, challenge:bytes

# upward (child -> framework), BinaryProtocol.java:79-84
OUTPUT = 50               # key:bytes, value:bytes
PARTITIONED_OUTPUT = 51   # partition:int, key:bytes, value:bytes
STATUS = 52               # message:str
PROGRESS = 53             # value:double
DONE = 54                 # -
REGISTER_COUNTER = 55     # id:int, group:str, name:str
INCREMENT_COUNTER = 56    # id:int, amount:int
AUTHENTICATION_RESP = 57  # digest:bytes


# one wire primitive, one implementation: the io layer's unsigned LEB128
write_varint = write_vint
read_varint = read_vint


def write_bytes(out: BinaryIO, data: bytes) -> None:
    write_varint(out, len(data))
    out.write(data)


def read_bytes(inp: BinaryIO) -> bytes:
    n = read_varint(inp)
    data = inp.read(n)
    if len(data) != n:
        raise EOFError("pipes stream closed mid-string")
    return data


def write_str(out: BinaryIO, s: str) -> None:
    write_bytes(out, s.encode("utf-8"))


def read_str(inp: BinaryIO) -> str:
    return read_bytes(inp).decode("utf-8")


def write_double(out: BinaryIO, x: float) -> None:
    out.write(struct.pack(">d", x))


def read_double(inp: BinaryIO) -> float:
    data = inp.read(8)
    if len(data) != 8:
        raise EOFError("pipes stream closed mid-double")
    return struct.unpack(">d", data)[0]


def create_digest(secret: bytes, message: bytes) -> bytes:
    """HMAC-SHA1 hex digest (≈ SecureShuffleUtils.hashFromString used by the
    pipes auth handshake)."""
    return hmac.new(secret, message, hashlib.sha1).hexdigest().encode("ascii")


class DownwardProtocol:
    """Framework side: sends downward messages, used by Application."""

    def __init__(self, out: BinaryIO) -> None:
        self.out = out

    def _code(self, code: int) -> None:
        write_varint(self.out, code)

    def authenticate(self, digest: bytes, challenge: bytes) -> None:
        self._code(AUTHENTICATION_REQ)
        write_bytes(self.out, digest)
        write_bytes(self.out, challenge)
        self.out.flush()

    def start(self) -> None:
        self._code(START)
        write_varint(self.out, PROTOCOL_VERSION)

    def set_job_conf(self, conf_items: dict) -> None:
        self._code(SET_JOB_CONF)
        write_varint(self.out, len(conf_items))
        for k, v in conf_items.items():
            write_str(self.out, str(k))
            write_str(self.out, "" if v is None else str(v))

    def set_input_types(self, key_type: str, value_type: str) -> None:
        self._code(SET_INPUT_TYPES)
        write_str(self.out, key_type)
        write_str(self.out, value_type)

    def run_map(self, split: bytes, num_reduces: int,
                piped_input: bool) -> None:
        self._code(RUN_MAP)
        write_bytes(self.out, split)
        write_varint(self.out, num_reduces)
        write_varint(self.out, int(piped_input))

    def map_item(self, key: bytes, value: bytes) -> None:
        self._code(MAP_ITEM)
        write_bytes(self.out, key)
        write_bytes(self.out, value)

    def run_reduce(self, partition: int, piped_output: bool) -> None:
        self._code(RUN_REDUCE)
        write_varint(self.out, partition)
        write_varint(self.out, int(piped_output))

    def reduce_key(self, key: bytes) -> None:
        self._code(REDUCE_KEY)
        write_bytes(self.out, key)

    def reduce_value(self, value: bytes) -> None:
        self._code(REDUCE_VALUE)
        write_bytes(self.out, value)

    def close(self) -> None:
        self._code(CLOSE)
        self.out.flush()

    def abort(self) -> None:
        self._code(ABORT)
        self.out.flush()

    def flush(self) -> None:
        self.out.flush()
