"""Pipes map runners + reducer — the framework side of external tasks.

≈ ``PipesMapRunner`` / ``PipesGPUMapRunner`` / ``PipesReducer`` /
``PipesPartitioner`` (reference: src/mapred/org/apache/hadoop/mapred/pipes/).
``PipesTPUMapRunner`` is the accelerator twin selected when the task carries
``run_on_tpu`` (≈ PipesGPUMapRunner.java:40-118, chosen at
MapTask.java:433-438): it launches the job's *second* cached executable and
hands it the task's device id — the TPU rename of the CUDA launch path.
"""

from __future__ import annotations

import json
import threading
import zlib
from typing import Any

from tpumr.mapred.api import (MapRunnable, OutputCollector, Partitioner,
                              Reducer, Reporter)
from tpumr.pipes.application import Application, select_executable


def encode(obj: Any) -> bytes:
    """Framework value → child bytes: bytes pass through, everything else is
    its UTF-8 text form (the child sees what a Text writable would carry)."""
    if isinstance(obj, bytes):
        return obj
    return str(obj).encode("utf-8")


def decode(data: bytes) -> Any:
    """Child bytes → framework value: UTF-8 text when possible (so outputs
    stay human-readable through TextOutputFormat), raw bytes otherwise."""
    try:
        return data.decode("utf-8")
    except UnicodeDecodeError:
        return data


def _cache_root(conf: Any) -> str:
    root = conf.get("tpumr.cache.dir")
    if not root:
        import os
        import tempfile
        root = os.path.join(tempfile.gettempdir(), "tpumr-cache")
        conf.set("tpumr.cache.dir", root)
    return root


def _wire_conf_items(conf: Any) -> dict:
    return {k: v for k, v in conf
            if isinstance(v, (str, int, float, bool)) or v is None}


class _ChildPartitionStash(threading.local):
    value: int | None = None


_stash = _ChildPartitionStash()


class PipesPartitioner(Partitioner):
    """≈ pipes/PipesPartitioner.java: when the child computed the partition
    itself (PARTITIONED_OUTPUT), return that cached value; otherwise hash."""

    def get_partition(self, key: Any, value: Any, num_partitions: int) -> int:
        part = _stash.value
        if part is not None:
            _stash.value = None
            return part % num_partitions
        return zlib.crc32(encode(key)) % num_partitions


class _UplinkCollector:
    """Bridges upward OUTPUT/PARTITIONED_OUTPUT into the task's collector
    (≈ pipes/OutputHandler.java)."""

    def __init__(self, output: OutputCollector) -> None:
        self._output = output

    def collect(self, kb: bytes, vb: bytes) -> None:
        self._output.collect(decode(kb), decode(vb))

    def partitioned_collect(self, part: int, kb: bytes, vb: bytes) -> None:
        _stash.value = part
        try:
            self._output.collect(decode(kb), decode(vb))
        finally:
            _stash.value = None


class PipesMapRunner(MapRunnable):
    """Stream the split's records to the CPU child executable
    (≈ pipes/PipesMapRunner.java)."""

    RUN_ON_TPU = False

    def __init__(self) -> None:
        self.conf: Any = None

    def configure(self, conf: Any) -> None:
        self.conf = conf

    def run(self, reader, output, reporter, task_ctx=None) -> None:
        conf = self.conf
        run_on_tpu = self.RUN_ON_TPU or bool(
            getattr(task_ctx, "run_on_tpu", False))
        device = getattr(task_ctx, "tpu_device_id", -1)
        executable = select_executable(conf, _cache_root(conf), run_on_tpu)
        num_reduces = int(conf.get("mapred.reduce.tasks", 1))
        app = Application(conf, executable, _UplinkCollector(output),
                          reporter, run_on_tpu=run_on_tpu,
                          tpu_device_id=device)
        try:
            down = app.downlink
            down.start()
            down.set_job_conf(_wire_conf_items(conf))
            split = getattr(task_ctx, "split", None) or {}
            # non-piped input (≈ Submitter -inputformat / isJavaInput=false,
            # the wordcount-nopipe mode): the child owns the record reader
            # and reads the split itself — no MAP_ITEM frames cross the pipe
            piped = conf.get_boolean("tpumr.pipes.piped.input", True)
            down.run_map(json.dumps(split).encode("utf-8"), num_reduces,
                         piped_input=piped)
            if piped:
                # per-record downlink hot loop ≈ PipesMapRunner.java:97-107
                # — kept for compatibility; the TPU-native path avoids it
                # entirely by running the map as a kernel in-process
                # (tpu_runner)
                for key, value in reader:
                    down.map_item(encode(key), encode(value))
            down.close()
            app.wait_for_finish()
        except Exception:
            app.cleanup(kill=True)
            raise
        finally:
            app.cleanup()


class PipesTPUMapRunner(PipesMapRunner):
    """The accelerator-side runner (≈ PipesGPUMapRunner.java:40-118): same
    record loop, but the child is the job's TPU executable launched with its
    assigned device id as argv[1] (Application.java:162-181)."""

    RUN_ON_TPU = True


class PipesReducer(Reducer):
    """≈ pipes/PipesReducer.java: lazily starts the child on the first key,
    then streams REDUCE_KEY/REDUCE_VALUE frames; DONE/commit on close."""

    def __init__(self) -> None:
        self.conf: Any = None
        self._app: Application | None = None

    def configure(self, conf: Any) -> None:
        self.conf = conf

    def _ensure_app(self, output: OutputCollector,
                    reporter: Reporter) -> Application:
        if self._app is None:
            executable = select_executable(self.conf,
                                           _cache_root(self.conf), False)
            self._app = Application(self.conf, executable,
                                    _UplinkCollector(output), reporter)
            down = self._app.downlink
            down.start()
            down.set_job_conf(_wire_conf_items(self.conf))
            down.run_reduce(0, piped_output=True)
        return self._app

    def reduce(self, key, values, output, reporter) -> None:
        app = self._ensure_app(output, reporter)
        app.downlink.reduce_key(encode(key))
        for v in values:
            app.downlink.reduce_value(encode(v))

    def close(self) -> None:
        if self._app is None:
            return
        try:
            self._app.downlink.close()
            self._app.wait_for_finish()
        finally:
            self._app.cleanup()
            self._app = None
