"""Child-side pipes runtime for Python executables.

The Python twin of the C++ child runtime (native/pipes/tpumr_pipes.cc;
reference C++ API: src/c++/pipes/api/hadoop/Pipes.hh:46-247 — Mapper,
Reducer, Factory, TaskContext — and event loop HadoopPipes.cc:475-546).
A pipes executable is any program that calls :func:`run_task` with a
:class:`Factory`; the framework launches it and speaks the protocol in
``tpumr.pipes.protocol`` over a loopback socket.

An accelerator child receives its device id as ``argv[1]``
(≈ Application.java:178-181) — a JAX child would pin that chip before
compiling its kernels.
"""

from __future__ import annotations

import socket
import sys
from typing import BinaryIO

from tpumr.pipes import protocol as P
from tpumr.pipes.application import ENV_PORT, ENV_SECRET


class JobConf:
    def __init__(self, items: dict | None = None) -> None:
        self._items = items or {}

    def get(self, key: str, default: str | None = None) -> str | None:
        return self._items.get(key, default)

    def get_int(self, key: str, default: int = 0) -> int:
        v = self._items.get(key)
        return int(v) if v not in (None, "") else default

    def has_key(self, key: str) -> bool:
        return key in self._items


class TaskContext:
    """≈ Pipes.hh TaskContext/MapContext/ReduceContext (:46-130)."""

    def __init__(self, up: "_Uplink", conf: JobConf) -> None:
        self._up = up
        self.job_conf = conf
        self.input_key: bytes = b""
        self.input_value: bytes = b""
        self.input_split: bytes = b""
        self.num_reduces = 0
        self._next_counter_id = 0

    def get_job_conf(self) -> JobConf:
        return self.job_conf

    def emit(self, key: bytes | str, value: bytes | str) -> None:
        self._up.output(_b(key), _b(value))

    def partitioned_emit(self, partition: int, key: bytes | str,
                         value: bytes | str) -> None:
        self._up.partitioned_output(partition, _b(key), _b(value))

    def progress(self, value: float) -> None:
        self._up.progress(value)

    def set_status(self, status: str) -> None:
        self._up.status(status)

    def get_counter(self, group: str, name: str) -> int:
        cid = self._next_counter_id
        self._next_counter_id += 1
        self._up.register_counter(cid, group, name)
        return cid

    def increment_counter(self, counter_id: int, amount: int = 1) -> None:
        self._up.increment_counter(counter_id, amount)

    # reduce-side value cursor, filled by the event loop
    def next_value(self) -> bool:
        return self._up.runner.advance_value(self)


def _b(x: bytes | str) -> bytes:
    return x if isinstance(x, bytes) else str(x).encode("utf-8")


class Mapper:
    def map(self, context: TaskContext) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class Reducer:
    def reduce(self, context: TaskContext) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class Factory:
    """≈ Pipes.hh Factory (:232-247)."""

    def create_mapper(self, context: TaskContext) -> Mapper:
        raise NotImplementedError

    def create_reducer(self, context: TaskContext) -> Reducer:
        raise NotImplementedError


class _Uplink:
    def __init__(self, out: BinaryIO, runner: "_TaskRunner") -> None:
        self.out = out
        self.runner = runner

    def output(self, k: bytes, v: bytes) -> None:
        P.write_varint(self.out, P.OUTPUT)
        P.write_bytes(self.out, k)
        P.write_bytes(self.out, v)

    def partitioned_output(self, part: int, k: bytes, v: bytes) -> None:
        P.write_varint(self.out, P.PARTITIONED_OUTPUT)
        P.write_varint(self.out, part)
        P.write_bytes(self.out, k)
        P.write_bytes(self.out, v)

    def status(self, msg: str) -> None:
        P.write_varint(self.out, P.STATUS)
        P.write_str(self.out, msg)
        self.out.flush()

    def progress(self, value: float) -> None:
        P.write_varint(self.out, P.PROGRESS)
        P.write_double(self.out, value)
        self.out.flush()

    def register_counter(self, cid: int, group: str, name: str) -> None:
        P.write_varint(self.out, P.REGISTER_COUNTER)
        P.write_varint(self.out, cid)
        P.write_str(self.out, group)
        P.write_str(self.out, name)

    def increment_counter(self, cid: int, amount: int) -> None:
        P.write_varint(self.out, P.INCREMENT_COUNTER)
        P.write_varint(self.out, cid)
        P.write_varint(self.out, amount)

    def done(self) -> None:
        P.write_varint(self.out, P.DONE)
        self.out.flush()


class _TaskRunner:
    """Child event loop ≈ HadoopPipes.cc:475-546."""

    def __init__(self, factory: Factory, rfile: BinaryIO,
                 wfile: BinaryIO) -> None:
        self.factory = factory
        self.inp = rfile
        self.up = _Uplink(wfile, self)
        self.ctx: TaskContext | None = None
        self.mapper: Mapper | None = None
        self.reducer: Reducer | None = None
        self._pending_key: bytes | None = None
        self._closed = False

    def authenticate(self, secret: bytes) -> None:
        code = P.read_varint(self.inp)
        if code != P.AUTHENTICATION_REQ:
            raise RuntimeError(f"expected auth request, got {code}")
        digest = P.read_bytes(self.inp)
        challenge = P.read_bytes(self.inp)
        if digest != P.create_digest(secret, b"CLIENT-AUTH"):
            raise RuntimeError("framework failed authentication")
        P.write_varint(self.up.out, P.AUTHENTICATION_RESP)
        P.write_bytes(self.up.out, P.create_digest(secret, challenge))
        self.up.out.flush()

    def run(self) -> int:
        conf = JobConf()
        while True:
            code = P.read_varint(self.inp)
            if code == P.START:
                version = P.read_varint(self.inp)
                if version != P.PROTOCOL_VERSION:
                    raise RuntimeError(f"protocol version {version}")
            elif code == P.SET_JOB_CONF:
                n = P.read_varint(self.inp)
                items = {}
                for _ in range(n):
                    k = P.read_str(self.inp)
                    items[k] = P.read_str(self.inp)
                conf = JobConf(items)
            elif code == P.SET_INPUT_TYPES:
                P.read_str(self.inp)
                P.read_str(self.inp)
            elif code == P.RUN_MAP:
                split = P.read_bytes(self.inp)
                nred = P.read_varint(self.inp)
                piped_input = P.read_varint(self.inp)
                self.ctx = TaskContext(self.up, conf)
                self.ctx.input_split = split
                self.ctx.num_reduces = nred
                self.mapper = self.factory.create_mapper(self.ctx)
                if not piped_input:
                    # own-reader mode (tpumr.pipes.piped.input=false): no
                    # MAP_ITEM frames will come — map() runs once over the
                    # whole split, which the mapper reads itself (same
                    # contract as the C++ child / wordcount-nopipe)
                    self.mapper.map(self.ctx)
            elif code == P.MAP_ITEM:
                assert self.mapper is not None and self.ctx is not None
                self.ctx.input_key = P.read_bytes(self.inp)
                self.ctx.input_value = P.read_bytes(self.inp)
                self.mapper.map(self.ctx)
            elif code == P.RUN_REDUCE:
                P.read_varint(self.inp)  # partition
                P.read_varint(self.inp)  # piped output flag
                self.ctx = TaskContext(self.up, conf)
                self.reducer = self.factory.create_reducer(self.ctx)
            elif code == P.REDUCE_KEY:
                assert self.reducer is not None and self.ctx is not None
                key = P.read_bytes(self.inp)
                self._run_reduce_groups(key)
                if self._closed:
                    break
            elif code == P.CLOSE:
                break
            elif code == P.ABORT:
                return 1
            else:
                raise RuntimeError(f"unknown downward code {code}")
        if self.mapper is not None:
            self.mapper.close()
        if self.reducer is not None:
            self.reducer.close()
        self.up.done()
        return 0

    def _run_reduce_groups(self, first_key: bytes) -> None:
        """Drive reduce(ctx) once per key; ctx.next_value() pulls
        REDUCE_VALUE frames off the wire (≈ the C++ context's nextValue)."""
        self._pending_key = first_key
        while self._pending_key is not None and not self._closed:
            assert self.ctx is not None and self.reducer is not None
            self.ctx.input_key = self._pending_key
            self._pending_key = None
            self.reducer.reduce(self.ctx)
            # drain any values the reducer didn't consume
            while self.advance_value(self.ctx):
                pass

    def advance_value(self, ctx: TaskContext) -> bool:
        if self._pending_key is not None or self._closed:
            return False
        code = P.read_varint(self.inp)
        if code == P.REDUCE_VALUE:
            ctx.input_value = P.read_bytes(self.inp)
            return True
        if code == P.REDUCE_KEY:
            self._pending_key = P.read_bytes(self.inp)
            return False
        if code == P.CLOSE:
            self._closed = True
            return False
        raise RuntimeError(f"unexpected code {code} inside reduce")


def run_task(factory: Factory) -> int:
    """Child entry point ≈ HadoopPipes::runTask (Pipes.hh:258)."""
    import os
    port = int(os.environ[ENV_PORT])
    secret = bytes.fromhex(os.environ[ENV_SECRET])
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.connect(("127.0.0.1", port))
    rfile = sock.makefile("rb")
    wfile = sock.makefile("wb")
    try:
        runner = _TaskRunner(factory, rfile, wfile)
        runner.authenticate(secret)
        rc = runner.run()
        wfile.flush()
        return rc
    finally:
        rfile.close()
        wfile.close()
        sock.close()


if __name__ == "__main__":
    sys.exit(0)
