"""Application — spawn and talk to one pipes child process.

≈ ``org.apache.hadoop.mapred.pipes.Application`` (reference: src/mapred/org/
apache/hadoop/mapred/pipes/Application.java:108-215). Reproduced contracts:

- executable selection from the ordered cache list:
  ``localCacheFiles[runOnTPU ? 1 : 0]`` (Application.java:162-172);
- the accelerator task appends its device id as ``argv[1]`` so the child can
  bind the device (Application.java:178-181 — the CUDA child did
  ``cudaSetDevice(argv[1])``; a TPU child pins its chip the same way);
- server-socket handshake: framework listens, child connects back using the
  port from its environment (≈ ``hadoop.pipes.command.port``), then mutual
  HMAC challenge/response (Application.java:138-215,
  BinaryProtocol.java:264-299);
- an upward message pump feeding OutputCollector/Reporter, with
  REGISTER_COUNTER / INCREMENT_COUNTER bridged to real counters
  (OutputHandler role).
"""

from __future__ import annotations

import os
import secrets
import socket
import subprocess
import threading
from typing import Any

from tpumr.pipes import protocol as P

#: child environment variable names (≈ hadoop.pipes.command.port /
#: hadoop.pipes.shared.secret, exported through TaskRunner's child env)
ENV_PORT = "TPUMR_PIPES_COMMAND_PORT"
ENV_SECRET = "TPUMR_PIPES_SHARED_SECRET"


class PipesChildError(RuntimeError):
    pass


class Application:
    """One pipes child process plus its protocol connection."""

    def __init__(self, conf: Any, executable: str, output: Any,
                 reporter: Any, run_on_tpu: bool = False,
                 tpu_device_id: int = -1, keep_child_output: bool = True,
                 connect_timeout: float = 30.0) -> None:
        self.conf = conf
        self.output = output
        self.reporter = reporter
        self.done = threading.Event()
        self.child_error: str | None = None
        self._counters: dict[int, tuple[str, str]] = {}

        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.bind(("127.0.0.1", 0))
        listener.listen(1)
        listener.settimeout(connect_timeout)
        port = listener.getsockname()[1]

        secret = secrets.token_bytes(16)
        self._secret = secret
        env = dict(os.environ)
        env[ENV_PORT] = str(port)
        env[ENV_SECRET] = secret.hex()

        cmd = [executable]
        if run_on_tpu:
            # device id as argv[1] ≈ Application.java:178-181
            cmd.append(str(tpu_device_id))
        stderr = None if keep_child_output else subprocess.DEVNULL
        try:
            self.process = subprocess.Popen(
                cmd, env=env, stdin=subprocess.DEVNULL, stderr=stderr)
        except OSError as e:
            listener.close()
            raise PipesChildError(f"cannot exec {executable}: {e}") from e
        try:
            self.sock, _ = listener.accept()
        except socket.timeout:
            self.process.kill()
            raise PipesChildError(
                f"pipes child {executable} never connected back "
                f"(rc={self.process.poll()})")
        finally:
            listener.close()

        self._rfile = self.sock.makefile("rb")
        self._wfile = self.sock.makefile("wb")
        self.downlink = P.DownwardProtocol(self._wfile)

        # memory-limit enforcement ≈ TaskMemoryManagerThread: register the
        # child with the process-wide manager when a limit is configured
        limit_mb = int(conf.get("mapred.task.limit.maxrss.mb", 0) or 0)
        self._mem_key: str | None = None
        if limit_mb > 0:
            from tpumr.mapred.node_health import GLOBAL_MEMORY_MANAGER
            self._mem_key = (str(conf.get("tpumr.task.attempt.id", ""))
                             or f"pid-{self.process.pid}")
            GLOBAL_MEMORY_MANAGER.register(
                self._mem_key, self.process.pid, limit_mb << 20,
                lambda _aid: self.process.kill())
        try:
            self._authenticate()
        except Exception:
            self.cleanup(kill=True)
            raise
        self._pump = threading.Thread(target=self._uplink_loop,
                                      name="pipes-uplink", daemon=True)
        self._pump.start()

    # ------------------------------------------------------------ handshake

    def _authenticate(self) -> None:
        """Mutual authentication: we prove knowledge of the secret by
        digesting a fixed password message; the child proves it by digesting
        our random challenge (≈ Application.java:138-215)."""
        challenge = secrets.token_hex(10).encode("ascii")
        digest = P.create_digest(self._secret, b"CLIENT-AUTH")
        self.downlink.authenticate(digest, challenge)
        code = P.read_varint(self._rfile)
        if code != P.AUTHENTICATION_RESP:
            raise PipesChildError(f"expected auth response, got code {code}")
        resp = P.read_bytes(self._rfile)
        expect = P.create_digest(self._secret, challenge)
        if resp != expect:
            raise PipesChildError("pipes child failed authentication")

    # ------------------------------------------------------------ uplink

    def _uplink_loop(self) -> None:
        """≈ OutputHandler + BinaryProtocol.UplinkReaderThread."""
        try:
            while True:
                code = P.read_varint(self._rfile)
                if code == P.OUTPUT:
                    k = P.read_bytes(self._rfile)
                    v = P.read_bytes(self._rfile)
                    self.output.collect(k, v)
                elif code == P.PARTITIONED_OUTPUT:
                    part = P.read_varint(self._rfile)
                    k = P.read_bytes(self._rfile)
                    v = P.read_bytes(self._rfile)
                    self.output.partitioned_collect(part, k, v)
                elif code == P.STATUS:
                    self.reporter.set_status(P.read_str(self._rfile))
                elif code == P.PROGRESS:
                    self.reporter.progress(P.read_double(self._rfile))
                elif code == P.REGISTER_COUNTER:
                    cid = P.read_varint(self._rfile)
                    group = P.read_str(self._rfile)
                    name = P.read_str(self._rfile)
                    self._counters[cid] = (group, name)
                elif code == P.INCREMENT_COUNTER:
                    cid = P.read_varint(self._rfile)
                    amount = P.read_varint(self._rfile)
                    group, name = self._counters.get(
                        cid, ("Pipes", f"counter-{cid}"))
                    self.reporter.incr_counter(group, name, amount)
                elif code == P.DONE:
                    self.done.set()
                    return
                else:
                    raise PipesChildError(f"unknown upward code {code}")
        except (EOFError, OSError) as e:
            if not self.done.is_set():
                self.child_error = f"pipes child died mid-task: {e}"
                self.done.set()
        except Exception as e:  # noqa: BLE001 — protocol or collector error:
            # the pump must never die silently or wait_for_finish blocks
            # until the task timeout with the real cause lost
            if not self.done.is_set():
                self.child_error = f"pipes uplink failed: " \
                                   f"{type(e).__name__}: {e}"
                self.done.set()

    # ------------------------------------------------------------ lifecycle

    def wait_for_finish(self, timeout: float | None = None) -> None:
        conf_timeout = None
        if timeout is None and self.conf is not None:
            ms = int(self.conf.get("mapred.task.timeout", 600_000) or 0)
            conf_timeout = ms / 1000.0 if ms > 0 else None
        if not self.done.wait(timeout if timeout is not None
                              else conf_timeout):
            self.abort()
            raise PipesChildError("pipes child timed out")
        if self.child_error:
            self.cleanup(kill=True)
            raise PipesChildError(self.child_error)
        rc = self.process.wait(timeout=30)
        if rc != 0:
            raise PipesChildError(f"pipes child exited rc={rc}")

    def abort(self) -> None:
        try:
            self.downlink.abort()
        except OSError:
            pass
        self.cleanup(kill=True)

    def cleanup(self, kill: bool = False) -> None:
        if self._mem_key is not None:
            from tpumr.mapred.node_health import GLOBAL_MEMORY_MANAGER
            GLOBAL_MEMORY_MANAGER.unregister(self._mem_key)
            self._mem_key = None
        if kill and self.process.poll() is None:
            self.process.kill()
        try:
            self._rfile.close()
            self._wfile.close()
            self.sock.close()
        except OSError:
            pass


def select_executable(conf: Any, cache_root: str, run_on_tpu: bool) -> str:
    """The dual-executable pick: localized cache list index 1 for the
    accelerator, 0 for CPU (Application.java:162-172). Falls back to slot 0
    when the job shipped only one binary."""
    from tpumr.mapred import filecache
    files = filecache.get_local_cache_files(
        conf, cache_root, job_id=str(conf.get("tpumr.job.id", "") or ""))
    if not files:
        raise PipesChildError("pipes job has no cached executables")
    idx = 1 if run_on_tpu and len(files) > 1 else 0
    return files[idx]
