"""Pipes job submission — dual CPU/TPU executables.

≈ ``org.apache.hadoop.mapred.pipes.Submitter`` (reference: src/mapred/org/
apache/hadoop/mapred/pipes/Submitter.java). Reproduced contracts:

- conf keys ``tpumr.pipes.executable`` / ``tpumr.pipes.tpu.executable``
  (≈ ``hadoop.pipes.executable`` :104 / ``hadoop.pipes.gpu.executable``
  :110-119 — the key the hybrid scheduler gates accelerator eligibility on,
  JobQueueTaskScheduler.java:342-347);
- cache layout: CPU binary at slot 0, TPU binary at slot 1
  (setupPipesJob, Submitter.java:349-379);
- CLI: ``-program`` / ``-tpubin`` (≈ ``-gpubin`` :527-528) / ``-input`` /
  ``-output`` / ``-reduces`` / ``-jobconf``.
"""

from __future__ import annotations

import os
from typing import Any

from tpumr.mapred import filecache
from tpumr.mapred.jobconf import JobConf

EXECUTABLE_KEY = "tpumr.pipes.executable"
TPU_EXECUTABLE_KEY = "tpumr.pipes.tpu.executable"


class Submitter:
    @staticmethod
    def set_executable(conf: Any, path: str) -> None:
        conf.set(EXECUTABLE_KEY, path)

    @staticmethod
    def get_executable(conf: Any) -> str | None:
        return conf.get(EXECUTABLE_KEY)

    @staticmethod
    def set_tpu_executable(conf: Any, path: str) -> None:
        """≈ Submitter.setGPUExecutable (Submitter.java:110-119)."""
        conf.set(TPU_EXECUTABLE_KEY, path)

    @staticmethod
    def get_tpu_executable(conf: Any) -> str | None:
        return conf.get(TPU_EXECUTABLE_KEY)

    @staticmethod
    def run_job(conf: JobConf):
        setup_pipes_job(conf)
        from tpumr.mapred.job_client import JobClient
        return JobClient(conf).run_job(conf)


def setup_pipes_job(conf: JobConf) -> None:
    """Wire runners + cache the executables in slot order
    (≈ Submitter.setupPipesJob, Submitter.java:291-380)."""
    from tpumr.pipes.runner import (PipesMapRunner, PipesPartitioner,
                                    PipesReducer, PipesTPUMapRunner)
    cpu_bin = Submitter.get_executable(conf)
    tpu_bin = Submitter.get_tpu_executable(conf)
    if not cpu_bin:
        raise ValueError(f"pipes job needs {EXECUTABLE_KEY}")
    if not os.path.exists(cpu_bin):
        raise FileNotFoundError(cpu_bin)

    conf.set_map_runner_class(PipesMapRunner)
    conf.set_tpu_map_runner_class(PipesTPUMapRunner)
    if conf.get_reducer_class() is None and conf.num_reduce_tasks > 0:
        conf.set_reducer_class(PipesReducer)
    if conf.get("mapred.partitioner.class") is None:
        conf.set_partitioner_class(PipesPartitioner)

    # ordered cache: CPU at 0, TPU at 1 (Submitter.java:349-379)
    if not conf.get(filecache.CACHE_FILES_KEY):
        filecache.add_cache_file(conf, cpu_bin, link="pipes-cpu-bin",
                                 executable=True)
        if tpu_bin:
            if not os.path.exists(tpu_bin):
                raise FileNotFoundError(tpu_bin)
            filecache.add_cache_file(conf, tpu_bin, link="pipes-tpu-bin",
                                     executable=True)


def main(argv: list[str]) -> int:
    """CLI ≈ Submitter.main (Submitter.java:420-540)."""
    import argparse
    ap = argparse.ArgumentParser(prog="tpumr pipes")
    ap.add_argument("-input", dest="input", required=True)
    ap.add_argument("-output", dest="output", required=True)
    ap.add_argument("-program", dest="program", required=True,
                    help="CPU executable")
    ap.add_argument("-tpubin", dest="tpubin", default=None,
                    help="TPU executable (≈ -gpubin)")
    ap.add_argument("-reduces", dest="reduces", type=int, default=1)
    ap.add_argument("-jobconf", dest="jobconf", action="append", default=[],
                    help="k=v[,k=v...]")
    args = ap.parse_args(argv)

    conf = JobConf()
    conf.set_input_paths(*args.input.split(","))
    conf.set_output_path(args.output)
    conf.set_num_reduce_tasks(args.reduces)
    Submitter.set_executable(conf, args.program)
    if args.tpubin:
        Submitter.set_tpu_executable(conf, args.tpubin)
    for chunk in args.jobconf:
        for kv in chunk.split(","):
            k, _, v = kv.partition("=")
            conf.set(k.strip(), v.strip())
    result = Submitter.run_job(conf)
    return 0 if result.successful else 1
