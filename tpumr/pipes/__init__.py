"""External-process task tier ("pipes").

≈ the reference's pipes mechanism (src/mapred/org/apache/hadoop/mapred/
pipes/, 2210 LoC Java + src/c++/pipes, 1.7k C++): user-supplied binaries run
map/reduce logic in a child process speaking a framed binary protocol over a
loopback socket, with *dual* CPU/accelerator executables selected per task —
the path the reference uses to reach CUDA, kept here as the
bring-your-own-binary compatibility tier next to the in-process JAX/Pallas
map runner (tpumr.mapred.tpu_runner), which is the TPU-native replacement.
"""

from tpumr.pipes.application import Application
from tpumr.pipes.runner import (PipesMapRunner, PipesReducer,
                                PipesTPUMapRunner)
from tpumr.pipes.submitter import Submitter, setup_pipes_job

__all__ = ["Application", "PipesMapRunner", "PipesTPUMapRunner",
           "PipesReducer", "Submitter", "setup_pipes_job"]
