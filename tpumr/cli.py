"""``tpumr`` — the framework's command-line entry point.

≈ the reference's ``bin/hadoop`` dispatch script (bin/hadoop:66-95): one
command name selects a daemon, a client tool, or a user program. Generic
options (≈ GenericOptionsParser, src/core/.../util/GenericOptionsParser.java)
come before the subcommand's own arguments: ``-D k=v``, ``-fs <uri>``,
``-jt <host:port|local>``.

Daemon commands run in the foreground until SIGINT (process supervision is
the operator's problem, as with the reference's hadoop-daemon.sh).
"""

from __future__ import annotations

import json
import signal
import os
import sys
import threading
import time
from typing import Any

USAGE = """\
Usage: tpumr [generic options] COMMAND [args]
Generic options: -D k=v  -conf FILE  -fs <default-fs-uri>  -jt <host:port|local>
Site config: $TPUMR_CONF_DIR/tpumr-site.{toml,json} loads automatically
(precedence: defaults < site file < -conf files < -D/-fs/-jt)

Daemons:
  namenode -dir DIR [-host H] [-port P]      run the tdfs NameNode
  datanode -nn HOST:PORT -dir DIR            run a tdfs DataNode
  secondarynamenode -nn HOST:PORT -dir DIR   periodic checkpoint daemon
  jobtracker [-host H] [-port P]             run the JobMaster
  tasktracker -jt HOST:PORT                  run a NodeRunner (worker)
  historyserver -dir DIR [-port P]           serve completed-job history

Clients:
  fs -CMD ...          filesystem shell (tpumr fs -help for commands)
  job ...              job control: -list | -status ID | -kill ID | -counters ID
                       offline: -history ID [DIR] | -diagnose ID [DIR] (vaidya)
                       tracing: trace ID [-out FILE] [-dir DIR] (Chrome trace
                       + critical path; needs tpumr.trace.enabled at submit)
  balancer -nn HOST:PORT                     rebalance tdfs blocks
  fsck [PATH]          tdfs health report (missing/under-replicated blocks)
  dfsadmin ...         quotas, decommissioning, safemode, cluster report
  pipes ...            submit an external-binary (pipes) job
  streaming ...        submit a script (streaming) job
  examples NAME ...    run an example program (examples -h lists them)
  distcp SRC DST       distributed copy (any scheme to any scheme)
  archive SRC DEST.tharch | archive -ls ARCH   pack/list archives
  rumen HISTORY_DIR    extract job traces from history
  failmon -collect|-merge   node failure monitoring (collect/upload/merge)
  gridmix [--scale S]  synthetic mixed-workload benchmark
  simulate [-trackers N] [-jobs J] [-maps M] [-reduces R] [-interval MS]
                       [-task-ms MEAN] [-timeout S] [-ff-rate P]
                       control-plane scale harness: a simulated tracker
                       fleet driving real heartbeat/RPC paths against
                       the -jt master (or a self-hosted one)
  keys SUBCMD          credentials: user-key USER | token [-nn] [-renewer R]
                       [-out FILE] | renew FILE | cancel FILE
  fetchdt TOKEN_FILE   fetch a NameNode delegation token (= keys token -nn)
  pipeline ...         DAG-of-jobs pipelines: submit GRAPH.json [-wait] |
                       status ID | -list | -kill ID | trace ID [-out FILE]
  queue ...            queue info: -list | -info Q [-showJobs] | -showacls
  mradmin -refreshQueues|-refreshNodes   live-reload queue ACLs / host lists
  daemonlog ...        -getlevel H:P LOGGER | -setlevel H:P LOGGER LEVEL
  prof HOST:PORT [-seconds N] [-out FILE] [-flame]
                       pull folded stacks (or -flame SVG) off a live
                       daemon's continuous sampler (tpumr.prof.enabled)
  rcc FILE.jr ...      compile Record I/O DDL to record classes (= bin/rcc)
  tdfsproxy -port P    read-only HTTP(S) storage gateway (= hdfsproxy)
  lint [--json FILE] [--rules R,..] [--conf-doc [FILE]] [--list-keys]
                       repo-native static analyzer (lock discipline,
                       config-key registry, clock discipline, docs
                       drift); exit 0 = clean. --conf-doc regenerates
                       docs/CONFIG.md from tpumr/core/confkeys.py
  version              print the version
"""

from tpumr import __version__ as VERSION


def _parse_generic(argv: list[str]) \
        -> tuple[dict[str, Any], list[str], list[str]]:
    """Strip leading generic options; return (overrides, conf_files,
    rest). ``-conf FILE`` ≈ GenericOptionsParser's -conf: an extra
    site-file resource layered below -D overrides."""
    over: dict[str, Any] = {}
    conf_files: list[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "-D" and i + 1 < len(argv):
            k, _, v = argv[i + 1].partition("=")
            over[k.strip()] = v.strip()
            i += 2
        elif a.startswith("-D") and "=" in a:
            k, _, v = a[2:].partition("=")
            over[k.strip()] = v.strip()
            i += 1
        elif a == "-conf" and i + 1 < len(argv):
            conf_files.append(argv[i + 1])
            i += 2
        elif a == "-fs" and i + 1 < len(argv):
            over["fs.default.name"] = argv[i + 1]
            i += 2
        elif a == "-jt" and i + 1 < len(argv):
            over["mapred.job.tracker"] = argv[i + 1]
            i += 2
        else:
            break
    return over, conf_files, argv[i:]


def _site_files(conf_files: list[str]) -> list[str]:
    """Resource files for this invocation, lowest precedence first:
    ``$TPUMR_CONF_DIR/tpumr-site.{toml,json}`` (≈ HADOOP_CONF_DIR's
    *-site.xml auto-loading), then explicit ``-conf`` files in order.
    A configured-but-missing conf dir site file is fine (the reference
    tolerates absent site files); an explicit -conf that is missing is
    an error the Configuration loader raises."""
    out: list[str] = []
    conf_dir = os.environ.get("TPUMR_CONF_DIR")
    if conf_dir:
        for name in ("tpumr-site.toml", "tpumr-site.json"):
            p = os.path.join(conf_dir, name)
            if os.path.exists(p):
                out.append(p)
    out.extend(conf_files)
    return out


def _conf(overrides: dict[str, Any]):
    from tpumr.mapred.jobconf import JobConf
    conf = JobConf()
    for k, v in overrides.items():
        conf.set(k, v)
    return conf


def _serve_forever(stop) -> int:
    """Block until SIGINT/SIGTERM, then stop() the daemon."""
    done = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: done.set())
        except ValueError:  # non-main thread (tests)
            pass
    try:
        while not done.is_set():
            time.sleep(0.5)
    finally:
        stop()
    return 0


def _kv_args(argv: list[str]) -> dict[str, str]:
    """Parse '-name value' pairs of the daemon commands."""
    out: dict[str, str] = {}
    i = 0
    while i < len(argv):
        if argv[i].startswith("-") and i + 1 < len(argv):
            out[argv[i].lstrip("-")] = argv[i + 1]
            i += 2
        else:
            raise SystemExit(f"unexpected argument: {argv[i]}")
    return out


def _host_port(s: str) -> tuple[str, int]:
    host, _, port = s.partition(":")
    return host or "127.0.0.1", int(port)


# ------------------------------------------------------------------ daemons


def cmd_namenode(conf, argv: list[str]) -> int:
    from tpumr.dfs.namenode import NameNode
    a = _kv_args(argv)
    nn = NameNode(a.get("dir", "/tmp/tpumr-name"), conf,
                  host=a.get("host", "127.0.0.1"),
                  port=int(a.get("port", 9000))).start()
    host, port = nn.address
    print(f"NameNode up at tdfs://{host}:{port}/", file=sys.stderr)
    return _serve_forever(nn.stop)


def cmd_datanode(conf, argv: list[str]) -> int:
    from tpumr.dfs.datanode import DataNode
    a = _kv_args(argv)
    host, port = _host_port(a["nn"])
    conf.set("tdfs.datanode.capacity", int(a.get("capacity", 1 << 34)))
    dn = DataNode(host, port, a.get("dir", "/tmp/tpumr-data"),
                  conf).start()
    print(f"DataNode up ({dn.addr}), reporting to {a['nn']}", file=sys.stderr)
    return _serve_forever(dn.stop)


def cmd_secondarynamenode(conf, argv: list[str]) -> int:
    from tpumr.dfs.secondary import SecondaryNameNode
    a = _kv_args(argv)
    host, port = _host_port(a["nn"])
    if "interval" in a:
        conf.set("fs.checkpoint.period", a["interval"])
    snn = SecondaryNameNode(host, port, a.get("dir", "/tmp/tpumr-secondary"),
                            conf=conf).start()
    print(f"SecondaryNameNode up, checkpointing {a['nn']}", file=sys.stderr)
    return _serve_forever(snn.stop)


def cmd_jobtracker(conf, argv: list[str]) -> int:
    from tpumr.mapred.jobtracker import JobMaster
    a = _kv_args(argv)
    jm = JobMaster(conf, host=a.get("host", "127.0.0.1"),
                   port=int(a.get("port", 9001))).start()
    host, port = jm.address
    print(f"JobMaster up at {host}:{port}", file=sys.stderr)
    return _serve_forever(jm.stop)


def cmd_tasktracker(conf, argv: list[str]) -> int:
    from tpumr.mapred.tasktracker import NodeRunner
    a = _kv_args(argv)
    jt = a.get("jt") or conf.get("mapred.job.tracker")
    if not jt or jt == "local" or ":" not in jt:
        print("tasktracker needs -jt HOST:PORT", file=sys.stderr)
        return 255
    host, port = _host_port(jt)
    nr = NodeRunner(host, port, conf).start()
    print(f"NodeRunner up, heartbeating to {host}:{port}", file=sys.stderr)
    return _serve_forever(nr.stop)


def cmd_historyserver(conf, argv: list[str]) -> int:
    from tpumr.mapred.history_server import JobHistoryServer
    a = _kv_args(argv)
    hs = JobHistoryServer(a.get("dir")
                          or conf.get("tpumr.history.dir")
                          or "/tmp/tpumr-history",
                          port=int(a.get("port", 9888)),
                          conf=conf).start()
    print(f"JobHistoryServer up at {hs.url}", file=sys.stderr)
    return _serve_forever(hs.stop)


def cmd_balancer(conf, argv: list[str]) -> int:
    from tpumr.dfs.balancer import Balancer
    a = _kv_args(argv)
    host, port = _host_port(a["nn"])
    moved = Balancer(host, port,
                     threshold=float(a.get("threshold", 0.1)),
                     conf=conf).balance()
    print(f"Balancer moved {moved} blocks")
    return 0


# ------------------------------------------------------------------ clients


def cmd_fs(conf, argv: list[str]) -> int:
    from tpumr.fs.shell import FsShell
    default_fs = conf.get("fs.default.name")
    return FsShell(conf, default_fs=default_fs).run(argv)


def cmd_job(conf, argv: list[str]) -> int:
    """≈ bin/hadoop job: -list, -status, -kill, -counters, -history."""
    from tpumr.ipc.rpc import RpcClient, RpcError
    if argv and argv[0] == "-history":
        # offline: reads the history dir directly (≈ HistoryViewer) — no
        # live master needed
        return _job_history(conf, argv[1:])
    if argv and argv[0] == "-diagnose":
        return _job_diagnose(conf, argv[1:])
    if argv and argv[0] in ("trace", "-trace"):
        return _job_trace(conf, argv[1:])
    if argv and argv[0] in ("stats", "-stats"):
        return _job_stats(conf, argv[1:])
    jt = conf.get("mapred.job.tracker")
    if not jt or jt == "local":
        print("job control needs -jt HOST:PORT", file=sys.stderr)
        return 255
    host, port = _host_port(jt)
    from tpumr.security import client_credentials
    secret, scope = client_credentials(conf, "jobtracker")
    client = RpcClient(host, port, secret=secret, scope=scope)
    usage = ("Usage: tpumr job -list | -status ID | -kill ID | "
             "-set-priority ID PRIO | -kill-task ATTEMPT | "
             "-fail-task ATTEMPT | -list-attempt-ids ID map|reduce "
             "running|completed | -list-active-trackers | "
             "-list-blacklisted-trackers | "
             "-counters ID | -counter ID GROUP NAME | -events ID | "
             "-history ID [HISTORY_DIR] | stats ID [HISTORY_DIR] | "
             "trace ID [-out FILE] [-dir DIR]")
    if not argv:
        print(usage, file=sys.stderr)
        return 255
    cmd, *rest = argv
    if cmd not in ("-list", "-list-active-trackers",
                   "-list-blacklisted-trackers") and not rest:
        print(usage, file=sys.stderr)
        return 255
    try:
        if cmd == "-list":
            for jid in client.call("list_jobs"):
                st = client.call("get_job_status", jid)
                print(f"{jid}\t{st.get('state')}"
                      f"\t{st.get('priority', 'NORMAL')}"
                      f"\tmaps={st.get('map_progress'):.2f}"
                      f"\treduces={st.get('reduce_progress'):.2f}")
            return 0
        if cmd == "-status":
            st = client.call("get_job_status", rest[0])
            if st.get("job_id") and st["job_id"] != rest[0]:
                # the master restarted and recovered this job under a
                # new id (job_recovered alias) — say so, then report
                # the live job (scripts parsing stdout still work)
                print(f"job {rest[0]} was recovered as {st['job_id']} "
                      f"after a master restart", file=sys.stderr)
            print(json.dumps(st, indent=2, default=str))
            return 0
        if cmd == "-counters":
            print(json.dumps(client.call("get_counters", rest[0]), indent=2,
                             default=str))
            return 0
        if cmd == "-counter":
            # ≈ `hadoop job -counter ID GROUP NAME`: one value, bare on
            # stdout (scriptable, the reference's contract)
            if len(rest) < 3:
                print("Usage: tpumr job -counter ID GROUP NAME",
                      file=sys.stderr)
                return 255
            groups = client.call("get_counters", rest[0])
            val = (groups.get(rest[1]) or {}).get(rest[2])
            if val is None:
                print(f"counter {rest[1]}.{rest[2]} not found "
                      f"(groups: {', '.join(sorted(groups))})",
                      file=sys.stderr)
                return 1
            print(val)
            return 0
        if cmd == "-kill":
            from tpumr.security import UserGroupInformation
            ok = client.call("kill_job", rest[0],
                             UserGroupInformation.get_current_user().user)
            print(f"Killed {rest[0]}" if ok
                  else f"{rest[0]} already finished; not killed")
            return 0 if ok else 1
        if cmd == "-events":
            for ev in client.call("get_map_completion_events",
                                  rest[0], 0, 100):
                print(ev)
            return 0
        if cmd in ("-kill-task", "-fail-task"):
            from tpumr.security import UserGroupInformation
            ok = client.call("kill_task", rest[0], cmd == "-fail-task",
                             UserGroupInformation.get_current_user().user)
            verb = "Failed" if cmd == "-fail-task" else "Killed"
            print(f"{verb} task attempt {rest[0]}" if ok else
                  f"{rest[0]} not running; nothing to do")
            return 0 if ok else 1
        if cmd == "-list-attempt-ids":
            if len(rest) < 3:
                print(usage, file=sys.stderr)
                return 255
            for aid in client.call("get_attempt_ids", rest[0], rest[1],
                                   rest[2]):
                print(aid)
            return 0
        if cmd == "-list-active-trackers":
            for name in client.call("get_active_trackers"):
                print(name)
            return 0
        if cmd == "-list-blacklisted-trackers":
            for name in client.call("get_blacklisted_trackers"):
                print(name)
            return 0
        if cmd == "-set-priority":
            if len(rest) < 2:
                print("Usage: tpumr job -set-priority ID "
                      "VERY_HIGH|HIGH|NORMAL|LOW|VERY_LOW",
                      file=sys.stderr)
                return 255
            from tpumr.security import UserGroupInformation
            p = client.call("set_job_priority", rest[0], rest[1],
                            UserGroupInformation.get_current_user().user)
            print(f"Changed job priority of {rest[0]} to {p}")
            return 0
    except RpcError as e:
        print(f"job {cmd}: {e}", file=sys.stderr)
        return 1
    print(f"job: unknown option {cmd}", file=sys.stderr)
    return 255


def cmd_pipeline(conf, argv: list[str]) -> int:
    """DAG-of-jobs pipeline control: submit a JobGraph spec (JSON wire
    form — nodes/edges/loop, see docs/OPERATIONS.md "Running
    pipelines"), poll status, list, kill, or pull the merged
    end-to-end trace."""
    from tpumr.ipc.rpc import RpcError
    usage = ("Usage: tpumr pipeline submit GRAPH.json [-wait] | "
             "status ID | -list | -kill ID | trace ID [-out FILE]")
    if not argv:
        print(usage, file=sys.stderr)
        return 255
    jt = conf.get("mapred.job.tracker")
    if not jt or jt == "local":
        print("pipelines need -jt HOST:PORT (a cluster master)",
              file=sys.stderr)
        return 255
    from tpumr.pipeline import PipelineClient
    client = PipelineClient(conf)
    cmd, *rest = argv
    try:
        if cmd == "submit":
            if not rest:
                print(usage, file=sys.stderr)
                return 255
            with open(rest[0]) as f:
                graph = json.load(f)
            running = client.submit(graph)
            print(running.pipeline_id)
            if "-wait" in rest:
                st = running.wait_for_completion()
                print(json.dumps(st, indent=2, default=str))
                return 0 if st["state"] == "SUCCEEDED" else 1
            return 0
        if cmd in ("status", "-status"):
            if not rest:
                print(usage, file=sys.stderr)
                return 255
            print(json.dumps(client.status(rest[0]), indent=2,
                             default=str))
            return 0
        if cmd == "-list":
            for p in client.list():
                done = sum(1 for n in p["nodes"].values()
                           if n["state"] == "SUCCEEDED")
                print(f"{p['pipeline_id']}\t{p['state']}"
                      f"\t{p.get('name', '')}"
                      f"\tstages={done}/{len(p['nodes'])}")
            return 0
        if cmd == "-kill":
            if not rest:
                print(usage, file=sys.stderr)
                return 255
            ok = client.running(rest[0]).kill()
            print(f"Killed {rest[0]}" if ok
                  else f"{rest[0]} already finished; not killed")
            return 0 if ok else 1
        if cmd in ("trace", "-trace"):
            if not rest:
                print(usage, file=sys.stderr)
                return 255
            from tpumr.core import tracing
            t = client.trace(rest[0])
            if not t["spans"]:
                print(t.get("error") or "no spans", file=sys.stderr)
                return 1
            chrome = tracing.to_chrome_trace(t["spans"])
            out = f"{rest[0]}-trace.json"
            if "-out" in rest:
                i = rest.index("-out") + 1
                if i >= len(rest):
                    print("Usage: tpumr pipeline trace ID -out FILE",
                          file=sys.stderr)
                    return 255
                out = rest[i]
            with open(out, "w") as f:
                json.dump(chrome, f)
            print(f"wrote {len(t['spans'])} spans to {out}")
            return 0
    except (RpcError, OSError, ValueError) as e:
        print(f"pipeline {cmd}: {e}", file=sys.stderr)
        return 1
    print(f"pipeline: unknown option {cmd}", file=sys.stderr)
    return 255


def cmd_fsck(conf, argv: list[str]) -> int:
    """≈ bin/hadoop fsck: namespace health report from the NameNode
    (reference: hdfs/server/namenode/NamenodeFsck.java)."""
    from tpumr.fs import get_filesystem
    from tpumr.fs.shell import FsShell
    target = argv[0] if argv else "/"
    # same resolution rules as the fs shell (relative paths against
    # fs.default.name) — no hand-rolled URI gluing
    uri = FsShell(conf,
                  default_fs=conf.get("fs.default.name"))._resolve(target)
    if "://" not in uri:
        print("fsck: no filesystem given — pass a tdfs:// path or set "
              "fs.default.name (-fs tdfs://HOST:PORT/)", file=sys.stderr)
        return 255
    fs = get_filesystem(uri, conf)
    fsck = getattr(fs, "fsck", None)
    if fsck is None:
        print(f"fsck: only meaningful on tdfs:// (got {uri})",
              file=sys.stderr)
        return 255
    r = fsck(uri)
    print(f"FSCK started for path {target}")
    print(f" Total dirs:\t{r['dirs']}")
    print(f" Total files:\t{r['files']}")
    print(f" Total blocks:\t{r['blocks']} (size {r['size']} B)")
    print(f" Under-replicated blocks:\t{len(r['under_replicated'])}")
    print(f" Over-replicated blocks:\t{len(r['over_replicated'])}")
    print(f" Missing blocks:\t{len(r['missing'])}")
    print(f" Corrupt blocks:\t{len(r['corrupt'])}")
    print(f" Files open for write:\t{len(r['open_files'])}")
    for kind in ("under_replicated", "missing", "corrupt"):
        for ent in r[kind]:
            print(f"  {kind}: block {ent['block_id']} of {ent['path']}")
    print(f"The filesystem under path '{target}' is "
          + ("HEALTHY" if r["healthy"] else "CORRUPT"))
    return 0 if r["healthy"] else 1


def cmd_dfsadmin(conf, argv: list[str]) -> int:
    """≈ bin/hadoop dfsadmin: quotas, decommissioning, cluster report."""
    from tpumr.fs import get_filesystem
    usage = ("Usage: tpumr dfsadmin -setQuota N PATH | -setSpaceQuota N "
             "PATH | -clrQuota PATH | -clrSpaceQuota PATH | "
             "-decommission ADDR start|stop | "
             "-report | -safemode enter|leave|get | -saveNamespace | "
             "-refreshNodes | -refreshServiceAcl")
    if not argv:
        print(usage, file=sys.stderr)
        return 255

    def dfs(path="/"):
        uri = path if "://" in path else \
            (conf.get("fs.default.name") or "") .rstrip("/") + path
        fs = get_filesystem(uri, conf)
        if not hasattr(fs, "client"):
            raise SystemExit(f"dfsadmin: {uri} is not a tdfs:// filesystem")
        return fs, uri

    cmd, *rest = argv
    if cmd == "-refreshNodes" and not rest:
        from tpumr.ipc.rpc import RpcError
        fs, _ = dfs()
        try:
            r = fs.client.nn.call("refresh_nodes")
        except RpcError as e:
            print(f"dfsadmin: {e}", file=sys.stderr)
            return 1
        inc = r["included"]
        print(f"Nodes refreshed: include="
              f"{inc if inc == '*' else ','.join(inc) or '(none)'} "
              f"exclude={','.join(r['excluded']) or '(none)'}")
        for addr, state in sorted(r["changed"].items()):
            print(f"  {addr}: {state}")
        return 0
    if cmd == "-refreshServiceAcl" and not rest:
        from tpumr.ipc.rpc import RpcError
        fs, _ = dfs()
        try:
            for key, spec in fs.client.nn.call(
                    "refresh_service_acl").items():
                print(f"{key} = {spec}")
        except RpcError as e:
            print(f"dfsadmin: {e}", file=sys.stderr)
            return 1
        return 0
    if cmd == "-setQuota" and len(rest) == 2:
        fs, uri = dfs(rest[1])
        fs.client.nn.call("set_quota", fs._p(uri), int(rest[0]), None)
        return 0
    if cmd == "-setSpaceQuota" and len(rest) == 2:
        fs, uri = dfs(rest[1])
        fs.client.nn.call("set_quota", fs._p(uri), None, int(rest[0]))
        return 0
    if cmd == "-clrQuota" and len(rest) == 1:
        fs, uri = dfs(rest[0])
        fs.client.nn.call("set_quota", fs._p(uri), -1, None)
        return 0
    if cmd == "-clrSpaceQuota" and len(rest) == 1:
        fs, uri = dfs(rest[0])
        fs.client.nn.call("set_quota", fs._p(uri), None, -1)
        return 0
    if cmd == "-decommission" and len(rest) == 2:
        fs, _ = dfs("/")
        state = fs.client.nn.call("set_decommission", rest[0], rest[1])
        print(f"{rest[0]}: {state}")
        return 0
    if cmd == "-safemode" and len(rest) == 1:
        fs, _ = dfs("/")
        print(f"Safe mode is {'ON' if fs.client.nn.call('safemode', rest[0]) else 'OFF'}")
        return 0
    if cmd == "-saveNamespace":
        fs, _ = dfs("/")
        fs.client.nn.call("save_namespace")
        return 0
    if cmd == "-report":
        fs, _ = dfs("/")
        for d in fs.client.datanode_report():
            cap = d.get("capacity") or 0
            used = d.get("used", 0)
            pct = f"{100 * used / cap:.1f}%" if cap else "?"
            print(f"{d.get('addr', '?')}\t{d.get('state', '?')}\t"
                  f"blocks={d.get('blocks', '?')}\tused={used} ({pct})")
        return 0
    print(usage, file=sys.stderr)
    return 255


def _job_diagnose(conf, argv: list[str]) -> int:
    """Post-execution diagnosis (≈ contrib/vaidya's
    PostExPerformanceDiagnoser): run the rule set over one job's history
    and print findings + prescriptions. Accepts a JOB_ID (+ history dir
    like -history) or a direct path to a history .jsonl file."""
    import os
    if not argv:
        print("Usage: tpumr job -diagnose JOB_ID [HISTORY_DIR] | "
              "-diagnose PATH.jsonl [-json]", file=sys.stderr)
        return 255
    as_json = "-json" in argv
    argv = [a for a in argv if a != "-json"]
    if not argv:
        print("Usage: tpumr job -diagnose JOB_ID [HISTORY_DIR] | "
              "-diagnose PATH.jsonl [-json]", file=sys.stderr)
        return 255
    from tpumr.tools import vaidya
    target = argv[0]
    if not target.endswith(".jsonl"):
        hist_dir = argv[1] if len(argv) > 1 else conf.get("tpumr.history.dir")
        if not hist_dir:
            print("job -diagnose: pass HISTORY_DIR or set "
                  "tpumr.history.dir", file=sys.stderr)
            return 255
        target = os.path.join(hist_dir, f"{target}.jsonl")
    if "://" not in target and not os.path.exists(target):
        print(f"no history file at {target}", file=sys.stderr)
        return 1
    report = vaidya.diagnose_file(target)
    if as_json:
        import json as _json
        print(_json.dumps(report, indent=2))
    else:
        print(vaidya.format_report(report))
    return 0 if not report["findings"] else 2


def _job_trace(conf, argv: list[str]) -> int:
    """``tpumr job trace JOB_ID [-out FILE] [-dir TRACE_DIR]`` — export
    one traced job's merged distributed trace (Chrome trace-event JSON,
    loadable by chrome://tracing / Perfetto) and print its critical
    path: the submit→schedule→launch→run chain that determined the
    makespan, with per-span contribution percentages. Live mode pulls
    the merge from the JobTracker (get_job_trace); offline mode
    (``-dir``, or no jobtracker configured) merges the span files the
    daemons flushed next to the job history."""
    from tpumr.core import tracing
    usage = "Usage: tpumr job trace JOB_ID [-out FILE] [-dir TRACE_DIR]"
    if not argv:
        print(usage, file=sys.stderr)
        return 255
    job_id, out, trace_dir = argv[0], None, None
    it = iter(argv[1:])
    for a in it:
        if a == "-out":
            out = next(it, None)
        elif a == "-dir":
            trace_dir = next(it, None)
        else:
            print(usage, file=sys.stderr)
            return 255
    spans: "list[dict]" = []
    jt = conf.get("mapred.job.tracker")
    if trace_dir is None and jt and jt != "local":
        client = _jt_client(conf)
        if client is None:
            return 255
        from tpumr.ipc.rpc import RpcError
        try:
            t = client.call("get_job_trace", job_id)
        except RpcError as e:
            print(f"job trace: {e}", file=sys.stderr)
            return 1
        if t.get("error"):
            print(f"job trace: {t['error']}", file=sys.stderr)
            return 1
        spans = t["spans"]
    else:
        trace_dir = trace_dir or tracing.trace_dir_from_conf(conf)
        if not trace_dir:
            print("job trace: pass -dir TRACE_DIR or set "
                  "tpumr.trace.dir / tpumr.history.dir", file=sys.stderr)
            return 255
        # the trace id IS the job id (jobtracker.submit_job)
        spans = tracing.read_trace_files(str(trace_dir), job_id)
    if not spans:
        print(f"job trace: no spans found for {job_id} (was the job "
              f"submitted with tpumr.trace.enabled=true?)",
              file=sys.stderr)
        return 1
    chrome = tracing.to_chrome_trace(spans)
    out = out or f"{job_id}-trace.json"
    with open(out, "w") as f:
        json.dump(chrome, f, indent=1)
    roles = sorted({s.get("role", "?") for s in spans})
    cp = tracing.critical_path(spans)
    print(f"Trace: {len(spans)} spans across roles "
          f"{', '.join(roles)}")
    print(f"Makespan: {cp['makespan_s']:.3f}s — Chrome trace written to "
          f"{out} (load in chrome://tracing or ui.perfetto.dev)")
    print(f"Critical path ({len(cp['path'])} spans, "
          f"{cp['total_s']:.3f}s summed, "
          f"{cp['self_total_s']:.3f}s self time):")
    print(f"  {'span':<28} {'role':<12} {'backend':<8} "
          f"{'duration':>10} {'self':>10} {'contrib':>8}")
    for p in cp["path"]:
        print(f"  {p['name']:<28} {p['role']:<12} "
              f"{p['backend'] or '—':<8} {p['duration_s']:>9.4f}s "
              f"{p['self_s']:>9.4f}s {p['contribution_pct']:>7.1f}%")
    return 0


def _fmt_latency(label: str, pct: dict) -> str:
    if not pct:
        return f"{label}: (no finished tasks)"
    return (f"{label}: n={pct['count']}  mean={pct['mean']:.3f}s  "
            f"p50={pct['p50']:.3f}s  p95={pct['p95']:.3f}s  "
            f"p99={pct['p99']:.3f}s  max={pct['max']:.3f}s")


def _job_stats(conf, argv: list[str]) -> int:
    """`tpumr job stats JOB_ID [HISTORY_DIR] [-json]`: print the per-job
    stats rollup (metrics-<jobid>.json, written next to job history at
    finalization) — latency percentiles, the TPU/CPU task-time split,
    and acceleration factors. Offline like -history: reads the rollup
    file, no live master needed."""
    import os
    as_json = "-json" in argv
    argv = [a for a in argv if a != "-json"]
    if not argv:
        print("Usage: tpumr job stats JOB_ID [HISTORY_DIR] [-json]",
              file=sys.stderr)
        return 255
    job_id = argv[0]
    hist_dir = argv[1] if len(argv) > 1 else conf.get("tpumr.history.dir")
    if not hist_dir:
        print("job stats: pass HISTORY_DIR or set tpumr.history.dir",
              file=sys.stderr)
        return 255
    path = os.path.join(hist_dir, f"metrics-{job_id}.json")
    if not os.path.exists(path):
        known = [f[len("metrics-"):-len(".json")]
                 for f in sorted(os.listdir(hist_dir))
                 if f.startswith("metrics-") and f.endswith(".json")] \
            if os.path.isdir(hist_dir) else []
        print(f"no stats rollup for {job_id} in {hist_dir} (written at "
              f"job finalization); known: {', '.join(known) or '(none)'}",
              file=sys.stderr)
        return 1
    with open(path) as f:
        r = json.load(f)
    if as_json:
        print(json.dumps(r, indent=2))
        return 0
    print(f"Job: {r.get('job_id', job_id)}"
          + (f"  ({r['job_name']})" if r.get("job_name") else ""))
    print(f"State: {r.get('state', '?')}   wall time: "
          f"{r.get('wall_time', 0):.2f}s   maps: {r.get('num_maps', 0)} "
          f"({r.get('finished_tpu_maps', 0)} tpu / "
          f"{r.get('finished_cpu_maps', 0)} cpu)   reduces: "
          f"{r.get('num_reduces', 0)}")
    print(_fmt_latency("map latency   ", r.get("map_latency") or {}))
    if r.get("map_latency_tpu"):
        print(_fmt_latency("  tpu maps    ", r["map_latency_tpu"]))
    if r.get("map_latency_cpu"):
        print(_fmt_latency("  cpu maps    ", r["map_latency_cpu"]))
    print(_fmt_latency("reduce latency", r.get("reduce_latency") or {}))
    split = r.get("task_time_split") or {}
    print(f"task time     : tpu {split.get('tpu_map_s', 0):.3f}s / "
          f"cpu {split.get('cpu_map_s', 0):.3f}s map "
          f"(tpu {split.get('tpu_fraction_of_map_time', 0):.0%} of map "
          f"task-time), reduce {split.get('reduce_s', 0):.3f}s")
    prof = r.get("acceleration_factor_profiled") or 0
    obs = r.get("acceleration_factor_observed") or 0
    if prof or obs:
        print(f"acceleration  : profiled {prof:.2f}x, observed "
              f"{obs:.2f}x")
    dropped = r.get("runtime_samples_dropped", 0)
    if dropped:
        print(f"(percentiles computed over a capped sample; "
              f"{dropped} runtimes dropped)")
    counters = r.get("counters") or {}
    n = sum(len(v) for v in counters.values())
    print(f"counters      : {n} across {len(counters)} groups "
          f"(full dump: tpumr job stats {job_id} -json)")
    return 0


def _job_history(conf, argv: list[str]) -> int:
    """Human summary of one job's history file (≈ HistoryViewer, the
    engine behind `hadoop job -history`)."""
    import os
    if not argv:
        print("Usage: tpumr job -history JOB_ID [HISTORY_DIR]",
              file=sys.stderr)
        return 255
    job_id = argv[0]
    hist_dir = argv[1] if len(argv) > 1 else \
        conf.get("tpumr.history.dir")
    if not hist_dir:
        print("job -history: pass HISTORY_DIR or set tpumr.history.dir",
              file=sys.stderr)
        return 255
    path = os.path.join(hist_dir, f"{job_id}.jsonl")
    if not os.path.exists(path):
        known = [f[:-6] for f in sorted(os.listdir(hist_dir))
                 if f.endswith(".jsonl")] if os.path.isdir(hist_dir) else []
        print(f"no history for {job_id} in {hist_dir}; known: "
              f"{', '.join(known) or '(none)'}", file=sys.stderr)
        return 1
    from tpumr.mapred.history import JobHistory
    from tpumr.mapred.history_server import job_summary
    events = JobHistory.read(path)
    s = job_summary(events)
    print(f"Job: {s.get('job_id', job_id)}")
    print(f"Name: {s.get('name', '')}")
    print(f"State: {s.get('state', 'INCOMPLETE')}")
    if s.get("wall_time") is not None:
        print(f"Wall time: {s['wall_time']:.2f}s")
    print(f"Maps: {s.get('num_maps', '?')}  Reduces: "
          f"{s.get('num_reduces', '?')}")
    print(f"TPU maps: {s.get('finished_tpu_maps', 0) or 0}  CPU maps: "
          f"{s.get('finished_cpu_maps', 0) or 0}")
    if s.get("acceleration_factor"):
        print(f"Acceleration factor: {s['acceleration_factor']:.2f}")
    if s.get("error"):
        print(f"Error: {s['error']}")
    kinds: dict = {}
    for ev in events:
        kinds[ev.get("event", "?")] = kinds.get(ev.get("event", "?"), 0) + 1
    print("Events: " + ", ".join(f"{k}={v}"
                                 for k, v in sorted(kinds.items())))
    # per-task failure diagnostics ≈ HistoryViewer's FAILED task listing
    for ev in events:
        if ev.get("event") == "TASK_FAILED":
            where = "tpu" if ev.get("run_on_tpu") else "cpu"
            print(f"  failed: {ev.get('attempt_id', '?')} ({where} on "
                  f"{ev.get('tracker', '?')}, "
                  f"{ev.get('runtime', 0):.2f}s)")
    return 0


def cmd_failmon(conf, argv: list[str]) -> int:
    """≈ contrib/failmon RunOnce + the HDFS merge step."""
    from tpumr.tools import failmon
    usage = ("Usage: tpumr failmon -collect [-store DIR] [-upload URL] "
             "[-anonymize] | -merge URL DEST")
    if not argv:
        print(usage, file=sys.stderr)
        return 255
    if argv[0] == "-merge":
        if len(argv) != 3:
            print(usage, file=sys.stderr)
            return 255
        n = failmon.merge(argv[1], argv[2])
        print(f"merged {n} events -> {argv[2]}")
        return 0
    if argv[0] != "-collect":
        print(usage, file=sys.stderr)
        return 255
    rest = argv[1:]
    anonymize = "-anonymize" in rest
    rest = [a for a in rest if a != "-anonymize"]
    opts: dict[str, str] = {}
    i = 0
    while i < len(rest):
        flag = rest[i]
        if flag not in ("-store", "-upload") or i + 1 >= len(rest):
            print(f"failmon: bad or valueless option {flag!r}\n{usage}",
                  file=sys.stderr)
            return 255
        opts[flag] = rest[i + 1]
        i += 2
    store_dir = opts.get("-store") or conf.get("failmon.store.dir") \
        or "/tmp/tpumr-failmon"
    store = failmon.LocalStore(store_dir, anonymize=anonymize)
    n = failmon.run_once(store, failmon.default_monitors(conf))
    print(f"collected {n} events -> {store_dir}")
    url = opts.get("-upload") or conf.get("failmon.upload.url")
    if url:
        dest = store.upload(url)
        print(f"uploaded -> {dest}" if dest else "nothing to upload")
    return 0


def cmd_gridmix(conf, argv: list[str]) -> int:
    from tpumr.benchmarks.gridmix import main as gridmix_main
    return gridmix_main(argv)


def cmd_simulate(conf, argv: list[str]) -> int:
    """Control-plane scale harness (tpumr/scale/): N simulated trackers
    speaking the real heartbeat protocol plus a synthetic multi-job
    workload, against the configured master (``-jt HOST:PORT``) or a
    self-hosted in-process one. With a self-hosted master the report
    includes the master-side saturation series (heartbeat p50/p99, lag
    p99, lock-wait p99, assign p99, RPC inflight peak); against a live
    master read those off its /metrics/prom. See docs/OPERATIONS.md
    "Sizing the master". ``-dfs N`` runs the storage twin instead: one
    DFS saturation rung against a fresh in-process mini-DFS (see
    "Monitoring the DFS")."""
    from tpumr.scale import ScaleDriver, SimFleet
    from tpumr.security import rpc_secret
    a = _kv_args(argv)
    if "scenario" in a:
        return _simulate_scenario(conf, a)
    if "dfs" in a:
        return _simulate_dfs(conf, a)
    n = int(a.get("trackers", 25))
    n_jobs = int(a.get("jobs", 4))
    maps = int(a.get("maps", 64))
    reduces = int(a.get("reduces", 2))
    interval_s = float(a.get("interval", 200)) / 1000.0
    task_mean_s = float(a.get("task-ms", 500)) / 1000.0
    timeout_s = float(a.get("timeout", 120))
    ff_rate = float(a.get("ff-rate", 0.0))
    jt = conf.get("mapred.job.tracker")
    master = None
    if jt and jt != "local" and ":" in str(jt):
        host, port = _host_port(str(jt))
    else:
        from tpumr.mapred.jobtracker import JobMaster
        conf.set("tpumr.heartbeat.interval.ms", int(interval_s * 1000))
        conf.set_if_unset("tpumr.tracker.expiry.ms", 60_000)
        master = JobMaster(conf).start()
        host, port = master.address
        print(f"self-hosted JobMaster at {host}:{port}", file=sys.stderr)
    secret = rpc_secret(conf)
    fleet = SimFleet(host, port, n, secret=secret, interval_s=interval_s,
                     task_time_mean_s=task_mean_s,
                     fetch_failure_rate=ff_rate).start()
    driver = ScaleDriver(host, port, secret=secret)
    try:
        print(f"simulate: {n} trackers @ {interval_s * 1000:.0f}ms "
              f"heartbeats, {n_jobs} jobs x {maps} maps / {reduces} "
              f"reduces, task mean {task_mean_s * 1000:.0f}ms",
              file=sys.stderr)
        result = driver.run_workload(n_jobs, maps, reduces,
                                     timeout_s=timeout_s)
        fl = fleet.stats()
        report = {
            "trackers": n,
            "jobs_succeeded": len(result["succeeded"]),
            "jobs_failed": len(result["failed"]),
            "jobs_unfinished": len(result["unfinished"]),
            "heartbeats": fl["heartbeats"],
            "tasks_completed": fl["tasks_completed"],
            "hb_errors": fl["hb_errors"],
            "client_rtt_p50_s": fl["hb_rtt"].get("p50", 0.0),
            "client_rtt_p99_s": fl["hb_rtt"].get("p99", 0.0),
            "client_lag_p99_s": fl["hb_lag"].get("p99", 0.0),
        }
        if master is not None:
            snap = master.metrics.snapshot()
            jt_m = snap.get("jobtracker", {})
            report.update({
                "heartbeat_p50_s": jt_m.get("heartbeat_seconds",
                                            {}).get("p50", 0.0),
                "heartbeat_p99_s": jt_m.get("heartbeat_seconds",
                                            {}).get("p99", 0.0),
                "heartbeat_lag_p99_s": jt_m.get("heartbeat_lag_seconds",
                                                {}).get("p99", 0.0),
                "lock_wait_p99_s": jt_m.get("jt_lock_wait_seconds",
                                            {}).get("p99", 0.0),
                "assign_p99_s": snap.get("scheduler", {}).get(
                    "assign_seconds", {}).get("p99", 0.0),
                "completion_event_lag_p99": jt_m.get(
                    "completion_event_lag", {}).get("p99", 0.0),
                "rpc_inflight_peak": master._server.inflight_peak(),
            })
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0 if not result["failed"] and not result["unfinished"] \
            else 1
    finally:
        fleet.stop()
        driver.close()
        if master is not None:
            master.stop()


def _simulate_dfs(conf, a: "dict[str, str]") -> int:
    """``simulate -dfs N`` — one DFS saturation rung: a fresh
    in-process MiniDFSCluster under a fleet of N real DFSClients on a
    fixed op cadence (``tpumr/scale/simdfs.py``), reported as the same
    joined row ``bench_dfs.py`` commits — NameNode op/lock/editlog
    attribution plus client-side round trips and hot-block skew.
    ``-seconds S`` measurement window, ``-interval MS`` per-client op
    cadence, ``-datanodes N``, ``-files N`` working-set size,
    ``-hot-p P`` hot-file read probability, ``-prom PATH`` scrapes the
    live NameNode /metrics/prom into PATH. The row is judged against
    the bench_dfs dual SLO (``tpumr.dfs.bench.op.slo.ms`` /
    ``.read.slo.ms``); exit 1 when it fails."""
    from tpumr.core import confkeys
    from tpumr.scale.simdfs import run_dfs_step
    row = run_dfs_step(
        int(a["dfs"]), conf=conf,
        interval_s=float(a.get("interval", 50)) / 1000.0,
        measure_s=float(a.get("seconds", 6)),
        num_datanodes=int(a.get("datanodes", 3)),
        n_files=int(a.get("files", 8)),
        hot_read_p=float(a.get("hot-p", 0.5)),
        read_bytes=int(a.get("read-bytes", 1 << 16)),
        seed=int(a.get("seed", 0)),
        prom_out=a.get("prom"))
    op_slo_s = confkeys.get_int(conf, "tpumr.dfs.bench.op.slo.ms") / 1e3
    read_slo_s = confkeys.get_int(conf,
                                  "tpumr.dfs.bench.read.slo.ms") / 1e3
    row["slo"] = {
        "op_slo_s": op_slo_s, "read_slo_s": read_slo_s,
        "pass": row["completed"] and row["nn_op_p99_s"] <= op_slo_s
                and row["read_rtt_p99_s"] <= read_slo_s}
    print(json.dumps(row, indent=2, sort_keys=True))
    return 0 if row["slo"]["pass"] else 1


def _simulate_scenario(conf, a: "dict[str, str]") -> int:
    """``simulate -scenario NAME`` — replay one scenario-lab mix
    (tpumr/scale/scenario.py) and gate on its per-class SLO verdicts.
    ``-seed S`` overrides the spec's seed, ``-report PATH`` writes the
    full machine-readable report there (stdout then carries a short
    verdict summary instead), ``-incidents DIR`` keeps history +
    incident bundles under DIR even on success."""
    from tpumr.core import confkeys
    from tpumr.scale.scenario import ScenarioError, run_named
    seed = int(a["seed"]) if "seed" in a else None
    scenario_dir = a.get("dir") \
        or confkeys.get(conf, "tpumr.scenario.dir")
    try:
        rep = run_named(a["scenario"], seed=seed,
                        scenario_dir=scenario_dir,
                        artifacts_dir=a.get("incidents"))
    except ScenarioError as e:
        print(f"scenario error: {e}", file=sys.stderr)
        return 2
    doc = json.dumps(rep, indent=2, sort_keys=True)
    if "report" in a:
        with open(a["report"], "w") as f:
            f.write(doc + "\n")
        jobs = rep["jobs"]
        print(f"scenario {rep['scenario']} seed {rep['seed']}: "
              f"{jobs['succeeded']}/{jobs['submitted']} jobs, "
              f"{jobs['failed']} failed, {jobs['unfinished']} "
              f"unfinished, wall {rep['wall_s']}s -> {a['report']}")
        for cls_name, row in sorted(rep["verdicts"].items()):
            print(f"  class {cls_name}: "
                  f"{'PASS' if row.get('pass') else 'FAIL'}")
        if rep.get("dfs"):
            d = rep["dfs"]
            heal = d.get("heal") or {}
            print(f"  dfs: {'PASS' if d['pass'] else 'FAIL'} "
                  f"({d['ops']} ops, {d['errors']} errors, "
                  f"{d['corrupt_reads']} corrupt reads, "
                  f"{d['safemode_refusals']} safemode refusals, "
                  f"heal {heal.get('heal_s')}s)")
        print(f"  overall: {'PASS' if rep['pass'] else 'FAIL'}")
    else:
        print(doc)
    return 0 if rep["pass"] else 1


def cmd_scenario(conf, argv: list[str]) -> int:
    """Scenario-lab catalog / runner:

    - ``scenario -list`` — the built-in mixes plus any ``*.toml`` specs
      under ``tpumr.scenario.dir`` (or ``-dir DIR``).
    - ``scenario NAME [-seed S] [-report PATH] [-incidents DIR]`` —
      replay one (same as ``simulate -scenario NAME``).
    """
    from tpumr.core import confkeys
    if argv and argv[0].lstrip("-") == "list":
        from tpumr.scale.scenario import list_scenarios
        a = _kv_args(argv[1:])
        scenario_dir = a.get("dir") \
            or confkeys.get(conf, "tpumr.scenario.dir")
        for row in list_scenarios(scenario_dir):
            if "error" in row:
                print(f"{row['name']}  [{row['origin']}]  "
                      f"ERROR: {row['error']}")
                continue
            chaos = ",".join(row["chaos"]) or "none"
            print(f"{row['name']}  [{row['origin']}]  "
                  f"jobs={row['jobs']} classes="
                  f"{','.join(row['classes'])} chaos={chaos} "
                  f"trace={row['trace_s']:.1f}s")
        return 0
    if argv and not argv[0].startswith("-"):
        a = _kv_args(argv[1:])
        a["scenario"] = argv[0]
        return _simulate_scenario(conf, a)
    print("usage: tpumr scenario -list | "
          "tpumr scenario NAME [-seed S] [-report PATH]",
          file=sys.stderr)
    return 2


def cmd_distcp(conf, argv: list[str]) -> int:
    from tpumr.tools.distcp import main as distcp_main
    return distcp_main(argv)


def cmd_archive(conf, argv: list[str]) -> int:
    from tpumr.tools.archive import main as archive_main
    return archive_main(argv)


def cmd_rumen(conf, argv: list[str]) -> int:
    from tpumr.tools.rumen import main as rumen_main
    return rumen_main(argv)


def cmd_pipes(conf, argv: list[str]) -> int:
    from tpumr.pipes.submitter import main as pipes_main
    return pipes_main(argv)


def cmd_streaming(conf, argv: list[str]) -> int:
    from tpumr.streaming.stream_job import main as stream_main
    return stream_main(argv)


def cmd_examples(conf, argv: list[str]) -> int:
    from tpumr.examples import main as ex_main
    return ex_main(argv)


def cmd_keys(conf, argv: list[str]) -> int:
    """Credential provisioning (tpumr/security/tokens.py):

    - ``keys user-key USER`` — derive USER's personal signing key from
      the cluster secret (operator-side; hand the hex to the user, who
      sets ``tpumr.rpc.user.key``). ≈ provisioning a service keytab.
    - ``keys token [-renewer R] [-out FILE]`` — obtain a delegation
      token from the JobTracker for the CALLER's identity and write the
      credential file (``tpumr.rpc.token.file``).
    - ``keys renew FILE`` / ``keys cancel FILE``.
    """
    usage = ("Usage: tpumr keys user-key USER | "
             "token [-renewer R] [-out FILE] | renew FILE | cancel FILE")
    if not argv:
        print(usage, file=sys.stderr)
        return 255
    sub, *rest = argv
    if sub == "user-key":
        from tpumr.security import rpc_secret
        from tpumr.security.tokens import derive_user_key
        secret = rpc_secret(conf)
        if secret is None or not rest:
            print("user-key needs USER and the cluster secret "
                  "(tpumr.rpc.secret[.file])", file=sys.stderr)
            return 1
        print(derive_user_key(secret, rest[0]).hex())
        return 0
    if sub in ("token", "renew", "cancel"):
        from tpumr.ipc.rpc import RpcClient, RpcError
        from tpumr.security import client_credentials
        # -nn targets the NameNode (tokens are per-issuing-service,
        # like the reference's NN vs JT delegation tokens)
        service = "namenode" if "-nn" in rest else "jobtracker"
        rest = [a for a in rest if a != "-nn"]
        if service == "namenode":
            default = str(conf.get("fs.default.name") or "")
            if not default.startswith("tdfs://"):
                print("-nn needs fs.default.name=tdfs://HOST:PORT",
                      file=sys.stderr)
                return 255
            host, port = _host_port(default[len("tdfs://"):].rstrip("/"))
        else:
            jt = conf.get("mapred.job.tracker")
            if not jt or jt == "local":
                print("token ops need -jt HOST:PORT", file=sys.stderr)
                return 255
            host, port = _host_port(jt)
        secret, scope = client_credentials(conf, service)
        client = RpcClient(host, port, secret=secret, scope=scope)
        try:
            if sub == "token":
                renewer, out = "", None
                it = iter(rest)
                for a in it:
                    if a == "-renewer":
                        renewer = next(it, "")
                    elif a == "-out":
                        out = next(it, None)
                wire = client.call("get_delegation_token", renewer)
                if out:
                    # merge under the service key so one credential file
                    # can hold both the JT and NN tokens
                    merged: dict = {}
                    if os.path.exists(out):
                        with open(out) as f:
                            prev = json.load(f)
                        if isinstance(prev, dict):
                            if "ident" in prev:
                                # flat single-service file: preserve the
                                # existing credential under the OTHER
                                # service key rather than discarding it
                                other = ("namenode"
                                         if service == "jobtracker"
                                         else "jobtracker")
                                merged = {other: prev}
                            else:
                                merged = prev
                    merged[service] = wire
                    fd = os.open(out, os.O_WRONLY | os.O_CREAT
                                 | os.O_TRUNC, 0o600)  # credential file
                    with os.fdopen(fd, "w") as f:
                        json.dump(merged, f, indent=2)
                        f.write("\n")
                    print(f"{service} token written to {out}")
                else:
                    print(json.dumps(wire, indent=2))
                return 0
            with open(rest[0]) as f:
                data = json.load(f)
            wire = data if "ident" in data else data[service]
            if sub == "renew":
                exp = client.call("renew_delegation_token", wire)
                print(f"renewed until {exp}")
            else:
                client.call("cancel_delegation_token", wire)
                print("canceled")
            return 0
        except (RpcError, OSError, IndexError, ValueError, KeyError) as e:
            print(f"keys {sub}: {e}", file=sys.stderr)
            return 1
    print(usage, file=sys.stderr)
    return 255


def _jt_client(conf):
    """An RPC client for the configured JobTracker, or None (with the
    error already printed) when mapred.job.tracker is unset/local."""
    from tpumr.ipc.rpc import RpcClient
    from tpumr.security import client_credentials
    jt = conf.get("mapred.job.tracker")
    if not jt or jt == "local" or ":" not in str(jt):
        print("this command needs -jt HOST:PORT "
              "(or mapred.job.tracker)", file=sys.stderr)
        return None
    host, port = _host_port(str(jt))
    secret, scope = client_credentials(conf, "jobtracker")
    return RpcClient(host, port, secret=secret, scope=scope)


def cmd_queue(conf, argv: list[str]) -> int:
    """≈ bin/hadoop queue: -list | -info QUEUE [-showJobs] | -showacls
    (reference CLI: JobQueueClient over JobClient.getQueues/
    getJobsFromQueue/getQueueAclsForCurrentUser)."""
    from tpumr.ipc.rpc import RpcError
    usage = "Usage: tpumr queue -list | -info QUEUE [-showJobs] | -showacls"
    if not argv or argv[0] not in ("-list", "-info", "-showacls"):
        print(usage, file=sys.stderr)
        return 255
    client = _jt_client(conf)
    if client is None:
        return 255
    cmd, *rest = argv
    try:
        if cmd == "-list":
            for q in client.call("get_queue_info"):
                print(f"Queue: {q['queue']}")
                print(f"  acl-submit-job: {q['acl_submit_job']}"
                      + ("" if q["acls_enabled"] else " (acls disabled)"))
                print(f"  acl-administer-jobs: {q['acl_administer_jobs']}")
                print(f"  jobs: {q['running_jobs']} running / "
                      f"{q['total_jobs']} total")
            return 0
        if cmd == "-info":
            if not rest:
                print(usage, file=sys.stderr)
                return 255
            queue, *flags = rest
            info = next((q for q in client.call("get_queue_info")
                         if q["queue"] == queue), None)
            if info is None:
                print(f"queue {queue!r} is not defined", file=sys.stderr)
                return 1
            print(json.dumps(info, indent=2))
            if "-showJobs" in flags:
                for jid in client.call("get_queue_jobs", queue):
                    # per-job view ACLs may hide a status from this
                    # caller; the queue listing itself must still
                    # complete (the id is queue metadata, not job data)
                    try:
                        state = client.call("get_job_status",
                                            jid).get("state")
                    except RpcError:
                        state = "(not viewable)"
                    print(f"{jid}\t{state}")
            return 0
        if cmd == "-showacls":
            from tpumr.security import UserGroupInformation
            me = UserGroupInformation.get_current_user().user
            print(f"Queue acls for user: {me}")
            for row in client.call("get_queue_acls", me):
                ops = ",".join(row["operations"]) or "(none)"
                print(f"  {row['queue']}: {ops}")
            return 0
    except RpcError as e:
        print(f"queue: {e}", file=sys.stderr)
        return 1
    print(usage, file=sys.stderr)
    return 255


def cmd_mradmin(conf, argv: list[str]) -> int:
    """≈ bin/hadoop mradmin (AdminOperationsProtocol), admin-gated when
    ACLs are enforced:

    - ``-refreshQueues``: re-read queue names + ACLs
      (mapred.queue.acls.file) on the live JobTracker, no restart.
    - ``-refreshNodes``: re-read mapred.hosts / mapred.hosts.exclude;
      trackers on newly excluded hosts are evicted (their work
      re-queues like a lost tracker's).
    """
    from tpumr.ipc.rpc import RpcError
    usage = ("Usage: tpumr mradmin -refreshQueues | -refreshNodes | "
             "-refreshServiceAcl")
    if argv not in (["-refreshQueues"], ["-refreshNodes"],
                    ["-refreshServiceAcl"]):
        # strict: silently ignoring a trailing flag would report an
        # operation as done that never ran
        print(usage, file=sys.stderr)
        return 255
    client = _jt_client(conf)
    if client is None:
        return 255
    from tpumr.security import UserGroupInformation
    me = UserGroupInformation.get_current_user().user
    try:
        if argv == ["-refreshQueues"]:
            queues = client.call("refresh_queues", me)
            print(f"Queues refreshed: {', '.join(queues)}")
        elif argv == ["-refreshServiceAcl"]:
            for key, spec in client.call("refresh_service_acl").items():
                print(f"{key} = {spec}")
        else:
            r = client.call("refresh_nodes", me)
            inc = r["included"]
            print(f"Nodes refreshed: include="
                  f"{inc if inc == '*' else ','.join(inc) or '(none)'} "
                  f"exclude={','.join(r['excluded']) or '(none)'}")
            for name in r["evicted_trackers"]:
                print(f"  evicted: {name}")
    except RpcError as e:
        print(f"mradmin: {e}", file=sys.stderr)
        return 1
    return 0


def cmd_daemonlog(conf, argv: list[str]) -> int:
    """≈ bin/hadoop daemonlog: get/set a live daemon's logger level
    through its status HTTP server (/json/logLevel ≈ the LogLevel
    servlet). Works against ANY tpumr daemon's HTTP port."""
    import urllib.error
    import urllib.parse
    import urllib.request
    usage = ("Usage: tpumr daemonlog -getlevel HOST:PORT LOGGER | "
             "-setlevel HOST:PORT LOGGER LEVEL")
    if len(argv) < 3 or argv[0] not in ("-getlevel", "-setlevel") \
            or (argv[0] == "-setlevel" and len(argv) < 4):
        print(usage, file=sys.stderr)
        return 255
    hostport, logger = argv[1], argv[2]
    params = {"log": "" if logger == "root" else logger}
    if argv[0] == "-setlevel":
        params["level"] = argv[3]
    url = (f"http://{hostport}/json/logLevel?"
           f"{urllib.parse.urlencode(params)}")
    try:
        # level mutation must travel as POST (the server rejects GET
        # sets so drive-by GETs can't silence a daemon's logging)
        req = urllib.request.Request(
            url, method="POST" if "level" in params else "GET")
        with urllib.request.urlopen(req, timeout=10) as resp:
            body = json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        # the server reports rejected levels/loggers as a JSON error
        # body — surface its message, not a bare "HTTP Error 500"
        try:
            detail = json.loads(e.read().decode("utf-8")).get("error", e)
        except ValueError:
            detail = e
        print(f"daemonlog: {detail}", file=sys.stderr)
        return 1
    except OSError as e:
        print(f"daemonlog: {hostport}: {e}", file=sys.stderr)
        return 1
    if "error" in body:
        print(f"daemonlog: {body['error']}", file=sys.stderr)
        return 1
    print(f"{body['log']}: level={body['level']} "
          f"effective={body['effective']}")
    return 0


def cmd_prof(conf, argv: list[str]) -> int:
    """Pull a profiling window off a live daemon's continuous sampler:
    ``tpumr prof HOST:PORT [-seconds N] [-out FILE] [-flame]``. Default
    output is the collapsed folded-stack text (one ``thread;frames
    count`` line per unique stack — pipe into any flamegraph tool);
    ``-flame`` asks the daemon for the self-contained SVG instead.
    Needs ``tpumr.prof.enabled`` on the target daemon."""
    import urllib.error
    import urllib.request
    usage = ("Usage: tpumr prof HOST:PORT [-seconds N] [-out FILE] "
             "[-flame]")
    if not argv or ":" not in argv[0]:
        print(usage, file=sys.stderr)
        return 255
    hostport, rest = argv[0], argv[1:]
    a = _kv_args([x for x in rest if x != "-flame"])
    flame = "-flame" in rest
    path = "flame" if flame else "stacks"
    url = f"http://{hostport}/{path}"
    if a.get("seconds"):
        url += f"?seconds={float(a['seconds'])}"
    try:
        with urllib.request.urlopen(url, timeout=30) as resp:
            body = resp.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        # a daemon without the sampler 404s — say what to enable
        detail = (f"{e} — is tpumr.prof.enabled set on the daemon?"
                  if e.code == 404 else e)
        print(f"prof: {hostport}: {detail}", file=sys.stderr)
        return 1
    except OSError as e:
        print(f"prof: {hostport}: {e}", file=sys.stderr)
        return 1
    out = a.get("out")
    if out:
        with open(out, "w") as f:
            f.write(body)
        print(f"wrote {len(body)} bytes to {out}", file=sys.stderr)
    else:
        sys.stdout.write(body)
    return 0


def cmd_fetchdt(conf, argv: list[str]) -> int:
    """≈ bin/hadoop fetchdt TOKEN_FILE: fetch a NameNode delegation
    token into a credential file — an alias for
    ``tpumr keys token -nn -out FILE``."""
    if len(argv) != 1:
        print("Usage: tpumr fetchdt TOKEN_FILE", file=sys.stderr)
        return 255
    return cmd_keys(conf, ["token", "-nn", "-out", argv[0]])


def cmd_rcc(conf, argv: list[str]) -> int:
    """≈ bin/rcc: compile Record I/O DDL to record classes."""
    from tpumr.recordio.rcc import main as rcc_main
    return rcc_main(argv)


def cmd_tdfsproxy(conf, argv: list[str]) -> int:
    """≈ contrib/hdfsproxy: read-only HTTP(S) storage gateway."""
    from tpumr.tools.tdfsproxy import main as proxy_main
    return proxy_main(argv, conf)


def cmd_lint(conf, argv: list[str]) -> int:
    """Repo-native static analyzer (tpumr/tools/tpulint): proves the
    master's lock-rank discipline, the config-key registry, monotonic-
    clock deadline arithmetic, and docs/code drift — the invariants the
    runtime only spot-checks on exercised paths."""
    from tpumr.tools.tpulint.cli import main as lint_main
    return lint_main(argv)


def cmd_version(conf, argv: list[str]) -> int:
    print(f"tpumr {VERSION}")
    return 0


COMMANDS = {
    "namenode": cmd_namenode,
    "datanode": cmd_datanode,
    "secondarynamenode": cmd_secondarynamenode,
    "jobtracker": cmd_jobtracker,
    "tasktracker": cmd_tasktracker,
    "historyserver": cmd_historyserver,
    "balancer": cmd_balancer,
    "fsck": cmd_fsck,
    "dfsadmin": cmd_dfsadmin,
    "fs": cmd_fs,
    "job": cmd_job,
    "pipeline": cmd_pipeline,
    "pipes": cmd_pipes,
    "streaming": cmd_streaming,
    "distcp": cmd_distcp,
    "failmon": cmd_failmon,
    "gridmix": cmd_gridmix,
    "simulate": cmd_simulate,
    "scenario": cmd_scenario,
    "archive": cmd_archive,
    "rumen": cmd_rumen,
    "examples": cmd_examples,
    "keys": cmd_keys,
    "queue": cmd_queue,
    "mradmin": cmd_mradmin,
    "daemonlog": cmd_daemonlog,
    "prof": cmd_prof,
    "fetchdt": cmd_fetchdt,
    "rcc": cmd_rcc,
    "tdfsproxy": cmd_tdfsproxy,
    "lint": cmd_lint,
    "version": cmd_version,
}


def main(argv: list[str] | None = None) -> int:
    # TPUMR_JAX_PLATFORM=cpu pins jax to a platform BEFORE any device
    # touch — the supported way to run CPU-only (a TPU plugin may
    # override the plain JAX_PLATFORMS env at interpreter startup)
    plat = os.environ.get("TPUMR_JAX_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)
    argv = list(sys.argv[1:] if argv is None else argv)
    overrides, conf_files, rest = _parse_generic(argv)
    if not rest:
        sys.stderr.write(USAGE)
        return 255
    cmd, *args = rest
    fn = COMMANDS.get(cmd)
    if fn is None:
        sys.stderr.write(f"Unknown command: {cmd}\n\n" + USAGE)
        return 255
    # resource layers for this invocation, lowest first: conf-dir site
    # file(s), -conf files, then -D/-fs/-jt overrides on top. Installed
    # as default resources ≈ GenericOptionsParser merging into the job
    # conf so they also reach confs the subcommand builds itself
    # (examples/pipes/streaming); removed afterwards so repeated
    # in-process invocations (tests, embedding) don't accumulate layers
    from tpumr.core.configuration import Configuration
    layers: "list[dict | str]" = list(_site_files(conf_files))
    if overrides:
        layers.append(overrides)
    if not layers:
        return fn(_conf(overrides), args)
    installed = 0
    try:
        for layer in layers:
            # a broken -conf file raises here, before dispatch — the
            # command never runs against partial configuration
            Configuration.add_default_resource(layer)
            installed += 1
        return fn(_conf(overrides), args)
    finally:
        if installed:
            del Configuration._default_resources[-installed:]


if __name__ == "__main__":
    sys.exit(main())
