"""tdfsproxy — read-only HTTP(S) gateway into cluster storage.

≈ the reference's hdfsproxy contrib (src/contrib/hdfsproxy/ —
``HdfsProxy.java``, ``ProxyListPathsServlet``/``ProxyStreamFile``
behind ``ProxyFilter``/``AuthorizationFilter``): expose file listing
and data to clients OUTSIDE the cluster's trust boundary, gated by a
per-user path allowlist, without giving them RPC access to the
NameNode. Same servlet surface:

- ``/listPaths/<path>``  — JSON recursive listing (the reference's XML
  ListPathsServlet, JSON like the rest of this stack's status ports);
- ``/data/<path>``       — streamed file bytes;
- ``/fileChecksum/<path>`` — MD5 of the content (the MD5-of-block-MD5s
  role; content MD5 since tdfs checksums are chunk-CRCs).

Access model (user-permissions.xml role): ``tdfsproxy.permissions.file``
is a TOML table of user → list of permitted path PREFIXES; absent user
= denied (fail closed, like AuthorizationFilter). Identity: the
reference authenticated by client TLS certificate
(``user-certs.xml``); this stack's posture elsewhere is simple-auth +
HMAC, so the proxy takes ``?user.name=`` and optionally pins each user
to source IPs (``ips = [...]`` per user — the certs analog), and can
serve TLS with ``tdfsproxy.ssl.cert``/``.key`` (stdlib ssl).
Documented divergence: no client-certificate auth.

Run: ``tpumr tdfsproxy -port 50479`` (0 = ephemeral, for tests).
"""

from __future__ import annotations

import hashlib
import json
import posixpath
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any
from urllib.parse import parse_qs, unquote, urlparse

from tpumr.metrics.core import MetricsSystem
from tpumr.metrics.histogram import BYTES
from tpumr.metrics.sampler import StackSampler

PERMISSIONS_KEY = "tdfsproxy.permissions.file"
SSL_CERT_KEY = "tdfsproxy.ssl.cert"
SSL_KEY_KEY = "tdfsproxy.ssl.key"


def load_permissions(path: str) -> "dict[str, dict]":
    """{user: {"paths": [prefix, ...], "ips": [ip, ...] | None}}.
    TOML (stdlib tomllib, Python >= 3.11) or JSON of the same shape when
    the path ends ``.json`` (the 3.10 route), e.g.::

        [alice]
        paths = ["/data/public", "/user/alice"]
        [bob]
        paths = ["/data/public"]
        ips = ["10.0.0.5"]
    """
    if path.endswith(".json"):
        with open(path) as jf:
            raw = json.load(jf)
    else:
        try:
            import tomllib     # stdlib only since 3.11
        except ImportError as e:
            raise RuntimeError(
                "TOML permissions need Python >= 3.11 (stdlib tomllib); "
                "on 3.10 use a .json permissions file with the same "
                "shape") from e
        with open(path, "rb") as f:
            raw = tomllib.load(f)
    perms: "dict[str, dict]" = {}
    for user, spec in raw.items():
        if not isinstance(spec, dict):
            raise ValueError(f"bad permissions entry for {user!r}")
        paths = [str(p) for p in spec.get("paths", [])]
        ips = spec.get("ips")
        # `ips = []` means "pinned to NO addresses" (deny all) — it must
        # not collapse into None ("no restriction"); only an absent key
        # leaves the user unpinned
        perms[user] = {"paths": paths,
                       "ips": ([str(i) for i in ips]
                               if ips is not None else None)}
    return perms


def path_permitted(perms: "dict[str, dict]", user: str, path: str,
                   remote_ip: str) -> bool:
    """Fail-closed prefix check (AuthorizationFilter.checkPath role):
    the normalized path must sit under one of the user's prefixes, and
    the peer must match the user's IP pins when present."""
    spec = perms.get(user)
    if spec is None:
        return False
    if spec["ips"] is not None and remote_ip not in spec["ips"]:
        return False
    norm = posixpath.normpath("/" + path.lstrip("/"))
    for prefix in spec["paths"]:
        p = posixpath.normpath("/" + prefix.lstrip("/"))
        if norm == p or norm.startswith(p.rstrip("/") + "/"):
            return True
    return False


class TdfsProxy:
    """The daemon: a threading HTTP(S) server over the FileSystem SPI."""

    def __init__(self, conf: Any, port: int = 50479,
                 host: str = "0.0.0.0") -> None:
        self.conf = conf
        perm_path = conf.get(PERMISSIONS_KEY)
        if not perm_path:
            raise ValueError(
                f"{PERMISSIONS_KEY} is required (fail-closed: a proxy "
                f"with no permissions file would deny everyone anyway)")
        self.permissions = load_permissions(str(perm_path))
        # the uniform daemon observability surface: the proxy has its
        # own stdlib HTTP stack (not StatusHttpServer), so it serves
        # /metrics, /metrics/prom, /stacks and /flame from the same
        # port as the data routes — same payload shapes as every other
        # daemon, so one scraper config covers the proxy too
        self.metrics = MetricsSystem("tdfsproxy")
        self._mreg = self.metrics.new_registry("tdfsproxy")
        self._req_hists: "dict[str, Any]" = {}
        self._data_bytes = self._mreg.histogram("proxy_data_bytes",
                                                bounds=BYTES)
        self.sampler = StackSampler.from_conf(conf, self.metrics)
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            #: per-connection socket timeout: a stalled peer must cost
            #: one handler thread for 30s, never wedge the daemon
            timeout = 30

            def log_message(self, *a):  # daemon logs, not stderr spam
                pass

            def do_GET(self) -> None:  # noqa: N802 — stdlib contract
                self._streaming = False
                try:
                    proxy._serve(self)
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001 — 500, not crash
                    if self._streaming:
                        # headers + partial body already sent: a second
                        # response would be counted as FILE BYTES by the
                        # client — drop the connection so the short read
                        # is detectable instead of silently corrupt
                        self.close_connection = True
                        return
                    try:
                        proxy._send_error(self, 500,
                                          f"{type(e).__name__}: {e}")
                    except Exception:  # noqa: BLE001
                        pass

        self.server = ThreadingHTTPServer((host, port), Handler)
        cert = conf.get(SSL_CERT_KEY)
        if cert:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(str(cert),
                                keyfile=(str(conf.get(SSL_KEY_KEY))
                                         if conf.get(SSL_KEY_KEY)
                                         else None))
            # handshake lazily in the per-connection handler thread: with
            # the default handshake-on-accept, one client that connects
            # and never sends a ClientHello parks the SINGLE accept loop
            # — a one-socket denial of service
            self.server.socket = ctx.wrap_socket(
                self.server.socket, server_side=True,
                do_handshake_on_connect=False)
            self.scheme = "https"
        else:
            self.scheme = "http"
        self._thread: "threading.Thread | None" = None

    # ------------------------------------------------------------ plumbing

    @property
    def port(self) -> int:
        return self.server.server_address[1]

    @property
    def url(self) -> str:
        host = self.server.server_address[0]
        if host == "0.0.0.0":
            host = "127.0.0.1"
        return f"{self.scheme}://{host}:{self.port}"

    def start(self) -> "TdfsProxy":
        if self.sampler is not None:
            self.sampler.start()
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        name="tdfsproxy", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self.sampler is not None:
            self.sampler.stop()
        self.server.shutdown()
        self.server.server_close()

    @staticmethod
    def _send_error(req: BaseHTTPRequestHandler, code: int,
                    msg: str) -> None:
        body = json.dumps({"error": msg}).encode()
        req.send_response(code)
        req.send_header("Content-Type", "application/json")
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    # ------------------------------------------------------------ serving

    def _fs(self, path: str):
        from tpumr.fs import get_filesystem
        return get_filesystem(path, self.conf)

    def _default_uri(self):
        from urllib.parse import urlsplit
        default = str(self.conf.get("fs.default.name", "file:///") or
                      "file:///")
        if "://" not in default:
            default = "file://" + default
        return urlsplit(default)

    def _qualify(self, path: str) -> str:
        """Relative paths resolve against fs.default.name, matching the
        reference's proxy forwarding to its configured namenode.
        URI-aware joining — naive string concat mangles the root
        namespace ('file:///'.rstrip('/') would yield 'file:')."""
        if "://" in path:
            # scheme-qualified requests could sidestep the prefix
            # check's normalization — the proxy serves ONE namespace
            raise ValueError("proxy paths are namespace-relative "
                             "(no scheme://)")
        from urllib.parse import urlunsplit
        s = self._default_uri()
        base = (s.path or "/").rstrip("/")
        return urlunsplit((s.scheme, s.netloc,
                           base + "/" + path.lstrip("/"), "", ""))

    def _relativize(self, full: str) -> str:
        """Back from a backing-store URI to the namespace-relative path
        clients speak — listings must neither leak the internal layout
        (file:///srv/cluster/..., namenode host:port) nor return paths
        /data/<path> would reject."""
        from urllib.parse import urlsplit
        s = self._default_uri()
        p = urlsplit(full if "://" in full else "file://" + full)
        base = (s.path or "/").rstrip("/")
        rel = p.path
        if base and rel.startswith(base):
            rel = rel[len(base):]
        return "/" + rel.lstrip("/")

    @staticmethod
    def _send_body(req: BaseHTTPRequestHandler, body: bytes,
                   content_type: str) -> None:
        req.send_response(200)
        req.send_header("Content-Type", content_type)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    def _serve_status(self, req: BaseHTTPRequestHandler, path: str,
                      query: dict) -> None:
        """Operator surfaces — unauthenticated like every other daemon's
        status port; they expose counters and stacks, never file data."""
        if path in ("metrics", "json/metrics"):
            self._send_body(req, json.dumps(self.metrics.snapshot())
                            .encode(), "application/json")
            return
        if path == "metrics/prom":
            from tpumr.metrics.prometheus import render_exposition
            self._send_body(req, render_exposition(
                self.metrics.typed_snapshot()).encode(),
                "text/plain; version=0.0.4")
            return
        # /stacks and /flame need the opt-in sampler
        if self.sampler is None:
            self._send_error(req, 404,
                             "profiling is off (tpumr.prof.enabled)")
            return
        seconds = float(query["seconds"]) if "seconds" in query else None
        if path == "stacks":
            self._send_body(req, self.sampler.folded(seconds).encode(),
                            "text/plain")
        else:
            self._send_body(req, self.sampler.flame_svg(
                seconds, title="tdfsproxy flame graph").encode(),
                "image/svg+xml")

    def _serve(self, req: BaseHTTPRequestHandler) -> None:
        parsed = urlparse(req.path)
        query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        user = query.get("user.name", "")
        status = parsed.path.strip("/")
        if status in ("metrics", "json/metrics", "metrics/prom",
                      "stacks", "flame"):
            self._serve_status(req, status, query)
            return
        route, _, rel = parsed.path.lstrip("/").partition("/")
        rel = unquote(rel)
        if route not in ("listPaths", "data", "fileChecksum"):
            self._send_error(req, 404,
                             "routes: /listPaths/<path>, /data/<path>, "
                             "/fileChecksum/<path> (+ /metrics, "
                             "/metrics/prom, /stacks, /flame)")
            return
        t0 = time.monotonic()
        try:
            self._serve_data(req, route, rel, user, query)
        finally:
            h = self._req_hists.get(route)
            if h is None:
                h = self._req_hists[route] = self._mreg.histogram(
                    f"proxy_request_seconds|route={route}")
            h.observe(time.monotonic() - t0)

    def _serve_data(self, req: BaseHTTPRequestHandler, route: str,
                    rel: str, user: str, query: dict) -> None:
        if not user:
            self._send_error(req, 401, "user.name query param required")
            return
        remote_ip = req.client_address[0]
        if not path_permitted(self.permissions, user, "/" + rel,
                              remote_ip):
            self._send_error(
                req, 403, f"user {user!r} is not permitted {'/' + rel!r}"
                          f" from {remote_ip}")
            return
        full = self._qualify("/" + rel)
        fs = self._fs(full)
        try:
            # ONE metadata call: exists()+get_status() would double the
            # namenode RPCs and turn a delete between them into a 500
            st = fs.get_status(full)
        except FileNotFoundError:
            self._send_error(req, 404, f"no such path: /{rel}")
            return
        if route == "listPaths":
            out = []
            entries = ([st] if not st.is_dir
                       else fs.list_files(full, recursive=True))
            for ent in entries:
                out.append({"path": self._relativize(str(ent.path)),
                            "is_dir": ent.is_dir,
                            "length": ent.length,
                            "mtime": getattr(ent, "mtime", 0)})
            body = json.dumps({"user": user, "paths": out}).encode()
            req.send_response(200)
            req.send_header("Content-Type", "application/json")
            req.send_header("Content-Length", str(len(body)))
            req.end_headers()
            req.wfile.write(body)
            return
        if st.is_dir:
            self._send_error(req, 400, f"/{rel} is a directory")
            return
        if route == "fileChecksum":
            md5 = hashlib.md5()
            with fs.open(full) as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    md5.update(chunk)
            body = json.dumps({"path": f"/{rel}", "algorithm": "MD5",
                               "checksum": md5.hexdigest()}).encode()
            req.send_response(200)
            req.send_header("Content-Type", "application/json")
            req.send_header("Content-Length", str(len(body)))
            req.end_headers()
            req.wfile.write(body)
            return
        # /data — stream the file. The flag flips BEFORE headers go out:
        # any later failure must close the connection, not append a 500
        # into the declared Content-Length
        req._streaming = True
        req.send_response(200)
        req.send_header("Content-Type", "application/octet-stream")
        req.send_header("Content-Length", str(st.length))
        req.end_headers()
        with fs.open(full) as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                req.wfile.write(chunk)
        self._data_bytes.observe(st.length)


def main(argv: "list[str]", conf: Any = None) -> int:
    import argparse

    from tpumr.mapred.jobconf import JobConf
    ap = argparse.ArgumentParser(
        prog="tpumr tdfsproxy",
        description="read-only HTTP(S) gateway into cluster storage "
                    "(= contrib/hdfsproxy)")
    ap.add_argument("-port", type=int, default=50479)
    ap.add_argument("-host", default="0.0.0.0")
    args = ap.parse_args(argv)
    conf = conf or JobConf()
    proxy = TdfsProxy(conf, port=args.port, host=args.host).start()
    print(f"tdfsproxy serving {conf.get('fs.default.name', 'file:///')} "
          f"on {proxy.url} ({len(proxy.permissions)} users)")
    try:
        proxy._thread.join()
    except KeyboardInterrupt:
        proxy.stop()
    return 0
