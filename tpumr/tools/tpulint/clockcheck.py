"""Rule family 3: clock discipline (the PR 2 sweep, un-regressable).

``clock-arith``: a ``time.time()`` value flowing into comparison or
add/subtract arithmetic inside one function. Deadlines, intervals,
expiry checks, and backoff math must use ``time.monotonic()`` — an NTP
step on a master mass-expires (or immortalizes) every tracker lease
computed from wall clock. Wall clock stays legal for human-facing
stamps (status pages, history events, trace alignment across hosts);
those sites carry ``# tpulint: disable=clock-arith`` with the reason
implied by the surrounding code.

Detection is deliberately local (one function at a time):

- a direct ``time.time()`` operand of ``+``/``-`` or a comparison;
- a local name assigned from ``time.time()`` later used as such an
  operand.

Cross-function flows (a wall stamp stored then compared elsewhere) are
out of scope here — storing the stamp is the legitimate use, and the
comparing site almost always re-reads ``time.time()`` locally, which
this rule does see.
"""

from __future__ import annotations

import ast

from tpumr.tools.tpulint.core import Finding, Module, call_name, \
    receiver_name

_MSG = ("wall-clock time.time() used in {what} — deadline/interval "
        "arithmetic must use time.monotonic(); if this is a "
        "human-facing stamp, pragma it")


def _is_walltime_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and call_name(node) == "time" and \
        receiver_name(node) in ("time", "_time")


class _Scope(ast.NodeVisitor):
    def __init__(self, m: Module, findings: "list[Finding]") -> None:
        self.m = m
        self.findings = findings
        self.tainted: set[str] = set()

    # each def gets its own taint scope
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        _Scope(self.m, self.findings).generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def _tainted_operand(self, node: ast.AST) -> bool:
        if _is_walltime_call(node):
            return True
        return isinstance(node, ast.Name) and node.id in self.tainted

    def visit_Assign(self, node: ast.Assign) -> None:
        if _is_walltime_call(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.tainted.add(tgt.id)
        elif isinstance(node.value, ast.IfExp):
            # t = time.time() if cond else 0.0  — still a wall stamp
            if _is_walltime_call(node.value.body) or \
                    _is_walltime_call(node.value.orelse):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.tainted.add(tgt.id)
            self.generic_visit(node)
        else:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.tainted.discard(tgt.id)
            self.generic_visit(node)

    def visit_BinOp(self, node: ast.BinOp) -> None:
        if isinstance(node.op, (ast.Add, ast.Sub)) and (
                self._tainted_operand(node.left)
                or self._tainted_operand(node.right)):
            what = "'+' arithmetic" if isinstance(node.op, ast.Add) \
                else "'-' arithmetic"
            self.findings.append(Finding(
                rule="clock-arith", path=self.m.rel, line=node.lineno,
                message=_MSG.format(what=what)))
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        operands = [node.left, *node.comparators]
        if any(self._tainted_operand(o) for o in operands):
            self.findings.append(Finding(
                rule="clock-arith", path=self.m.rel, line=node.lineno,
                message=_MSG.format(what="a comparison")))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if isinstance(node.op, (ast.Add, ast.Sub)) and \
                self._tainted_operand(node.value):
            self.findings.append(Finding(
                rule="clock-arith", path=self.m.rel, line=node.lineno,
                message=_MSG.format(what="'+='/'-=' arithmetic")))
        self.generic_visit(node)


def check_clock(mods: "list[Module]") -> "list[Finding]":
    findings: "list[Finding]" = []
    for m in mods:
        _Scope(m, findings).visit(m.tree)
    return findings
