"""Rule family 2: the config-key registry, enforced.

``tpumr/core/confkeys.py`` is the single source of truth for every
configuration key the tree reads — key, type, default, one doc line.
This pass keeps the registry and the code from drifting apart:

``conf-key``
    A typed-getter read (``conf.get*("tpumr..."...)``) of a key the
    registry doesn't know. The finding carries edit-distance
    suggestions, because in a dotted-string config system a typo'd key
    silently reads the default forever (the reference shipped exactly
    such bugs).

``conf-default``
    The same key read with different literal fallback defaults in
    different call sites, or with a literal default that contradicts
    the registry. Defaults live in ONE place; a second opinion in a
    call site is a latent config fork.

``conf-unread``
    A registered key nothing in the tree reads — a knob the docs
    promise but the code ignores.

``conf-example``
    ``conf/tpumr-site.example.toml`` names a key (active or
    suggested-commented) the registry doesn't know.

Dynamic keys (f-strings like ``f"tpumr.fi.{point}.probability"``)
match registry entries carrying ``pattern=True`` wildcards.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass

from tpumr.tools.tpulint.core import (Finding, Module, call_name,
                                      const_str, joined_prefix,
                                      receiver_name)


def registry_module(root: str, mods: "list[Module] | None" = None):
    """The confkeys module OF THE TREE BEING LINTED. Linting a foreign
    checkout (another branch, a colleague's tree) must judge its code
    against ITS registry, not whatever this process imported — so the
    root's ``tpumr/core/confkeys.py`` is executed in a private module
    namespace, with the imported module as fallback (fixture roots in
    tests carry no registry of their own)."""
    import types

    path = os.path.join(root, "tpumr", "core", "confkeys.py")
    src = None
    if mods is not None:
        for m in mods:
            if m.rel == "tpumr/core/confkeys.py":
                src, path = m.source, m.path
                break
    if src is None and os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            src = f.read()
    if src is not None:
        import sys

        ns = types.ModuleType("_tpulint_root_confkeys")
        ns.__file__ = path
        # dataclass processing resolves sys.modules[cls.__module__]
        # at class-creation time — the module must be registered while
        # its body executes
        prev = sys.modules.get(ns.__name__)
        sys.modules[ns.__name__] = ns
        try:
            exec(compile(src, path, "exec"), ns.__dict__)
        except Exception:
            src = None   # unexecutable registry: fall back (the file's
        finally:         # own parse error is reported separately)
            if prev is None:
                sys.modules.pop(ns.__name__, None)
            else:
                sys.modules[ns.__name__] = prev
        if src is not None and hasattr(ns, "REGISTRY") and \
                hasattr(ns, "lookup"):
            return ns
    from tpumr.core import confkeys as fallback
    return fallback

GETTER_TYPES = {
    "get": "str", "get_int": "int", "get_float": "float",
    "get_boolean": "bool", "get_strings": "strings", "get_size": "size",
    "get_class": "class",
}

#: prefixes under registry enforcement (reads of other prefixes may be
#: registered for the generated docs, but are not required to be)
ENFORCED_PREFIXES = ("tpumr.", "mapred.", "mapreduce.", "io.")

#: receivers a plain ``.get("key")`` counts as a CONFIG read on —
#: filters out dict lookups that happen to use dotted keys (counter
#: groups, status dicts). Typed getters (``get_int`` …) are
#: unambiguous and accepted on any receiver.
CONF_RECEIVERS = {"conf", "self", "_conf", "conf_dict", "jc", "jobconf",
                  "job_conf", "cfg", "site", "fi_conf", "confkeys"}

#: helpers that read conf keys handed to them as string arguments —
#: function name -> (key_idx, default_idx|None) pairs (e.g.
#: ``read_hosts_lists(conf, "mapred.hosts", "mapred.hosts.exclude")``;
#: ``self._conf_get("tdfs.client.dn.conns", 2)`` carries a call-site
#: default at index 1 that conf-default checks against the registry)
INDIRECT_READERS = {"read_hosts_lists": ((1, None), (2, None)),
                    "_conf_get": ((0, 1),)}


@dataclass
class Read:
    rel: str
    line: int
    key: str             # literal key, or f-string prefix for dynamic
    dynamic: bool
    type: str
    default: object      # literal default or _NO_DEFAULT
    typed: bool          # via a typed getter (not plain .get)


_NO_DEFAULT = object()


def _literal(node: "ast.AST | None"):
    if node is None:
        return _NO_DEFAULT
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub) \
            and isinstance(node.operand, ast.Constant):
        return -node.operand.value
    return _NO_DEFAULT   # computed defaults aren't literal opinions


def _const_maps(mods: "list[Module]") \
        -> "tuple[dict[str, dict[str, str]], dict[str, str]]":
    """UPPER_CASE string-constant assignments, per module and globally
    (for keys read through names like ``conf.get(ENABLED_KEY)``). A
    name assigned different strings in different modules is dropped
    from the global map (ambiguous across imports)."""
    per_mod: dict[str, dict[str, str]] = {}
    global_map: dict[str, str] = {}
    clashed: set[str] = set()
    for m in mods:
        consts = per_mod.setdefault(m.name, {})
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id.isupper():
                        consts[tgt.id] = node.value.value
                        if tgt.id in global_map and \
                                global_map[tgt.id] != node.value.value:
                            clashed.add(tgt.id)
                        global_map.setdefault(tgt.id, node.value.value)
    for name in clashed:
        global_map.pop(name, None)
    return per_mod, global_map


def _key_of(arg: ast.AST, consts: "dict[str, str]",
            global_consts: "dict[str, str]") \
        -> "tuple[str, bool] | None":
    """(key, dynamic) for an argument that names a config key."""
    key = const_str(arg)
    if key is not None:
        return key, False
    if isinstance(arg, ast.JoinedStr):
        prefix = joined_prefix(arg)
        return (prefix, True) if prefix else None
    if isinstance(arg, ast.Name) and arg.id.isupper():
        val = consts.get(arg.id, global_consts.get(arg.id))
        if val is not None:
            return val, False
    if isinstance(arg, ast.Attribute) and arg.attr.isupper():
        val = global_consts.get(arg.attr)
        if val is not None:
            return val, False
    return None


def collect_reads(mods: "list[Module]") -> "list[Read]":
    per_mod, global_consts = _const_maps(mods)
    reads: "list[Read]" = []
    for m in mods:
        consts = per_mod.get(m.name, {})
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            getter = call_name(node)
            if getter in INDIRECT_READERS:
                for idx, didx in INDIRECT_READERS[getter]:
                    if idx < len(node.args):
                        got = _key_of(node.args[idx], consts,
                                      global_consts)
                        if got is not None:
                            default = _NO_DEFAULT
                            if didx is not None and didx < len(node.args):
                                default = _literal(node.args[didx])
                            reads.append(Read(
                                rel=m.rel, line=node.lineno, key=got[0],
                                dynamic=got[1], type="str",
                                default=default, typed=False))
                continue
            if getter not in GETTER_TYPES or \
                    not isinstance(node.func, ast.Attribute):
                continue
            if getter == "get" and \
                    receiver_name(node) not in CONF_RECEIVERS:
                continue
            got = _key_of(node.args[0], consts, global_consts)
            default_idx = 1
            if got is None and len(node.args) > 1:
                # confkeys.get_*(conf, "key") — registry-backed readers
                # carry the key second and no call-site default
                got = _key_of(node.args[1], consts, global_consts)
                default_idx = 2
            if got is None:
                continue
            key, dynamic = got
            if not re.match(r"^[a-z][A-Za-z0-9_.\-]*$",
                            key if not dynamic else key + "x") or \
                    "." not in key:
                continue
            default = _NO_DEFAULT
            if len(node.args) > default_idx:
                default = _literal(node.args[default_idx])
            for kw in node.keywords:
                if kw.arg == "default":
                    default = _literal(kw.value)
            reads.append(Read(rel=m.rel, line=node.lineno, key=key,
                              dynamic=dynamic, type=GETTER_TYPES[getter],
                              default=default, typed=getter != "get"))
    return reads


def _is_read(ck, entry, reads: "list[Read]") -> bool:
    for r in reads:
        if r.dynamic:
            if entry.pattern and ck.pattern_covers(entry.key, r.key):
                return True
            continue
        if entry.pattern:
            if ck.pattern_matches(entry.key, r.key):
                return True
        elif r.key == entry.key:
            return True
    return False


def _toml_keys(path: str) -> "list[tuple[str, int]]":
    """(dotted key, line) for every active AND suggested-commented key
    in a site-example TOML: table headers combine with quoted keys;
    ``#"sub.key" = v`` comment lines document a knob and count."""
    out: "list[tuple[str, int]]" = []
    table = ""
    with open(path, encoding="utf-8") as f:
        for i, raw in enumerate(f, start=1):
            line = raw.strip()
            m = re.match(r"^\[([A-Za-z0-9_.\"\-]+)\]$", line)
            if m:
                table = m.group(1).replace('"', "")
                continue
            m = re.match(r"^#?\s*\"([^\"]+)\"\s*=", line) or \
                re.match(r"^#?\s*([A-Za-z0-9_.\-]+)\s*=\s*[^=]", line)
            if m and not line.startswith("##"):
                key = m.group(1)
                if line.startswith("#") and not re.match(
                        r"^#\s*\"", line):
                    continue   # prose comment, not a commented key
                out.append((f"{table}.{key}" if table else key, i))
    return out


def check_conf(mods: "list[Module]", root: str) -> "list[Finding]":
    findings: "list[Finding]" = []
    reads = collect_reads(mods)
    ck = registry_module(root, mods)
    registry = ck.REGISTRY

    # conf-key: enforced-prefix reads must be registered
    for r in reads:
        if not r.key.startswith(ENFORCED_PREFIXES):
            continue
        if r.dynamic:
            if not any(e.pattern and ck.pattern_covers(e.key, r.key)
                       for e in registry.values()):
                findings.append(Finding(
                    rule="conf-key", path=r.rel, line=r.line,
                    message=(f"dynamic config key '{r.key}…' matches no "
                             f"registered pattern — add a pattern entry "
                             f"to tpumr/core/confkeys.py")))
            continue
        if ck.lookup(r.key) is None:
            hint = ck.suggest(r.key)
            extra = f" (did you mean: {', '.join(hint)}?)" if hint else ""
            findings.append(Finding(
                rule="conf-key", path=r.rel, line=r.line,
                message=(f"config key '{r.key}' is not in the registry "
                         f"(tpumr/core/confkeys.py){extra}")))

    # conf-default: literal defaults must agree across sites + registry
    by_key: dict[str, list[Read]] = {}
    for r in reads:
        if not r.dynamic and r.default is not _NO_DEFAULT:
            by_key.setdefault(r.key, []).append(r)
    for key, sites in sorted(by_key.items()):
        entry = ck.lookup(key)
        distinct = {repr(s.default) for s in sites}
        if entry is not None and not entry.pattern:
            bad = [s for s in sites if s.default != entry.default]
            for s in bad:
                findings.append(Finding(
                    rule="conf-default", path=s.rel, line=s.line,
                    message=(f"'{key}' read with default "
                             f"{s.default!r} but the registry says "
                             f"{entry.default!r} — defaults live in "
                             f"confkeys.py only")))
        elif len(distinct) > 1:
            where = ", ".join(f"{s.rel}:{s.line}={s.default!r}"
                              for s in sites)
            findings.append(Finding(
                rule="conf-default", path=sites[0].rel,
                line=sites[0].line,
                message=(f"'{key}' read with conflicting defaults "
                         f"({where}) — register it and pick one")))

    # conf-unread: every registry entry must be read somewhere
    ck_rel, ck_lines = _registry_source(mods)
    for entry in registry.values():
        if not _is_read(ck, entry, reads):
            findings.append(Finding(
                rule="conf-unread", path=ck_rel,
                line=ck_lines.get(entry.key, 1),
                message=(f"registered key '{entry.key}' is read "
                         f"nowhere in tpumr/ — dead knob (remove it or "
                         f"wire it up)")))

    # conf-example: the shipped example file names only known keys
    example = os.path.join(root, "conf", "tpumr-site.example.toml")
    if os.path.exists(example):
        rel = os.path.relpath(example, root).replace(os.sep, "/")
        for key, line in _toml_keys(example):
            if ck.lookup(key) is None:
                findings.append(Finding(
                    rule="conf-example", path=rel, line=line,
                    message=(f"example conf names '{key}', which is not "
                             f"a registered key (phantom knob)")))
    return findings


def _registry_source(mods: "list[Module]") \
        -> "tuple[str, dict[str, int]]":
    """Line of each registered key string inside confkeys.py, for
    anchoring conf-unread findings."""
    for m in mods:
        if m.rel.endswith("core/confkeys.py"):
            lines: dict[str, int] = {}
            for i, text in enumerate(m.source.splitlines(), start=1):
                mm = re.search(r'''_K\(["']([^"']+)["']''', text)
                if mm:
                    lines.setdefault(mm.group(1), i)
            return m.rel, lines
    return "tpumr/core/confkeys.py", {}
