"""``python -m tpumr.tools.tpulint`` — the warning-free module entry
point (running ``.cli`` directly trips runpy's already-imported
warning because the package __init__ re-exports it)."""

import sys

from tpumr.tools.tpulint.cli import main

if __name__ == "__main__":
    sys.exit(main())
