"""Rule family 4: drift between prose and code.

Operators navigate this system through ``docs/OPERATIONS.md`` — metric
names to graph, fault-injection seams to pull in chaos drills. A
renamed metric or seam that the doc still advertises is a page that
lies during an incident. The reference tree's equivalent failure mode
was `/** MODIFIED FOR GPGPU Usage! **/` comment tags drifting away
from the code they annotated (PAPER.md).

``drift-metric``
    A backticked code-ish token in OPERATIONS.md (``tpumr_*`` series,
    ``*_seconds{...}`` histograms, counters, identifiers) that nothing
    in ``tpumr/`` registers or defines. Matching is prefix-aware:
    ``tpumr_`` is the Prometheus namespace the exporter prepends, and
    composite gauges flatten to ``name_key``.

``drift-fi``
    A fault-seam name advertised in OPERATIONS.md or the
    ``tpumr/utils/fi.py`` module docstring (``tpumr.fi.<point>...``)
    that no ``maybe_fail()``/``fires()`` call site can ever fire.
    Placeholder syntax is honored: ``tpu.execute[.d<id>]`` means the
    base seam plus a templated variant.
"""

from __future__ import annotations

import ast
import os
import re

from tpumr.tools.tpulint.core import (Finding, Module, call_name,
                                      const_str, joined_prefix)

_BACKTICK = re.compile(r"`([^`\n]+)`")
_TOKEN = re.compile(r"^[a-z][a-z0-9_]*$")
_METRIC_CALLS = {"incr", "set_gauge", "histogram", "Histogram"}
_FI_CALLS = {"maybe_fail", "fires", "fired"}
_SEAM = re.compile(r"^[a-z][a-z0-9_<>]*(\.[a-z0-9_<>]+)+$")


def _registered_metrics(mods: "list[Module]") -> set[str]:
    names: set[str] = set()
    for m in mods:
        consts: dict[str, str] = {}
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        consts[tgt.id] = node.value.value
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call) and \
                    call_name(node) in _METRIC_CALLS and node.args:
                arg = node.args[0]
                name = const_str(arg)
                if name is None and isinstance(arg, ast.Name):
                    name = consts.get(arg.id)
                if name is None and isinstance(arg, ast.JoinedStr):
                    name = joined_prefix(arg) + "*"
                if name is None and isinstance(arg, ast.BinOp) and \
                        isinstance(arg.op, ast.Add):
                    # reg.histogram(name + "_request_bytes"): dynamic
                    # prefix, literal suffix
                    suffix = const_str(arg.right)
                    if suffix:
                        name = "*" + suffix
                if name:
                    names.add(name)
                    # internal labeled-series convention is
                    # "family|label=value" — docs write {label=...};
                    # the family name is the identity
                    names.add(name.split("|", 1)[0])
    return names


def _identifiers(mods: "list[Module]") -> set[str]:
    ids: set[str] = set()
    for m in mods:
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Name):
                ids.add(node.id)
            elif isinstance(node, ast.Attribute):
                ids.add(node.attr)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                ids.add(node.name)
            elif isinstance(node, ast.arg):
                ids.add(node.arg)
            elif isinstance(node, ast.keyword) and node.arg:
                ids.add(node.arg)
            elif isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    _TOKEN.match(node.value):
                # dict-key / counter-name string literals count: docs
                # legitimately name JSON fields and counter rows
                ids.add(node.value)
        ids.update(k.split("=")[0] for k in ())
    return ids


def _metric_known(token: str, metrics: set[str]) -> bool:
    base = token.split("{", 1)[0]
    for cand in ({base} | ({base[len("tpumr_"):]}
                           if base.startswith("tpumr_") else set())):
        if cand in metrics:
            return True
        for name in metrics:
            if name.endswith("*") and cand.startswith(name[:-1]):
                return True
            if name.startswith("*") and cand.endswith(name[1:]):
                return True
            # composite gauges flatten to name_key in exposition
            if not name.startswith("*") and \
                    cand.startswith(name.rstrip("*") + "_"):
                return True
    return False


def _root_modules(root: str) -> "list[Module]":
    """Top-level repo scripts (bench_scale.py & friends) — their row
    keys and identifiers are legitimately named in OPERATIONS.md."""
    import glob

    from tpumr.tools.tpulint.core import Pragmas
    out: "list[Module]" = []
    for path in sorted(glob.glob(os.path.join(root, "*.py"))):
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src)
        except (OSError, SyntaxError):
            continue
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        out.append(Module(path=path, rel=rel, source=src, tree=tree,
                          pragmas=Pragmas("")))
    return out


def check_metric_drift(mods: "list[Module]", root: str) \
        -> "list[Finding]":
    doc = os.path.join(root, "docs", "OPERATIONS.md")
    if not os.path.exists(doc):
        return []
    rel = os.path.relpath(doc, root).replace(os.sep, "/")
    corpus = mods + _root_modules(root)
    metrics = _registered_metrics(corpus)
    idents = _identifiers(corpus)
    findings: "list[Finding]" = []
    seen: set[str] = set()
    with open(doc, encoding="utf-8") as f:
        for lineno, line in enumerate(f, start=1):
            if "tpulint: disable=drift-metric" in line:
                continue   # markdown can't carry python pragmas; an
                           # HTML comment on the line suppresses it
            for span in _BACKTICK.findall(line):
                token = span.strip()
                base = token.split("{", 1)[0]
                if "_" not in base or not _TOKEN.match(base):
                    continue
                if token in seen:
                    continue
                if _metric_known(token, metrics) or base in idents:
                    continue
                seen.add(token)
                findings.append(Finding(
                    rule="drift-metric", path=rel, line=lineno,
                    message=(f"docs name `{token}` but nothing in "
                             f"tpumr/ registers or defines it — "
                             f"renamed or removed?")))
    return findings


# ------------------------------------------------------------------- fi


def _fired_points(mods: "list[Module]") -> set[str]:
    """Seam names call sites can fire; f-string seams contribute their
    literal prefix + '*'."""
    points: set[str] = set()
    for m in mods:
        if m.rel.endswith("utils/fi.py"):
            continue
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call) and \
                    call_name(node) in _FI_CALLS and node.args:
                arg = node.args[0]
                point = const_str(arg)
                if point is None and isinstance(arg, ast.JoinedStr):
                    point = joined_prefix(arg) + "*"
                if point:
                    points.add(point)
    return points


def _expand_placeholder(tok: str) -> "list[str]":
    """'tpu.execute[.d<id>]' -> ['tpu.execute', 'tpu.execute.d*'];
    '<...>' placeholders become '*'."""
    m = re.match(r"^([^\[\]]*)\[([^\[\]]+)\](.*)$", tok)
    if m:
        variants = [m.group(1) + m.group(3),
                    m.group(1) + m.group(2) + m.group(3)]
    else:
        variants = [tok]
    return [re.sub(r"<[^>]*>", "*", v) for v in variants]


def _seam_known(seam: str, fired: set[str]) -> bool:
    """A doc seam matches a fired point exactly, or by wildcard prefix
    overlap in either direction (doc 'tpu.execute.d*' vs fired
    f-string prefix 'tpu.execute.d*')."""
    if seam in fired:
        return True
    want = seam[:-1] if seam.endswith("*") else None
    for p in fired:
        got = p[:-1] if p.endswith("*") else None
        if want is not None and got is not None:
            if got.startswith(want) or want.startswith(got):
                return True
        elif want is not None and p.startswith(want):
            return True
        elif got is not None and seam.startswith(got):
            return True
    return False


def _doc_seams(text: str) -> "list[tuple[str, int]]":
    """Seam names a document advertises: ``tpumr.fi.<seam>.probability``
    / ``.max.failures`` config references, with placeholders."""
    out: "list[tuple[str, int]]" = []
    for lineno, line in enumerate(text.splitlines(), start=1):
        for m in re.finditer(
                r"tpumr\.fi\.([a-z0-9_.<>\[\]]+?)"
                r"\.(?:probability|max\.failures)", line):
            out.append((m.group(1), lineno))
    return out


def _fi_docstring_seams(fi_mod: Module) -> "list[tuple[str, int]]":
    """Bare seam names listed in fi.py's MODULE docstring (the seam
    catalog)."""
    doc = ast.get_docstring(fi_mod.tree, clean=False) or ""
    out: "list[tuple[str, int]]" = []
    for lineno, line in enumerate(doc.splitlines(), start=2):
        for raw in re.split(r"[\s/]+", line):
            tok = raw.strip(",;:()").rstrip(".")
            if not _SEAM.match(tok) or tok.startswith("tpumr."):
                continue
            segs = tok.replace("<", " ").replace(">", " ").split(".")
            if all(len(s.strip()) <= 1 for s in segs):
                continue   # 'e.g', 'i.e'
            out.append((tok, lineno))
    return out


def check_fi_drift(mods: "list[Module]", root: str) -> "list[Finding]":
    fired = _fired_points(mods)
    findings: "list[Finding]" = []
    doc = os.path.join(root, "docs", "OPERATIONS.md")
    sources: "list[tuple[str, list[tuple[str, int]]]]" = []
    if os.path.exists(doc):
        with open(doc, encoding="utf-8") as f:
            sources.append((
                os.path.relpath(doc, root).replace(os.sep, "/"),
                _doc_seams(f.read())))
    fi_mod = next((m for m in mods if m.rel.endswith("utils/fi.py")),
                  None)
    if fi_mod is not None:
        seams = _fi_docstring_seams(fi_mod) + _doc_seams(fi_mod.source)
        sources.append((fi_mod.rel, seams))
    for rel, seams in sources:
        reported: set[str] = set()
        for tok, lineno in seams:
            for seam in _expand_placeholder(tok):
                if seam in reported or _seam_known(seam, fired):
                    continue
                reported.add(seam)
                findings.append(Finding(
                    rule="drift-fi", path=rel, line=lineno,
                    message=(f"fault seam '{seam}' is advertised but no "
                             f"maybe_fail()/fires() call site fires it")))
    return findings
