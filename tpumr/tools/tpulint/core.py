"""tpulint infrastructure: corpus loading, findings, pragmas.

The analyzer is repo-native: every rule encodes an invariant THIS
codebase promises (the master's lock-rank order, the config-key
registry, monotonic-clock deadline arithmetic, docs/code drift), not a
general style opinion. Rules operate on stdlib ``ast`` trees — no new
dependencies — and report :class:`Finding` rows a CLI renders as text
or JSON.

Suppression is per-rule and per-line::

    deadline = time.time() + 30   # tpulint: disable=clock-arith

A pragma on a comment-only line suppresses the next code line; a
pragma in the leading comment block (before any code) suppresses the
rule for the whole file. Pragmas are deliberately narrow — one rule
name each (comma-separated for several) — so a disable never outlives
the violation it excuses.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Iterable

PRAGMA_RE = re.compile(r"#\s*tpulint:\s*disable=([a-z\-*,\s]+)")

#: rule families, in report order
ALL_RULES = (
    "parse-error",      # file failed to parse — every other rule is blind to it
    "lock-order",       # ranked-lock acquisition violating the master's order
    "lock-blocking",    # blocking call reachable while a ranked lock is held
    "conf-key",         # config key read but not in the confkeys registry
    "conf-default",     # key read with a default conflicting across sites/registry
    "conf-unread",      # registered key nothing reads
    "conf-example",     # example conf file key not in the registry (or phantom)
    "clock-arith",      # time.time() flowing into deadline/interval arithmetic
    "drift-metric",     # docs name a metric the code never registers
    "drift-fi",         # docs/fi.py name a fault seam no call site fires
)


@dataclass
class Finding:
    rule: str
    path: str            # repo-relative
    line: int
    message: str
    chain: "list[str]" = field(default_factory=list)

    def render(self) -> str:
        head = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if self.chain:
            head += "".join(f"\n    {hop}" for hop in self.chain)
        return head

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "chain": list(self.chain)}


class Pragmas:
    """Per-file suppression table parsed from the raw source."""

    def __init__(self, source: str) -> None:
        self.line_rules: dict[int, set[str]] = {}
        self.file_rules: set[str] = set()
        in_header = True
        for i, text in enumerate(source.splitlines(), start=1):
            stripped = text.strip()
            if in_header and stripped and not stripped.startswith("#"):
                in_header = False
            m = PRAGMA_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            if in_header and stripped.startswith("#"):
                self.file_rules |= rules
            elif stripped.startswith("#"):
                # comment-only line: the pragma governs the next line
                self.line_rules.setdefault(i + 1, set()).update(rules)
            else:
                self.line_rules.setdefault(i, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_rules or "*" in self.file_rules:
            return True
        rules = self.line_rules.get(line, ())
        return rule in rules or "*" in rules


@dataclass
class Module:
    """One parsed source file plus everything rules need from it."""

    path: str            # absolute
    rel: str             # repo-relative, '/'-separated
    source: str
    tree: ast.Module
    pragmas: Pragmas
    #: (lineno, message) when the file failed to parse — the tree is
    #: then empty and every other rule is blind to the file, so the
    #: error MUST surface as a finding of its own
    parse_error: "tuple[int, str] | None" = None

    @property
    def name(self) -> str:
        """Dotted module name (tpumr.mapred.jobtracker)."""
        return self.rel[:-3].replace("/", ".").replace(".__init__", "")


def _iter_py(root: str, subdir: str) -> Iterable[str]:
    base = os.path.join(root, subdir)
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in sorted(filenames):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def load_corpus(root: str, subdirs: "tuple[str, ...]" = ("tpumr",)) \
        -> "list[Module]":
    mods: "list[Module]" = []
    for sub in subdirs:
        for path in _iter_py(root, sub):
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            parse_error = None
            try:
                tree = ast.parse(src, filename=path)
            except SyntaxError as e:  # a broken file is its own finding
                tree = ast.Module(body=[], type_ignores=[])
                parse_error = (e.lineno or 1, e.msg or "syntax error")
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            mods.append(Module(path=path, rel=rel, source=src, tree=tree,
                               pragmas=Pragmas(src),
                               parse_error=parse_error))
    return mods


def parse_error_findings(mods: "list[Module]") -> "list[Finding]":
    return [Finding(rule="parse-error", path=m.rel,
                    line=m.parse_error[0],
                    message=(f"file does not parse "
                             f"({m.parse_error[1]}) — every other rule "
                             f"is blind to it"))
            for m in mods if m.parse_error is not None]


def apply_pragmas(mods: "list[Module]",
                  findings: "list[Finding]") -> "list[Finding]":
    by_rel = {m.rel: m for m in mods}
    out = []
    for f in findings:
        m = by_rel.get(f.path)
        if m is not None and m.pragmas.suppressed(f.rule, f.line):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


# ------------------------------------------------------------- ast helpers


def const_str(node: ast.AST) -> "str | None":
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def joined_prefix(node: ast.JoinedStr) -> str:
    """Literal prefix of an f-string, up to the first interpolation."""
    out = []
    for part in node.values:
        s = const_str(part)
        if s is None:
            break
        out.append(s)
    return "".join(out)


def call_name(node: ast.Call) -> str:
    """Rightmost name of the called thing: foo() / a.b.foo() -> 'foo'."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def receiver_name(node: ast.Call) -> str:
    """Name of the call receiver: a.foo() -> 'a', self.b.foo() -> 'b',
    foo() -> ''."""
    fn = node.func
    if not isinstance(fn, ast.Attribute):
        return ""
    obj = fn.value
    if isinstance(obj, ast.Name):
        return obj.id
    if isinstance(obj, ast.Attribute):
        return obj.attr
    return ""
