"""tpulint — the repo-native static analyzer behind ``tpumr lint``.

Four rule families, each proving an invariant the runtime only
spot-checks (see the module docstrings for the contracts):

- :mod:`tpumr.tools.tpulint.lockcheck` — the master's ranked-lock
  acquisition order and the no-blocking-under-lock rule, derived
  interprocedurally (rank table parsed from ``tpumr/metrics/locks.py``).
- :mod:`tpumr.tools.tpulint.confcheck` — the config-key registry
  (``tpumr/core/confkeys.py``) as the single source of truth for
  keys, types, and defaults.
- :mod:`tpumr.tools.tpulint.clockcheck` — ``time.time()`` must not
  flow into deadline/interval arithmetic (monotonic-clock discipline).
- :mod:`tpumr.tools.tpulint.driftcheck` — docs/OPERATIONS.md metric
  names and fault-injection seams checked against what the code
  actually registers and fires.

Per-line suppression: ``# tpulint: disable=<rule>[,<rule>...]``.
"""

from tpumr.tools.tpulint.core import ALL_RULES, Finding  # noqa: F401
from tpumr.tools.tpulint.cli import main, run_lint  # noqa: F401
