"""Rule family 1: the master's lock discipline, proven statically.

Since the lock decomposition (PR 8) the control plane runs on five
ranked lock classes (``tpumr/metrics/locks.py``) whose acquisition
order is asserted only at runtime, on paths tests happen to exercise.
This pass re-derives the invariant from source:

``lock-order``
    A ``with``-acquisition of a ranked lock whose rank is LOWER than a
    rank already held — directly, or anywhere down an interprocedural
    call chain (the runtime assertion only fires if the path runs).

``lock-blocking``
    A blocking operation (RPC call, socket/file I/O, ``time.sleep``,
    ``.join()`` on a thread, ``.wait()``, subprocess waits) reachable
    while a ranked lock is held. Ranked locks guard the heartbeat fast
    path; one blocked holder convoys every contender (PAPERS.md "It's
    the Critical Path!").

Scope: ``tpumr/mapred/`` + ``tpumr/ipc/`` + ``tpumr/metrics/`` (where
the ranks live) + ``tpumr/dfs/`` (the NameNode's ``namespace`` rank —
PR 17). Lock identity is derived from
``InstrumentedRLock(..., rank=...)`` assignments; the rank constants
are parsed out of ``tpumr/metrics/locks.py`` itself so this file never
restates the order. Unranked locks (plain ``threading.Lock``/``RLock``)
are out of scope by design — the discipline is a contract between the
five master lock classes, not every mutex in the tree.

Heuristics, stated plainly (a repo-native analyzer can afford them):

- ``self.X`` resolves through the enclosing class (and corpus bases);
  other receivers resolve when the attribute is ranked in exactly one
  class, or via :data:`RECEIVER_HINTS` (``jip``/``job`` are always a
  ``JobInProgress``, etc.).
- Calls resolve: ``self.m()`` within the class/bases;
  ``self.attr.m()`` when ``self.attr = SomeCorpusClass(...)`` is
  assigned anywhere in the class; ``recv.m()`` via hints; bare ``f()``
  within the module or its corpus ``from``-imports. Unresolvable calls
  are skipped — the rule prefers silence to noise.
- Code inside nested ``def``/``lambda`` is NOT considered to run under
  an enclosing ``with`` (it is deferred work); it is analyzed as its
  own function and charged at its call sites.
- A ``# tpulint: disable=lock-blocking`` pragma ON THE BLOCKING CALL
  ITSELF (not just at a locked call site) removes it as a blocking
  SOURCE everywhere — direct and through transitive chains. This is
  for invariant-documented blocking the design pins under a lock (the
  edit log's write-ahead roll); the justification comment lives at the
  one line that blocks, instead of a pragma at every caller.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tpumr.tools.tpulint.core import (Finding, Module, call_name,
                                      receiver_name)

#: receiver-variable naming conventions this codebase holds to; used
#: only when an attribute name is ranked in more than one class
RECEIVER_HINTS = {
    "jip": "JobInProgress",
    "job": "JobInProgress",
    "info": "_TrackerInfo",
    "tracker_info": "_TrackerInfo",
}

#: methods returning ``(ranked_lock, ...)`` tuples — the tracker
#: registry's stripe accessor
TUPLE_LOCK_METHODS = {"shard_of": "RANK_TRACKERS"}

#: fallback rank table; overridden by whatever tpumr/metrics/locks.py
#: actually declares when it is in the corpus
DEFAULT_RANKS = {"RANK_TRACKER_BEAT": 5, "RANK_SCHEDULER": 10,
                 "RANK_PIPELINE": 15, "RANK_GLOBAL": 20,
                 "RANK_NAMESPACE": 25, "RANK_NAMESPACE_STRIPE": 26,
                 "RANK_NAMESPACE_BLOCKS": 27, "RANK_TRACKERS": 30,
                 "RANK_JOB": 40}

_SOCKETY = ("sock", "conn", "channel")
_THREADY = ("thread", "worker", "pumper", "_t")
_RPC_RECEIVERS = {"client", "rpc", "proxy", "nn", "jt", "master",
                  "umbilical", "_client"}
_BLOCK_SOCKET_METHODS = {"recv", "recv_into", "sendall", "accept",
                         "connect", "makefile"}
_BLOCK_SUBPROCESS = {"run", "check_output", "check_call", "communicate"}


@dataclass
class FuncInfo:
    key: str                     # module:Class.name or module:name
    rel: str
    node: ast.AST
    cls: "str | None"
    acquires: "list[tuple[int, str, int]]" = field(default_factory=list)
    blocking: "list[tuple[str, int]]" = field(default_factory=list)
    # (candidate keys, line, held ranks [(rank, lockname)], callee label)
    calls: "list[tuple[tuple[str, ...], int, tuple, str]]" = \
        field(default_factory=list)
    direct_findings: "list[Finding]" = field(default_factory=list)


class LockWorld:
    """Everything the rule knows about locks, classes, and functions."""

    def __init__(self, mods: "list[Module]") -> None:
        self.mods = mods
        self.ranks = dict(DEFAULT_RANKS)
        # (class, attr) -> (rank, lockname); attr -> {class, ...}
        self.class_attr_rank: dict[tuple[str, str], tuple[int, str]] = {}
        self.attr_classes: dict[str, set[str]] = {}
        self.bases: dict[str, list[str]] = {}
        self.funcs: dict[str, FuncInfo] = {}
        self.methods_by_name: dict[str, list[str]] = {}
        self.module_funcs: dict[tuple[str, str], str] = {}
        self.imports: dict[str, dict[str, str]] = {}
        self.class_names: set[str] = set()
        # (class, attr) -> corpus class the attr is an instance of
        self.attr_types: dict[tuple[str, str], str] = {}
        for m in mods:
            for node in m.tree.body:
                if isinstance(node, ast.ClassDef):
                    self.class_names.add(node.name)
        self._collect_ranks()
        self._collect_defs()

    # -------------------------------------------------------- collection

    def _collect_ranks(self) -> None:
        for m in self.mods:
            if not m.rel.endswith("metrics/locks.py"):
                continue
            for node in m.tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id.startswith("RANK_") \
                        and isinstance(node.value, ast.Constant):
                    self.ranks[node.targets[0].id] = int(node.value.value)

    def _rank_of_call(self, call: ast.Call) -> "tuple[int, str] | None":
        if call_name(call) != "InstrumentedRLock":
            return None
        rank, name = 0, ""
        for kw in call.keywords:
            if kw.arg == "rank":
                if isinstance(kw.value, ast.Name):
                    rank = self.ranks.get(kw.value.id, 0)
                elif isinstance(kw.value, ast.Constant):
                    rank = int(kw.value.value)
            elif kw.arg == "name":
                if isinstance(kw.value, ast.Constant):
                    name = str(kw.value.value)
                elif isinstance(kw.value, ast.JoinedStr):
                    from tpumr.tools.tpulint.core import joined_prefix
                    name = joined_prefix(kw.value) + "*"
        return (rank, name) if rank else None

    def _lock_value(self, value: ast.AST) -> "tuple[int, str] | None":
        """Rank of an assigned value: a ranked-lock ctor call, or a
        list/comprehension of them (stripe arrays)."""
        if isinstance(value, ast.Call):
            return self._rank_of_call(value)
        if isinstance(value, ast.ListComp) and \
                isinstance(value.elt, ast.Call):
            return self._rank_of_call(value.elt)
        if isinstance(value, ast.List):
            for elt in value.elts:
                if isinstance(elt, ast.Call):
                    got = self._rank_of_call(elt)
                    if got:
                        return got
        return None

    def _collect_defs(self) -> None:
        for m in self.mods:
            self.imports[m.name] = imps = {}
            for node in ast.walk(m.tree):
                if isinstance(node, ast.ImportFrom) and node.module:
                    for alias in node.names:
                        imps[alias.asname or alias.name] = \
                            f"{node.module}:{alias.name}"
            for node in m.tree.body:
                if isinstance(node, ast.ClassDef):
                    self.bases[node.name] = [
                        b.id for b in node.bases if isinstance(b, ast.Name)]
                    self._collect_class(m, node)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    self._add_func(m, node, None)

    def _collect_class(self, m: Module, cls: ast.ClassDef) -> None:
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                got = self._lock_value(node.value)
                inst = None
                if got is None and isinstance(node.value, ast.Call):
                    cname = call_name(node.value)
                    if cname in self.class_names:
                        inst = cname
                    elif call_name(node.value) in ("bind", "start") and \
                            isinstance(node.value.func, ast.Attribute) and \
                            isinstance(node.value.func.value, ast.Call) \
                            and call_name(node.value.func.value) \
                            in self.class_names:
                        # self.x = Cls(...).bind(...) / .start()
                        inst = call_name(node.value.func.value)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        if got:
                            self.class_attr_rank[(cls.name, tgt.attr)] = got
                            self.attr_classes.setdefault(
                                tgt.attr, set()).add(cls.name)
                        elif inst:
                            self.attr_types[(cls.name, tgt.attr)] = inst
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_func(m, node, cls.name)

    def _add_func(self, m: Module, node: ast.AST, cls: "str | None",
                  prefix: str = "") -> None:
        label = f"{cls}.{node.name}" if cls else node.name
        if prefix:
            label = f"{prefix}.{label}"
        key = f"{m.name}:{label}"
        self.funcs[key] = FuncInfo(key=key, rel=m.rel, node=node, cls=cls)
        if cls:
            self.methods_by_name.setdefault(node.name, []).append(key)
        else:
            self.module_funcs[(m.name, node.name)] = key
            self.methods_by_name.setdefault(node.name, []).append(key)
        # nested defs get their own (deferred-execution) summaries
        for stmt in ast.walk(node):
            if stmt is not node and isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                    getattr(stmt, "_tpulint_seen", False) is False:
                stmt._tpulint_seen = True  # type: ignore[attr-defined]
                self._add_func(m, stmt, cls, prefix=node.name)

    # -------------------------------------------------------- resolution

    def attr_rank(self, cls: "str | None", attr: str,
                  recv: str) -> "tuple[int, str] | None":
        """Rank of ``recv.attr`` seen from a method of ``cls``."""
        if recv == "self" and cls:
            seen, stack = set(), [cls]
            while stack:
                c = stack.pop()
                if c in seen:
                    continue
                seen.add(c)
                got = self.class_attr_rank.get((c, attr))
                if got:
                    return got
                stack.extend(self.bases.get(c, ()))
        owners = self.attr_classes.get(attr, set())
        if len(owners) == 1:
            return self.class_attr_rank[(next(iter(owners)), attr)]
        hint = RECEIVER_HINTS.get(recv)
        if hint and (hint, attr) in self.class_attr_rank:
            return self.class_attr_rank[(hint, attr)]
        return None

    def resolve_call(self, mod: str, cls: "str | None",
                     call: ast.Call) -> "tuple[str, ...]":
        name = call_name(call)
        if not name or name.startswith("__"):
            return ()
        fn = call.func
        if isinstance(fn, ast.Name):
            key = self.module_funcs.get((mod, name))
            if key:
                return (key,)
            imp = self.imports.get(mod, {}).get(name)
            if imp:
                imod, iname = imp.split(":", 1)
                key = self.module_funcs.get((imod, iname))
                if key:
                    return (key,)
            return ()
        recv = receiver_name(call)
        if recv == "self" and cls:
            return self._class_method(cls, name)
        # self.attr.m() where self.attr = CorpusClass(...)
        if isinstance(fn.value, ast.Attribute) and \
                isinstance(fn.value.value, ast.Name) and \
                fn.value.value.id == "self" and cls:
            owner = self.attr_types.get((cls, fn.value.attr))
            if owner:
                return self._class_method(owner, name)
            return ()
        hint = RECEIVER_HINTS.get(recv)
        if hint:
            return self._class_method(hint, name)
        return ()

    def _class_method(self, cls: str, name: str) -> "tuple[str, ...]":
        seen, stack = set(), [cls]
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            for key in self.methods_by_name.get(name, ()):
                if self.funcs[key].cls == c:
                    return (key,)
            stack.extend(self.bases.get(c, ()))
        return ()


def _blocking_kind(call: ast.Call) -> "str | None":
    name = call_name(call)
    recv = receiver_name(call)
    if name == "sleep" and recv in ("", "time", "_time"):
        return "time.sleep()"
    if name == "call" and recv in _RPC_RECEIVERS:
        return f"RPC {recv}.call()"
    if name == "wait" or name == "waitpid":
        return f"{recv or 'os'}.{name}()"
    if name == "join" and any(h in recv.lower() for h in _THREADY):
        return f"thread join ({recv}.join())"
    if name in _BLOCK_SOCKET_METHODS and \
            any(h in recv.lower() for h in _SOCKETY):
        return f"socket {recv}.{name}()"
    if recv == "socket" and name == "create_connection":
        return "socket.create_connection()"
    if recv == "subprocess" and name in _BLOCK_SUBPROCESS | {"Popen"}:
        return f"subprocess.{name}()"
    if name == "open" and isinstance(call.func, ast.Name):
        return "file open()"
    if name == "urlopen":
        return "urllib urlopen()"
    return None


class _FuncScanner:
    """Single in-order pass over one function's statements, tracking
    the held ranked-lock stack and a local var -> rank environment."""

    def __init__(self, world: LockWorld, m: Module, fi: FuncInfo) -> None:
        self.w = world
        self.m = m
        self.fi = fi
        self.env: dict[str, tuple[int, str]] = {}
        self.held: "list[tuple[int, str, int]]" = []   # (rank, name, line)

    # lock identity of an arbitrary expression, or None
    def lock_of(self, node: ast.AST) -> "tuple[int, str] | None":
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, (ast.Name, ast.Attribute)):
            recv = node.value.id if isinstance(node.value, ast.Name) \
                else node.value.attr
            return self.w.attr_rank(self.fi.cls, node.attr, recv)
        if isinstance(node, ast.Name):
            return self.env.get(node.id)
        if isinstance(node, ast.Subscript):
            return self.lock_of(node.value)
        if isinstance(node, ast.Call):
            got = self.w._rank_of_call(node)
            if got:
                return got
            const = TUPLE_LOCK_METHODS.get(call_name(node))
            if const:
                return (self.w.ranks.get(const, 0), "trackers")
        return None

    def _track_assign(self, node: ast.Assign) -> None:
        value = node.value
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                got = self.lock_of(value)
                if got:
                    self.env[tgt.id] = got
                else:
                    self.env.pop(tgt.id, None)
            elif isinstance(tgt, ast.Tuple) and isinstance(value, ast.Call):
                const = TUPLE_LOCK_METHODS.get(call_name(value))
                if const and tgt.elts and isinstance(tgt.elts[0], ast.Name):
                    self.env[tgt.elts[0].id] = \
                        (self.w.ranks.get(const, 0), "trackers")

    def _note_calls(self, stmt: ast.stmt) -> None:
        """Record every Call in ``stmt`` (excluding nested defs) with
        the current held stack; record direct blocking ops."""
        held = tuple(self.held)
        for node in _walk_no_defs(stmt):
            if not isinstance(node, ast.Call):
                continue
            kind = _blocking_kind(node)
            if kind and not self.m.pragmas.suppressed(
                    "lock-blocking", node.lineno):
                self.fi.blocking.append((kind, node.lineno))
                if held:
                    top = max(held)
                    self.fi.direct_findings.append(Finding(
                        rule="lock-blocking", path=self.m.rel,
                        line=node.lineno,
                        message=(f"{kind} while holding ranked lock "
                                 f"'{top[1]}' (rank {top[0]}) acquired at "
                                 f"line {top[2]} — blocking under a "
                                 f"master lock convoys every contender")))
            cands = self.w.resolve_call(self.m.name, self.fi.cls, node)
            if cands:
                self.fi.calls.append(
                    (cands, node.lineno, held, call_name(node)))

    def scan(self, body: "list[ast.stmt]") -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue   # deferred execution: analyzed separately
            if isinstance(stmt, ast.Assign):
                self._track_assign(stmt)
            if isinstance(stmt, ast.With):
                self._scan_with(stmt)
                continue
            self._note_calls(stmt)
            for sub in _stmt_bodies(stmt):
                self.scan(sub)

    def _scan_with(self, stmt: ast.With) -> None:
        # the with-items' own expressions run before acquisition
        pushed = 0
        for item in stmt.items:
            for node in _walk_no_defs_expr(item.context_expr):
                if isinstance(node, ast.Call):
                    cands = self.w.resolve_call(self.m.name, self.fi.cls,
                                                node)
                    if cands:
                        self.fi.calls.append((cands, node.lineno,
                                              tuple(self.held),
                                              call_name(node)))
            got = self.lock_of(item.context_expr)
            if not got:
                continue
            rank, name = got
            self.fi.acquires.append((rank, name, stmt.lineno))
            if self.held:
                top = max(self.held)
                if top[0] > rank and top[1] != name:
                    self.fi.direct_findings.append(Finding(
                        rule="lock-order", path=self.m.rel,
                        line=stmt.lineno,
                        message=(f"acquiring '{name}' (rank {rank}) while "
                                 f"holding '{top[1]}' (rank {top[0]}) — "
                                 f"violates the master's lock order")))
            self.held.append((rank, name, stmt.lineno))
            pushed += 1
        self.scan(stmt.body)
        del self.held[len(self.held) - pushed:]


def _stmt_bodies(stmt: ast.stmt) -> "list[list[ast.stmt]]":
    out = []
    for attr in ("body", "orelse", "finalbody"):
        sub = getattr(stmt, attr, None)
        if isinstance(sub, list) and sub and isinstance(sub[0], ast.stmt):
            out.append(sub)
    for h in getattr(stmt, "handlers", ()):
        out.append(h.body)
    return out


def _walk_no_defs(stmt: ast.stmt):
    """Walk a statement's expressions without descending into control
    bodies (scanned recursively) or nested function/class defs."""
    todo: "list[ast.AST]" = []
    for f, v in ast.iter_fields(stmt):
        if f in ("body", "orelse", "finalbody", "handlers"):
            continue
        if isinstance(v, list):
            todo.extend(x for x in v if isinstance(x, ast.AST))
        elif isinstance(v, ast.AST):
            todo.append(v)
    while todo:
        node = todo.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        todo.extend(ast.iter_child_nodes(node))


def _walk_no_defs_expr(expr: ast.AST):
    todo: "list[ast.AST]" = [expr]
    while todo:
        node = todo.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        todo.extend(ast.iter_child_nodes(node))


# --------------------------------------------------------- transitive pass


class _Transitive:
    """Memoized transitive (acquires, blocking) summaries with one
    representative chain per entry; cycle-safe.

    A summary computed while a back-edge (recursion cycle) or the depth
    cutoff truncated some subtree is PARTIAL — memoizing it would
    poison every later query through that function and silently hide
    real violations (a mutually-recursive pair's acquisitions would
    vanish for all callers). Partial results are returned but never
    cached; a later query with a fresh stack recomputes the full set.
    """

    MAX_DEPTH = 6

    def __init__(self, world: LockWorld) -> None:
        self.w = world
        self.memo: dict[str, tuple] = {}

    def of(self, key: str, depth: int = 0,
           stack: "frozenset[str]" = frozenset()) -> tuple:
        """-> (acquires, blocking, truncated)."""
        if key in self.memo:
            return self.memo[key]
        if key in stack or depth > self.MAX_DEPTH:
            return ({}, {}, True)
        fi = self.w.funcs.get(key)
        if fi is None:
            return ({}, {}, False)
        acquires: dict[int, tuple] = {}
        blocking: dict[str, tuple] = {}
        truncated = False
        label = _short(key)
        for rank, name, line in fi.acquires:
            acquires.setdefault(
                rank, (name, (f"{label} acquires '{name}' (rank {rank}) "
                              f"at {fi.rel}:{line}",)))
        for kind, line in fi.blocking:
            blocking.setdefault(
                kind, ((f"{label} does {kind} at {fi.rel}:{line}",),))
        for cands, line, _held, cname in fi.calls:
            for cand in cands:
                sub_acq, sub_blk, sub_trunc = self.of(cand, depth + 1,
                                                      stack | {key})
                truncated |= sub_trunc
                hop = f"{label} calls {_short(cand)} at {fi.rel}:{line}"
                for rank, (name, chain) in sub_acq.items():
                    acquires.setdefault(rank, (name, (hop,) + chain))
                for kind, (chain,) in sub_blk.items():
                    blocking.setdefault(kind, ((hop,) + chain,))
        result = (acquires, blocking, truncated)
        if not truncated:
            self.memo[key] = result
        return result


def _short(key: str) -> str:
    mod, label = key.split(":", 1)
    return f"{mod.rsplit('.', 1)[-1]}.{label}"


def check_locks(mods: "list[Module]") -> "list[Finding]":
    scope = [m for m in mods
             if "/mapred/" in f"/{m.rel}" or "/ipc/" in f"/{m.rel}"
             or "/metrics/" in f"/{m.rel}" or "/dfs/" in f"/{m.rel}"]
    world = LockWorld(scope)
    by_name = {m.name: m for m in scope}
    findings: "list[Finding]" = []
    for key, fi in world.funcs.items():
        m = by_name[key.split(":", 1)[0]]
        _FuncScanner(world, m, fi).scan(fi.node.body)
        findings.extend(fi.direct_findings)
    trans = _Transitive(world)
    for key, fi in world.funcs.items():
        for cands, line, held, cname in fi.calls:
            if not held:
                continue
            top = max(held)
            for cand in cands:
                acq, blk, _trunc = trans.of(cand)
                for rank, (name, chain) in sorted(acq.items()):
                    if rank < top[0] and name != top[1]:
                        findings.append(Finding(
                            rule="lock-order", path=fi.rel, line=line,
                            message=(f"call to {_short(cand)}() while "
                                     f"holding '{top[1]}' (rank "
                                     f"{top[0]}) reaches acquisition of "
                                     f"'{name}' (rank {rank})"),
                            chain=list(chain)))
                for kind, (chain,) in sorted(blk.items()):
                    findings.append(Finding(
                        rule="lock-blocking", path=fi.rel, line=line,
                        message=(f"call to {_short(cand)}() while "
                                 f"holding '{top[1]}' (rank {top[0]}) "
                                 f"reaches {kind}"),
                        chain=list(chain)))
    return findings
