"""Rumen-lite — job-history trace extraction.

≈ ``src/tools/org/apache/hadoop/tools/rumen`` (TraceBuilder: parse job
history into machine-readable traces for simulation/analysis). Input is
the history directory's JSON-lines event files; output is one trace
object per job with per-task runtimes split by backend — the exact data
the hybrid scheduler's profiling consumes, made available offline.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Any

from tpumr.mapred.history import JobHistory


def build_trace(events: list[dict]) -> dict[str, Any]:
    """One job's event stream → trace (≈ rumen LoggedJob)."""
    trace: dict[str, Any] = {"tasks": []}
    attempts: dict[str, dict] = {}
    for ev in events:
        kind = ev.get("event")
        if kind == "JOB_SUBMITTED":
            trace.update(job_id=ev.get("job_id"), name=ev.get("job_name"),
                         num_maps=ev.get("num_maps"),
                         num_reduces=ev.get("num_reduces"),
                         kernel=ev.get("kernel"),
                         submit_time=ev.get("ts"))
        elif kind == "JOB_FINISHED":
            trace.update(outcome=ev.get("state"),
                         wall_time=ev.get("wall_time"),
                         acceleration_factor=ev.get("acceleration_factor"))
        elif kind in ("TASK_FINISHED", "TASK_FAILED", "TASK_KILLED"):
            attempt = ev.get("attempt_id", "")
            rec = attempts.setdefault(attempt, {"attempt_id": attempt})
            rec.update(
                outcome={"TASK_FINISHED": "SUCCEEDED",
                         "TASK_KILLED": "KILLED"}.get(kind, "FAILED"),
                is_map=ev.get("is_map"),
                backend="tpu" if ev.get("run_on_tpu") else "cpu",
                device=ev.get("tpu_device_id"),
                runtime=ev.get("runtime"),
                tracker=ev.get("tracker"))
    trace["tasks"] = sorted(attempts.values(),
                            key=lambda r: r["attempt_id"])
    done = [t for t in trace["tasks"] if t.get("outcome") == "SUCCEEDED"]
    cpu = [t["runtime"] for t in done
           if t.get("backend") == "cpu" and t.get("runtime")]
    tpu = [t["runtime"] for t in done
           if t.get("backend") == "tpu" and t.get("runtime")]
    trace["cpu_task_mean"] = sum(cpu) / len(cpu) if cpu else None
    trace["tpu_task_mean"] = sum(tpu) / len(tpu) if tpu else None
    return trace


def build_traces(history_dir: str) -> list[dict]:
    out = []
    if not os.path.isdir(history_dir):
        return out
    for f in sorted(os.listdir(history_dir)):
        if f.endswith(".jsonl"):
            out.append(build_trace(
                JobHistory.read(os.path.join(history_dir, f))))
    return out


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr rumen")
    ap.add_argument("history_dir")
    ap.add_argument("-o", "--output", default="-",
                    help="trace file (JSON, default stdout)")
    args = ap.parse_args(argv)
    traces = build_traces(args.history_dir)
    text = json.dumps(traces, indent=2, default=str)
    if args.output == "-":
        print(text)
    else:
        with open(args.output, "w") as f:
            f.write(text)
    return 0
