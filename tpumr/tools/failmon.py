"""Failure/condition monitoring — the "failmon" tier.

≈ ``src/contrib/failmon`` (reference: contrib/failmon/*.java — monitor
jobs (CPUParser, SystemLogParser, HadoopLogParser, SMARTParser…) produce
``EventRecord``s into a ``LocalStore`` whose contents are periodically
uploaded to HDFS and merged for offline failure analysis; ``RunOnce`` /
``Continuous`` drive collection, ``Anonymizer`` scrubs identities).

The tpumr analog keeps the same pipeline with 2026-era sources: each
monitor snapshots one node dimension into an event record; records
append to a local JSONL store; ``upload`` rotates the store into any
FileSystem URL (one file per host per rotation); ``merge`` concatenates
every host's uploads into one dataset for analysis (rumen/vaidya-style
post-processing). Log monitors keep a persistent byte offset so each
scan reports only NEW error lines (the reference's PersistentState
role). Hostname anonymization is a stable hash, matching the
Anonymizer's intent.

CLI::

    tpumr failmon -collect [-store DIR] [-upload URL] [-anonymize]
    tpumr failmon -merge URL DEST_FILE
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import socket
import time
from typing import Any, Iterable

_ERROR_PAT = re.compile(
    r"error|fail|fatal|panic|oops|traceback|segfault|corrupt", re.I)


def _hostname(anonymize: bool) -> str:
    name = socket.gethostname()
    if anonymize:
        return "host-" + hashlib.sha256(name.encode()).hexdigest()[:12]
    return name


def event(source: str, kind: str, **fields: Any) -> dict:
    """One EventRecord ≈ contrib/failmon EventRecord: self-describing,
    timestamped, host-stamped (host filled at store time)."""
    return {"ts": time.time(), "source": source, "kind": kind, **fields}


# ------------------------------------------------------------------ monitors


class Monitor:
    """One monitored dimension ≈ the Monitored interface."""

    name = ""

    def poll(self, state: dict) -> "Iterable[dict]":
        raise NotImplementedError


class CpuMonitor(Monitor):
    """Load + core count ≈ CPUParser."""

    name = "cpu"

    def poll(self, state: dict) -> "Iterable[dict]":
        la1, la5, la15 = os.getloadavg()
        yield event(self.name, "load", load1=la1, load5=la5, load15=la15,
                    cores=os.cpu_count() or 1)


class MemoryMonitor(Monitor):
    """/proc/meminfo snapshot (total/available/swap)."""

    name = "memory"

    def poll(self, state: dict) -> "Iterable[dict]":
        try:
            with open("/proc/meminfo") as f:
                info = {}
                for line in f:
                    k, _, rest = line.partition(":")
                    parts = rest.split()
                    if parts:
                        info[k] = int(parts[0])  # kB
        except OSError:
            return
        yield event(self.name, "meminfo",
                    total_kb=info.get("MemTotal", 0),
                    available_kb=info.get("MemAvailable", 0),
                    swap_free_kb=info.get("SwapFree", 0))


class DiskMonitor(Monitor):
    """Capacity/usage of the monitored paths ≈ the df/SMART role (smartctl
    isn't assumed present; a full SMART parser plugs in as another
    Monitor)."""

    name = "disk"

    def __init__(self, paths: "list[str] | None" = None) -> None:
        self.paths = paths or ["/"]

    def poll(self, state: dict) -> "Iterable[dict]":
        import shutil
        for p in self.paths:
            try:
                u = shutil.disk_usage(p)
            except OSError as e:
                yield event(self.name, "probe-failed", path=p, error=str(e))
                continue
            yield event(self.name, "usage", path=p, total=u.total,
                        used=u.used, free=u.free,
                        pct_used=round(100.0 * u.used / max(1, u.total), 1))


class LogMonitor(Monitor):
    """Error-line scanner over one log file ≈ SystemLogParser /
    HadoopLogParser: persistent byte offset per file, so each poll emits
    only lines that appeared since the previous poll. A truncated/rotated
    file (size < saved offset) rescans from the start."""

    name = "log"

    def __init__(self, path: str, pattern: "re.Pattern[str]" = _ERROR_PAT,
                 max_events: int = 100) -> None:
        self.path = path
        self.pattern = pattern
        self.max_events = max_events

    def poll(self, state: dict) -> "Iterable[dict]":
        key = f"log.offset:{self.path}"
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        offset = int(state.get(key, 0))
        if size < offset:
            offset = 0  # rotated
        emitted = 0
        # binary + manual offset accounting: text iteration disables
        # tell(), and the offset MUST advance past scanned lines even
        # when max_events truncates the pass (otherwise every later pass
        # re-emits the same lines forever)
        tail_key = f"log.tailwait:{self.path}"
        with open(self.path, "rb") as f:
            f.seek(offset)
            while emitted < self.max_events:
                line = f.readline()
                if line and not line.endswith(b"\n"):
                    # partial trailing line: usually a writer mid-append —
                    # leave the offset BEFORE it so the next poll scans
                    # the complete line. But a writer that DIED mid-write
                    # never finishes it, and that last gasp is often the
                    # error that matters: once the file stays the same
                    # size across two polls, emit the unterminated tail.
                    if state.get(tail_key) == size:
                        state.pop(tail_key, None)
                        offset += len(line)
                        text = line.decode("utf-8", errors="replace")
                        if self.pattern.search(text):
                            yield event(self.name, "error-line",
                                        file=self.path,
                                        line=text.rstrip()[:500])
                    else:
                        state[tail_key] = size
                    break
                if not line:
                    state.pop(tail_key, None)
                    break
                state.pop(tail_key, None)
                offset += len(line)
                text = line.decode("utf-8", errors="replace")
                if self.pattern.search(text):
                    emitted += 1
                    yield event(self.name, "error-line", file=self.path,
                                line=text.rstrip()[:500])
            state[key] = offset


# ------------------------------------------------------------------ store


class LocalStore:
    """Append-only local JSONL event store ≈ contrib/failmon LocalStore,
    with ``upload`` as the rotate-to-cluster step."""

    STATE_FILE = "failmon.state.json"
    EVENTS_FILE = "failmon.events.jsonl"

    def __init__(self, store_dir: str, anonymize: bool = False) -> None:
        self.dir = store_dir
        self.host = _hostname(anonymize)
        os.makedirs(store_dir, exist_ok=True)
        self._state_path = os.path.join(store_dir, self.STATE_FILE)
        self._events_path = os.path.join(store_dir, self.EVENTS_FILE)

    def load_state(self) -> dict:
        try:
            with open(self._state_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def save_state(self, state: dict) -> None:
        tmp = self._state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self._state_path)

    def append(self, events: "Iterable[dict]") -> int:
        n = 0
        with open(self._events_path, "a") as f:
            for ev in events:
                ev.setdefault("host", self.host)
                f.write(json.dumps(ev) + "\n")
                n += 1
        return n

    def upload(self, url: str) -> "str | None":
        """Rotate the local store into ``url`` (any FileSystem scheme) as
        one per-host-per-rotation file; returns the destination path or
        None when there is nothing to ship. The rotation is an atomic
        rename FIRST, so events appended concurrently (an overlapping
        collect pass) land in the fresh file instead of being deleted
        with the shipped one."""
        from tpumr.fs import get_filesystem
        stamp = int(time.time() * 1000)
        rotated = f"{self._events_path}.shipping.{stamp}"
        try:
            os.rename(self._events_path, rotated)
        except OSError:
            return None  # nothing collected yet
        with open(rotated, "rb") as f:
            data = f.read()
        if not data:
            os.remove(rotated)
            return None
        try:
            fs = get_filesystem(url)
            dest = url.rstrip("/") + f"/{self.host}-{stamp}.jsonl"
            fs.write_bytes(dest, data)
        except Exception:
            # failed ship: fold the rotated events back so a retry (or
            # the next upload) still carries them
            with open(self._events_path, "ab") as f:
                f.write(data)
            os.remove(rotated)
            raise
        os.remove(rotated)
        return dest


def default_monitors(conf: Any = None) -> "list[Monitor]":
    paths = ["/"]
    logs: list[str] = []
    if conf is not None:
        paths = list(conf.get_strings("failmon.disk.paths") or ["/"])
        logs = list(conf.get_strings("failmon.log.files") or [])
    mons: "list[Monitor]" = [CpuMonitor(), MemoryMonitor(),
                             DiskMonitor(paths)]
    mons.extend(LogMonitor(p) for p in logs)
    return mons


def run_once(store: LocalStore, monitors: "list[Monitor]") -> int:
    """One collection pass ≈ RunOnce: poll every monitor, append events,
    persist monitor state (log offsets). Returns events appended."""
    state = store.load_state()
    total = 0
    for mon in monitors:
        try:
            total += store.append(mon.poll(state))
        except Exception as e:  # noqa: BLE001 — one bad monitor must not
            total += store.append([event(mon.name, "monitor-failed",
                                         error=str(e))])  # kill the pass
    store.save_state(state)
    return total


def merge(url: str, dest: str) -> int:
    """Concatenate every uploaded per-host file under ``url`` into one
    time-ordered JSONL dataset at ``dest`` ≈ the offline merge step.
    Returns the record count."""
    from tpumr.fs import get_filesystem
    fs = get_filesystem(url)
    records: "list[dict]" = []
    dest_tail = dest.split("://", 1)[-1]
    for st in fs.list_files(url):
        if not str(st.path).endswith(".jsonl"):
            continue
        if str(st.path).split("://", 1)[-1] == dest_tail:
            continue  # a previous merge output under url: never re-merge
        for line in fs.read_bytes(st.path).decode().splitlines():
            if line.strip():
                records.append(json.loads(line))
    records.sort(key=lambda r: r.get("ts", 0))
    out = "\n".join(json.dumps(r) for r in records)
    get_filesystem(dest).write_bytes(dest, (out + "\n").encode()
                                     if out else b"")
    return len(records)
