"""Archives — pack many small files into one indexed container.

≈ ``src/tools/org/apache/hadoop/tools/HadoopArchives.java`` + the ``har://``
FileSystem: an archive directory holds ``_index`` (a MapFile of
relative-path → (offset, length)) and ``part-0`` (concatenated file
bytes). The ``tharch`` FileSystem scheme serves transparent reads:

    tharch://<underlying-scheme>/path/to/name.tharch/inner/path

so MapReduce inputs can point inside an archive exactly like the
reference's har:// paths (the many-small-files problem: one container,
no per-file namespace cost).
"""

from __future__ import annotations

import argparse
import io
from typing import Any, BinaryIO

from tpumr.fs import get_filesystem
from tpumr.fs.filesystem import (BlockLocation, FileStatus, FileSystem,
                                 Path)
from tpumr.io import mapfile

SUFFIX = ".tharch"
INDEX = "_index"
PART = "part-0"


def create_archive(src_dir: str, archive_dir: str, conf: Any = None) -> int:
    """Pack ``src_dir`` (recursively) into ``archive_dir`` (a *.tharch
    directory on the same or another fs). Returns number of files packed."""
    if not archive_dir.rstrip("/").endswith(SUFFIX):
        raise ValueError(f"archive name must end with {SUFFIX}")
    sfs = get_filesystem(src_dir, conf)
    afs = get_filesystem(archive_dir, conf)
    afs.mkdirs(archive_dir)
    base = str(sfs.get_status(src_dir).path)
    files = sorted(sfs.list_files(src_dir, recursive=True),
                   key=lambda st: str(st.path))
    entries: list[tuple[str, tuple[int, int]]] = []
    offset = 0
    with afs.create(Path(archive_dir).child(PART)) as part:
        for st in files:
            # stream in chunks — one huge source file must not be
            # materialized in memory
            length = 0
            with sfs.open(st.path) as fin:
                while True:
                    chunk = fin.read(1 << 20)
                    if not chunk:
                        break
                    part.write(chunk)
                    length += len(chunk)
            rel = str(st.path)[len(base):].lstrip("/")
            entries.append((rel, (offset, length)))
            offset += length
    entries.sort()
    with mapfile.Writer(afs, Path(archive_dir).child(INDEX)) as w:
        for rel, span in entries:
            w.append(rel, span)
    return len(entries)


def list_archive(archive_dir: str, conf: Any = None) -> list[tuple[str, int]]:
    afs = get_filesystem(archive_dir, conf)
    with mapfile.Reader(afs, Path(archive_dir).child(INDEX)) as r:
        return [(k, span[1]) for k, span in r]


class ArchiveFileSystem(FileSystem):
    """Read-only view into archives ≈ HarFileSystem. The authority names
    the underlying scheme; the path is split at the ``.tharch`` component."""

    scheme = "tharch"

    def __init__(self, conf: Any = None, authority: str = "") -> None:
        self.conf = conf
        self.under_scheme = authority or "file"

    # ------------------------------------------------------------ helpers

    def _split(self, path: "str | Path") -> tuple[str, str]:
        """-> (underlying archive dir URI, inner path)."""
        s = str(path)
        if "://" in s:
            s = s.split("://", 1)[1]
            s = "/" + s.split("/", 1)[1] if "/" in s else "/"
        marker = SUFFIX + "/"
        if s.endswith(SUFFIX):
            arch, inner = s, ""
        elif marker in s:
            idx = s.index(marker) + len(SUFFIX)
            arch, inner = s[:idx], s[idx + 1:]
        else:
            raise FileNotFoundError(f"no {SUFFIX} component in {path}")
        return f"{self.under_scheme}://{arch}", inner

    def _index(self, arch_uri: str) -> "mapfile.Reader":
        afs = get_filesystem(arch_uri, self.conf)
        return mapfile.Reader(afs, Path(arch_uri).child(INDEX))

    # ------------------------------------------------------------ SPI

    def open(self, path: "str | Path") -> BinaryIO:
        arch, inner = self._split(path)
        with self._index(arch) as idx:
            span = idx.get(inner)
        if span is None:
            raise FileNotFoundError(f"{inner!r} not in archive {arch}")
        offset, length = span
        afs = get_filesystem(arch, self.conf)
        with afs.open(Path(arch).child(PART)) as f:
            f.seek(offset)
            return io.BytesIO(f.read(length))

    def create(self, path, overwrite: bool = True) -> BinaryIO:
        raise PermissionError("tharch archives are immutable (re-create "
                              "with `tpumr archive`)")

    append = create

    def delete(self, path, recursive: bool = False) -> bool:
        raise PermissionError("tharch archives are immutable")

    def rename(self, src, dst) -> bool:
        raise PermissionError("tharch archives are immutable")

    def mkdirs(self, path) -> bool:
        raise PermissionError("tharch archives are immutable")

    def exists(self, path: "str | Path") -> bool:
        try:
            self.get_status(path)
            return True
        except FileNotFoundError:
            return False

    def get_status(self, path: "str | Path") -> FileStatus:
        arch, inner = self._split(path)
        if not inner:
            return FileStatus(Path(str(path)), is_dir=True)
        with self._index(arch) as idx:
            span = idx.get(inner)
            if span is not None:
                return FileStatus(Path(str(path)), length=span[1])
            prefix = inner.rstrip("/") + "/"
            for k, _ in idx:
                if k.startswith(prefix):
                    return FileStatus(Path(str(path)), is_dir=True)
        raise FileNotFoundError(str(path))

    def list_status(self, path: "str | Path") -> list[FileStatus]:
        arch, inner = self._split(path)
        prefix = inner.rstrip("/") + "/" if inner else ""
        seen: dict[str, FileStatus] = {}
        base = str(path).rstrip("/")
        with self._index(arch) as idx:
            for k, (off, length) in idx:
                if not k.startswith(prefix):
                    continue
                rest = k[len(prefix):]
                head = rest.split("/", 1)[0]
                full = Path(f"{base}/{head}")
                if "/" in rest:
                    seen.setdefault(head, FileStatus(full, is_dir=True))
                else:
                    seen[head] = FileStatus(full, length=length)
        return [seen[k] for k in sorted(seen)]

    def get_block_locations(self, path, offset: int,
                            length: int) -> list[BlockLocation]:
        return [BlockLocation([], offset, length)]


FileSystem.register("tharch", ArchiveFileSystem)


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr archive")
    ap.add_argument("-ls", action="store_true", dest="ls",
                    help="list an existing archive instead of creating")
    ap.add_argument("paths", nargs="+",
                    help="create: SRC DEST.tharch | list: ARCHIVE.tharch")
    args = ap.parse_args(argv)
    if args.ls:
        for name, size in list_archive(args.paths[0]):
            print(f"{size:>12} {name}")
        return 0
    if len(args.paths) != 2:
        ap.error("create needs SRC and DEST.tharch")
    n = create_archive(args.paths[0], args.paths[1])
    print(f"Archived {n} files into {args.paths[1]}")
    return 0
