"""Archives — pack many small files into one indexed container.

≈ ``src/tools/org/apache/hadoop/tools/HadoopArchives.java`` + the ``har://``
FileSystem: an archive directory holds ``_index`` (a MapFile of
relative-path → (offset, length)) and ``part-0`` (concatenated file
bytes). The ``tharch`` FileSystem scheme serves transparent reads:

    tharch://<underlying-scheme>/path/to/name.tharch/inner/path

so MapReduce inputs can point inside an archive exactly like the
reference's har:// paths (the many-small-files problem: one container,
no per-file namespace cost).
"""

from __future__ import annotations

import argparse
import io
import threading
from typing import Any, BinaryIO

from tpumr.fs import get_filesystem
from tpumr.fs.filesystem import (BlockLocation, FileStatus, FileSystem,
                                 Path)
from tpumr.io import mapfile

SUFFIX = ".tharch"
INDEX = "_index"
PART = "part-0"


def create_archive(src_dir: str, archive_dir: str, conf: Any = None) -> int:
    """Pack ``src_dir`` (recursively) into ``archive_dir`` (a *.tharch
    directory on the same or another fs). Returns number of files packed."""
    if not archive_dir.rstrip("/").endswith(SUFFIX):
        raise ValueError(f"archive name must end with {SUFFIX}")
    sfs = get_filesystem(src_dir, conf)
    afs = get_filesystem(archive_dir, conf)
    afs.mkdirs(archive_dir)
    base = str(sfs.get_status(src_dir).path)
    files = sorted(sfs.list_files(src_dir, recursive=True),
                   key=lambda st: str(st.path))
    entries: list[tuple[str, tuple[int, int]]] = []
    offset = 0
    with afs.create(Path(archive_dir).child(PART)) as part:
        for st in files:
            # stream in chunks — one huge source file must not be
            # materialized in memory
            length = 0
            with sfs.open(st.path) as fin:
                while True:
                    chunk = fin.read(1 << 20)
                    if not chunk:
                        break
                    part.write(chunk)
                    length += len(chunk)
            rel = str(st.path)[len(base):].lstrip("/")
            entries.append((rel, (offset, length)))
            offset += length
    entries.sort()
    with mapfile.Writer(afs, Path(archive_dir).child(INDEX)) as w:
        for rel, span in entries:
            w.append(rel, span)
    return len(entries)


def list_archive(archive_dir: str, conf: Any = None) -> list[tuple[str, int]]:
    afs = get_filesystem(archive_dir, conf)
    with mapfile.Reader(afs, Path(archive_dir).child(INDEX)) as r:
        return [(k, span[1]) for k, span in r]


class _BoundedFile(io.RawIOBase):
    """Window [offset, offset+length) over the part stream — reads stream
    through, nothing is materialized."""

    def __init__(self, raw: BinaryIO, offset: int, length: int) -> None:
        self._raw = raw
        self._start = offset
        self._length = length
        self._pos = 0

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, pos: int, whence: int = io.SEEK_SET) -> int:
        if whence == io.SEEK_SET:
            self._pos = pos
        elif whence == io.SEEK_CUR:
            self._pos += pos
        else:
            self._pos = self._length + pos
        self._pos = max(0, min(self._pos, self._length))
        self._raw.seek(self._start + self._pos)
        return self._pos

    def tell(self) -> int:
        return self._pos

    def readinto(self, b: bytearray) -> int:  # type: ignore[override]
        n = min(len(b), self._length - self._pos)
        if n <= 0:
            return 0
        self._raw.seek(self._start + self._pos)
        data = self._raw.read(n)
        b[: len(data)] = data
        self._pos += len(data)
        return len(data)

    def close(self) -> None:
        try:
            self._raw.close()
        finally:
            super().close()


class ArchiveFileSystem(FileSystem):
    """Read-only view into archives ≈ HarFileSystem. The authority names
    the underlying scheme; the path is split at the ``.tharch`` component."""

    scheme = "tharch"

    def __init__(self, conf: Any = None, authority: str = "") -> None:
        self.conf = conf
        self.under_scheme = authority or "file"
        #: archive uri -> cached in-memory index entries (immutable files)
        self._index_cache: dict[str, list[tuple[str, tuple[int, int]]]] = {}
        self._cache_lock = threading.Lock()

    # ------------------------------------------------------------ helpers

    def _split(self, path: "str | Path") -> tuple[str, str]:
        """-> (underlying archive dir URI, inner path)."""
        s = str(path)
        if "://" in s:
            s = s.split("://", 1)[1]
            s = "/" + s.split("/", 1)[1] if "/" in s else "/"
        marker = SUFFIX + "/"
        if s.endswith(SUFFIX):
            arch, inner = s, ""
        elif marker in s:
            idx = s.index(marker) + len(SUFFIX)
            arch, inner = s[:idx], s[idx + 1:]
        else:
            raise FileNotFoundError(f"no {SUFFIX} component in {path}")
        return f"{self.under_scheme}://{arch}", inner

    def _entries(self, arch_uri: str) -> list[tuple[str, tuple[int, int]]]:
        """Cached index entries — archives are immutable, and reloading
        the index per open() would make N-file jobs O(N × index)."""
        with self._cache_lock:
            cached = self._index_cache.get(arch_uri)
        if cached is not None:
            return cached
        afs = get_filesystem(arch_uri, self.conf)
        with mapfile.Reader(afs, Path(arch_uri).child(INDEX)) as r:
            entries = list(r)
        with self._cache_lock:
            self._index_cache[arch_uri] = entries
        return entries

    def _lookup(self, arch_uri: str, inner: str) -> "tuple[int, int] | None":
        for k, span in self._entries(arch_uri):
            if k == inner:
                return span
        return None

    # ------------------------------------------------------------ SPI

    def open(self, path: "str | Path") -> BinaryIO:
        arch, inner = self._split(path)
        span = self._lookup(arch, inner)
        if span is None:
            raise FileNotFoundError(f"{inner!r} not in archive {arch}")
        offset, length = span
        afs = get_filesystem(arch, self.conf)
        f = afs.open(Path(arch).child(PART))
        f.seek(offset)
        return _BoundedFile(f, offset, length)

    def create(self, path, overwrite: bool = True) -> BinaryIO:
        raise PermissionError("tharch archives are immutable (re-create "
                              "with `tpumr archive`)")

    append = create

    def delete(self, path, recursive: bool = False) -> bool:
        raise PermissionError("tharch archives are immutable")

    def rename(self, src, dst) -> bool:
        raise PermissionError("tharch archives are immutable")

    def mkdirs(self, path) -> bool:
        raise PermissionError("tharch archives are immutable")

    def exists(self, path: "str | Path") -> bool:
        try:
            self.get_status(path)
            return True
        except FileNotFoundError:
            return False

    def get_status(self, path: "str | Path") -> FileStatus:
        arch, inner = self._split(path)
        if not inner:
            return FileStatus(Path(str(path)), is_dir=True)
        span = self._lookup(arch, inner)
        if span is not None:
            return FileStatus(Path(str(path)), length=span[1])
        prefix = inner.rstrip("/") + "/"
        for k, _ in self._entries(arch):
            if k.startswith(prefix):
                return FileStatus(Path(str(path)), is_dir=True)
        raise FileNotFoundError(str(path))

    def list_status(self, path: "str | Path") -> list[FileStatus]:
        arch, inner = self._split(path)
        prefix = inner.rstrip("/") + "/" if inner else ""
        seen: dict[str, FileStatus] = {}
        base = str(path).rstrip("/")
        for k, (off, length) in self._entries(arch):
            if not k.startswith(prefix):
                continue
            rest = k[len(prefix):]
            head = rest.split("/", 1)[0]
            full = Path(f"{base}/{head}")
            if "/" in rest:
                seen.setdefault(head, FileStatus(full, is_dir=True))
            else:
                seen[head] = FileStatus(full, length=length)
        return [seen[k] for k in sorted(seen)]

    def get_block_locations(self, path, offset: int,
                            length: int) -> list[BlockLocation]:
        return [BlockLocation([], offset, length)]


FileSystem.register("tharch", ArchiveFileSystem)


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr archive")
    ap.add_argument("-ls", action="store_true", dest="ls",
                    help="list an existing archive instead of creating")
    ap.add_argument("paths", nargs="+",
                    help="create: SRC DEST.tharch | list: ARCHIVE.tharch")
    args = ap.parse_args(argv)
    if args.ls:
        for name, size in list_archive(args.paths[0]):
            print(f"{size:>12} {name}")
        return 0
    if len(args.paths) != 2:
        ap.error("create needs SRC and DEST.tharch")
    n = create_archive(args.paths[0], args.paths[1])
    print(f"Archived {n} files into {args.paths[1]}")
    return 0
