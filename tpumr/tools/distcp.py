"""DistCp — distributed copy as a map-only MapReduce job.

≈ ``src/tools/org/apache/hadoop/tools/DistCp.java``: expand the source
tree into a file list, one map task per batch of files, each map copies
its files through the FileSystem SPI (so any scheme→any scheme works:
local→tdfs, mem→local, …), preserving relative paths. ``-update`` skips
files whose destination already exists with the same length.
"""

from __future__ import annotations

import argparse

from tpumr.fs import get_filesystem
from tpumr.fs.filesystem import Path
from tpumr.mapred.api import Mapper
from tpumr.mapred.input_formats import NLineInputFormat
from tpumr.mapred.job_client import run_job
from tpumr.mapred.jobconf import JobConf


class DistCpMapper(Mapper):
    """Input record "<src-uri><TAB><dst-uri>": copy one file."""

    def configure(self, conf) -> None:
        self._update = bool(conf.get("tpumr.distcp.update", False))
        self._conf = conf

    def map(self, key, value, output, reporter):
        s = value.decode() if isinstance(value, (bytes, bytearray)) else value
        src, _, dst = s.partition("\t")
        if not dst:
            return
        sfs = get_filesystem(src, self._conf)
        dfs = get_filesystem(dst, self._conf)
        length = sfs.get_status(src).length
        if self._update and dfs.exists(dst) \
                and dfs.get_status(dst).length == length:
            reporter.incr_counter("distcp", "skipped")
            return
        copied = sfs.copy(src, dfs, dst)
        reporter.incr_counter("distcp", "copied")
        reporter.incr_counter("distcp", "bytes", copied)


def build_file_list(src: str, dst: str, conf=None) -> list[str]:
    """Expand src (file or tree) into "<src>\t<dst>" copy records."""
    sfs = get_filesystem(src, conf)
    st = sfs.get_status(src)
    pairs: list[str] = []
    if not st.is_dir:
        name = Path(src).name
        dfs = get_filesystem(dst, conf)
        target = (str(Path(dst).child(name))
                  if dfs.exists(dst) and dfs.get_status(dst).is_dir else dst)
        return [f"{src}\t{target}"]
    base = str(st.path)
    for f in sfs.list_files(src, recursive=True):
        rel = str(f.path)[len(base):].lstrip("/")
        pairs.append(f"{f.path}\t{dst.rstrip('/')}/{rel}")
    return sorted(pairs)


def distcp(src: str, dst: str, maps: int = 4, update: bool = False,
           conf: JobConf | None = None) -> bool:
    conf = conf or JobConf()
    pairs = build_file_list(src, dst, conf)
    if not pairs:
        return True
    # the staging listing must be readable by remote task processes, so it
    # lives NEXT TO the destination (a shared fs by definition) unless the
    # caller overrides — mem:// scratch would be client-process-local
    work = conf.get("tpumr.distcp.work")
    own_work = work is None
    if own_work:
        work = dst.rstrip("/") + ".distcp-work"
    listing = f"{work.rstrip('/')}/files.txt"
    get_filesystem(listing, conf).write_bytes(
        listing, ("\n".join(pairs) + "\n").encode())
    per_map = max(1, (len(pairs) + maps - 1) // maps)
    conf.set_job_name("distcp")
    conf.set_input_paths(listing)
    conf.set_output_path(f"{work.rstrip('/')}/out")
    conf.set_input_format(NLineInputFormat)
    conf.set("mapred.line.input.format.linespermap", per_map)
    conf.set("tpumr.distcp.update", update)
    conf.set_mapper_class(DistCpMapper)
    conf.set_num_reduce_tasks(0)
    from tpumr.mapred.output_formats import NullOutputFormat
    conf.set_output_format(NullOutputFormat)
    try:
        return run_job(conf).successful
    finally:
        # only clean up scratch WE created — a caller-supplied work dir may
        # be a shared staging area with unrelated contents
        if own_work:
            get_filesystem(work, conf).delete(work, recursive=True)


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr distcp")
    ap.add_argument("src")
    ap.add_argument("dst")
    ap.add_argument("-m", "--maps", type=int, default=4)
    ap.add_argument("-update", action="store_true",
                    help="skip files already at the destination with the "
                         "same size")
    args = ap.parse_args(argv)
    return 0 if distcp(args.src, args.dst, maps=args.maps,
                       update=args.update) else 1
