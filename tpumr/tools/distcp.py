"""DistCp — distributed copy as a map-only MapReduce job.

≈ ``src/tools/org/apache/hadoop/tools/DistCp.java``: expand the source
tree into a file list, one map task per batch of files, each map copies
its files through the FileSystem SPI (so any scheme→any scheme works:
local→tdfs, mem→local, …), preserving relative paths. ``-update`` skips
files whose destination already exists with the same length;
``-delete`` (with -update, the reference's pairing) removes destination
files absent from the source; ``-p`` preserves owner and permission
bits where the filesystems expose them (tdfs does).
"""

from __future__ import annotations

import argparse
import sys

from tpumr.fs import get_filesystem
from tpumr.fs.filesystem import Path
from tpumr.mapred.api import Mapper
from tpumr.mapred.input_formats import NLineInputFormat
from tpumr.mapred.job_client import run_job
from tpumr.mapred.jobconf import JobConf


class DistCpMapper(Mapper):
    """Input record "<src-uri><TAB><dst-uri>": copy one file."""

    def configure(self, conf) -> None:
        self._update = bool(conf.get("tpumr.distcp.update", False))
        self._preserve = bool(conf.get("tpumr.distcp.preserve", False))
        self._conf = conf

    def map(self, key, value, output, reporter):
        s = value.decode() if isinstance(value, (bytes, bytearray)) else value
        src, _, dst = s.partition("\t")
        if not dst:
            return
        sfs = get_filesystem(src, self._conf)
        dfs = get_filesystem(dst, self._conf)
        st = sfs.get_status(src)
        if self._update and dfs.exists(dst) \
                and dfs.get_status(dst).length == st.length:
            reporter.incr_counter("distcp", "skipped")
            # -p -update: an unchanged file may still have changed owner
            # or mode — the reference refreshes preserved attributes even
            # on skipped files (DistCp updateDestStatus)
            self._preserve_attrs(sfs, src, st, dfs, dst, reporter)
            return
        copied = sfs.copy(src, dfs, dst)
        reporter.incr_counter("distcp", "copied")
        reporter.incr_counter("distcp", "bytes", copied)
        self._preserve_attrs(sfs, src, st, dfs, dst, reporter)

    def _preserve_attrs(self, sfs, src, st, dfs, dst, reporter) -> None:
        """-p: owner + mode where both ends expose them (best effort
        across schemes — a local->tdfs copy preserves what the source
        can report); reuses the status fetched by map()."""
        if not self._preserve:
            return
        if st.owner and hasattr(dfs, "set_owner"):
            dfs.set_owner(dst, st.owner)
        get_perm = getattr(sfs, "get_permission", None)
        if get_perm is not None and hasattr(dfs, "set_permission"):
            dfs.set_permission(dst, get_perm(src))
            reporter.incr_counter("distcp", "preserved")


def build_file_list(src: str, dst: str, conf=None) -> list[str]:
    """Expand src (file or tree) into "<src>\t<dst>" copy records."""
    sfs = get_filesystem(src, conf)
    st = sfs.get_status(src)
    pairs: list[str] = []
    if not st.is_dir:
        name = Path(src).name
        dfs = get_filesystem(dst, conf)
        target = (str(Path(dst).child(name))
                  if dfs.exists(dst) and dfs.get_status(dst).is_dir else dst)
        return [f"{src}\t{target}"]
    base = str(st.path)
    for f in sfs.list_files(src, recursive=True):
        rel = str(f.path)[len(base):].lstrip("/")
        pairs.append(f"{f.path}\t{dst.rstrip('/')}/{rel}")
    return sorted(pairs)


def distcp(src: str, dst: str, maps: int = 4, update: bool = False,
           delete: bool = False, preserve: bool = False,
           conf: JobConf | None = None) -> bool:
    if delete and not update:
        # the reference pairs -delete with -update/-overwrite; without
        # the comparison pass, deleting is too easy to fire by accident
        raise ValueError("-delete requires -update")
    conf = conf or JobConf()
    pairs = build_file_list(src, dst, conf)
    if not pairs:
        # an emptied source still syncs: the -delete pass must run or
        # stale destination files survive forever
        if delete:
            _delete_extraneous(dst, pairs, conf)
        return True
    # the staging listing must be readable by remote task processes, so it
    # lives NEXT TO the destination (a shared fs by definition) unless the
    # caller overrides — mem:// scratch would be client-process-local
    work = conf.get("tpumr.distcp.work")
    own_work = work is None
    if own_work:
        work = dst.rstrip("/") + ".distcp-work"
    listing = f"{work.rstrip('/')}/files.txt"
    get_filesystem(listing, conf).write_bytes(
        listing, ("\n".join(pairs) + "\n").encode())
    per_map = max(1, (len(pairs) + maps - 1) // maps)
    conf.set_job_name("distcp")
    conf.set_input_paths(listing)
    conf.set_output_path(f"{work.rstrip('/')}/out")
    conf.set_input_format(NLineInputFormat)
    conf.set("mapred.line.input.format.linespermap", per_map)
    conf.set("tpumr.distcp.update", update)
    conf.set("tpumr.distcp.preserve", preserve)
    conf.set_mapper_class(DistCpMapper)
    conf.set_num_reduce_tasks(0)
    from tpumr.mapred.output_formats import NullOutputFormat
    conf.set_output_format(NullOutputFormat)
    try:
        ok = run_job(conf).successful
        if ok and delete:
            _delete_extraneous(dst, pairs, conf)
        return ok
    finally:
        # only clean up scratch WE created — a caller-supplied work dir may
        # be a shared staging area with unrelated contents
        if own_work:
            get_filesystem(work, conf).delete(work, recursive=True)


def _delete_extraneous(dst: str, pairs: list[str],
                       conf) -> int:
    """rsync-style -delete: destination files whose RELATIVE path does
    not exist under the source are removed (reference DistCp's -delete;
    runs after a successful copy pass, driver-side). Compared by
    relative path so scheme/authority spelling differences can't make
    everything look extraneous."""
    dfs = get_filesystem(dst, conf)
    if not dfs.exists(dst) or not dfs.get_status(dst).is_dir:
        return 0
    dst_base = dst.rstrip("/")
    wanted_rel = set()
    for p in pairs:
        target = p.split("\t", 1)[1]
        if target.startswith(dst_base):
            wanted_rel.add(target[len(dst_base):].lstrip("/"))
    base = str(dfs.get_status(dst).path)
    removed = 0
    # directories first, top-down: a stale dir (no wanted file beneath
    # it) goes wholesale, so the tree converges to the source like the
    # reference's -delete — not just a file-level sweep
    def sweep_dirs(path: str) -> None:
        nonlocal removed
        for st in dfs.list_status(path):
            if not st.is_dir:
                continue
            rel = str(st.path)[len(base):].lstrip("/")
            if rel and not any(w == rel or w.startswith(rel + "/")
                               for w in wanted_rel):
                dfs.delete(str(st.path), recursive=True)
                removed += 1
            else:
                sweep_dirs(str(st.path))
    sweep_dirs(dst)
    for f in dfs.list_files(dst, recursive=True):
        rel = str(f.path)[len(base):].lstrip("/")
        if rel and rel not in wanted_rel:
            dfs.delete(str(f.path))
            removed += 1
    return removed


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="tpumr distcp")
    ap.add_argument("src")
    ap.add_argument("dst")
    ap.add_argument("-m", "--maps", type=int, default=4)
    ap.add_argument("-delete", action="store_true",
                    help="remove dst files absent from src (needs -update)")
    ap.add_argument("-p", dest="preserve", action="store_true",
                    help="preserve owner + permission bits")
    ap.add_argument("-update", action="store_true",
                    help="skip files already at the destination with the "
                         "same size")
    args = ap.parse_args(argv)
    try:
        ok = distcp(args.src, args.dst, maps=args.maps,
                    update=args.update, delete=args.delete,
                    preserve=args.preserve)
    except ValueError as e:
        print(f"distcp: {e}", file=sys.stderr)
        return 255
    return 0 if ok else 1
