"""Tools tier ≈ the reference's ``src/tools/org/apache/hadoop/tools``:
DistCp (distributed copy), archives (HAR analog), and the rumen history
trace extractor."""
