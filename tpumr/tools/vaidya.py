"""Post-execution job diagnosis — the "vaidya" tier.

≈ ``src/contrib/vaidya`` (reference: vaidya/postexdiagnosis/tests/
{BalancedReducePartitioning,MapSideDiskSpill,MapsReExecutionImpact,
ReducesReExecutionImpact}.java driven by PostExPerformanceDiagnoser and
the postex_diagnosis_tests.xml rule list): each diagnostic rule reads a
finished job's statistics and returns an *impact* in [0, 1]; impact at or
above the rule's threshold flags the problem and attaches a prescription.
The reference parses the field-encoded history format; here the rules read
the JSON-lines job history (tpumr.mapred.history) directly, and two
TPU-era rules replace the HDFS-side-effect rule: backend placement
(is the hybrid scheduler using the measured acceleration?) and map
granularity (the reference's NLineInputFormat 1-line-per-map config made
tiny maps easy to create by accident).

Usage::

    tpumr job -diagnose <history.jsonl>      # CLI
    report = diagnose(events)                # library
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any

from tpumr.core.counters import TaskCounter

_FW = TaskCounter.FRAMEWORK_GROUP


@dataclass
class JobStatistics:
    """A finished job's history, shaped for the rules."""

    job_id: str = ""
    job_name: str = ""
    num_maps: int = 0
    num_reduces: int = 0
    state: str = ""
    wall_time: float = 0.0
    acceleration_factor: float = 0.0
    conf: dict = field(default_factory=dict)
    #: one dict per TERMINAL attempt: event, is_map, run_on_tpu, runtime,
    #: counters {group: {name: value}}
    attempts: list = field(default_factory=list)

    @classmethod
    def from_events(cls, events: "list[dict]") -> "JobStatistics":
        st = cls()
        for ev in events:
            kind = ev.get("event")
            if kind == "JOB_SUBMITTED":
                st.job_id = ev.get("job_id", "")
                st.job_name = ev.get("job_name", "")
                st.num_maps = int(ev.get("num_maps", 0))
                st.num_reduces = int(ev.get("num_reduces", 0))
                st.conf = ev.get("conf", {}) or {}
            elif kind in ("TASK_FINISHED", "TASK_FAILED", "TASK_KILLED"):
                st.attempts.append(ev)
            elif kind == "JOB_FINISHED":
                st.state = ev.get("state", "")
                st.wall_time = float(ev.get("wall_time", 0.0))
                st.acceleration_factor = float(
                    ev.get("acceleration_factor", 0.0) or 0.0)
        return st

    # ------------------------------------------------------------ helpers

    def counter(self, attempt: dict, name: str, group: str = _FW) -> int:
        return int((attempt.get("counters") or {})
                   .get(group, {}).get(name, 0))

    def finished(self, is_map: bool) -> "list[dict]":
        return [a for a in self.attempts
                if a.get("event") == "TASK_FINISHED"
                and a.get("is_map") == is_map]

    def failed(self, is_map: bool) -> "list[dict]":
        return [a for a in self.attempts
                if a.get("event") == "TASK_FAILED"
                and a.get("is_map") == is_map]


class DiagnosticTest:
    """One rule. ``evaluate`` returns impact in [0, 1]; impact >=
    ``threshold`` is a positive finding (the reference's SuccessThreshold
    contract)."""

    name: str = ""
    title: str = ""
    importance: str = "Medium"          # High | Medium | Low
    threshold: float = 0.5

    def evaluate(self, stats: JobStatistics) -> float:
        raise NotImplementedError

    def prescription(self, stats: JobStatistics) -> str:
        return ""


class BalancedReducePartitioning(DiagnosticTest):
    """≈ BalancedReducePartitioning.java: what fraction of reduces carry
    ``percent`` of the reduce input records? Impact = 1 - busy/total."""

    name = "balanced-reduce-partitioning"
    title = "Reduce input is concentrated on few reducers"
    importance = "High"
    threshold = 0.4
    percent = 0.90

    def evaluate(self, stats: JobStatistics) -> float:
        reduces = stats.finished(is_map=False)
        if len(reduces) < 2:
            return 0.0
        recs = sorted(stats.counter(a, TaskCounter.REDUCE_INPUT_RECORDS)
                      for a in reduces)
        total = sum(recs)
        if total == 0:
            return 0.0
        target = self.percent * total
        busy, acc = 0, 0
        for r in reversed(recs):
            acc += r
            busy += 1
            if acc >= target:
                break
        return 1.0 - busy / len(recs)

    def prescription(self, stats: JobStatistics) -> str:
        return ("Partitioning is skewed: use a better partitioner "
                "(TotalOrderPartitioner with sampled splitters, or a "
                "custom get_partition) so reduce input spreads evenly.")


class MapSideDiskSpill(DiagnosticTest):
    """≈ MapSideDiskSpill.java: spilled records beyond the final spill
    mean the sort buffer re-wrote map output to disk multiple times."""

    name = "map-side-disk-spill"
    title = "Map output spills to disk more than once"
    importance = "Medium"
    threshold = 0.3

    def evaluate(self, stats: JobStatistics) -> float:
        maps = stats.finished(is_map=True)
        out = sum(stats.counter(a, TaskCounter.MAP_OUTPUT_RECORDS)
                  for a in maps)
        spilled = sum(stats.counter(a, TaskCounter.SPILLED_RECORDS)
                      for a in maps)
        if out == 0 or spilled <= out:
            return 0.0
        # spilled == out is the single final spill; every extra multiple
        # is a full re-write of the map output
        return min(1.0, (spilled - out) / out)

    def prescription(self, stats: JobStatistics) -> str:
        return ("Raise io.sort.mb (or lower io.sort.spill.percent "
                "pressure) so map output fits the sort buffer in one "
                "spill; add a combiner to shrink records before the "
                "spill.")


class MapsReExecutionImpact(DiagnosticTest):
    """≈ MapsReExecutionImpact.java: failed map attempts re-ran work."""

    name = "maps-reexecution-impact"
    title = "Failed map attempts re-executed work"
    importance = "Medium"
    threshold = 0.3

    def evaluate(self, stats: JobStatistics) -> float:
        done = len(stats.finished(is_map=True))
        failed = len(stats.failed(is_map=True))
        if done + failed == 0:
            return 0.0
        return failed / (done + failed)

    def prescription(self, stats: JobStatistics) -> str:
        return ("Map attempts failed and re-ran: check task logs "
                "(tpumr job -logs), memory limits "
                "(mapred.task.maxvmem.mb), and input corruption.")


class ReducesReExecutionImpact(MapsReExecutionImpact):
    """≈ ReducesReExecutionImpact.java."""

    name = "reduces-reexecution-impact"
    title = "Failed reduce attempts re-executed work"

    def evaluate(self, stats: JobStatistics) -> float:
        done = len(stats.finished(is_map=False))
        failed = len(stats.failed(is_map=False))
        if done + failed == 0:
            return 0.0
        return failed / (done + failed)

    def prescription(self, stats: JobStatistics) -> str:
        return ("Reduce attempts failed and re-ran: check shuffle "
                "fetch failures and reducer memory use.")


class BackendPlacement(DiagnosticTest):
    """TPU-era rule (no reference analog — the GPU work's observability
    was log-only, SURVEY.md §5): when the measured acceleration factor
    says one backend is much faster, most map work should land there.
    Impact = share of map runtime spent on the slower backend, scaled by
    how lopsided the acceleration factor is."""

    name = "backend-placement"
    title = "Map work ran mostly on the slower backend"
    importance = "High"
    threshold = 0.4

    def evaluate(self, stats: JobStatistics) -> float:
        maps = stats.finished(is_map=True)
        accel = stats.acceleration_factor
        if not maps or not accel or accel <= 0:
            return 0.0
        tpu_t = sum(float(a.get("runtime", 0.0)) for a in maps
                    if a.get("run_on_tpu"))
        cpu_t = sum(float(a.get("runtime", 0.0)) for a in maps
                    if not a.get("run_on_tpu"))
        total = tpu_t + cpu_t
        if total == 0:
            return 0.0
        # accel > 1: TPU faster — impact is the CPU share; accel < 1:
        # CPU faster — impact is the TPU share. Near-1 factors mean the
        # backends are comparable and placement doesn't matter.
        lopsided = min(1.0, abs(accel - 1.0))
        slow_share = (cpu_t / total) if accel > 1.0 else (tpu_t / total)
        return lopsided * slow_share

    def prescription(self, stats: JobStatistics) -> str:
        fast = "TPU" if stats.acceleration_factor > 1.0 else "CPU"
        return (f"The measured acceleration factor "
                f"({stats.acceleration_factor:.2f}) says {fast} map "
                f"slots are faster for this job: raise that pool's slot "
                f"count (mapred.tasktracker.map."
                f"{fast.lower()}.tasks.maximum) or enable "
                f"mapred.jobtracker.map.optionalscheduling so the "
                f"scheduler concentrates maps there.")


class MapGranularity(DiagnosticTest):
    """TPU-era rule: per-map runtime far below scheduling overhead means
    the job is paying heartbeat/launch latency per sliver of work (easy
    to hit with NLineInputFormat 1-line-per-map — the reference's GPU
    default config, conf/mapred-site.xml:14-21)."""

    name = "map-granularity"
    title = "Map tasks are too small to amortize scheduling"
    importance = "Low"
    threshold = 0.5
    min_useful_runtime = 1.0  # seconds

    def evaluate(self, stats: JobStatistics) -> float:
        maps = stats.finished(is_map=True)
        if len(maps) < 8:
            return 0.0
        mean = sum(float(a.get("runtime", 0.0)) for a in maps) / len(maps)
        if mean >= self.min_useful_runtime:
            return 0.0
        return 1.0 - mean / self.min_useful_runtime

    def prescription(self, stats: JobStatistics) -> str:
        return ("Increase split size (mapred.min.split.size, "
                "tpumr.dense.split.rows, or linespermap) so each map "
                "carries enough work to amortize launch and heartbeat "
                "latency.")


DEFAULT_TESTS: "list[DiagnosticTest]" = [
    BalancedReducePartitioning(),
    MapSideDiskSpill(),
    MapsReExecutionImpact(),
    ReducesReExecutionImpact(),
    BackendPlacement(),
    MapGranularity(),
]


def diagnose(events: "list[dict]",
             tests: "list[DiagnosticTest] | None" = None) -> dict:
    """Run every rule over one job's history events. Returns the report:
    ``{job_id, job_name, state, wall_time, findings: [...], passed: [...]}``
    with findings ordered High→Low importance then impact."""
    stats = JobStatistics.from_events(events)
    findings, passed = [], []
    for test in tests or DEFAULT_TESTS:
        impact = float(test.evaluate(stats))
        row = {"test": test.name, "title": test.title,
               "importance": test.importance, "impact": round(impact, 3),
               "threshold": test.threshold}
        if impact >= test.threshold:
            row["prescription"] = test.prescription(stats)
            findings.append(row)
        else:
            passed.append(row)
    rank = {"High": 0, "Medium": 1, "Low": 2}
    findings.sort(key=lambda r: (rank.get(r["importance"], 3),
                                 -r["impact"]))
    return {"job_id": stats.job_id, "job_name": stats.job_name,
            "state": stats.state, "wall_time": round(stats.wall_time, 3),
            "findings": findings, "passed": passed}


def diagnose_file(path: str) -> dict:
    """Diagnose a history .jsonl file (local path or any FS URL)."""
    from tpumr.fs import get_filesystem
    if "://" in path:
        data = get_filesystem(path).read_bytes(path).decode()
    else:
        with open(path) as f:
            data = f.read()
    events = [json.loads(line) for line in data.splitlines() if line.strip()]
    return diagnose(events)


def format_report(report: dict) -> str:
    lines = [f"Job {report['job_id']} ({report['job_name'] or 'unnamed'}) "
             f"state={report['state']} wall={report['wall_time']}s",
             f"{len(report['findings'])} finding(s), "
             f"{len(report['passed'])} rule(s) passed", ""]
    for f in report["findings"]:
        lines.append(f"[{f['importance'].upper()}] {f['title']} "
                     f"(impact {f['impact']:.2f} >= {f['threshold']})")
        lines.append(f"  rule: {f['test']}")
        for ln in f["prescription"].splitlines():
            lines.append(f"  {ln}")
        lines.append("")
    if not report["findings"]:
        lines.append("No problems detected.")
    return "\n".join(lines)
