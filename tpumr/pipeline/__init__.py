"""DAG-of-jobs pipeline engine.

Every real workload in this tree is already a chain — kmeans resubmits a
job per round, terasort is teragen→sort→validate, gridmix replays job
mixes — yet each stage used to round-trip its output through DFS and pay
full client-observed submit+schedule latency. This package makes the DAG
first-class:

- :mod:`tpumr.pipeline.graph` — the client-side :class:`JobGraph` API
  (nodes = jobconfs, edges = data deps, loop nodes with a round barrier
  and a convergence predicate) and its validated wire form;
- :mod:`tpumr.pipeline.pipeline_in_progress` — the master-side engine
  that submits downstream stages as upstream reduces commit, driven off
  the same append-only completion machinery the shuffle already uses;
- :mod:`tpumr.pipeline.handoff` — streamed stage handoff: reduce output
  re-served in map-output (IFile) framing over the existing shuffle
  wire, so downstream maps fetch upstream partitions instead of
  re-reading DFS (the committed DFS artifact stays the fallback truth);
- :mod:`tpumr.pipeline.client` — submission + polling
  (:class:`PipelineClient` / :class:`RunningPipeline`), master-restart
  aware like the job client.

Grounding: PAPERS.md "High-throughput Execution of Hierarchical
Analysis Pipelines on Hybrid Cluster Platforms"; ROADMAP "DAG-of-jobs
pipeline engine with streamed stage handoff".
"""

from tpumr.pipeline.graph import JobGraph, PipelineError  # noqa: F401
from tpumr.pipeline.client import (PipelineClient,  # noqa: F401
                                   RunningPipeline)
