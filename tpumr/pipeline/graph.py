"""JobGraph — the client-side pipeline description and its wire form.

A pipeline is a DAG whose nodes are job confs and whose edges are data
dependencies. Two edge modes:

``dfs`` (default)
    The downstream stage reads the upstream stage's committed output
    directory; it is submitted once the upstream job finalized (output
    promoted). Input wiring is automatic when the downstream conf names
    no ``mapred.input.dir`` of its own.

``stream``
    The upstream reduce output is ALSO written in map-output (IFile)
    framing and served over the shuffle wire; the downstream stage's
    maps are one-per-upstream-partition and fetch their records from
    the serving tracker instead of re-reading DFS — submitted as soon
    as upstream reduces start committing, not when the whole job
    finalized. Requires the upstream stage to have reduces and to write
    SequenceFiles (the committed part files remain the byte-truth a
    lost intermediate falls back to).

A ``loop`` node is one job resubmitted round-by-round behind a round
barrier: round ``r+1`` is submitted only after round ``r``'s job
succeeded AND either the convergence predicate (a counter threshold on
the round job's aggregated counters) held false and ``max_rounds`` is
not exhausted. Conf values may embed ``{round}`` / ``{prev_round}`` /
``{next_round}`` placeholders, expanded per round — iterative drivers
version their state files per round instead of rewriting one path
(which is what lets the HBM-resident side-input cache survive rounds,
see ops/devcache.py).
"""

from __future__ import annotations

import re
from typing import Any

#: convergence predicate comparators (counter value OP threshold)
_CONVERGE_OPS = {"lt", "le", "gt", "ge"}

#: node/pipeline id alphabet — ids land in file names and URLs
_ID_RE = re.compile(r"^[A-Za-z0-9_.\-]{1,64}$")

_ROUND_RE = re.compile(r"\{(round|prev_round|next_round)\}")


class PipelineError(ValueError):
    """Graph validation failure (cycle, dangling edge, duplicate id,
    unsatisfiable stream edge, malformed loop spec)."""


def expand_round(conf: dict, rnd: int) -> dict:
    """Per-round conf instantiation: every string value's ``{round}`` /
    ``{prev_round}`` / ``{next_round}`` placeholders become ``rnd`` /
    ``rnd-1`` / ``rnd+1``. Non-string values pass through untouched."""
    vals = {"round": rnd, "prev_round": rnd - 1, "next_round": rnd + 1}

    def sub(v: Any) -> Any:
        if isinstance(v, str) and "{" in v:
            return _ROUND_RE.sub(lambda m: str(vals[m.group(1)]), v)
        return v

    return {k: sub(v) for k, v in conf.items()}


class JobGraph:
    """Builder + validator for one pipeline submission.

    >>> g = JobGraph("terasort-chain")
    >>> g.node("gen", gen_conf)
    >>> g.node("sort", sort_conf, conf_hook="pkg.mod.sample_hook")
    >>> g.node("validate", val_conf)
    >>> g.edge("gen", "sort")
    >>> g.edge("sort", "validate", stream=True)
    >>> pid = PipelineClient(conf).submit(g).pipeline_id
    """

    def __init__(self, name: str = "", conf: "dict | None" = None) -> None:
        self.name = name
        #: pipeline-wide conf defaults merged under every stage conf
        #: (queue, priority, tracing switches)
        self.conf: dict = dict(conf or {})
        self.nodes: "dict[str, dict]" = {}
        self.edges: "list[dict]" = []

    # ------------------------------------------------------------ build

    def node(self, node_id: str, conf: dict,
             conf_hook: "str | None" = None) -> "JobGraph":
        """One job stage. ``conf_hook`` names an importable
        ``fn(conf_dict, upstreams) -> None`` the master calls right
        before submitting the stage — the seam for prep that needs the
        upstream output to exist (terasort's partition-file sampling)."""
        if node_id in self.nodes:
            raise PipelineError(f"duplicate node id {node_id!r}")
        if not _ID_RE.match(node_id or ""):
            raise PipelineError(f"bad node id {node_id!r} (want "
                                f"[A-Za-z0-9_.-], max 64 chars)")
        self.nodes[node_id] = {"id": node_id, "conf": dict(conf),
                               "conf_hook": conf_hook}
        return self

    def loop(self, node_id: str, conf: dict, max_rounds: int,
             converge: "dict | None" = None,
             conf_hook: "str | None" = None) -> "JobGraph":
        """An iterative node: the job resubmits round-by-round (round
        barrier) until ``converge`` — ``{"group": G, "counter": C,
        "op": lt|le|gt|ge, "value": V}`` over the round job's aggregated
        counters — holds, or ``max_rounds`` is exhausted (the cutoff)."""
        self.node(node_id, conf, conf_hook)
        self.nodes[node_id]["loop"] = {
            "max_rounds": int(max_rounds),
            "converge": dict(converge) if converge else None,
        }
        return self

    def edge(self, src: str, dst: str, stream: bool = False) -> "JobGraph":
        self.edges.append({"src": src, "dst": dst,
                           "stream": bool(stream)})
        return self

    # ------------------------------------------------------------- wire

    def to_dict(self) -> dict:
        return {"name": self.name, "conf": dict(self.conf),
                "nodes": [dict(n) for n in self.nodes.values()],
                "edges": [dict(e) for e in self.edges]}

    @staticmethod
    def from_dict(d: dict) -> "JobGraph":
        g = JobGraph(str(d.get("name", "") or ""),
                     dict(d.get("conf") or {}))
        for n in d.get("nodes") or []:
            nid = str(n.get("id", ""))
            loop = n.get("loop")
            if loop:
                g.loop(nid, dict(n.get("conf") or {}),
                       int(loop.get("max_rounds", 1)),
                       loop.get("converge"),
                       n.get("conf_hook"))
            else:
                g.node(nid, dict(n.get("conf") or {}),
                       n.get("conf_hook"))
        for e in d.get("edges") or []:
            g.edge(str(e.get("src", "")), str(e.get("dst", "")),
                   bool(e.get("stream")))
        return g

    # ------------------------------------------------------ topology

    def upstreams(self, node_id: str) -> "list[dict]":
        return [e for e in self.edges if e["dst"] == node_id]

    def downstreams(self, node_id: str) -> "list[dict]":
        return [e for e in self.edges if e["src"] == node_id]

    def topo_order(self) -> "list[str]":
        """Kahn topological order; raises :class:`PipelineError` on a
        cycle (naming the nodes stuck in it)."""
        indeg = {nid: 0 for nid in self.nodes}
        for e in self.edges:
            indeg[e["dst"]] += 1
        ready = sorted(nid for nid, d in indeg.items() if d == 0)
        order: "list[str]" = []
        while ready:
            nid = ready.pop(0)
            order.append(nid)
            for e in self.downstreams(nid):
                indeg[e["dst"]] -= 1
                if indeg[e["dst"]] == 0:
                    ready.append(e["dst"])
            ready.sort()
        if len(order) != len(self.nodes):
            stuck = sorted(set(self.nodes) - set(order))
            raise PipelineError(f"cycle through nodes {stuck}")
        return order

    # ---------------------------------------------------- validation

    def validate(self) -> "JobGraph":
        """Raise :class:`PipelineError` on anything the master would
        choke on later — an invalid graph must be rejected at submit,
        never half-run."""
        if not self.nodes:
            raise PipelineError("empty pipeline (no nodes)")
        for e in self.edges:
            for end in ("src", "dst"):
                if e[end] not in self.nodes:
                    raise PipelineError(
                        f"dangling edge endpoint {e[end]!r} "
                        f"({e['src']} -> {e['dst']})")
            if e["src"] == e["dst"]:
                raise PipelineError(
                    f"self-edge on {e['src']!r} (iterate with a loop "
                    f"node instead)")
        self.topo_order()   # cycle rejection
        for nid, n in self.nodes.items():
            conf = n["conf"]
            if not str(conf.get("mapred.output.dir") or ""):
                raise PipelineError(
                    f"node {nid!r} has no mapred.output.dir — every "
                    f"stage needs one (downstream wiring + recovery "
                    f"fall back to the committed artifact)")
            loop = n.get("loop")
            if loop is not None:
                if loop["max_rounds"] < 1:
                    raise PipelineError(
                        f"loop node {nid!r}: max_rounds must be >= 1")
                conv = loop.get("converge")
                if conv is not None:
                    missing = {"group", "counter", "op",
                               "value"} - set(conv)
                    if missing:
                        raise PipelineError(
                            f"loop node {nid!r}: converge spec is "
                            f"missing {sorted(missing)}")
                    if conv["op"] not in _CONVERGE_OPS:
                        raise PipelineError(
                            f"loop node {nid!r}: converge op "
                            f"{conv['op']!r} not in "
                            f"{sorted(_CONVERGE_OPS)}")
                    if isinstance(conv["value"], bool) or \
                            not isinstance(conv["value"], (int, float)):
                        # a string threshold would TypeError against
                        # the int counter on EVERY advance — the
                        # pipeline would spin RUNNING forever
                        raise PipelineError(
                            f"loop node {nid!r}: converge value "
                            f"{conv['value']!r} must be a number")
            ins = self.upstreams(nid)
            modes = {bool(e["stream"]) for e in ins}
            if len(modes) > 1:
                raise PipelineError(
                    f"node {nid!r} mixes stream and dfs in-edges — a "
                    f"stage reads through one input format")
            if ins and modes == {True} \
                    and str(conf.get("mapred.input.dir") or ""):
                raise PipelineError(
                    f"node {nid!r} has stream in-edges AND its own "
                    f"mapred.input.dir — streamed input is wired by "
                    f"the engine")
        for e in self.edges:
            if not e["stream"]:
                continue
            # NOTE: a stream edge OUT of a converging loop node is
            # legal — streaming just begins only once the loop settles
            # on its final round (see _stream_ready's degradation)
            src = self.nodes[e["src"]]
            sconf = src["conf"]
            if int(sconf.get("mapred.reduce.tasks", 1) or 0) < 1:
                raise PipelineError(
                    f"stream edge {e['src']} -> {e['dst']}: upstream "
                    f"is map-only — streamed handoff serves REDUCE "
                    f"output (use a dfs edge)")
            out_fmt = str(sconf.get("mapred.output.format.class", "")
                          or "")
            if "SequenceFileOutputFormat" not in out_fmt:
                raise PipelineError(
                    f"stream edge {e['src']} -> {e['dst']}: upstream "
                    f"must write SequenceFiles (got "
                    f"{out_fmt or 'the text default'}) — the committed "
                    f"part files are the record-identical fallback for "
                    f"a lost intermediate")
        return self
