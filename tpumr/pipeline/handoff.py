"""Streamed stage handoff — reduce output served over the shuffle wire.

The write side (:class:`HandoffWriter`, driven by reduce_task) tees the
reduce's emitted records into ONE single-partition IFile next to the
normal OutputFormat write, under the tracker's handoff tree; the tracker
registers it post-commit under the ``handoff:<job_id>`` serving key so
the EXISTING shuffle endpoints (``get_map_output`` /
``get_map_output_chunk``) serve it unchanged — the wire, chunking, and
fault-injection seams are all the PR-1 machinery.

The read side (:class:`PipelineHandoffInputFormat` over
:class:`HandoffSplit`) is a downstream map whose "split" is one
upstream reduce partition. Discovery reuses the completion-event
protocol verbatim: the master keeps a per-job append-only
``handoff_events`` feed (same :class:`CompletionEventFeed`,
``map_index`` = reduce partition) and the reader drives the same
:class:`~tpumr.mapred.tasktracker.MapLocator` over it — OBSOLETE
tombstones (serving tracker evicted) drop the cached location exactly
like a withdrawn map output. A partition the stream cannot serve falls
back to the upstream stage's COMMITTED SequenceFile part file, which
holds record-identical data: residency on the wire is an optimization,
the DFS artifact stays the truth (the device_output.py stance).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Iterator

from tpumr.core import confkeys
from tpumr.mapred.split import InputSplit

#: one locator attempt's budget before the reader interleaves a DFS
#: fallback probe — short enough that a dead stream degrades quickly,
#: long enough that locate() amortizes its event polls
_LOCATE_SLICE_S = 2.0

#: counters the reader emits (group "Pipeline") — one per split, so the
#: job's aggregated counters say how much of the stage actually streamed
COUNTER_GROUP = "Pipeline"
COUNTER_STREAMED = "HANDOFF_STREAMED_SPLITS"
COUNTER_FALLBACK = "HANDOFF_DFS_FALLBACK_SPLITS"

#: serving-key namespace on the tracker: handoff entries live beside map
#: outputs but are keyed off the job id proper, so job cleanup can't
#: collide with them and the pipeline controls their lifetime
SERVE_PREFIX = "handoff:"


def serve_key(job_id: str) -> str:
    return SERVE_PREFIX + job_id


# ----------------------------------------------------------------- write


class HandoffWriter:
    """Tee of one reduce attempt's output records into a
    single-partition IFile (the map-output spill framing, so the
    existing shuffle server serves it without a new wire format)."""

    def __init__(self, path: str, codec: str = "none") -> None:
        from tpumr.io import ifile
        self.path = path
        self._f = open(path, "wb")
        self._w = ifile.Writer(self._f, codec=codec)
        self._w.start_partition()
        self._n = 0

    def append(self, key: Any, value: Any) -> None:
        from tpumr.io.writable import serialize
        self._w.append_raw(serialize(key), serialize(value))
        self._n += 1

    def finish(self, partition: int) -> dict:
        """Close and return the registration payload the tracker stores
        beside map-output indexes."""
        self._w.end_partition()
        index = self._w.close()
        self._f.close()
        return {"path": self.path, "index": index,
                "partition": partition, "records": self._n}

    def abort(self) -> None:
        import os
        try:
            self._f.close()
        finally:
            try:
                os.unlink(self.path)
            except OSError:
                pass

    @staticmethod
    def open_for(conf: Any, task: Any) -> "HandoffWriter | None":
        """A writer when this reduce attempt should stream its output:
        the stage opted in AND the runtime provided a handoff dir (the
        tracker does; LocalJobRunner has no serving side)."""
        if not confkeys.get_boolean(conf, "tpumr.pipeline.stream.handoff"):
            return None
        d = conf.get("tpumr.pipeline.handoff.dir")
        if not d:
            return None
        import os
        os.makedirs(d, exist_ok=True)
        return HandoffWriter(os.path.join(d, f"{task.attempt_id}.handoff"))


# ------------------------------------------------------------------ read


@dataclass
class HandoffSplit(InputSplit):
    """One upstream reduce partition as a downstream map's input: fetch
    it over the shuffle wire from whichever tracker committed it, fall
    back to the upstream stage's committed part file."""

    upstream_job: str = ""
    partition: int = 0
    #: the upstream stage's mapred.output.dir — the DFS fallback root
    fallback_dir: str = ""
    #: records the upstream reduce emitted (0 = unknown): progress hint
    num_records: int = 0

    def describe(self) -> str:
        return f"{self.upstream_job}[r{self.partition}]"


def build_handoff_splits(upstream_job: str, num_reduces: int,
                         output_dir: str,
                         serving: "dict[int, str] | None" = None
                         ) -> "list[HandoffSplit]":
    """Master-side split construction for a streamed stage: one split
    per upstream reduce partition; locality hints from the partitions
    already committed (``serving``: partition -> shuffle_addr)."""
    serving = serving or {}
    out = []
    for p in range(num_reduces):
        addr = serving.get(p, "")
        host = addr.rsplit(":", 1)[0] if addr else ""
        out.append(HandoffSplit(locations=[host] if host else [],
                                upstream_job=upstream_job, partition=p,
                                fallback_dir=output_dir))
    return out


class PipelineHandoffInputFormat:
    """Input format of a streamed downstream stage. ``get_splits`` is
    never called — the master builds :class:`HandoffSplit`\\ s when it
    submits the stage (that is the point: no client round trip, no DFS
    listing)."""

    def get_splits(self, conf: Any, num_splits: int):
        raise RuntimeError(
            "PipelineHandoffInputFormat splits are built by the "
            "pipeline engine at stage submit — this job must be "
            "submitted through a pipeline, not directly")

    def get_record_reader(self, split: HandoffSplit, conf: Any,
                          reporter: Any = None
                          ) -> "Iterator[tuple[Any, Any]]":
        assert isinstance(split, HandoffSplit), split
        timeout_s = confkeys.get_int(
            conf, "tpumr.pipeline.handoff.timeout.ms") / 1000.0
        poll_s = confkeys.get_int(
            conf, "tpumr.pipeline.handoff.poll.ms") / 1000.0
        # the tracker's in-process seam: a factory of per-upstream-job
        # handoff sources (MapLocator over the master's handoff feed +
        # the tracker's rpc credentials). Absent outside a tracker
        # (child isolation, local tests) — DFS fallback only.
        factory = conf.get("tpumr.pipeline.handoff.source")
        src = factory(split.upstream_job) if callable(factory) else None
        counters = getattr(reporter, "counters", None)

        def bump(name: str) -> None:
            if counters is not None:
                counters.counter(COUNTER_GROUP, name).increment()

        # monotonic deadline: an NTP step mid-wait must not fire (or
        # stall) the handoff timeout
        from tpumr.io.compress import wire_codec_or_none
        wire = wire_codec_or_none(
            confkeys.get(conf, "tpumr.shuffle.wire.codec"))
        deadline = time.monotonic() + timeout_s
        while True:
            if src is not None:
                records = self._try_stream(src, split, wire)
                if records is not None:
                    bump(COUNTER_STREAMED)
                    return records
            records = self._try_fallback(split, conf)
            if records is not None:
                bump(COUNTER_FALLBACK)
                return records
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"handoff partition {split.describe()} never became "
                    f"available (stream or committed fallback) within "
                    f"{timeout_s:.0f}s")
            if reporter is not None:
                # waiting for an upstream reduce is progress, not a
                # hang — keep the task-timeout reaper informed
                keepalive = getattr(reporter, "progress", None)
                if keepalive is not None:
                    keepalive()
            time.sleep(poll_s)

    # one source object per upstream job is shared by every map task of
    # the stage on a tracker; locate() and the fetch itself are
    # thread-safe (MapLocator's own locking + per-thread RpcClients)

    #: one streamed-fetch chunk on the wire — the tracker's own
    #: chunked-transfer discipline (its MAX_CHUNK_BYTES server cap):
    #: whole partitions never ride one RPC response, so a multi-GB
    #: upstream partition streams memory-bounded on both ends
    FETCH_CHUNK_BYTES = 4 << 20

    #: streamed-fetch chunk requests kept in flight per connection
    #: (the copier's pipelined-window discipline, inherited here)
    PIPELINE_DEPTH = 4

    def _try_stream(self, src: Any, split: HandoffSplit,
                    wire: str = "none"):
        """One bounded attempt at the streamed path: locate the serving
        tracker via the handoff completion-event feed, then stream the
        single-partition segment through the CHUNKED shuffle endpoint
        (first chunk fetched eagerly so a dead server demotes the
        location instead of failing the attempt; a mid-stream loss
        raises into the normal attempt-retry protocol). None = not
        (yet) streamable — the caller interleaves the DFS fallback.

        The stream inherits the shuffle wire-path machinery: when the
        source hands out a pooled target (``lease``), remaining chunks
        ride a PIPELINED window over one leased connection
        (offset-predictive — the server's chunk length is
        deterministic), and ``wire`` wire-compresses uncompressed
        handoff spills in flight."""
        from tpumr.io import ifile
        try:
            client = src.locate(split.partition)
        except TimeoutError:
            return None
        if client is None:
            return None
        key = serve_key(split.upstream_job)
        try:
            first = client.call("get_map_output_chunk", key,
                                split.partition, 0, 0,
                                self.FETCH_CHUNK_BYTES, wire)
        except Exception:  # noqa: BLE001 — serving tracker gone/lame:
            # demote the cached location (the feed's OBSOLETE tombstone
            # or a fresh event decides its fate) and fall back
            src.invalidate(split.partition)
            return None
        from tpumr.io.writable import deserialize

        def decode(out: dict) -> bytes:
            if out.get("wire"):
                from tpumr.io.compress import get_codec
                return get_codec(out["wire"]).decompress(out["data"])
            return out["data"]

        def chunks() -> Iterator[bytes]:
            total = int(first["total"])
            data = decode(first)
            yield data
            off = len(data)
            if off >= total:
                return
            lease = getattr(client, "lease", None)
            if lease is None:
                # legacy bare-client source: sequential chunks
                while off < total:
                    out = client.call("get_map_output_chunk", key,
                                      split.partition, 0, off,
                                      self.FETCH_CHUNK_BYTES, wire)
                    data = decode(out)
                    if not data:
                        raise EOFError(
                            f"handoff stream for {split.describe()} "
                            f"truncated at {off}/{total}")
                    yield data
                    off += len(data)
                return
            cli = lease()
            dead = False
            try:
                offsets = range(off, total, self.FETCH_CHUNK_BYTES)
                inflight = 0
                i = 0
                while inflight or i < len(offsets):
                    while i < len(offsets) \
                            and inflight < self.PIPELINE_DEPTH:
                        cli.call_begin(
                            "get_map_output_chunk", key,
                            split.partition, 0, offsets[i],
                            self.FETCH_CHUNK_BYTES, wire)
                        i += 1
                        inflight += 1
                    out = cli.call_finish()
                    inflight -= 1
                    data = decode(out)
                    if not data:
                        raise EOFError(
                            f"handoff stream for {split.describe()} "
                            f"truncated at {off}/{total}")
                    yield data
                    off += len(data)
            except (ConnectionError, OSError):
                dead = True
                raise
            finally:
                # abandoned window ⇒ outstanding responses ⇒ the pool
                # closes the connection instead of reusing it
                client.release(cli, dead=dead)

        def gen() -> Iterator[tuple[Any, Any]]:
            for kb, vb in ifile.iter_chunked_segment(
                    chunks(), first.get("codec", "none")):
                yield deserialize(kb), deserialize(vb)

        return gen()

    def _try_fallback(self, split: HandoffSplit, conf: Any):
        """The committed part file, once the upstream stage's output
        promotion made it visible. Record-identical to the stream: the
        stream edge contract pins the upstream output format to
        SequenceFiles."""
        from tpumr.fs.filesystem import FileSystem, Path
        from tpumr.io import sequencefile
        from tpumr.mapred.output_formats import part_name
        path = str(Path(split.fallback_dir).child(
            part_name(split.partition)))
        fs = FileSystem.get(path, conf)
        try:
            if not fs.exists(path):
                return None
            length = fs.get_status(path).length
        except OSError:
            return None

        def gen() -> Iterator[tuple[Any, Any]]:
            f = fs.open(path)
            try:
                yield from sequencefile.Reader(f).iter_range(0, length)
            finally:
                f.close()

        return gen()


@dataclass
class HandoffSource:
    """The tracker-built per-upstream-job stream source: a
    :class:`~tpumr.mapred.tasktracker.MapLocator` (reused verbatim —
    the handoff feed speaks the same event dialect) plus bookkeeping.
    ``locate`` returns the serving tracker's RpcClient or raises
    TimeoutError after its bounded slice."""

    locator: Any = None
    upstream_job: str = ""

    def locate(self, partition: int):
        return self.locator(partition)

    def invalidate(self, partition: int) -> None:
        self.locator.invalidate(partition)


def make_handoff_source(upstream_job: str, events_fn: Any,
                        secret: "bytes | None",
                        poll_s: float) -> HandoffSource:
    """Build the stream source the tracker stashes in the stage conf:
    the PR-1 MapLocator over the master's handoff completion-event feed,
    with a SHORT per-call timeout so the reader can interleave DFS
    fallback probes between locate slices."""
    from tpumr.mapred.tasktracker import make_map_locator
    locator = make_map_locator(events_fn, secret, poll_s=poll_s,
                               timeout_s=_LOCATE_SLICE_S)
    return HandoffSource(locator=locator, upstream_job=upstream_job)
