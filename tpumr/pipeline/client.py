"""Pipeline submission + monitoring — the JobClient of DAGs.

One atomic RPC submits the whole validated graph; the master owns all
subsequent stage submissions (split computation included), so an
N-stage chain costs ONE client round trip instead of N×(submit + poll
until terminal + resubmit) — the per-stage overhead the bench.py
``kmeans_pipeline`` row measures.

Partition tolerance matches the job client: polls retry through master
restarts (pipeline ids are stable across restarts — the recovered
pipeline keeps its id, unlike stage jobs, which rebind through the
job-recovery alias under the covers).
"""

from __future__ import annotations

import time
from typing import Any

from tpumr.ipc.rpc import RpcClient
from tpumr.mapred.jobconf import JobConf
from tpumr.pipeline.graph import JobGraph


class RunningPipeline:
    def __init__(self, client: RpcClient, pipeline_id: str) -> None:
        self._client = client
        self.pipeline_id = pipeline_id

    def status(self) -> dict:
        return self._client.call("get_pipeline_status", self.pipeline_id)

    def is_complete(self) -> bool:
        return self.status()["state"] in ("SUCCEEDED", "FAILED", "KILLED")

    def kill(self) -> bool:
        from tpumr.security import UserGroupInformation
        return self._client.call(
            "kill_pipeline", self.pipeline_id,
            UserGroupInformation.get_current_user().user)

    def wait_for_completion(self, poll_s: float = 0.2,
                            timeout: float = 3600.0) -> dict:
        deadline = time.monotonic() + timeout
        while True:
            st = self.status()
            if st["state"] in ("SUCCEEDED", "FAILED", "KILLED"):
                return st
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"pipeline {self.pipeline_id} did not finish within "
                    f"{timeout}s: {st}")
            time.sleep(poll_s)


class PipelineClient:
    def __init__(self, conf: JobConf) -> None:
        self.conf = conf
        tracker = conf.get("mapred.job.tracker")
        if not tracker or tracker == "local":
            raise ValueError(
                "pipelines need a cluster master (mapred.job.tracker); "
                "run the stages through LocalJobRunner individually for "
                "daemon-less execution")
        host, port = str(tracker).rsplit(":", 1)
        from tpumr.core import confkeys
        from tpumr.security import client_credentials
        secret, scope = client_credentials(conf, "jobtracker")
        self._client = RpcClient(
            host, int(port), secret=secret, scope=scope,
            retries=confkeys.get_int(conf, "tpumr.jobclient.rpc.retries"),
            backoff_ms=conf.get_int("tpumr.rpc.client.backoff.ms", 200))

    def submit(self, graph: "JobGraph | dict") -> RunningPipeline:
        """Validate client-side (fail fast, no half-submitted graphs),
        then hand the wire form to the master — which validates AGAIN
        before admitting it (clients lie)."""
        if isinstance(graph, JobGraph):
            graph.validate()
            graph = graph.to_dict()
        else:
            JobGraph.from_dict(graph).validate()
        graph = dict(graph)
        # client-local credentials must never ride the graph: node
        # confs built from a client JobConf may carry the user key /
        # token paths, and the master JOURNALS the full graph (the
        # _wire_conf stripping, pipeline edition — the master scrubs
        # again, but secrets shouldn't even cross the wire)
        from tpumr.mapred.job_client import scrub_credentials
        conf = scrub_credentials(dict(graph.get("conf") or {}))
        if not conf.get("user.name"):
            from tpumr.security import UserGroupInformation
            conf["user.name"] = \
                UserGroupInformation.get_current_user().user
        graph["conf"] = conf
        graph["nodes"] = [
            {**n, "conf": scrub_credentials(dict(n.get("conf") or {}))}
            for n in graph.get("nodes") or []]
        pid = self._client.call("submit_pipeline", graph)
        return RunningPipeline(self._client, pid)

    def list(self) -> "list[dict]":
        return self._client.call("list_pipelines")

    def status(self, pipeline_id: str) -> dict:
        return self._client.call("get_pipeline_status", pipeline_id)

    def trace(self, pipeline_id: str) -> dict:
        """The merged end-to-end trace of a traced pipeline (raw span
        dicts; feed to tracing.to_chrome_trace for viewers)."""
        return self._client.call("get_pipeline_trace", pipeline_id)

    def running(self, pipeline_id: str) -> RunningPipeline:
        return RunningPipeline(self._client, pipeline_id)


def run_pipeline(conf: JobConf, graph: "JobGraph | dict",
                 timeout: float = 3600.0) -> dict:
    """Submit and wait; raises on a non-SUCCEEDED terminal state."""
    running = PipelineClient(conf).submit(graph)
    st = running.wait_for_completion(timeout=timeout)
    if st["state"] != "SUCCEEDED":
        raise RuntimeError(
            f"pipeline {running.pipeline_id} {st['state']}: "
            f"{st.get('error', '')}")
    return st
