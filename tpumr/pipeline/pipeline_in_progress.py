"""PipelineInProgress — the master-side DAG engine.

One instance per submitted pipeline. The master drives :meth:`advance`
from the heartbeat's DEFERRED phase (after every lock is released) and
from the expiry loop: pipeline bookkeeping NEVER rides the heartbeat
fast path, and the engine's own lock (rank ``pipeline``, slotted
between ``scheduler`` and ``global`` in metrics/locks.py) is held only
for state transitions — stage submission (split computation, conf
hooks, history I/O) runs OUTSIDE it, with a SUBMITTING mark making
concurrent advances idempotent.

Stage readiness:

- no in-edges → ready at pipeline submit;
- ``dfs`` in-edges → every upstream node SUCCEEDED and its job
  FINALIZED (output promoted — the downstream input format lists it);
- ``stream`` in-edges → every upstream node's job has started
  COMMITTING reduces (``finished_reduces >= 1``; loop upstreams: the
  loop settled on its final round first) — downstream maps fetch
  partitions as they commit and wait on the handoff feed for the rest.

Loop nodes run one job per round behind a round barrier; after a round
SUCCEEDS the convergence predicate is evaluated on the round job's
aggregated counters, and either the node settles (predicate holds, or
``max_rounds`` exhausted — the cutoff) or the next round submits with
``{round}``-expanded conf.

Restart recovery: the pipeline journals PIPELINE_SUBMITTED (full graph)
and one PIPELINE_STAGE_SUBMITTED per stage job into its own history
file; :meth:`from_recovery` replays them, mapping stage job ids through
the master's job-recovery alias table — completed upstream stages are
adopted terminal from history (never re-run), in-flight stages re-bind
to their recovered jobs, unsubmitted stages submit normally once their
upstreams settle.
"""

from __future__ import annotations

import time
from typing import Any

from tpumr.pipeline.graph import JobGraph, expand_round


class PipelineState:
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    KILLED = "KILLED"
    TERMINAL = {SUCCEEDED, FAILED, KILLED}


class NodeState:
    PENDING = "PENDING"
    SUBMITTING = "SUBMITTING"   # a plan is in flight outside the lock
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    SKIPPED = "SKIPPED"         # pipeline died before this stage ran
    TERMINAL = {SUCCEEDED, FAILED, SKIPPED}


class _Node:
    __slots__ = ("spec", "state", "round", "jobs", "job_id",
                 "output_dir", "num_reduces", "error")

    def __init__(self, spec: dict) -> None:
        self.spec = spec
        self.state = NodeState.PENDING
        self.round = 0
        #: every job this node submitted, in order (loop rounds)
        self.jobs: "list[str]" = []
        #: the CURRENT (or final) round's job id
        self.job_id = ""
        #: the settled output dir (final round's, for loops)
        self.output_dir = ""
        self.num_reduces = 0
        self.error = ""

    @property
    def is_loop(self) -> bool:
        return self.spec.get("loop") is not None

    def round_conf(self, pipeline_conf: dict, rnd: int) -> dict:
        conf = dict(pipeline_conf)
        conf.update(self.spec["conf"])
        return expand_round(conf, rnd) if self.is_loop else conf


class PipelineInProgress:
    def __init__(self, pipeline_id: str, graph: JobGraph,
                 user: str = "") -> None:
        self.pipeline_id = pipeline_id
        self.graph = graph
        self.user = user
        self.state = PipelineState.RUNNING
        self.error = ""
        #: wall stamp for status surfaces AND the scheduler's pipeline
        #: anchor (stage jobs inherit this as their FIFO sort key so a
        #: late stage never queues behind jobs submitted mid-pipeline)
        self.start_time = time.time()
        self.finish_time = 0.0
        self.nodes: "dict[str, _Node]" = {
            nid: _Node(spec) for nid, spec in graph.nodes.items()}
        self.order = graph.topo_order()
        #: open pipeline root span (traced pipelines only)
        self.trace_root: Any = None
        self.trace_id = ""

    # --------------------------------------------------------- queries

    def node_of_job(self, job_id: str) -> "str | None":
        for nid, n in self.nodes.items():
            if job_id in n.jobs:
                return nid
        return None

    def status_dict(self) -> dict:
        return {
            "pipeline_id": self.pipeline_id,
            "name": self.graph.name,
            "state": self.state,
            "error": self.error,
            "start_time": self.start_time,
            "finish_time": self.finish_time,
            "nodes": {nid: {
                "state": n.state,
                "round": n.round,
                "rounds_run": len(n.jobs),
                "job_id": n.job_id,
                "jobs": list(n.jobs),
                "output_dir": n.output_dir,
                "error": n.error,
            } for nid, n in self.nodes.items()},
        }

    # ------------------------------------------------------- readiness

    def _upstream_ready(self, master: Any, nid: str) -> bool:
        """All in-edges satisfied for ``nid``'s (next) submission.
        Reads of member jobs are lock-free (job table insert-only;
        jip.state / finished_reduces are GIL-atomic reads — staleness
        costs one extra advance pass, never correctness)."""
        for e in self.graph.upstreams(nid):
            up = self.nodes[e["src"]]
            if up.state != NodeState.SUCCEEDED:
                return False
            if e["stream"]:
                continue   # a SUCCEEDED stream upstream is settled
            jip = master.jobs.get(up.job_id)
            if jip is not None and not jip.finalized.is_set():
                return False   # output not promoted yet
        return True

    def _stream_ready(self, master: Any, nid: str) -> bool:
        """Early readiness for an all-stream-in-edge node: every
        upstream has settled WHICH job serves (non-loop: its only job;
        loop: the final round) and that job began committing reduces."""
        ins = self.graph.upstreams(nid)
        if not ins or not all(e["stream"] for e in ins):
            return False
        for e in ins:
            up = self.nodes[e["src"]]
            if up.state == NodeState.SUCCEEDED:
                continue
            if up.is_loop or up.state != NodeState.RUNNING:
                # a loop's current round may not be its last — wait for
                # the node to settle (documented degradation)
                return False
            jip = master.jobs.get(up.job_id)
            if jip is None or jip.finished_reduces < 1:
                return False
        return True

    # --------------------------------------------------------- advance

    def plan_locked(self, master: Any
                    ) -> "tuple[list[tuple[str, int]], list[tuple[str, str]]]":
        """Fold member-job outcomes into node states and return
        ``(plans, unresolved)``: the (node, round) submissions now due
        (marked SUBMITTING), and (node, job_id) pairs whose job only
        HISTORY remembers — the caller resolves those OUTSIDE this lock
        (history reads are file I/O) and feeds the verdicts back via
        :meth:`apply_retired`. Caller holds the master's pipeline lock;
        everything here is in-memory — no I/O, no ranked lock below
        ``pipeline`` (job-state reads are lock-free)."""
        if self.state in PipelineState.TERMINAL:
            return [], []
        plans: "list[tuple[str, int]]" = []
        unresolved: "list[tuple[str, str]]" = []
        for nid in self.order:
            n = self.nodes[nid]
            if n.state == NodeState.RUNNING:
                jip = master.jobs.get(n.job_id)
                if jip is None:
                    unresolved.append((nid, n.job_id))
                else:
                    self._fold_job_outcome(nid, n, jip, plans)
            if n.state == NodeState.PENDING \
                    and (self._upstream_ready(master, nid)
                         or self._stream_ready(master, nid)):
                n.state = NodeState.SUBMITTING
                plans.append((nid, n.round))
        if self.state == PipelineState.RUNNING and all(
                n.state == NodeState.SUCCEEDED
                for n in self.nodes.values()):
            self.state = PipelineState.SUCCEEDED
            self.finish_time = time.time()
        return plans, unresolved

    def _fold_job_outcome(self, nid: str, n: _Node, jip: Any,
                          plans: "list[tuple[str, int]]") -> None:
        """One RUNNING node's live current job: settle, iterate, or
        fail. Caller holds the pipeline lock."""
        st = jip.state
        if st == "SUCCEEDED":
            if n.is_loop and not self._loop_settled(n, jip):
                n.round += 1
                n.state = NodeState.SUBMITTING
                plans.append((nid, n.round))
                return
            n.state = NodeState.SUCCEEDED
        elif st in ("FAILED", "KILLED"):
            n.state = NodeState.FAILED
            n.error = jip.error or f"stage job {n.job_id} {st}"
            self._fail(f"stage {nid!r} {st.lower()}: {n.error}")

    def apply_retired(self, nid: str, state: str) -> None:
        """Feed back one history-resolved stage outcome (caller re-took
        the pipeline lock). Loops settle conservatively — the finished
        round's counters died with the old master, so convergence can't
        be evaluated and the loop keeps iterating toward max_rounds."""
        n = self.nodes.get(nid)
        if n is None or n.state != NodeState.RUNNING:
            return
        if state == "SUCCEEDED":
            if n.is_loop and n.round + 1 < int(
                    n.spec["loop"]["max_rounds"]):
                n.round += 1
                n.state = NodeState.PENDING
            else:
                n.state = NodeState.SUCCEEDED
        elif state in ("FAILED", "KILLED"):
            n.state = NodeState.FAILED
            n.error = f"stage job {n.job_id} {state} (history)"
            self._fail(f"stage {nid!r} {state.lower()}: {n.error}")

    def _loop_settled(self, n: _Node, jip: Any) -> bool:
        """True when this loop node is done iterating: convergence
        predicate holds on the finished round's counters, or the
        max-rounds cutoff is reached."""
        loop = n.spec["loop"]
        if n.round + 1 >= int(loop["max_rounds"]):
            return True
        conv = loop.get("converge")
        if not conv or jip is None:
            return False
        value = jip.counters.value(str(conv["group"]),
                                   str(conv["counter"]))
        threshold = conv["value"]
        op = conv["op"]
        return (value < threshold if op == "lt" else
                value <= threshold if op == "le" else
                value > threshold if op == "gt" else
                value >= threshold)

    @staticmethod
    def _retired_state(master: Any, job_id: str) -> str:
        """Terminal state of a stage job only history remembers (the
        job finished before a master restart)."""
        if not job_id:
            return "RUNNING"
        st = master.history.retired_job_status(job_id)
        return str(st["state"]) if st else "RUNNING"

    def _fail(self, error: str) -> None:
        if self.state in PipelineState.TERMINAL:
            return
        self.state = PipelineState.FAILED
        self.error = self.error or error
        self.finish_time = time.time()
        for n in self.nodes.values():
            if n.state in (NodeState.PENDING, NodeState.SUBMITTING):
                n.state = NodeState.SKIPPED

    def record_submitted(self, nid: str, rnd: int, job_id: str,
                         output_dir: str, num_reduces: int) -> bool:
        """A planned submission landed (caller re-took the pipeline
        lock). Returns False when the pipeline died while the
        submission was in flight outside the lock (kill/fail flipped
        the SUBMITTING node) — the CALLER must kill the just-submitted
        job, or it runs to completion as an orphan burning slots."""
        n = self.nodes[nid]
        n.jobs.append(job_id)
        n.job_id = job_id
        n.round = rnd
        n.output_dir = output_dir
        n.num_reduces = num_reduces
        if n.state == NodeState.SUBMITTING:
            n.state = NodeState.RUNNING
            return True
        return False

    def record_submit_failed(self, nid: str, error: str) -> None:
        n = self.nodes[nid]
        n.state = NodeState.FAILED
        n.error = error
        self._fail(f"stage {nid!r} submission failed: {error}")

    def kill(self) -> "list[str]":
        """Mark KILLED; returns the in-flight stage job ids the caller
        must kill (outside the pipeline lock — kill_job does I/O)."""
        if self.state in PipelineState.TERMINAL:
            return []
        self.state = PipelineState.KILLED
        self.finish_time = time.time()
        victims = []
        for n in self.nodes.values():
            if n.state == NodeState.RUNNING:
                # settle the node observably — advancement stops on a
                # terminal pipeline, so nothing would ever fold it
                if n.job_id:
                    victims.append(n.job_id)
                n.state = NodeState.FAILED
                n.error = n.error or "killed with pipeline"
            if n.state in (NodeState.PENDING, NodeState.SUBMITTING):
                n.state = NodeState.SKIPPED
        return victims

    # -------------------------------------------------------- recovery

    @staticmethod
    def from_recovery(pipeline_id: str, graph_dict: dict,
                      stage_events: "list[dict]", master: Any,
                      user: str = "") -> "PipelineInProgress":
        """Rebuild an interrupted pipeline from its journal: replay each
        PIPELINE_STAGE_SUBMITTED through the master's job-recovery alias
        (a stage job the restart resubmitted is watched under its NEW
        id), adopt history-terminal stages without re-running them, and
        leave the rest for normal advancement."""
        pip = PipelineInProgress(
            pipeline_id, JobGraph.from_dict(graph_dict), user=user)
        # a traced pipeline keeps its trace identity across the restart
        # (the id was stamped into the journaled graph conf): the
        # merged trace file spans both masters' spans. No root span —
        # the old master's root closed with it.
        pip.trace_id = str(pip.graph.conf.get("tpumr.trace.id", "")
                           or "")
        for ev in stage_events:
            nid = str(ev.get("node", ""))
            n = pip.nodes.get(nid)
            if n is None:
                continue
            job_id = str(ev.get("stage_job_id", ""))
            job_id = master._recovered.get(job_id, job_id)
            n.jobs.append(job_id)
            n.job_id = job_id
            n.round = int(ev.get("round", 0) or 0)
            n.output_dir = str(ev.get("output_dir", "") or "")
            n.num_reduces = int(ev.get("num_reduces", 0) or 0)
            n.state = NodeState.RUNNING
        # settle nodes whose job already has a terminal outcome: live
        # recovered jobs fold on the first advance; history-only jobs
        # (finished before the crash) settle here so completed upstream
        # stages are adopted, never re-run (runs at master startup —
        # no ranked lock held, history file reads are fine)
        for nid, n in pip.nodes.items():
            if n.state == NodeState.RUNNING \
                    and master.jobs.get(n.job_id) is None:
                pip.apply_retired(nid, pip._retired_state(master,
                                                          n.job_id))
        return pip
